"""Perf report: the utilization story behind bench.py's headline number
(VERDICT r2 item 4 — "turn one number into a utilization story").

Runs four graded-workload-class benchmarks on the real chip and writes
PERF.md next to the driver's BENCH artifacts:

1. PPO + MLP on ``jax:lift``  (the headline: BASELINE config ③/north-star
   class) — steps/s, XLA-reported FLOP/s, MFU, and a rollout-vs-learn
   top-line breakdown, plus a jax.profiler trace window.
2. IMPALA + NatureCNN on ``jax:pong``  (BASELINE config ⑤ class).
3. DDPG + prioritized replay on ``jax:lift``  (BASELINE config ③ class).
4. PPO + NatureCNN from pixels on ``jax:nut_pixels``  (BASELINE config ④
   class — envs rendered AND learned on device).

MFU uses the TPU v5e public peak (197 TFLOP/s bf16). These workloads are
LATENCY-BOUND on long scans of tiny elementwise env ops, not matmul-bound
— MFU is expectedly tiny and reported for transparency; the headline
metric remains env steps/s/chip (BASELINE.json).

Round-3 measurement correction: all timing is fenced by jax.device_get —
jax.block_until_ready returns WITHOUT waiting on this image's tunneled
backend, which inflated earlier recorded numbers ~1000x (see bench.py's
module doc for the forensics).

Usage:  python perf_report.py            # writes PERF.md
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from bench import PEAK_FLOPS_BF16, _iter_flops

WARMUP = 2
ITERS = 10  # match bench.py's window; short windows over the tunneled
            # chip showed ~1.6x run-to-run spread on sub-ms iterations


def _timeit_chained(step, carry0, key, iters=ITERS):
    """Time ``iters`` CHAINED calls: each call consumes the previous
    call's outputs, so launches cannot overlap on the device.

    MEASUREMENT INTEGRITY: the completion fence is ``jax.device_get`` of
    the final observable — on this image's tunneled backend
    ``jax.block_until_ready`` RETURNS WITHOUT WAITING, which inflated
    earlier recorded numbers ~1000x (caught as >100% MFU, a physical
    impossibility; verified honest by linearity in ``iters``). Chaining
    alone is NOT sufficient; only pulling real result bytes is.

    ``step(carry, key) -> (carry, observable)``; returns (seconds, carry).
    """
    k = key
    carry = carry0
    obs = None
    t0 = time.perf_counter()
    for _ in range(iters):
        k, sub = jax.random.split(k)
        carry, obs = step(carry, sub)
    jax.device_get(obs)  # the only trustworthy fence on this backend
    return time.perf_counter() - t0, carry


def ppo_lift_headline() -> dict:
    from surreal_tpu.launch.rollout import device_rollout, init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 4096, 256
    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=horizon, epochs=4, num_minibatches=4),
        ),
        env_config=Config(name="jax:lift", num_envs=num_envs),
        session_config=Config(
            folder="/tmp/perf_lift",
            metrics=Config(every_n_iters=10_000),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    carry = init_device_carry(trainer.env, env_key, num_envs)

    for _ in range(WARMUP):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)
    flops = _iter_flops(trainer._train_iter, state, carry, key)

    def fused_step(sc, k):
        s, c = sc
        s, c, m = trainer._train_iter(s, c, k)
        return (s, c), m

    # throwaway window: the first timed program after process start has
    # shown a ~10x one-time tunnel warmup artifact (observed: 3967 ms/iter
    # first window vs 400 ms/iter for the identical geometry later in the
    # same process); record the steady window
    _, (state, carry) = _timeit_chained(fused_step, (state, carry), key, iters=2)
    dt, (state, carry) = _timeit_chained(fused_step, (state, carry), key)
    sps = ITERS * num_envs * horizon / dt

    # top-line breakdown: rollout-only vs learn-only compiled separately
    # (the fused iter overlaps them in one program; this is the attribution)
    roll = jax.jit(
        lambda s, c, k: device_rollout(
            trainer.env, trainer.learner, s, c, k, horizon
        )
    )
    key, rk = jax.random.split(key)
    carry2, batch = roll(state, carry, rk)
    jax.device_get(batch["reward"][-1])

    def roll_step(c, k):
        c2, b = roll(state, c, k)
        # small observable: fencing on the full [T, B, ...] batch would
        # pull ~0.5 GB through the tunnel and bill the transfer (~1.5 s)
        # to the rollout — observed before this slice was added
        return c2, b["reward"][-1]

    _, carry_w = _timeit_chained(roll_step, carry, key, iters=2)  # throwaway
    dt_roll, _ = _timeit_chained(roll_step, carry_w, key)

    learn_batch = {
        k: batch[k]
        for k in ("obs", "next_obs", "action", "reward", "done", "terminated",
                  "behavior_logp", "behavior")
    }
    learn = jax.jit(trainer.learner.learn)
    key, lk = jax.random.split(key)
    s2, m2 = learn(state, learn_batch, lk)
    jax.device_get(m2["loss/pg"])

    def learn_step(s, k):
        s2, m = learn(s, learn_batch, k)
        return s2, m

    _, state_w = _timeit_chained(learn_step, state, key, iters=2)  # throwaway
    dt_learn, _ = _timeit_chained(learn_step, state_w, key)

    attrib = _learn_attribution(trainer, state, learn_batch, key)

    # NOTE: no jax.profiler.trace here — on the axon backend a trace
    # window poisons every program compiled AFTER it (observed 500-1000x
    # slowdowns on post-trace compilations); the report's trace runs LAST
    # in main(), after all measurements.
    out = {
        "attrib": attrib,
        "workload": "PPO+MLP jax:lift (BASELINE ③/north-star class)",
        "geometry": f"{num_envs} envs x {horizon} horizon, 4 epochs x 4 minibatches",
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
        "rollout_only_ms": dt_roll / ITERS * 1e3,
        "learn_only_ms": dt_learn / ITERS * 1e3,
        "_trace_fn": lambda: _capture_trace(trainer, state, carry, key),
    }
    if flops is not None:
        out["flops_per_iter"] = flops
        out["model_flops_per_s"] = flops * ITERS / dt
        out["mfu"] = out["model_flops_per_s"] / PEAK_FLOPS_BF16
    return out


def _learn_attribution(trainer, state, learn_batch, key) -> dict:
    """Where the learn phase's milliseconds go (round-4 VERDICT weak #1).

    Sub-programs compiled and timed separately at the headline geometry.
    The round-4 finding this documents: with row shuffling (the
    reference's per-epoch reshuffle semantics), ~70% of learn time was
    the per-epoch 1M-element argsort permutation + random row gathers
    (4-byte-row leaves walk the TPU scalar unit); ALL sixteen grad steps
    cost ~20 ms. algo.shuffle='block' (now the default) permutes
    contiguous blocks instead and collapses the learn phase ~17x.
    """
    import jax.numpy as jnp
    import optax

    learner = trainer.learner
    out = {}

    # learn-only under the reference-semantics row shuffle (the A/B)
    from surreal_tpu.learners import build_learner
    from surreal_tpu.session.config import Config

    row_learner = build_learner(
        Config(algo=Config(shuffle="row")).extend(trainer.learner.config),
        trainer.env.specs,
    )
    learn_row = jax.jit(row_learner.learn)
    key, k0 = jax.random.split(key)
    s0, m0 = learn_row(state, learn_batch, k0)
    jax.device_get(m0["loss/pg"])

    def row_step(s, k):
        s2, m = learn_row(s, learn_batch, k)
        return s2, m["loss/pg"]

    _, sw = _timeit_chained(row_step, state, key, iters=2)
    dt_row, _ = _timeit_chained(row_step, sw, key)
    out["learn_row_ms"] = dt_row / ITERS * 1e3

    # sub-programs (block learner), each chained + device_get-fenced
    obs_n = learner._norm_obs(state.obs_stats, learn_batch["obs"])
    values = learner.model.apply(state.params, obs_n).value
    v_next = learner.model.apply(
        state.params, learner._norm_obs(state.obs_stats, learn_batch["next_obs"])
    ).value
    jax.device_get(values[-1, -1])

    # value forwards (the two applies)
    vf = jax.jit(
        lambda s, c: learner.model.apply(
            s.params, learner._norm_obs(s.obs_stats, learn_batch["obs"]) + c
        ).value
        + learner.model.apply(
            s.params, learner._norm_obs(s.obs_stats, learn_batch["next_obs"])
        ).value
    )
    jax.device_get(vf(state, jnp.float32(0))[-1, -1])

    def vf_step(c, k):
        v = vf(state, c)
        # the carry MUST consume the output (the chaining contract): a
        # carry independent of v would let the backend overlap launches
        return v[-1, -1] * 0.0, v[-1, -1]

    _timeit_chained(vf_step, jnp.float32(0), key, iters=2)
    dt_vf, _ = _timeit_chained(vf_step, jnp.float32(0), key)
    out["value_forwards_ms"] = dt_vf / ITERS * 1e3

    # GAE alone
    gb = {k_: learn_batch[k_] for k_ in ("reward", "done", "terminated")}
    g = jax.jit(lambda c: learner._gae(gb, values + c, v_next)[0])
    jax.device_get(g(jnp.float32(0))[-1, -1])

    def g_step(c, k):
        a = g(c)
        return a[-1, -1] * 0.0, a[-1, -1]  # carry consumes the output

    _timeit_chained(g_step, jnp.float32(0), key, iters=2)
    dt_g, _ = _timeit_chained(g_step, jnp.float32(0), key)
    out["gae_ms"] = dt_g / ITERS * 1e3

    # grad steps with NO shuffling/gathers: 16 steps on one fixed slice
    adv, tgt = learner._gae(gb, values, v_next)
    N = adv.size
    flat = {
        "obs": obs_n.reshape(N, *obs_n.shape[2:]),
        "action": learn_batch["action"].reshape(N, -1),
        "behavior_logp": learn_batch["behavior_logp"].reshape(N),
        "adv": adv.reshape(N),
        "target": tgt.reshape(N),
        "value_old": values.reshape(N),
        "b_mean": learn_batch["behavior"]["mean"].reshape(N, -1),
        "b_log_std": learn_batch["behavior"]["log_std"].reshape(N, -1),
    }
    mb0 = jax.tree.map(lambda x: x[: N // 4], flat)
    grad_fn = jax.grad(learner._loss_fn, has_aux=True)

    def steps16(s, k):
        def body(carry, _):
            params, opt_state = carry
            grads, aux = grad_fn(params, mb0, s.kl_beta, jnp.float32(1.0))
            updates, opt_state = learner.tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), aux["kl"]

        (p, o), kls = jax.lax.scan(body, (s.params, s.opt_state), None, length=16)
        return s._replace(params=p, opt_state=o), kls[-1]

    sj = jax.jit(steps16)
    s1, kl1 = sj(state, key)
    jax.device_get(kl1)
    _timeit_chained(lambda s, k: sj(s, k), state, key, iters=2)
    dt_s, _ = _timeit_chained(lambda s, k: sj(s, k), state, key)
    out["gradsteps16_nogather_ms"] = dt_s / ITERS * 1e3
    return out


def impala_pong() -> dict:
    from surreal_tpu.launch.rollout import init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 1024, 32
    cfg = Config(
        learner_config=Config(
            algo=Config(name="impala", horizon=horizon),
            model=Config(cnn=Config(enabled=True)),
        ),
        env_config=Config(name="jax:pong", num_envs=num_envs),
        session_config=Config(
            folder="/tmp/perf_pong",
            metrics=Config(every_n_iters=10_000),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    carry = init_device_carry(trainer.env, env_key, num_envs)
    for _ in range(WARMUP):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)
    flops = _iter_flops(trainer._train_iter, state, carry, key)

    def fused_step(sc, k):
        s, c = sc
        s, c, m = trainer._train_iter(s, c, k)
        return (s, c), m

    # throwaway window first: freshly compiled programs show a one-time
    # multi-second tunnel artifact on their first timed window
    _, sc_w = _timeit_chained(fused_step, (state, carry), key, iters=2)
    dt, _ = _timeit_chained(fused_step, sc_w, key)
    sps = ITERS * num_envs * horizon / dt
    out = {
        "workload": "IMPALA+NatureCNN jax:pong pixels (BASELINE ⑤ class)",
        "geometry": f"{num_envs} envs x {horizon} unroll, 42x42x2 uint8 pixels",
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
    }
    if flops is not None:
        out["flops_per_iter"] = flops
        out["model_flops_per_s"] = flops * ITERS / dt
        out["mfu"] = out["model_flops_per_s"] / PEAK_FLOPS_BF16
    return out


def ppo_cnn_nut_pixels() -> dict:
    from surreal_tpu.launch.rollout import init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 512, 32
    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=horizon, epochs=2, num_minibatches=4),
            model=Config(cnn=Config(enabled=True)),
        ),
        env_config=Config(name="jax:nut_pixels", num_envs=num_envs),
        session_config=Config(
            folder="/tmp/perf_nut_pixels",
            metrics=Config(every_n_iters=10_000),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    carry = init_device_carry(trainer.env, env_key, num_envs)
    for _ in range(WARMUP):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)
    flops = _iter_flops(trainer._train_iter, state, carry, key)

    def fused_step(sc, k):
        s, c = sc
        s, c, m = trainer._train_iter(s, c, k)
        return (s, c), m

    # throwaway window first: freshly compiled programs show a one-time
    # multi-second tunnel artifact on their first timed window
    _, sc_w = _timeit_chained(fused_step, (state, carry), key, iters=2)
    dt, _ = _timeit_chained(fused_step, sc_w, key)
    sps = ITERS * num_envs * horizon / dt
    out = {
        "workload": "PPO+NatureCNN jax:nut_pixels (BASELINE ④ class, on-device rendering)",
        "geometry": f"{num_envs} envs x {horizon} horizon, 64x64x4 uint8 pixels",
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
    }
    if flops is not None:
        out["flops_per_iter"] = flops
        out["model_flops_per_s"] = flops * ITERS / dt
        out["mfu"] = out["model_flops_per_s"] / PEAK_FLOPS_BF16
    return out


def ddpg_prioritized_lift() -> dict:
    from surreal_tpu.launch.offpolicy_trainer import OffPolicyTrainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 2048, 16
    steps_per_iter = num_envs * horizon

    def make_trainer():
        cfg = Config(
            learner_config=Config(
                algo=Config(name="ddpg", horizon=horizon,
                            exploration=Config(warmup_steps=0)),
                replay=Config(kind="prioritized", capacity=200_000,
                              start_sample_size=steps_per_iter,
                              batch_size=256),
            ),
            env_config=Config(name="jax:lift", num_envs=num_envs),
            session_config=Config(
                folder="/tmp/perf_ddpg",
                metrics=Config(every_n_iters=10_000, tensorboard=False,
                               console=False),
                checkpoint=Config(every_n_iters=0),
                eval=Config(every_n_iters=0),
            ),
        ).extend(base_config())
        return OffPolicyTrainer(cfg)

    trainer = make_trainer()
    # warmup run: compile everything (jit cache lives on the trainer)
    trainer.run(max_env_steps=2 * steps_per_iter)
    t0 = time.perf_counter()
    trainer.run(max_env_steps=ITERS * steps_per_iter)
    dt = time.perf_counter() - t0
    sps = ITERS * steps_per_iter / dt
    return {
        "workload": "DDPG+prioritized replay jax:lift (BASELINE ③ class)",
        "geometry": (
            f"{num_envs} envs x {horizon} collect, 64 updates/iter x 256 batch, "
            "200k prioritized replay"
        ),
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
    }


def headline_scaling() -> list[dict]:
    """Throughput vs geometry for the headline workload — how far the
    batch amortizes per-iteration dispatch before compute saturates."""
    from surreal_tpu.launch.rollout import init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    rows = []
    for num_envs, horizon in (
        (1024, 256), (2048, 256), (4096, 256), (8192, 256), (16384, 256)
    ):
        cfg = Config(
            learner_config=Config(
                algo=Config(name="ppo", horizon=horizon, epochs=4, num_minibatches=4),
            ),
            env_config=Config(name="jax:lift", num_envs=num_envs),
            session_config=Config(
                folder="/tmp/perf_scaling",
                metrics=Config(every_n_iters=10_000),
                checkpoint=Config(every_n_iters=0),
                eval=Config(every_n_iters=0),
            ),
        ).extend(base_config())
        trainer = Trainer(cfg)
        key = jax.random.key(0)
        key, init_key, env_key = jax.random.split(key, 3)
        state = trainer.learner.init(init_key)
        carry = init_device_carry(trainer.env, env_key, num_envs)
        for _ in range(WARMUP):
            key, it_key = jax.random.split(key)
            state, carry, metrics = trainer._train_iter(state, carry, it_key)
        jax.device_get(metrics)

        def fused_step(sc, k, _t=trainer):
            s, c = sc
            s, c, m = _t._train_iter(s, c, k)
            return (s, c), m

        # per-geometry throwaway window: freshly compiled programs show a
        # one-time multi-second tunnel warmup on their first timed window
        _, sc_w = _timeit_chained(fused_step, (state, carry), key, iters=2)
        dt, _ = _timeit_chained(fused_step, sc_w, key)
        rows.append(
            {
                "geometry": f"{num_envs} x {horizon}",
                "env_steps_per_s": ITERS * num_envs * horizon / dt,
                "iter_ms": dt / ITERS * 1e3,
            }
        )
        print(json.dumps(rows[-1], default=float))
    return rows


def _capture_trace(trainer, state, carry, key) -> str | None:
    """Profiler window over two fused iters (SURVEY.md §5.1). MUST run
    after every measurement: see the axon post-trace-compilation note."""
    trace_dir = "/tmp/perf_lift/profile"
    try:
        with jax.profiler.trace(trace_dir):
            for _ in range(2):
                key, it_key = jax.random.split(key)
                state, carry, metrics = trainer._train_iter(state, carry, it_key)
            jax.device_get(metrics)  # real fence: trace must span execution
        return trace_dir
    except Exception:
        return None


def main(argv=None) -> None:
    import sys

    argv = sys.argv[1:] if argv is None else argv
    rows = []
    trace_fn = None
    for fn in (
        ppo_lift_headline, impala_pong, ddpg_prioritized_lift, ppo_cnn_nut_pixels
    ):
        r = fn()
        trace_fn = r.pop("_trace_fn", None) or trace_fn  # not JSON-able
        rows.append(r)
        print(json.dumps(r, default=float))
    scaling = headline_scaling() if "--scaling" in argv else None
    # trace LAST: everything compiled after a trace window runs degraded
    rows[0]["trace_dir"] = trace_fn() if trace_fn else None

    dev = jax.devices()[0]
    lines = [
        "# PERF — measured utilization report",
        "",
        f"Device: `{dev.device_kind}` (1 chip; via the axon tunnel). "
        f"MFU denominator: {PEAK_FLOPS_BF16 / 1e12:.0f} TFLOP/s (TPU v5e "
        "public bf16 peak). FLOPs are XLA's own `cost_analysis()` of the "
        "compiled training iteration — model + env + optimizer, everything "
        "in the program.",
        "",
        "All timings are fenced by `jax.device_get` of a program output — "
        "`jax.block_until_ready` does not wait on this backend, which "
        "inflated pre-round-3 records ~1000x (bench.py module doc has the "
        "forensics). These workloads are LATENCY-BOUND on long scans of "
        "tiny elementwise env ops, not matmul-bound — MFU is expectedly "
        "tiny and reported for transparency; the graded metric stays env "
        "steps/s/chip.",
        "",
        "| Workload | Geometry | env steps/s/chip | iter ms | FLOP/s | MFU |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        fl = r.get("model_flops_per_s")
        mfu = r.get("mfu")
        lines.append(
            "| {w} | {g} | {s:,.0f} | {ms:.1f} | {fl} | {mfu} |".format(
                w=r["workload"],
                g=r["geometry"],
                s=r["env_steps_per_s"],
                ms=r["iter_ms"],
                fl=f"{fl / 1e12:.2f} TFLOP/s" if fl else "n/a",
                mfu=f"{mfu * 100:.2f}%" if mfu else "n/a",
            )
        )
    head = rows[0]
    parts_sum = head["rollout_only_ms"] + head["learn_only_ms"]
    if head["iter_ms"] < 0.9 * parts_sum:
        verdict = (
            "The fused iteration beats rollout+learn compiled separately "
            f"({head['iter_ms']:.2f} ms vs {parts_sum:.2f} ms summed): one "
            "program lets XLA overlap env stepping with learning work and "
            "keep intermediates in HBM/VMEM instead of round-tripping "
            "between dispatches — the reason the trainer fuses the whole "
            "iteration."
        )
    else:
        verdict = (
            "Rollout and learn compiled separately sum close to the fused "
            f"iteration ({parts_sum:.2f} ms vs {head['iter_ms']:.2f} ms): "
            "fusion is not load-bearing at this geometry; the split shows "
            "which half dominates."
        )
    lines += [
        "",
        "## Top-line breakdown (headline workload)",
        "",
        f"- fused train iteration: {head['iter_ms']:.2f} ms",
        f"- rollout-only program (policy forward + env step x 256): "
        f"{head['rollout_only_ms']:.2f} ms",
        f"- learn-only program (GAE + 4x4 minibatch SGD): "
        f"{head['learn_only_ms']:.2f} ms",
        "",
        verdict,
    ]
    at = head.get("attrib")
    if at:
        lines += [
            "",
            "## Learn-phase attribution (round-4 finding)",
            "",
            "Sub-programs compiled and timed separately at the headline "
            "geometry (device_get-fenced, chained):",
            "",
            "| Component | ms/iter |",
            "|---|---|",
            f"| learn-only, `algo.shuffle='row'` (reference semantics: per-epoch row reshuffle) | {at['learn_row_ms']:.1f} |",
            f"| learn-only, `algo.shuffle='block'` (default) | {head['learn_only_ms']:.1f} |",
            f"| value forwards (2x model.apply over [T, B]) | {at['value_forwards_ms']:.1f} |",
            f"| GAE recurrence | {at['gae_ms']:.1f} |",
            f"| ALL 16 grad steps (4 epochs x 4 minibatches), no shuffling/gathers | {at['gradsteps16_nogather_ms']:.1f} |",
            "",
            "With row shuffling, learn time was dominated NOT by training "
            "compute but by minibatch assembly: a ~1M-element argsort "
            "permutation per epoch plus random row gathers whose "
            "4-byte-row leaves (advantages, logps) walk the TPU scalar "
            "unit. `algo.shuffle='block'` (learners/ppo.py `_sgd_epochs`) "
            "permutes contiguous blocks instead — statistically benign "
            "here because a flat-layout block is a same-timestep slab of "
            "independent envs — and removes that cost wholesale; 'row' "
            "remains selectable for exact reference semantics.",
        ]
    if scaling:
        lines += [
            "",
            "## Headline geometry scaling (`--scaling`)",
            "",
            "| Geometry (envs x horizon) | env steps/s/chip | iter ms |",
            "|---|---|---|",
        ]
        for r in scaling:
            lines.append(
                f"| {r['geometry']} | {r['env_steps_per_s']:,.0f} "
                f"| {r['iter_ms']:.2f} |"
            )
        lines += [
            "",
            "Horizon costs linearly (the env scan is sequential) and width "
            "costs linearly once elementwise env ops saturate, so "
            "throughput is flat-to-declining past the knee. bench.py "
            "records the headline at its own swept knee (4096 x 256 since "
            "the round-4 block-shuffle change); this sweep holds horizon "
            "at 256 to show the width axis in isolation.",
        ]
    if head.get("trace_dir"):
        lines += [
            "",
            f"A `jax.profiler` trace of two fused iterations was captured to "
            f"`{head['trace_dir']}` (TensorBoard profile plugin format; not "
            "committed — rerun `python perf_report.py` to regenerate).",
        ]
    lines += [
        "",
        "_Generated by `perf_report.py`; bench.py prints the headline line "
        "with `mfu` for the driver's BENCH artifact._",
        "",
    ]
    with open("PERF.md", "w") as f:
        f.write("\n".join(lines))
    print("wrote PERF.md")
    _update_readme(rows)


def _update_readme(rows) -> None:
    """Regenerate README's measured-throughput table from THIS run plus
    the newest driver BENCH artifact on disk, so the three sources
    (README / PERF.md / BENCH_r0N.json) cannot drift (round-3 VERDICT
    weak #2). Rewrites only the marked block; wall-clock learning rows
    outside the markers are separate end-to-end runs and stay manual."""
    import glob
    import os

    start, end = "<!-- PERF-TABLE-START -->", "<!-- PERF-TABLE-END -->"
    try:
        with open("README.md") as f:
            readme = f.read()
    except OSError:
        return
    if start not in readme or end not in readme:
        print("README markers not found; table not updated")
        return

    artifact = None
    bench_files = sorted(glob.glob("BENCH_r*.json"))
    if bench_files:
        try:
            with open(bench_files[-1]) as f:
                data = json.load(f)
            # driver artifacts wrap the bench line under "parsed"
            parsed = data.get("parsed", data)
            if "value" in parsed:
                artifact = (os.path.basename(bench_files[-1]), parsed)
        except (OSError, json.JSONDecodeError):
            pass

    head = rows[0]
    art_txt = ""
    if artifact:
        vsb = artifact[1].get("vs_baseline", artifact[1]["value"] / 1e5)
        art_txt = (
            f" Driver artifact of record `{artifact[0]}`: "
            f"{artifact[1]['value']:,.0f} steps/s ({vsb:,.0f}x target)."
        )
    body = [
        "| Workload (BASELINE config class) | Geometry | env steps/s/chip | vs 100k north star |",
        "|---|---|---|---|",
    ]
    for r in rows:
        body.append(
            "| {w} | {g} | **{s:,.0f}** | {x:,.0f}x |".format(
                w=r["workload"], g=r["geometry"],
                s=r["env_steps_per_s"], x=r["env_steps_per_s"] / 1e5,
            )
        )
    body += [
        "",
        f"_Table generated by `perf_report.py` (device_get-fenced, this "
        f"run's measurements; headline iter {head['iter_ms']:.1f} ms, "
        f"MFU {head.get('mfu', 0) * 100:.2f}%).{art_txt} Full breakdown, "
        "learn-phase attribution, and geometry sweep: `PERF.md`._",
    ]
    new = (
        readme[: readme.index(start) + len(start)]
        + "\n"
        + "\n".join(body)
        + "\n"
        + readme[readme.index(end):]
    )
    with open("README.md", "w") as f:
        f.write(new)
    print("updated README.md perf table")


if __name__ == "__main__":
    main()
