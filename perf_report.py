"""Perf report: the utilization story behind bench.py's headline number
(VERDICT r2 item 4 — "turn one number into a utilization story").

Runs four graded-workload-class benchmarks on the real chip and writes
PERF.md next to the driver's BENCH artifacts:

1. PPO + MLP on ``jax:lift``  (the headline: BASELINE config ③/north-star
   class) — steps/s, XLA-reported FLOP/s, MFU, and a rollout-vs-learn
   top-line breakdown, plus a jax.profiler trace window.
2. IMPALA + NatureCNN on ``jax:pong``  (BASELINE config ⑤ class).
3. DDPG + prioritized replay on ``jax:lift``  (BASELINE config ③ class).
4. PPO + NatureCNN from pixels on ``jax:nut_pixels``  (BASELINE config ④
   class — envs rendered AND learned on device).

MFU uses the TPU v5e public peak (197 TFLOP/s bf16). RL env-step
workloads are not matmul-bound — tiny MLPs, env physics, scatter-heavy
replay — so single-digit MFU is expected and honest; the headline metric
remains env steps/s/chip (BASELINE.json), MFU says what the chip had left.

Usage:  python perf_report.py            # writes PERF.md
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from bench import PEAK_FLOPS_BF16, _iter_flops

WARMUP = 2
ITERS = 10  # match bench.py's window; short windows over the tunneled
            # chip showed ~1.6x run-to-run spread on sub-ms iterations


def _timeit(fn, *args, iters=ITERS, split_key=True, key=None):
    """Time ``iters`` calls of a compiled fn; returns (seconds, last_out)."""
    out = None
    t0 = time.perf_counter()
    k = key
    for _ in range(iters):
        if split_key and k is not None:
            k, sub = jax.random.split(k)
            out = fn(*args, sub)
        else:
            out = fn(*args)
    jax.block_until_ready(out)
    return time.perf_counter() - t0, out


def ppo_lift_headline() -> dict:
    from surreal_tpu.launch.rollout import device_rollout, init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 4096, 256
    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=horizon, epochs=4, num_minibatches=4),
        ),
        env_config=Config(name="jax:lift", num_envs=num_envs),
        session_config=Config(
            folder="/tmp/perf_lift",
            metrics=Config(every_n_iters=10_000),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    carry = init_device_carry(trainer.env, env_key, num_envs)

    for _ in range(WARMUP):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.block_until_ready(metrics)
    flops = _iter_flops(trainer._train_iter, state, carry, key)

    dt, _ = _timeit(
        lambda s, c, k: trainer._train_iter(s, c, k)[2], state, carry, key=key
    )
    # keep state/carry from the timing loop out of the breakdown: re-run
    # the pieces on the same shapes
    sps = ITERS * num_envs * horizon / dt

    # top-line breakdown: rollout-only vs learn-only compiled separately
    # (the fused iter overlaps them in one program; this is the attribution)
    roll = jax.jit(
        lambda s, c, k: device_rollout(
            trainer.env, trainer.learner, s, c, k, horizon
        )
    )
    key, rk = jax.random.split(key)
    carry2, batch = roll(state, carry, rk)
    jax.block_until_ready(batch)
    dt_roll, _ = _timeit(lambda s, c, k: roll(s, c, k)[1], state, carry, key=key)

    learn_batch = {
        k: batch[k]
        for k in ("obs", "next_obs", "action", "reward", "done", "terminated",
                  "behavior_logp", "behavior")
    }
    learn = jax.jit(trainer.learner.learn)
    key, lk = jax.random.split(key)
    s2, m2 = learn(state, learn_batch, lk)
    jax.block_until_ready(m2)
    dt_learn, _ = _timeit(
        lambda s, b, k: learn(s, b, k)[1], state, learn_batch, key=key
    )

    # profiler window over two fused iters (SURVEY.md §5.1)
    trace_dir = "/tmp/perf_lift/profile"
    try:
        with jax.profiler.trace(trace_dir):
            for _ in range(2):
                key, it_key = jax.random.split(key)
                state, carry, metrics = trainer._train_iter(state, carry, it_key)
            jax.block_until_ready(metrics)
        traced = True
    except Exception:
        traced = False

    out = {
        "workload": "PPO+MLP jax:lift (BASELINE ③/north-star class)",
        "geometry": f"{num_envs} envs x {horizon} horizon, 4 epochs x 4 minibatches",
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
        "rollout_only_ms": dt_roll / ITERS * 1e3,
        "learn_only_ms": dt_learn / ITERS * 1e3,
        "trace_dir": trace_dir if traced else None,
    }
    if flops is not None:
        out["flops_per_iter"] = flops
        out["model_flops_per_s"] = flops * ITERS / dt
        out["mfu"] = out["model_flops_per_s"] / PEAK_FLOPS_BF16
    return out


def impala_pong() -> dict:
    from surreal_tpu.launch.rollout import init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 1024, 32
    cfg = Config(
        learner_config=Config(
            algo=Config(name="impala", horizon=horizon),
            model=Config(cnn=Config(enabled=True)),
        ),
        env_config=Config(name="jax:pong", num_envs=num_envs),
        session_config=Config(
            folder="/tmp/perf_pong",
            metrics=Config(every_n_iters=10_000),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    carry = init_device_carry(trainer.env, env_key, num_envs)
    for _ in range(WARMUP):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.block_until_ready(metrics)
    flops = _iter_flops(trainer._train_iter, state, carry, key)
    dt, _ = _timeit(
        lambda s, c, k: trainer._train_iter(s, c, k)[2], state, carry, key=key
    )
    sps = ITERS * num_envs * horizon / dt
    out = {
        "workload": "IMPALA+NatureCNN jax:pong pixels (BASELINE ⑤ class)",
        "geometry": f"{num_envs} envs x {horizon} unroll, 42x42x2 uint8 pixels",
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
    }
    if flops is not None:
        out["flops_per_iter"] = flops
        out["model_flops_per_s"] = flops * ITERS / dt
        out["mfu"] = out["model_flops_per_s"] / PEAK_FLOPS_BF16
    return out


def ppo_cnn_nut_pixels() -> dict:
    from surreal_tpu.launch.rollout import init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 512, 32
    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=horizon, epochs=2, num_minibatches=4),
            model=Config(cnn=Config(enabled=True)),
        ),
        env_config=Config(name="jax:nut_pixels", num_envs=num_envs),
        session_config=Config(
            folder="/tmp/perf_nut_pixels",
            metrics=Config(every_n_iters=10_000),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    carry = init_device_carry(trainer.env, env_key, num_envs)
    for _ in range(WARMUP):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.block_until_ready(metrics)
    flops = _iter_flops(trainer._train_iter, state, carry, key)
    dt, _ = _timeit(
        lambda s, c, k: trainer._train_iter(s, c, k)[2], state, carry, key=key
    )
    sps = ITERS * num_envs * horizon / dt
    out = {
        "workload": "PPO+NatureCNN jax:nut_pixels (BASELINE ④ class, on-device rendering)",
        "geometry": f"{num_envs} envs x {horizon} horizon, 64x64x4 uint8 pixels",
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
    }
    if flops is not None:
        out["flops_per_iter"] = flops
        out["model_flops_per_s"] = flops * ITERS / dt
        out["mfu"] = out["model_flops_per_s"] / PEAK_FLOPS_BF16
    return out


def ddpg_prioritized_lift() -> dict:
    from surreal_tpu.launch.offpolicy_trainer import OffPolicyTrainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 2048, 16
    steps_per_iter = num_envs * horizon

    def make_trainer():
        cfg = Config(
            learner_config=Config(
                algo=Config(name="ddpg", horizon=horizon,
                            exploration=Config(warmup_steps=0)),
                replay=Config(kind="prioritized", capacity=200_000,
                              start_sample_size=steps_per_iter,
                              batch_size=256),
            ),
            env_config=Config(name="jax:lift", num_envs=num_envs),
            session_config=Config(
                folder="/tmp/perf_ddpg",
                metrics=Config(every_n_iters=10_000, tensorboard=False,
                               console=False),
                checkpoint=Config(every_n_iters=0),
                eval=Config(every_n_iters=0),
            ),
        ).extend(base_config())
        return OffPolicyTrainer(cfg)

    trainer = make_trainer()
    # warmup run: compile everything (jit cache lives on the trainer)
    trainer.run(max_env_steps=2 * steps_per_iter)
    t0 = time.perf_counter()
    trainer.run(max_env_steps=ITERS * steps_per_iter)
    dt = time.perf_counter() - t0
    sps = ITERS * steps_per_iter / dt
    return {
        "workload": "DDPG+prioritized replay jax:lift (BASELINE ③ class)",
        "geometry": (
            f"{num_envs} envs x {horizon} collect, 64 updates/iter x 256 batch, "
            "200k prioritized replay"
        ),
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
    }


def main() -> None:
    rows = []
    for fn in (
        ppo_lift_headline, impala_pong, ddpg_prioritized_lift, ppo_cnn_nut_pixels
    ):
        r = fn()
        rows.append(r)
        print(json.dumps(r, default=float))

    dev = jax.devices()[0]
    lines = [
        "# PERF — measured utilization report",
        "",
        f"Device: `{dev.device_kind}` (1 chip; via the axon tunnel). "
        f"MFU denominator: {PEAK_FLOPS_BF16 / 1e12:.0f} TFLOP/s (TPU v5e "
        "public bf16 peak). FLOPs are XLA's own `cost_analysis()` of the "
        "compiled training iteration — model + env + optimizer, everything "
        "in the program.",
        "",
        "RL env-step workloads are usually not matmul-bound (small MLPs, "
        "env physics, scatter-heavy replay) — MFU here says what fraction "
        "of the chip the headline steps/s actually uses; the graded metric "
        "stays env steps/s/chip.",
        "",
        "| Workload | Geometry | env steps/s/chip | iter ms | FLOP/s | MFU |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        fl = r.get("model_flops_per_s")
        mfu = r.get("mfu")
        lines.append(
            "| {w} | {g} | {s:,.0f} | {ms:.1f} | {fl} | {mfu} |".format(
                w=r["workload"],
                g=r["geometry"],
                s=r["env_steps_per_s"],
                ms=r["iter_ms"],
                fl=f"{fl / 1e12:.2f} TFLOP/s" if fl else "n/a",
                mfu=f"{mfu * 100:.2f}%" if mfu else "n/a",
            )
        )
    head = rows[0]
    parts_sum = head["rollout_only_ms"] + head["learn_only_ms"]
    if head["iter_ms"] < 0.9 * parts_sum:
        verdict = (
            "The fused iteration beats rollout+learn compiled separately "
            f"({head['iter_ms']:.2f} ms vs {parts_sum:.2f} ms summed): one "
            "program lets XLA overlap env stepping with learning work and "
            "keep intermediates in HBM/VMEM instead of round-tripping "
            "between dispatches — the reason the trainer fuses the whole "
            "iteration."
        )
    else:
        verdict = (
            "Rollout and learn compiled separately sum close to the fused "
            f"iteration ({parts_sum:.2f} ms vs {head['iter_ms']:.2f} ms): "
            "fusion is not load-bearing at this geometry; the split shows "
            "which half dominates."
        )
    lines += [
        "",
        "## Top-line breakdown (headline workload)",
        "",
        f"- fused train iteration: {head['iter_ms']:.2f} ms",
        f"- rollout-only program (policy forward + env step x 256): "
        f"{head['rollout_only_ms']:.2f} ms",
        f"- learn-only program (GAE + 4x4 minibatch SGD): "
        f"{head['learn_only_ms']:.2f} ms",
        "",
        verdict,
    ]
    if head.get("trace_dir"):
        lines += [
            "",
            f"A `jax.profiler` trace of two fused iterations was captured to "
            f"`{head['trace_dir']}` (TensorBoard profile plugin format; not "
            "committed — rerun `python perf_report.py` to regenerate).",
        ]
    lines += [
        "",
        "_Generated by `perf_report.py`; bench.py prints the headline line "
        "with `mfu` for the driver's BENCH artifact._",
        "",
    ]
    with open("PERF.md", "w") as f:
        f.write("\n".join(lines))
    print("wrote PERF.md")


if __name__ == "__main__":
    main()
