"""Perf report: the utilization story behind bench.py's headline number
(VERDICT r2 item 4 — "turn one number into a utilization story").

Runs four graded-workload-class benchmarks on the real chip and writes
PERF.md next to the driver's BENCH artifacts:

1. PPO + MLP on ``jax:lift``  (the headline: BASELINE config ③/north-star
   class) — steps/s, XLA-reported FLOP/s, MFU, and a rollout-vs-learn
   top-line breakdown, plus a jax.profiler trace window.
2. IMPALA + NatureCNN on ``jax:pong``  (BASELINE config ⑤ class).
3. DDPG + prioritized replay on ``jax:lift``  (BASELINE config ③ class).
4. PPO + NatureCNN from pixels on ``jax:nut_pixels``  (BASELINE config ④
   class — envs rendered AND learned on device).

MFU uses the TPU v5e public peak (197 TFLOP/s bf16). These workloads are
LATENCY-BOUND on long scans of tiny elementwise env ops, not matmul-bound
— MFU is expectedly tiny and reported for transparency; the headline
metric remains env steps/s/chip (BASELINE.json).

Round-3 measurement correction: all timing is fenced by jax.device_get —
jax.block_until_ready returns WITHOUT waiting on this image's tunneled
backend, which inflated earlier recorded numbers ~1000x (see bench.py's
module doc for the forensics).

Usage:  python perf_report.py                # writes PERF.md + README table
        python perf_report.py --sync-readme  # citation-only: re-point
            README's 'artifact of record' at the newest BENCH_r*.json
            (no benchmarks; tests/test_perf_docs.py fails when stale)
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from bench import PEAK_FLOPS_BF16, _iter_flops

WARMUP = 2
ITERS = 10  # match bench.py's window; short windows over the tunneled
            # chip showed ~1.6x run-to-run spread on sub-ms iterations


def _timeit_chained(step, carry0, key, iters=ITERS):
    """Time ``iters`` CHAINED calls: each call consumes the previous
    call's outputs, so launches cannot overlap on the device.

    MEASUREMENT INTEGRITY: the completion fence is ``jax.device_get`` of
    the final observable — on this image's tunneled backend
    ``jax.block_until_ready`` RETURNS WITHOUT WAITING, which inflated
    earlier recorded numbers ~1000x (caught as >100% MFU, a physical
    impossibility; verified honest by linearity in ``iters``). Chaining
    alone is NOT sufficient; only pulling real result bytes is.

    ``step(carry, key) -> (carry, observable)``; returns (seconds, carry).
    """
    k = key
    carry = carry0
    obs = None
    t0 = time.perf_counter()
    for _ in range(iters):
        k, sub = jax.random.split(k)
        carry, obs = step(carry, sub)
    jax.device_get(obs)  # the only trustworthy fence on this backend
    return time.perf_counter() - t0, carry


def ppo_lift_headline() -> dict:
    from surreal_tpu.launch.rollout import device_rollout, init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 4096, 256
    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=horizon, epochs=4, num_minibatches=4),
        ),
        env_config=Config(name="jax:lift", num_envs=num_envs),
        session_config=Config(
            folder="/tmp/perf_lift",
            metrics=Config(every_n_iters=10_000),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    carry = init_device_carry(trainer.env, env_key, num_envs)

    for _ in range(WARMUP):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)
    flops = _iter_flops(trainer._train_iter, state, carry, key)

    def fused_step(sc, k):
        s, c = sc
        s, c, m = trainer._train_iter(s, c, k)
        return (s, c), m

    # throwaway window: the first timed program after process start has
    # shown a ~10x one-time tunnel warmup artifact (observed: 3967 ms/iter
    # first window vs 400 ms/iter for the identical geometry later in the
    # same process); record the steady window
    _, (state, carry) = _timeit_chained(fused_step, (state, carry), key, iters=2)
    dt, (state, carry) = _timeit_chained(fused_step, (state, carry), key)
    sps = ITERS * num_envs * horizon / dt

    # top-line breakdown: rollout-only vs learn-only compiled separately
    # (the fused iter overlaps them in one program; this is the attribution)
    roll = jax.jit(
        lambda s, c, k: device_rollout(
            trainer.env, trainer.learner, s, c, k, horizon
        )
    )
    key, rk = jax.random.split(key)
    carry2, batch = roll(state, carry, rk)
    jax.device_get(batch["reward"][-1])

    def roll_step(c, k):
        c2, b = roll(state, c, k)
        # small observable: fencing on the full [T, B, ...] batch would
        # pull ~0.5 GB through the tunnel and bill the transfer (~1.5 s)
        # to the rollout — observed before this slice was added
        return c2, b["reward"][-1]

    _, carry_w = _timeit_chained(roll_step, carry, key, iters=2)  # throwaway
    dt_roll, _ = _timeit_chained(roll_step, carry_w, key)

    learn_batch = {
        k: batch[k]
        for k in ("obs", "next_obs", "action", "reward", "done", "terminated",
                  "behavior_logp", "behavior")
    }
    learn = jax.jit(trainer.learner.learn)
    key, lk = jax.random.split(key)
    s2, m2 = learn(state, learn_batch, lk)
    jax.device_get(m2["loss/pg"])

    def learn_step(s, k):
        s2, m = learn(s, learn_batch, k)
        return s2, m

    _, state_w = _timeit_chained(learn_step, state, key, iters=2)  # throwaway
    dt_learn, _ = _timeit_chained(learn_step, state_w, key)

    attrib = _learn_attribution(trainer, state, learn_batch, key)

    # NOTE: no jax.profiler.trace here — on the axon backend a trace
    # window poisons every program compiled AFTER it (observed 500-1000x
    # slowdowns on post-trace compilations); the report's trace runs LAST
    # in main(), after all measurements.
    out = {
        "attrib": attrib,
        "workload": "PPO+MLP jax:lift (BASELINE ③/north-star class)",
        "geometry": f"{num_envs} envs x {horizon} horizon, 4 epochs x 4 minibatches",
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
        "rollout_only_ms": dt_roll / ITERS * 1e3,
        "learn_only_ms": dt_learn / ITERS * 1e3,
        "_trace_fn": lambda: _capture_trace(trainer, state, carry, key),
    }
    if flops is not None:
        out["flops_per_iter"] = flops
        out["model_flops_per_s"] = flops * ITERS / dt
        out["mfu"] = out["model_flops_per_s"] / PEAK_FLOPS_BF16
    return out


def _learn_attribution(trainer, state, learn_batch, key) -> dict:
    """Where the learn phase's milliseconds go (round-4 VERDICT weak #1).

    Sub-programs compiled and timed separately at the headline geometry.
    The round-4 finding this documents: with row shuffling (the
    reference's per-epoch reshuffle semantics), ~70% of learn time was
    the per-epoch 1M-element argsort permutation + random row gathers
    (4-byte-row leaves walk the TPU scalar unit); ALL sixteen grad steps
    cost ~20 ms. algo.shuffle='block' (now the default) permutes
    contiguous blocks instead and collapses the learn phase ~17x.
    """
    import jax.numpy as jnp
    import optax

    learner = trainer.learner
    out = {}

    # learn-only under the reference-semantics row shuffle (the A/B)
    from surreal_tpu.learners import build_learner
    from surreal_tpu.session.config import Config

    row_learner = build_learner(
        Config(algo=Config(shuffle="row")).extend(trainer.learner.config),
        trainer.env.specs,
    )
    learn_row = jax.jit(row_learner.learn)
    key, k0 = jax.random.split(key)
    s0, m0 = learn_row(state, learn_batch, k0)
    jax.device_get(m0["loss/pg"])

    def row_step(s, k):
        s2, m = learn_row(s, learn_batch, k)
        return s2, m["loss/pg"]

    _, sw = _timeit_chained(row_step, state, key, iters=2)
    dt_row, _ = _timeit_chained(row_step, sw, key)
    out["learn_row_ms"] = dt_row / ITERS * 1e3

    # sub-programs (block learner), each chained + device_get-fenced
    obs_n = learner._norm_obs(state.obs_stats, learn_batch["obs"])
    values = learner.model.apply(state.params, obs_n).value
    v_next = learner.model.apply(
        state.params, learner._norm_obs(state.obs_stats, learn_batch["next_obs"])
    ).value
    jax.device_get(values[-1, -1])

    # value forwards (the two applies)
    vf = jax.jit(
        lambda s, c: learner.model.apply(
            s.params, learner._norm_obs(s.obs_stats, learn_batch["obs"]) + c
        ).value
        + learner.model.apply(
            s.params, learner._norm_obs(s.obs_stats, learn_batch["next_obs"])
        ).value
    )
    jax.device_get(vf(state, jnp.float32(0))[-1, -1])

    def vf_step(c, k):
        v = vf(state, c)
        # the carry MUST consume the output (the chaining contract): a
        # carry independent of v would let the backend overlap launches
        return v[-1, -1] * 0.0, v[-1, -1]

    _timeit_chained(vf_step, jnp.float32(0), key, iters=2)
    dt_vf, _ = _timeit_chained(vf_step, jnp.float32(0), key)
    out["value_forwards_ms"] = dt_vf / ITERS * 1e3

    # GAE alone
    gb = {k_: learn_batch[k_] for k_ in ("reward", "done", "terminated")}
    g = jax.jit(lambda c: learner._gae(gb, values + c, v_next)[0])
    jax.device_get(g(jnp.float32(0))[-1, -1])

    def g_step(c, k):
        a = g(c)
        return a[-1, -1] * 0.0, a[-1, -1]  # carry consumes the output

    _timeit_chained(g_step, jnp.float32(0), key, iters=2)
    dt_g, _ = _timeit_chained(g_step, jnp.float32(0), key)
    out["gae_ms"] = dt_g / ITERS * 1e3

    # grad steps with NO shuffling/gathers: 16 steps on one fixed slice
    adv, tgt = learner._gae(gb, values, v_next)
    N = adv.size
    flat = {
        "obs": obs_n.reshape(N, *obs_n.shape[2:]),
        "action": learn_batch["action"].reshape(N, -1),
        "behavior_logp": learn_batch["behavior_logp"].reshape(N),
        "adv": adv.reshape(N),
        "target": tgt.reshape(N),
        "value_old": values.reshape(N),
        "b_mean": learn_batch["behavior"]["mean"].reshape(N, -1),
        "b_log_std": learn_batch["behavior"]["log_std"].reshape(N, -1),
    }
    mb0 = jax.tree.map(lambda x: x[: N // 4], flat)
    grad_fn = jax.grad(learner._loss_fn, has_aux=True)

    def steps16(s, k):
        def body(carry, _):
            params, opt_state = carry
            grads, aux = grad_fn(params, mb0, s.kl_beta, jnp.float32(1.0))
            updates, opt_state = learner.tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), aux["kl"]

        (p, o), kls = jax.lax.scan(body, (s.params, s.opt_state), None, length=16)
        return s._replace(params=p, opt_state=o), kls[-1]

    sj = jax.jit(steps16)
    s1, kl1 = sj(state, key)
    jax.device_get(kl1)
    _timeit_chained(lambda s, k: sj(s, k), state, key, iters=2)
    dt_s, _ = _timeit_chained(lambda s, k: sj(s, k), state, key)
    out["gradsteps16_nogather_ms"] = dt_s / ITERS * 1e3
    return out


def impala_pong() -> dict:
    from surreal_tpu.launch.rollout import init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 1024, 32
    cfg = Config(
        learner_config=Config(
            algo=Config(name="impala", horizon=horizon),
            model=Config(cnn=Config(enabled=True)),
        ),
        env_config=Config(name="jax:pong", num_envs=num_envs),
        session_config=Config(
            folder="/tmp/perf_pong",
            metrics=Config(every_n_iters=10_000),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    carry = init_device_carry(trainer.env, env_key, num_envs)
    for _ in range(WARMUP):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)
    flops = _iter_flops(trainer._train_iter, state, carry, key)

    def fused_step(sc, k):
        s, c = sc
        s, c, m = trainer._train_iter(s, c, k)
        return (s, c), m

    # throwaway window first: freshly compiled programs show a one-time
    # multi-second tunnel artifact on their first timed window
    _, sc_w = _timeit_chained(fused_step, (state, carry), key, iters=2)
    dt, _ = _timeit_chained(fused_step, sc_w, key)
    sps = ITERS * num_envs * horizon / dt
    out = {
        "workload": "IMPALA+NatureCNN jax:pong pixels (BASELINE ⑤ class)",
        "geometry": f"{num_envs} envs x {horizon} unroll, 42x42x2 uint8 pixels",
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
        "pong_attrib": _pong_attribution(
            trainer, sc_w[0], sc_w[1], key, num_envs, horizon
        ),
    }
    if flops is not None:
        out["flops_per_iter"] = flops
        out["model_flops_per_s"] = flops * ITERS / dt
        out["mfu"] = out["model_flops_per_s"] / PEAK_FLOPS_BF16
    return out


def _pong_attribution(trainer, state, carry, key, num_envs, horizon) -> dict:
    """Where the pixel iteration's milliseconds go (round-5 VERDICT weak
    #4: the CNN paths sat at ~3% MFU with no decomposition). Sub-programs
    compiled and timed separately at the pong geometry:

    - env-only: the rollout scan with RANDOM actions (no policy) — pixel
      rendering + game logic;
    - act-only: the NatureCNN policy forward on a fixed [B, 42, 42, 2]
      frame, scanned x horizon — the acting compute;
    - rollout (policy act + env step, the real collector);
    - learn-only: V-trace + one CNN fwd/bwd over the [T, B] batch.
    """
    from surreal_tpu.envs.jax.base import batch_step
    from surreal_tpu.launch.rollout import RolloutCarry, device_rollout

    env = trainer.env
    learner = trainer.learner
    n_actions = env.specs.action.n

    roll = jax.jit(
        lambda s, c, k: device_rollout(env, learner, s, c, k, horizon)
    )
    key, rk = jax.random.split(key)
    carry2, batch = roll(state, carry, rk)
    jax.device_get(batch["reward"][-1])

    def roll_step(c, k):
        c2, b = roll(state, c, k)
        return c2, b["reward"][-1]

    _, cw = _timeit_chained(roll_step, carry, key, iters=2)
    dt_roll, _ = _timeit_chained(roll_step, cw, key)

    def _env_only(c, k):
        def step(cc, k_):
            a = jax.random.randint(k_, (num_envs,), 0, n_actions)
            env_state, obs2, reward, done, _ = batch_step(env, cc.env_state, a)
            return (
                RolloutCarry(env_state, obs2, cc.ep_return, cc.ep_length),
                reward,
            )

        c2, rs = jax.lax.scan(step, c, jax.random.split(k, horizon))
        return c2, rs[-1]

    env_only = jax.jit(_env_only)
    c2, r = env_only(carry, key)
    jax.device_get(r)
    _, cw = _timeit_chained(env_only, carry, key, iters=2)
    dt_env, _ = _timeit_chained(env_only, cw, key)

    obs_fixed = carry.obs

    def _act_only(tot, k):
        def step(t, k_):
            a, info = learner.act(state, obs_fixed, k_, "training")
            return t + info["logp"].sum(), a

        t2, _ = jax.lax.scan(step, tot, jax.random.split(k, horizon))
        return t2, t2

    act_only = jax.jit(_act_only)
    t2, _ = act_only(jnp.zeros(()), key)
    jax.device_get(t2)
    _, tw = _timeit_chained(act_only, jnp.zeros(()), key, iters=2)
    dt_act, _ = _timeit_chained(act_only, tw, key)

    learn_batch = {
        k: batch[k]
        for k in ("obs", "next_obs", "action", "reward", "done", "terminated",
                  "behavior_logp", "behavior")
    }
    learn = jax.jit(learner.learn)
    key, lk = jax.random.split(key)
    s2, m2 = learn(state, learn_batch, lk)
    jax.device_get(m2["loss/pg"])

    def learn_step(s, k):
        s2, m = learn(s, learn_batch, k)
        return s2, m["loss/pg"]

    _, sw = _timeit_chained(learn_step, state, key, iters=2)
    dt_learn, _ = _timeit_chained(learn_step, sw, key)

    return {
        "num_envs": num_envs,
        "horizon": horizon,
        "rollout_ms": dt_roll / ITERS * 1e3,
        "env_only_ms": dt_env / ITERS * 1e3,
        "act_only_ms": dt_act / ITERS * 1e3,
        "learn_ms": dt_learn / ITERS * 1e3,
    }


def ppo_cnn_nut_pixels() -> dict:
    from surreal_tpu.launch.rollout import init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 512, 32
    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=horizon, epochs=2, num_minibatches=4),
            model=Config(cnn=Config(enabled=True)),
        ),
        env_config=Config(name="jax:nut_pixels", num_envs=num_envs),
        session_config=Config(
            folder="/tmp/perf_nut_pixels",
            metrics=Config(every_n_iters=10_000),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    carry = init_device_carry(trainer.env, env_key, num_envs)
    for _ in range(WARMUP):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)
    flops = _iter_flops(trainer._train_iter, state, carry, key)

    def fused_step(sc, k):
        s, c = sc
        s, c, m = trainer._train_iter(s, c, k)
        return (s, c), m

    # throwaway window first: freshly compiled programs show a one-time
    # multi-second tunnel artifact on their first timed window
    _, sc_w = _timeit_chained(fused_step, (state, carry), key, iters=2)
    dt, _ = _timeit_chained(fused_step, sc_w, key)
    sps = ITERS * num_envs * horizon / dt
    out = {
        "workload": "PPO+NatureCNN jax:nut_pixels (BASELINE ④ class, on-device rendering)",
        "geometry": f"{num_envs} envs x {horizon} horizon, 64x64x4 uint8 pixels",
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
    }
    if flops is not None:
        out["flops_per_iter"] = flops
        out["model_flops_per_s"] = flops * ITERS / dt
        out["mfu"] = out["model_flops_per_s"] / PEAK_FLOPS_BF16
    return out


def ddpg_prioritized_lift(capacity: int = 200_000) -> dict:
    from surreal_tpu.launch.offpolicy_trainer import OffPolicyTrainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 2048, 16
    steps_per_iter = num_envs * horizon

    def make_trainer():
        cfg = Config(
            learner_config=Config(
                algo=Config(name="ddpg", horizon=horizon,
                            exploration=Config(warmup_steps=0)),
                replay=Config(kind="prioritized", capacity=capacity,
                              start_sample_size=steps_per_iter,
                              batch_size=256),
            ),
            env_config=Config(name="jax:lift", num_envs=num_envs),
            session_config=Config(
                folder="/tmp/perf_ddpg",
                metrics=Config(every_n_iters=10_000, tensorboard=False,
                               console=False),
                checkpoint=Config(every_n_iters=0),
                eval=Config(every_n_iters=0),
            ),
        ).extend(base_config())
        return OffPolicyTrainer(cfg)

    trainer = make_trainer()
    # warmup run: compile everything (jit cache lives on the trainer)
    trainer.run(max_env_steps=2 * steps_per_iter)
    t0 = time.perf_counter()
    trainer.run(max_env_steps=ITERS * steps_per_iter)
    dt = time.perf_counter() - t0
    sps = ITERS * steps_per_iter / dt
    cap_txt = f"{capacity // 1000}k" if capacity < 10**6 else f"{capacity / 1e6:.0f}M"
    return {
        "workload": "DDPG+prioritized replay jax:lift (BASELINE ③ class)"
        + (" — reference-scale 1e6 buffer" if capacity >= 10**6 else ""),
        "geometry": (
            f"{num_envs} envs x {horizon} collect, 64 updates/iter x 256 batch, "
            f"{cap_txt} prioritized replay"
        ),
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
    }


def ddpg_prioritized_lift_1m() -> dict:
    """Round-5 VERDICT missing-measurement #7: the cumsum+searchsorted
    sampler (no sum-tree — replay/prioritized.py design note) measured at
    the reference-scale 1e6 capacity ON CHIP. The per-sample cost is one
    fused O(N) bandwidth-bound pass (~8 MB through HBM at 1e6 x f32); if
    this row collapses vs the 200k row, the two-level segmented cumsum is
    the planned fix — the measurement decides."""
    return ddpg_prioritized_lift(capacity=1_000_000)


def headline_scaling() -> list[dict]:
    """Throughput vs geometry for the headline workload — how far the
    batch amortizes per-iteration dispatch before compute saturates."""
    from surreal_tpu.launch.rollout import init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    rows = []
    for num_envs, horizon in (
        (1024, 256), (2048, 256), (4096, 256), (8192, 256), (16384, 256)
    ):
        cfg = Config(
            learner_config=Config(
                algo=Config(name="ppo", horizon=horizon, epochs=4, num_minibatches=4),
            ),
            env_config=Config(name="jax:lift", num_envs=num_envs),
            session_config=Config(
                folder="/tmp/perf_scaling",
                metrics=Config(every_n_iters=10_000),
                checkpoint=Config(every_n_iters=0),
                eval=Config(every_n_iters=0),
            ),
        ).extend(base_config())
        trainer = Trainer(cfg)
        key = jax.random.key(0)
        key, init_key, env_key = jax.random.split(key, 3)
        state = trainer.learner.init(init_key)
        carry = init_device_carry(trainer.env, env_key, num_envs)
        for _ in range(WARMUP):
            key, it_key = jax.random.split(key)
            state, carry, metrics = trainer._train_iter(state, carry, it_key)
        jax.device_get(metrics)

        def fused_step(sc, k, _t=trainer):
            s, c = sc
            s, c, m = _t._train_iter(s, c, k)
            return (s, c), m

        # per-geometry throwaway window: freshly compiled programs show a
        # one-time multi-second tunnel warmup on their first timed window
        _, sc_w = _timeit_chained(fused_step, (state, carry), key, iters=2)
        dt, _ = _timeit_chained(fused_step, sc_w, key)
        rows.append(
            {
                "geometry": f"{num_envs} x {horizon}",
                "env_steps_per_s": ITERS * num_envs * horizon / dt,
                "iter_ms": dt / ITERS * 1e3,
            }
        )
        print(json.dumps(rows[-1], default=float))
    return rows


def ppo_trajectory_pendulum() -> dict:
    """The long-context path's own cost (round-4/5 capability —
    model.encoder.kind='trajectory'): fused rollout with KV-cached
    incremental acting (O(T) attention per env step) + whole-segment
    sequence learn, on the trajectory-tested pendulum workload. No
    BASELINE class covers this (the reference has no attention policies);
    the row documents what the capability costs next to the MLP headline."""
    from surreal_tpu.launch.rollout import init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 1024, 128
    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=horizon, epochs=2, num_minibatches=2),
            model=Config(
                encoder=Config(
                    kind="trajectory", features=64, num_layers=2,
                    num_heads=4, head_dim=16,
                )
            ),
        ),
        env_config=Config(name="jax:pendulum", num_envs=num_envs),
        session_config=Config(
            folder="/tmp/perf_traj",
            metrics=Config(every_n_iters=10_000),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    carry = init_device_carry(trainer.env, env_key, num_envs)
    for _ in range(WARMUP):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)
    flops = _iter_flops(trainer._train_iter, state, carry, key)

    def fused_step(sc, k):
        s, c = sc
        s, c, m = trainer._train_iter(s, c, k)
        return (s, c), m

    _, sc_w = _timeit_chained(fused_step, (state, carry), key, iters=2)
    dt, _ = _timeit_chained(fused_step, sc_w, key)
    sps = ITERS * num_envs * horizon / dt
    out = {
        "workload": "PPO+trajectory-transformer jax:pendulum (long-context "
                    "path; beyond-reference capability)",
        "geometry": f"{num_envs} envs x {horizon} horizon, 2-layer causal "
                    "attention, KV-cached acting",
        "env_steps_per_s": sps,
        "iter_ms": dt / ITERS * 1e3,
    }
    if flops is not None:
        out["flops_per_iter"] = flops
        out["model_flops_per_s"] = flops * ITERS / dt
        out["mfu"] = out["model_flops_per_s"] / PEAK_FLOPS_BF16
    return out


def host_env_cheetah():
    """BASELINE config ② (PPO on dm_control cheetah-run, 32 actors) — the
    reference's ACTUAL operating shape: CPU MuJoCo envs feeding the chip
    per step (upstream `surreal/agent/base.py` actors + `surreal/replay/
    base.py` over ZMQ; SURVEY.md §3.2-3.3). Round-5 VERDICT missing #1:
    this was the one perf surface with no on-chip number.

    Measures three drive modes on the real chip, plus a per-phase
    attribution of the alternation iteration:

    - host-alternation Trainer, ``topology.overlap_rollouts=false``
      (strict rollout -> learn; the chip idles during env stepping);
    - the same with ``overlap_rollouts=true`` (double-buffered collector
      thread — iteration ~ max(rollout, learn));
    - the SEED path (``num_env_workers`` OS processes -> InferenceServer
      -> learner), the reference's disaggregated fleet shape.
    """
    try:
        import dm_control  # noqa: F401
    except Exception:
        print("dm_control unavailable; skipping host-env workload")
        return None
    import shutil
    import tempfile
    from functools import partial

    import numpy as np

    from surreal_tpu.envs import make_env
    from surreal_tpu.launch.rollout import host_rollout
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.learners import build_learner
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    num_envs, horizon = 32, 64

    def _cfg(folder, overlap, workers=0, worker_envs=None):
        return Config(
            learner_config=Config(
                algo=Config(name="ppo", horizon=horizon, epochs=4,
                            num_minibatches=4),
            ),
            env_config=Config(
                name="dm_control:cheetah-run",
                num_envs=worker_envs if worker_envs else num_envs,
            ),
            session_config=Config(
                folder=folder,
                total_env_steps=10**12,
                metrics=Config(every_n_iters=1, tensorboard=False,
                               console=False),
                checkpoint=Config(every_n_iters=0),
                eval=Config(every_n_iters=0),
                topology=Config(
                    overlap_rollouts=overlap,
                    num_env_workers=workers,
                    worker_mode="process",
                ),
            ),
        ).extend(base_config())

    # -- per-phase attribution (hand-rolled alternation loop) ---------------
    cfg0 = _cfg("/tmp/perf_cheetah_attrib", overlap=False)
    env = make_env(cfg0.env_config)
    learner = build_learner(cfg0.learner_config, env.specs)
    act = jax.jit(partial(learner.act, mode="training"))
    learn = jax.jit(learner.learn)
    key = jax.random.key(0)
    key, ik, rk, lk = jax.random.split(key, 4)
    state = learner.init(ik)
    obs = env.reset(seed=0)
    # warmup: compile act + learn, settle the tunnel
    obs, batch, _ = host_rollout(env, act, state, obs, rk, horizon)
    state, m = learn(state, batch, lk)
    jax.device_get(m["loss/pg"])

    def t_phase(fn, n):
        t0 = time.perf_counter()
        for i in range(n):
            fn(i)
        return (time.perf_counter() - t0) / n * 1e3  # ms per call

    # policy act: TWO device round trips per env step — the obs upload
    # (numpy -> device, exactly what host_rollout's jnp.asarray does per
    # step) and the action download (device_get fence). Passing the numpy
    # obs into the jit makes the upload part of the measured call.
    obs_np = np.asarray(obs)
    akeys = jax.random.split(key, 64)
    act_ms = t_phase(
        lambda i: jax.device_get(act(state, obs_np, akeys[i])[0]), 64
    )
    # env step: 32 serial MuJoCo steps on the host
    fixed_action = np.zeros((num_envs, *env.specs.action.shape), np.float32)
    env_ms = t_phase(lambda i: env.step(fixed_action), 64)
    # learn: fenced
    def learn_once(i):
        nonlocal state
        state, mm = learn(state, batch, akeys[i])
        jax.device_get(mm["loss/pg"])
    learn_ms = t_phase(learn_once, 5)
    host_attrib = {
        "act_ms_per_step": act_ms,
        "env_ms_per_step": env_ms,
        "learn_ms_per_iter": learn_ms,
        "rollout_projected_ms": (act_ms + env_ms) * horizon,
    }
    env.close()

    # -- whole-trainer wall-clock, three drive modes ------------------------
    WARM_ITERS, MEAS_ITERS = 3, 12

    def timed_run(trainer_cls, config):
        trainer = trainer_cls(config)
        marks = []  # (t, env_steps): measured steps, not an assumed
        # per-iteration width (SEED chunk width halves under pipelining)

        def on_m(it, m):
            marks.append((time.perf_counter(), m["time/env_steps"]))
            return len(marks) >= WARM_ITERS + MEAS_ITERS

        trainer.run(on_metrics=on_m)
        if hasattr(trainer, "env") and hasattr(trainer.env, "close"):
            trainer.env.close()
        n = len(marks) - WARM_ITERS
        (t0, s0), (t1, s1) = marks[WARM_ITERS - 1], marks[-1]
        return (s1 - s0) / (t1 - t0), (t1 - t0) / n * 1e3

    folders = [tempfile.mkdtemp(prefix="perf_cheetah_") for _ in range(3)]
    try:
        sps_alt, iter_alt = timed_run(Trainer, _cfg(folders[0], overlap=False))
        print(json.dumps({"host_env_alternate_sps": sps_alt,
                          "iter_ms": iter_alt}, default=float))
        sps_ovl, iter_ovl = timed_run(Trainer, _cfg(folders[1], overlap=True))
        print(json.dumps({"host_env_overlap_sps": sps_ovl,
                          "iter_ms": iter_ovl}, default=float))
        from surreal_tpu.launch.seed_trainer import SEEDTrainer

        # 4 worker processes x 8 envs = the same 32-env fleet (chunk
        # geometry [horizon, 4] per pipelined sub-slice)
        sps_seed, iter_seed = timed_run(
            SEEDTrainer, _cfg(folders[2], overlap=False, workers=4, worker_envs=8)
        )
        print(json.dumps({"host_env_seed_sps": sps_seed,
                          "iter_ms": iter_seed}, default=float))
    finally:
        for f in folders:
            shutil.rmtree(f, ignore_errors=True)

    host_attrib.update(
        alternate_sps=sps_alt, alternate_iter_ms=iter_alt,
        overlap_sps=sps_ovl, overlap_iter_ms=iter_ovl,
        seed_sps=sps_seed, seed_iter_ms=iter_seed,
    )
    best = max(sps_alt, sps_ovl, sps_seed)
    return {
        "host_attrib": host_attrib,
        "workload": "PPO dm_control:cheetah-run — HOST MuJoCo envs feeding "
                    "the chip (BASELINE ② — the reference's operating shape)",
        "geometry": f"{num_envs} CPU envs x {horizon} horizon, best of "
                    "alternate/overlap/SEED-4-proc",
        "env_steps_per_s": best,
        "iter_ms": iter_ovl if best == sps_ovl else (
            iter_alt if best == sps_alt else iter_seed
        ),
    }


def _load_host_bench():
    """Load the host data-plane artifact (`BENCH_host.json`, written by
    `perf_wallclock.py --host-path` / `bench.py --host-path`) if present —
    like block_vs_row.json, keeping it as an artifact lets PERF.md regens
    preserve the measured section without re-running the campaign."""
    try:
        with open("BENCH_host.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or "value" not in data:
        return None  # failed-round artifact ({"error": ..., "parsed": null})
    return data


def _host_data_plane_lines() -> list[str]:
    """The 'Host data plane rebuild' PERF.md section: static mechanism
    text plus the measured table from the BENCH_host.json artifact when
    one exists. One function so `main()` and the standalone section
    patcher cannot drift."""
    lines = [
        "",
        "## Host data plane rebuild (zero-copy shm transport + pipelined "
        "env workers)",
        "",
        "The SEED host path was rebuilt end to end "
        "(`distributed/shm_transport.py`), attacking the 288 steps/s row "
        "above — which paid a full pickle of the obs/reward/done dict, a "
        "TCP round trip carrying those bytes, and an action re-pickle on "
        "EVERY worker step, with each worker idle for the whole server "
        "round trip:",
        "",
        "- **Zero-copy transport** — per-worker shared-memory slabs "
        "(obs/reward/done/truncated/terminal_obs in, actions out) "
        "negotiated at a hello handshake; afterwards ZMQ carries only "
        "~20-byte control frames (slot index, flags, latency/occupancy "
        "gauges, episode-stat floats). The server OWNS every segment — "
        "created at hello, reused when a respawned worker re-negotiates "
        "through ROUTER_HANDOVER, unlinked at close — so a SIGKILLed "
        "worker cannot leak `/dev/shm` (tests assert this). The original "
        "pickle wire remains the negotiated fallback (thread-mode tests, "
        "remote workers), per worker and invisible to the trainer; a "
        "record-equivalence test proves both transports assemble "
        "byte-identical trajectory chunks for the same seed.",
        "- **Pipelined workers** — `run_env_worker` splits its env slice "
        "into two sub-slices and keeps one sub-slice's request in flight "
        "while stepping the other (double-buffered acting, Stooke & "
        "Abbeel 1803.02811), hiding the act round trip that the old "
        "strictly-serial send→poll→step loop ate per step "
        "(`topology.pipeline_workers`).",
        "- **Copy-free server assembly + auto-tuned coalescing** — "
        "`_serve_batch` reads worker slabs straight into one preallocated "
        "scratch batch (no per-serve `np.concatenate`, no per-slice "
        "pickling), writes action slices directly into each worker's "
        "action slab, and retunes `min_batch`/`max_wait_ms` from the "
        "live connected-worker count and its serve-latency EWMA, so the "
        "fleet keeps coalescing into one forward per lockstep round "
        "through worker death and respawn.",
    ]
    hostdp = _load_host_bench()
    if hostdp:
        shm_r, pkl_r = hostdp.get("shm", {}), hostdp.get("pickle", {})
        lines += [
            "",
            f"Measured through the real SEED trainer at the record's "
            f"geometry ({hostdp['geometry']}; `BENCH_host.json`, platform "
            f"`{hostdp.get('platform')}`; warm iterations discarded):",
            "",
            "| Transport | env steps/s | wire bytes/step | iter ms |",
            "|---|---|---|---|",
            "| shm (negotiated; pipelined sub-slices) | "
            f"{shm_r.get('env_steps_per_s', 0):,.0f} | "
            f"{shm_r.get('transport', {}).get('wire_bytes_per_step', 0):,.1f} | "
            f"{shm_r.get('iter_ms', 0):,.1f} |",
            "| pickle fallback (same geometry) | "
            f"{pkl_r.get('env_steps_per_s', 0):,.0f} | "
            f"{pkl_r.get('transport', {}).get('wire_bytes_per_step', 0):,.1f} | "
            f"{pkl_r.get('iter_ms', 0):,.1f} |",
            "",
            f"**{hostdp['vs_host_baseline']:.0f}x the 288 steps/s "
            "round-5 record** with the shm transport active at the same "
            "32-env x 64-horizon dm_control geometry. Honesty notes: "
            "this artifact was measured on "
            f"`{hostdp.get('platform')}` (no chip tunnel in the round), "
            "and on this one-core box BOTH transports now saturate the "
            "LEARNER, not the wire — their steps/s agree to within the "
            "run-to-run spread (a cheaper send lets workers outrun the "
            "saturated learner and burn the shared core on steps the "
            "eviction path discards), and the transport's direct win "
            "shows in the wire gauge (the bytes column: control frames "
            "vs pickled arrays, "
            f"~{pkl_r.get('transport', {}).get('wire_bytes_per_step', 0) / max(shm_r.get('transport', {}).get('wire_bytes_per_step', 1), 1e-9):,.0f}"
            "x less traffic) and in the serve path doing zero "
            "serialization work. The old 288 record was transport/latency"
            "-bound; the rebuilt plane moved the bottleneck back to "
            "compute, which is the point.",
        ]
    return lines


def _load_experience_bench():
    """Load the experience-plane artifact (``BENCH_experience.json``,
    written by ``bench.py --experience-plane``) if present — like
    BENCH_host.json, keeping it as an artifact lets PERF.md regens
    preserve the measured section without re-running the campaign."""
    try:
        with open("BENCH_experience.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or data.get("value") is None:
        return None  # failed-campaign artifact ({"error": ..., "parsed": null})
    return data


def _experience_plane_lines() -> list[str]:
    """The 'Sharded experience plane' PERF.md section: static mechanism
    text plus the measured per-transport table from the
    BENCH_experience.json artifact when one exists. One function so
    ``main()`` and the committed PERF.md cannot drift."""
    lines = [
        "",
        "## Sharded experience plane (cross-host replay shards + "
        "never-blocking learner sampler)",
        "",
        "The ExperienceSender -> ShardedReplay path the reference ran as "
        "separate processes behind a caraml proxy, rebuilt as "
        "`surreal_tpu/experience/` (ISSUE 8): `ReplayShardServer` "
        "processes own host-memory NumPy rings mirroring `replay/base.py` "
        "semantics (uniform sampling BIT-EQUAL to the in-process replay "
        "for the same keys — tested; prioritized within a documented f32 "
        "tolerance), actors hash-route env slots to shards through an "
        "`ExperienceSender` with bounded retry/backoff and slab/window "
        "backpressure, and the learner's `ShardedSampler` fans in every "
        "iteration's batches through a `Prefetcher` during the PREVIOUS "
        "iteration's SGD drain — the learner never waits on experience "
        "ingest (the residue is the `experience/sample_wait_ms` gauge, "
        "gated by perf_gate). The wire negotiates per peer at a hello "
        "carrying the run trace id: shm slabs same-host, a length-framed "
        "tcp codec cross-host, pickle as the fallback (sampling-near-the-"
        "data per arXiv:2110.13506; the disaggregated tier shape of "
        "RollArt, arXiv:2512.22560). Priority updates ship as ONE batched "
        "frame per shard per iteration (`sample_many`'s discipline "
        "on-wire); sample requests carry ingestion watermarks so "
        "strict-mode training records are exactly reproducible.",
    ]
    xp = _load_experience_bench()
    if xp:
        lines += [
            "",
            f"Measured through the real off-policy trainer at the "
            f"local-shards geometry ({xp['geometry']}; "
            f"`BENCH_experience.json`, platform `{xp.get('platform')}`; "
            "warm iterations discarded):",
            "",
            "| Arm | env steps/s | iter ms | wire B/step | learner "
            "sample-wait ms | final return |",
            "|---|---|---|---|---|---|",
        ]
        for name in ("inprocess", "shm", "tcp", "pickle"):
            r = xp.get(name) or {}
            wire = r.get("wire_bytes_per_step")
            wait = r.get("sample_wait_ms")
            lines.append(
                "| {a} | {s:,.0f} | {ms:.1f} | {w} | {sw} | {fr} |".format(
                    a=r.get("arm", name),
                    s=float(r.get("env_steps_per_s", 0)),
                    ms=float(r.get("iter_ms", 0)),
                    w=f"{float(wire):.1f}" if wire is not None else "n/a (in-process)",
                    sw=f"{float(wait):.2f}" if wait is not None else "n/a",
                    fr=(
                        f"{float(r['final_return']):.0f}"
                        if r.get("final_return") is not None else "n/a"
                    ),
                )
            )
        shm = xp.get("shm") or {}
        record = float(xp.get("shm_wire_record_bps", 5.8))
        wire = float(shm.get("wire_bytes_per_step") or 0.0)
        lines += [
            "",
            f"The shm arm's wire carries {wire:.1f} B per ingested "
            f"transition (control frames + sample requests only; the "
            f"PR-3 slab record is {record:.1f} B/step — the gate commits "
            f"to <= 2x), and the learner's sample-wait is "
            f"{float(shm.get('sample_wait_ms') or 0):.2f} ms against a "
            f"{float(shm.get('iter_ms') or 0):.1f} ms iteration: the "
            "prefetched fan-in keeps the learner fed from batches staged "
            "during the previous drain. The fixed-seed reward "
            "trajectories of the remote arms ride the artifact next to "
            "the in-process reference's (the curves track each other; "
            "per-shard sampling is the same stratified-composition "
            "change the dp-sharded device replay documents). Honesty "
            "notes: this box measures LOCAL thread shards — the "
            "cross-host claim is the negotiated tcp codec itself, "
            "exercised as a first-class arm; and on one core the remote "
            "arms pay the shard servers' CPU time out of the same core "
            "the learner uses, so steps/s differences between arms are "
            "dominated by that contention, not by the wire.",
        ]
    return lines


def _load_act_bench():
    """Load the act-serving-tier artifact (``BENCH_act.json``, written by
    ``bench.py --act-path``) if present — the BENCH_host.json discipline:
    PERF.md regens preserve the measured section without re-running."""
    try:
        with open("BENCH_act.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or data.get("value") is None:
        return None  # failed-campaign artifact
    return data


def _act_path_lines() -> list[str]:
    """The 'Autoscaling act-serving tier' PERF.md section: static
    mechanism text plus the measured 1-vs-N replica table and the
    fanout bytes-per-publish table from the BENCH_act.json artifact.
    One function so ``main()`` and the committed PERF.md cannot drift."""
    lines = [
        "",
        "## Autoscaling act-serving tier (replicated inference servers "
        "+ versioned parameter fanout)",
        "",
        "The last unscaled hop after the experience plane: one "
        "`InferenceServer` process owned the whole act path, and "
        "`ParameterClient.fetch` shipped a full msgpack pytree "
        "point-to-point per client. `distributed/fleet.py` replicates "
        "the server (ISSUE 10; the disaggregated inference tier of "
        "RollArt, arXiv:2512.22560, on the act-throughput discipline of "
        "Accelerated Methods, arXiv:1803.02811): workers "
        "rendezvous-hash to a replica at spawn and stay there (session "
        "affinity — trajectory streams and shm slabs keep one owner), "
        "each replica coalesces with its OWN `min_batch` budget (its "
        "affinity share, auto-tuned against per-replica liveness), a "
        "dead replica respawns in place under the PR-5 exponential "
        "backoff while its workers re-hello to survivors "
        "(chaos-tested), and autoscaling adds/drains replicas off the "
        "serve-latency EWMA within `[min_replicas, max_replicas]`. "
        "Parameter distribution becomes a broadcast "
        "(`distributed/param_fanout.py`): versioned weight frames over "
        "pub/sub — one encode + N subscribes — with a zlib'd "
        "delta arm keyed to subscriber acks (a stale ack re-keys with a "
        "full frame) and a bf16 wire arm (f32 reconstruct, exactly the "
        "bf16-rounded value); `ParameterClient.fetch` stays as the "
        "late-joiner/fallback path, counted never silent.",
    ]
    act = _load_act_bench()
    if act:
        single, fleet = act.get("single") or {}, act.get("fleet") or {}
        lines += [
            "",
            f"Measured through the real SEED trainer at the act-path "
            f"geometry ({act.get('geometry', 'unrecorded')}; "
            f"`BENCH_act.json`, platform "
            f"`{act.get('platform')}`; warm iterations discarded):",
            "",
            "| Replicas | env steps/s | iter ms | serve p50 ms | "
            "serve p99 ms |",
            "|---|---|---|---|---|",
        ]
        for r in (single, fleet):
            p50, p99 = r.get("serve_ms_p50"), r.get("serve_ms_p99")
            lines.append(
                "| {n} | {s:,.0f} | {ms:.1f} | {p50} | {p99} |".format(
                    n=r.get("replicas", "?"),
                    s=float(r.get("env_steps_per_s", 0)),
                    ms=float(r.get("iter_ms", 0)),
                    p50=f"{float(p50):.2f}" if p50 is not None else "n/a",
                    p99=f"{float(p99):.2f}" if p99 is not None else "n/a",
                )
            )
        fan = act.get("fanout") or {}
        arms = fan.get("arms") or {}
        if arms:
            lines += [
                "",
                f"Fanout bytes per publish (acting view of a "
                f"{'x'.join(str(h) for h in fan.get('model_hidden', []))} "
                f"MLP policy; point-to-point baseline = one "
                f"`ParameterClient.fetch` blob per client, "
                f"{float(fan.get('pointtopoint_fetch_bytes', 0)):,.0f} B "
                "x N clients; steady bytes exclude the first key frame):",
                "",
                "| Arm | steady B/publish | first frame B | reconstruct "
                "max abs err |",
                "|---|---|---|---|",
            ]
            for name in ("full_f32", "delta", "bf16", "delta_bf16"):
                a = arms.get(name) or {}
                if not a:
                    continue
                lines.append(
                    "| {n} | {b:,.0f} | {f:,.0f} | {e:.2e} |".format(
                        n=name,
                        b=float(a.get("bytes_per_publish", 0)),
                        f=float(a.get("first_frame_bytes", 0)),
                        e=float(a.get("reconstruct_abs_err_max", 0)),
                    )
                )
        ratio = None
        if single.get("env_steps_per_s") and fleet.get("env_steps_per_s"):
            ratio = (
                float(fleet["env_steps_per_s"])
                / float(single["env_steps_per_s"])
            )
        lines += [
            "",
            "Honesty notes: this box has ONE core, so the "
            f"{fleet.get('replicas', 'N')}-replica arm cannot win here "
            "by construction — each lockstep round's single coalesced "
            "forward becomes N SERIAL smaller forwards (per-dispatch "
            "overhead dominates a small CPU act), and the extra serve "
            "thread contends with the learner for the same core. The "
            "gated commitment locally is that replication does not "
            "COLLAPSE throughput "
            + (
                f"(measured ratio {ratio:.2f} vs the "
                f">= {float(act.get('act_honesty_ratio', 0.5)):.2f} "
                "bound); " if ratio is not None else "; "
            )
            + "the scaling claim is the tier mechanism itself — "
            "affinity routing, per-replica budgets, survivor re-hello — "
            "exercised for real, with cross-core speedups to be "
            "recorded on a multi-core measurement round. The fanout "
            "bytes table is platform-independent (codec arithmetic, no "
            "timed window); delta/bf16 both sit below the full-f32 "
            "frame, which itself replaces N per-client fetch blobs "
            "with one encode (gated by `perf_gate.gate_act`).",
        ]
    return lines


def _load_gateway_bench():
    """Load the session-gateway artifact (``BENCH_gateway.json``, written
    by ``bench.py --gateway``) if present — same BENCH_host.json
    discipline: PERF.md regens preserve the measured section without
    re-running the campaign."""
    try:
        with open("BENCH_gateway.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or data.get("value") is None:
        return None  # failed-campaign artifact
    return data


def _gateway_lines() -> list[str]:
    """The 'Production session gateway' PERF.md section: static mechanism
    text plus the measured attach/RTT/cache table from the
    BENCH_gateway.json artifact. One function so ``main()`` and the
    committed PERF.md cannot drift."""
    lines = [
        "",
        "## Production session gateway (multi-tenant act serving)",
        "",
        "The fleet's act path was internal-only: workers rendezvous-hash "
        "to a replica at spawn and speak the private worker protocol. "
        "`gateway/` (ISSUE 12) puts a tenant-facing front on it: "
        "`GatewayServer` owns attach/detach sessions with ids and "
        "leases (a silent tenant is reaped, counted), admission control "
        "per tenant (token-bucket act rates, max-session quotas, "
        "bounded backpressure queues that evict oldest, counted never "
        "silent), and a session table whose journal of wire frames "
        "self-compacts and replays onto a survivor when a replica dies "
        "— the tenant's next act lands on the new replica without the "
        "session id changing (chaos-tested: invisible failover). "
        "Sessions may pin a parameter version; the fanout holds pinned "
        "versions until released, and an evicted pin triggers a counted "
        "`catch_up` to the live version instead of a silent swap. A "
        "bounded LRU act cache keyed on (version, obs digest) serves "
        "repeat observations without a forward.",
    ]
    gw = _load_gateway_bench()
    if gw:
        attach = gw.get("attach_ms") or {}
        rtt = gw.get("act_rtt_ms") or {}
        direct = gw.get("direct_ms") or {}
        cache = gw.get("cache") or {}
        hit = cache.get("hit_ms") or {}
        served = cache.get("served_ms") or {}
        lines += [
            "",
            f"Measured against a live 2-replica fleet serving the "
            f"{gw.get('policy', 'benchmark')} policy "
            f"(`BENCH_gateway.json`, platform `{gw.get('platform')}`; "
            "warm iterations discarded):",
            "",
            "| Path | p50 ms | p99 ms |",
            "|---|---|---|",
        ]
        for name, row in (
            ("attach", attach),
            ("act RTT (gateway, cache off)", rtt),
            ("act (direct `fleet.serve_act`)", direct),
            ("act RTT (cache hit)", hit),
            ("act RTT (cache miss -> forward)", served),
        ):
            if not row:
                continue
            p50, p99 = row.get("p50"), row.get("p99")
            lines.append(
                "| {n} | {a} | {b} |".format(
                    n=name,
                    a=f"{float(p50):.3f}" if p50 is not None else "n/a",
                    b=f"{float(p99):.3f}" if p99 is not None else "n/a",
                )
            )
        ratio = gw.get("rtt_ratio_p50")
        lines += [
            "",
            "Honesty notes: this box has ONE core, so the gateway hop "
            "(client thread + gateway serve thread + fleet replica all "
            "contending for it) is measured at its WORST — the gated "
            "commitment is that the wire hop does not double the act "
            + (
                f"(measured RTT/direct p50 ratio {float(ratio):.2f} vs "
                f"the <= {float(gw.get('rtt_ratio_max', 2.0)):.1f}x "
                "bound), " if ratio is not None else ", "
            )
            + "and that a cache hit is STRICTLY faster than a served "
            "forward"
            + (
                f" (hit-rate {float(cache.get('hit_rate', 0)):.2f} on "
                "the duplicated-obs workload)"
                if cache.get("hit_rate") is not None else ""
            )
            + " — both gated by `perf_gate.gate_gateway`, folded into "
            "`gate()`.",
        ]
    return lines


def _load_ops_bench():
    """Load the ops-plane artifact (``BENCH_ops.json``, written by
    ``bench.py --ops-plane``) if present — same BENCH_host.json
    discipline: PERF.md regens preserve the measured section without
    re-running the campaign."""
    try:
        with open("BENCH_ops.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or data.get("value") is None:
        return None  # failed-campaign artifact
    return data


def _ops_plane_lines() -> list[str]:
    """The 'Live ops plane' PERF.md section: static mechanism text plus
    the measured per-cadence cost table from the BENCH_ops.json
    artifact. One function so ``main()`` and the committed PERF.md
    cannot drift."""
    lines = [
        "",
        "## Live ops plane (cross-tier aggregation, per-tenant SLOs, "
        "flight recorder)",
        "",
        "Telemetry was post-hoc: per-process JSONL that `diag` replays "
        "after the run. `session/opsplane.py` (ISSUE 13) gives a "
        "running multi-tier session ONE live view: every tier (gateway "
        "serve loop, fleet replicas, experience shards, parameter "
        "fanout, learner) pushes its gauge/hop rows over its OWN "
        "cadence-bounded PUSH socket (zmq sockets are not thread-safe; "
        "process tiers inherit the address through spawn kwargs like "
        "the trace id), and the learner-side aggregator merges the "
        "latest row per tier into a trace-id-stamped snapshot at the "
        "metrics cadence — atomically replaced on disk, rendered live "
        "by `surreal_tpu top <folder>`. Declared `session.slo.*` "
        "objectives (act RTT p99, attach p99, per-tenant throttle "
        "rate, parameter staleness) are evaluated per snapshot window "
        "with rolling error budgets: every breached window is a "
        "counted `slo_breach` event, and a budget exhaustion — like a "
        "recovery trip or a chaos fault — dumps the flight recorder's "
        "bounded ring of pre-incident snapshots + fault events to "
        "`telemetry/flightrec/<trigger>/`. A tier silent for 3x its "
        "own declared cadence renders DEAD, never silently fine.",
    ]
    ops = _load_ops_bench()
    if ops:
        snap = ops.get("snapshot_ms") or {}
        push = ops.get("push_ms") or {}
        lines += [
            "",
            f"Measured at a production tier census "
            f"({ops.get('workload', 'benchmark workload')}; "
            f"`BENCH_ops.json`, platform `{ops.get('platform')}`):",
            "",
            "| Cost | p50 ms | p99 ms |",
            "|---|---|---|",
        ]
        for name, row in (
            ("snapshot build (merge + SLO eval + atomic write)", snap),
            ("tier push (serve-loop side, one row)", push),
        ):
            if not row:
                continue
            p50, p99 = row.get("p50"), row.get("p99")
            lines.append(
                "| {n} | {a} | {b} |".format(
                    n=name,
                    a=f"{float(p50):.4f}" if p50 is not None else "n/a",
                    b=f"{float(p99):.4f}" if p99 is not None else "n/a",
                )
            )
        frac = ops.get("snapshot_frac_of_iter")
        iter_ms = ops.get("iter_ms")
        lines += [
            "",
            "Overhead commitment: the whole snapshot path is pure host "
            "python (the transfer-guard suite runs it under "
            "`disallow_device_to_host` — zero device syncs added)"
            + (
                f", and one snapshot costs {float(frac):.2%} of the "
                f"{float(iter_ms):.0f} ms steady-state iteration at the "
                "committed headline geometry (commitment <= "
                f"{float(ops.get('snapshot_frac_max', 0.05)):.0%}"
                if frac is not None and iter_ms is not None else "("
            )
            + "); a tier push is non-blocking with a small HWM — a full "
            "queue drops the row, counted, never stalls a serve loop. "
            "Both gated by `perf_gate.gate_ops`, folded into `gate()`.",
        ]
    return lines


def _load_trace_bench():
    """Load the causal-tracing artifact (``BENCH_trace.json``, written by
    ``bench.py --trace``) if present — same BENCH_host.json discipline:
    PERF.md regens preserve the measured section without re-running the
    campaign."""
    try:
        with open("BENCH_trace.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or data.get("value") is None:
        return None  # failed-campaign artifact
    return data


def _trace_lines() -> list[str]:
    """The 'Causal tracing & lineage' PERF.md section: static mechanism
    text plus the measured span/lineage cost table from the
    BENCH_trace.json artifact. One function so ``main()`` and the
    committed PERF.md cannot drift."""
    lines = [
        "",
        "## Causal tracing & experience lineage",
        "",
        "Aggregate gauges say a tier is slow; they cannot say what ONE "
        "request did. `session/telemetry.py` (ISSUE 14) head-samples "
        "exemplars (1-in-`telemetry.trace.sample_n` per gateway session "
        "and per worker stream) and threads a `TraceContext` "
        "(trace/span/parent ids) through every hop it touches — gateway "
        "act frame -> fleet replica's coalesced forward -> reply, and "
        "worker STEP -> inference server -> experience chunk -> the "
        "learner dispatch that consumed it. Each hop emits a `span` "
        "event; `surreal_tpu trace <folder>` assembles them into "
        "per-exemplar span-tree timelines (pure file reading, like "
        "`top`), with chaos-dropped hops counted in "
        "`trace/dropped_spans` and rendered as torn, never hidden. "
        "Independently, every transition is stamped at collection with "
        "its lineage (worker, episode, step range, acting policy "
        "version); the learner reduces each batch's version column into "
        "the EXACT per-update staleness distribution (`lineage/*` "
        "gauges, pure host numpy over an already-fetched column — zero "
        "device syncs), which replaces the ops plane's "
        "published-vs-held staleness approximation in the SLO "
        "evaluation (`staleness_source: lineage`).",
    ]
    tr = _load_trace_bench()
    if tr:
        span = tr.get("span_emit_ms") or {}
        lin = tr.get("lineage_reduce_ms") or {}
        lines += [
            "",
            f"Measured at the headline census ({tr.get('workload', 'benchmark workload')}; "
            f"`BENCH_trace.json`, platform `{tr.get('platform')}`):",
            "",
            "| Cost | p50 ms | p99 ms |",
            "|---|---|---|",
        ]
        for name, row in (
            ("span emit (JSONL append + exemplar ring)", span),
            (f"lineage reduce ({tr.get('lineage_rows', '?')} rows)", lin),
        ):
            if not row:
                continue
            p50, p99 = row.get("p50"), row.get("p99")
            lines.append(
                "| {n} | {a} | {b} |".format(
                    n=name,
                    a=f"{float(p50):.4f}" if p50 is not None else "n/a",
                    b=f"{float(p99):.4f}" if p99 is not None else "n/a",
                )
            )
        frac = tr.get("overhead_frac_of_iter")
        iter_ms = tr.get("iter_ms")
        lines += [
            "",
            f"One span costs {float(tr.get('bytes_per_span', 0)):.0f} B "
            f"on disk at {float(tr.get('spans_per_s', 0)):,.0f} spans/s"
            + (
                f"; the modeled per-iteration census "
                f"({tr.get('spans_per_iter')} spans priced at p99 + one "
                f"full lineage reduction) costs {float(frac):.3%} of the "
                f"{float(iter_ms):.0f} ms steady-state iteration "
                f"(commitment <= "
                f"{float(tr.get('overhead_frac_max', 0.02)):.0%})"
                if frac is not None and iter_ms is not None else ""
            )
            + ". Gated by `perf_gate.gate_trace`, folded into `gate()`.",
        ]
    return lines


def _load_watchdog_bench():
    """Load the watchdog artifact (``BENCH_watchdog.json``, written by
    ``bench.py --watchdog``) if present — same BENCH_host.json
    discipline: PERF.md regens preserve the measured section without
    re-running the campaign."""
    try:
        with open("BENCH_watchdog.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or data.get("value") is None:
        return None  # failed-campaign artifact
    return data


def _watchdog_lines() -> list[str]:
    """The 'Watchdog & incidents' PERF.md section: static mechanism text
    plus the measured sweep-cost table from the BENCH_watchdog.json
    artifact. One function so ``main()`` and the committed PERF.md
    cannot drift."""
    lines = [
        "",
        "## Watchdog & incident engine",
        "",
        "PRs 13-14 collect; ISSUE 15 interprets. `session/watchdog.py` "
        "runs a detector sweep over every merged ops snapshot (the "
        "metrics cadence): robust median/MAD breakouts on the headline "
        "latencies and throughputs (iteration time, env steps/s, "
        "sample-wait, gateway act-RTT p99, fleet serve), queue/"
        "backpressure saturation and respawn-rate bursts, monotonic "
        "growth of every counted-never-silent `*dropped*`/`*bad_frames` "
        "counter plus the `lineage/staleness_p99` ramp, tier liveness "
        "from the ops plane's DEAD rendering, and online regression "
        "against the committed BENCH baseline for the live platform "
        "fingerprint (`perf_gate.load_rows`). Firings feed "
        "`session/incidents.py`, which opens root-caused incidents: "
        "evidence correlated in a bounded window (chaos faults, "
        "recovery trips, SLO breaches, slowest exemplar spans, dead "
        "tiers), cause hypotheses ranked upstream-first over the static "
        "tier dataflow graph, one auto-captured profiler window + "
        "flight-recorder dump per incident (cooldown-bounded), closed "
        "only on sustained-healthy windows. `surreal_tpu why <folder>` "
        "renders the records (pure file reading, like `top`/`trace`); "
        "every sweep is pure host arithmetic over the snapshot dict — "
        "zero added device->host syncs (transfer-guard tested).",
    ]
    wd = _load_watchdog_bench()
    if wd:
        ev = wd.get("eval_ms") or {}
        lines += [
            "",
            f"Measured at the production census ({wd.get('workload', 'benchmark workload')}; "
            f"`BENCH_watchdog.json`, platform `{wd.get('platform')}`):",
            "",
            "| Cost | p50 ms | p99 ms |",
            "|---|---|---|",
        ]
        p50, p99 = ev.get("p50"), ev.get("p99")
        lines.append(
            "| detector sweep + incident observe | {a} | {b} |".format(
                a=f"{float(p50):.4f}" if p50 is not None else "n/a",
                b=f"{float(p99):.4f}" if p99 is not None else "n/a",
            )
        )
        open_ms = wd.get("incident_open_ms")
        if open_ms is not None:
            lines.append(
                f"| incident open e2e (sweep -> ranked record on disk) "
                f"| {float(open_ms):.4f} | — |"
            )
        frac = wd.get("eval_frac_of_iter")
        iter_ms = wd.get("iter_ms")
        lines += [
            "",
            (
                f"The sweep p99 costs {float(frac):.3%} of the "
                f"{float(iter_ms):.0f} ms steady-state iteration "
                f"(commitment <= "
                f"{float(wd.get('eval_frac_max', 0.01)):.0%})"
                if frac is not None and iter_ms is not None
                else "The overhead fraction was not recorded"
            )
            + ". Gated by `perf_gate.gate_watchdog`, folded into "
            "`gate()`.",
        ]
    return lines


def _load_control_bench():
    """Load the control-loop artifact (``BENCH_control.json``, written
    by ``bench.py --control``) if present — same BENCH_host.json
    discipline: PERF.md regens preserve the measured section without
    re-running the campaign."""
    try:
        with open("BENCH_control.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or data.get("value") is None:
        return None  # failed-campaign artifact
    return data


def _control_lines() -> list[str]:
    """The 'Closed-loop control' PERF.md section: static mechanism text
    plus the measured decision-sweep table from the BENCH_control.json
    artifact. One function so ``main()`` and the committed PERF.md
    cannot drift."""
    lines = [
        "",
        "## Closed-loop control & load generator",
        "",
        "ISSUE 15 diagnoses; ISSUE 16 acts. `session/remediate.py` runs "
        "one bounded decision sweep per metrics cadence: the open "
        "incident's top-ranked cause tier maps to exactly one action on "
        "an existing actuator (fleet `scale_up`, per-tenant admission "
        "`set_quota` throttle/shed, RespawnSchedule-backed targeted "
        "restart, learner batch/precision downshift), guarded in order "
        "by a per-run action budget, per-kind cooldowns, and one-action-"
        "per-incident in flight. Every action is journaled atomically "
        "(`telemetry/actions/action-<n>.json`, `remediation` events, "
        "`remediation/*` gauges) and watched by a counter-detector: the "
        "action's objective is sampled for `verify_windows` post-action "
        "sweeps, and an action whose objective regressed further is "
        "ruled ineffective and reverted where reversible — counted, "
        "never silent. `gateway/loadgen.py` replays the PR-12 chaos "
        "sites as tenant traffic (steady pacing, attach storms, hot-key "
        "hammering, act bursts, adversarial frames) so the loop is "
        "exercised against production-shaped load.",
    ]
    ct = _load_control_bench()
    if ct:
        dec = ct.get("decide_ms") or {}
        lines += [
            "",
            f"Measured at the production census ({ct.get('workload', 'benchmark workload')}; "
            f"`BENCH_control.json`, platform `{ct.get('platform')}`):",
            "",
            "| Cost | p50 ms | p99 ms |",
            "|---|---|---|",
        ]
        p50, p99 = dec.get("p50"), dec.get("p99")
        lines.append(
            "| remediation decision sweep (action in flight) | {a} | {b} |".format(
                a=f"{float(p50):.4f}" if p50 is not None else "n/a",
                b=f"{float(p99):.4f}" if p99 is not None else "n/a",
            )
        )
        e2e = ct.get("incident_to_action_ms")
        if e2e is not None:
            lines.append(
                f"| incident -> journaled action e2e (detect + map + "
                f"actuate + write) | {float(e2e):.4f} | — |"
            )
        lg = ct.get("loadgen") or {}
        if lg.get("acts_per_s") is not None:
            lines += [
                "",
                (
                    f"The load generator sustained "
                    f"{float(lg['acts_per_s']):.1f} acts/s against a "
                    f"live fleet + gateway "
                    f"(offered {float(lg.get('offered_hz', 0)):.0f} Hz, "
                    f"client act RTT "
                    f"{float(lg.get('act_rtt_ms', 0)):.2f} ms mean)."
                ),
            ]
        frac = ct.get("decide_frac_of_iter")
        iter_ms = ct.get("iter_ms")
        lines += [
            "",
            (
                f"The decision sweep p99 costs {float(frac):.3%} of the "
                f"{float(iter_ms):.0f} ms steady-state iteration "
                f"(commitment <= "
                f"{float(ct.get('decide_frac_max', 0.01)):.0%})"
                if frac is not None and iter_ms is not None
                else "The overhead fraction was not recorded"
            )
            + ". Gated by `perf_gate.gate_control`, folded into "
            "`gate()`.",
        ]
    return lines


def _load_tune_bench():
    """Load the autotuner artifact (``BENCH_tune.json``, written by
    ``surreal_tpu tune ... --out BENCH_tune.json``) if present — like
    BENCH_host.json, keeping it as an artifact lets PERF.md regens
    preserve the measured section without re-running the search."""
    try:
        with open("BENCH_tune.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if (
        not isinstance(data, dict)
        or not isinstance(data.get("workloads"), list)
        or not data["workloads"]
    ):
        return None
    return data


def _load_tiers_bench():
    """Load the replay-tiers artifact (``BENCH_tiers.json``, written by
    ``bench.py --replay-tiers``) if present — the BENCH_host.json
    discipline: PERF.md regens preserve the measured section without
    re-running."""
    try:
        with open("BENCH_tiers.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or data.get("value") is None:
        return None  # failed-campaign artifact
    return data


def _replay_tiers_lines() -> list[str]:
    """The 'Hierarchical replay tiers' PERF.md section: static mechanism
    text plus the measured warm-vs-hot table from the BENCH_tiers.json
    artifact. One function so ``main()`` and the committed PERF.md
    cannot drift."""
    lines = [
        "",
        "## Hierarchical replay tiers (device-resident hot ring, "
        "quantized spill WAL)",
        "",
        "The replay hierarchy (ISSUE 18): `replay.tiers.hot` fronts the "
        "PR-8 shard fan-in with a fixed-capacity ring of the NEWEST "
        "transitions held as committed device arrays "
        "(`replay/tiers.py`), filled from the collector's "
        "still-device-resident n-step fold and drawn by the same "
        "`jax.random.randint` + `ring_gather` as the in-process "
        "`UniformReplay` (BIT-EQUAL for the same keys — tested; the "
        "PR-7 Pallas row-DMA kernel carries the gather on TPU), so a "
        "steady-state uniform sample never touches the host: no wire "
        "frame, no `spec.unpack`, no host->device transfer. Misses "
        "while the ring fills fall back to the warm shard fan-in with "
        "the SAME key chain — counted in `tier/hot_misses`, never "
        "silent. `replay.tiers.spill` turns shard ingest into a durable "
        "write-ahead log (`experience/spill.py`): length-framed, "
        "CRC-checked segments in global `(seq, shard)` order, cold "
        "rewards/values quantized to uint8 against per-segment ranges "
        "(HEPPO-GAE, arXiv:2501.12703) with the error bound recorded in "
        "the header, other f32 columns as f16. "
        "`OffPolicyTrainer.replay_from_log` replays the WAL into a "
        "fresh ring and reruns the update schedule — two passes are "
        "bit-identical (tested), and torn tail segments (crash "
        "mid-append; the `experience.spill` chaos site) are skipped by "
        "magic-resync and counted in `tier/torn_segments`. Tiers off is "
        "bit-identical to the untiered plane (tested).",
    ]
    tb = _load_tiers_bench()
    if tb:
        warm, hot = tb.get("warm") or {}, tb.get("hot") or {}
        lines += [
            "",
            f"Measured through the real off-policy trainer "
            f"({tb['geometry']}; `BENCH_tiers.json`, platform "
            f"`{tb.get('platform')}`; warm iterations discarded):",
            "",
            "| Arm | env steps/s | iter ms | learner sample-wait ms | "
            "wire B/step |",
            "|---|---|---|---|---|",
        ]
        for r in (warm, hot):
            lines.append(
                "| {a} | {s:,.0f} | {ms:.1f} | {sw:.3f} | {w:.2f} |".format(
                    a=r.get("arm"),
                    s=float(r.get("env_steps_per_s", 0)),
                    ms=float(r.get("iter_ms", 0)),
                    sw=float(r.get("sample_wait_ms", 0)),
                    w=float(r.get("wire_bytes_per_step", 0)),
                )
            )
        lines += [
            "",
            "The hot arm served {hits:,.0f}/{tot:,.0f} updates from the "
            "device ring (sample-wait {hw:.3f} ms vs the warm arm's "
            "{ww:.2f} ms — the draw dispatches on-device at request "
            "time and overlaps the learner), while the spill WAL "
            "appended {wal:.1f} B/env-step at {cold:.0f} B/transition "
            "against the {raw} B raw f32 row ({ratio:.2f}x, gate "
            "commits <= 0.75). One-core honesty: both arms share one "
            "CPU core with the shard servers, so arm-to-arm steps/s "
            "differences are contention-dominated; the committed wins "
            "are the sample path and the cold bytes.".format(
                hits=float(tb.get("hot_hits") or 0),
                tot=float(tb.get("hot_hits") or 0)
                + float(tb.get("hot_misses") or 0),
                hw=float(hot.get("sample_wait_ms") or 0),
                ww=float(warm.get("sample_wait_ms") or 0),
                wal=float(tb.get("wal_bytes_per_step") or 0),
                cold=float(tb.get("cold_bytes_per_transition") or 0),
                raw=tb.get("raw_bytes_per_transition"),
                ratio=float(tb.get("cold_vs_raw_ratio") or 0),
            ),
        ]
    return lines


def _load_engine_bench():
    """Load the loop-engine artifact (``BENCH_engine.json``, written by
    ``bench.py --loop-engine``) if present — the BENCH_host.json
    discipline: PERF.md regens preserve the measured section without
    re-running."""
    try:
        with open("BENCH_engine.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or not data.get("drivers"):
        return None  # failed-campaign artifact
    return data


def _engine_lines() -> list[str]:
    """The 'Loop engine' PERF.md section: static mechanism text plus the
    per-driver off-vs-on table from the BENCH_engine.json artifact. One
    function so ``main()`` and the committed PERF.md cannot drift."""
    lines = [
        "",
        "## Loop engine (software-pipelined iteration boundary)",
        "",
        "All five single-host driver loops (fused/alternate/overlap PPO, "
        "device/host off-policy, SEED) and the three multi-host "
        "subclasses run on ONE iteration skeleton "
        "(`engine/core.py::LoopEngine`): each driver declares its stages "
        "(`collect -> stage -> learn` plus the shared "
        "`publish/checkpoint/recover/observe` side-bands) as `StageSpec` "
        "rows with an EXPLICIT donation bit, and hands the engine a step "
        "closure. With `session_config.engine.pipeline_sidebands` off "
        "(default) the boundary runs inline and the engine is "
        "bit-identical to the historical loops (tested per driver, "
        "params digest + metrics rows + checkpoint bytes). With it on, "
        "the boundary — metrics sync (the one `float()` device fence), "
        "publish, checkpoint, tracer/ops emits — is submitted to a "
        "single staging worker and overlaps iteration k+1's "
        "collect/learn. Donation safety: when any declared stage "
        "donates (the fused device programs jit with "
        "`donate_argnums=(0, 1)`), the param tree is snapshotted with "
        "`jax.tree.map(jnp.copy, ...)` BEFORE the next donating "
        "dispatch can reuse the buffers; host drivers pass the "
        "reference (rebinding, never mutation, is the loop discipline). "
        "Stop/recovery verdicts land with at most one iteration of lag; "
        "a wedged boundary (the `engine.stage` chaos site) gets "
        "`stage_timeout_s` before subsequent boundaries are skipped — "
        "counted in `engine/skipped_boundaries`, never silent — and the "
        "SIGTERM latch is checked inline every iteration, so preemption "
        "stops at an iteration boundary with the emergency checkpoint "
        "intact under overlap (tested).",
    ]
    eb = _load_engine_bench()
    if eb:
        lines += [
            "",
            f"Measured through the real drivers ({eb['geometry']}; "
            f"`BENCH_engine.json`, platform `{eb.get('platform')}`, "
            f"{eb.get('cores', '?')} core(s), mode `{eb.get('mode')}`; "
            f"median of {eb.get('meas_iters')} steady-state iterations):",
            "",
            "| Driver | geometry | legacy iter ms | pipelined iter ms | "
            "ratio | boundary share reclaimed |",
            "|---|---|---|---|---|---|",
        ]
        for name in sorted(eb["drivers"]):
            r = eb["drivers"][name]
            off, on = r.get("off") or {}, r.get("on") or {}
            rec = r.get("reclaimed_frac")
            lines.append(
                "| {n} | {g} | {o:.1f} | {p:.1f} | {ra:.3f} | {re} |".format(
                    n=name, g=r.get("geometry"),
                    o=float(off.get("iter_ms", 0)),
                    p=float(on.get("iter_ms", 0)),
                    ra=float(r.get("iter_ratio_on_vs_off") or 0),
                    re=f"{float(rec):.1%}" if rec is not None else "-",
                )
            )
        if eb.get("mode") != "overlap":
            lines += [
                "",
                "One-core honesty: this box has "
                f"{eb.get('cores', 1)} CPU core(s), so the staging "
                "worker time-slices the compute thread and the arms "
                "measure bookkeeping overhead, not overlap — the "
                "`perf_gate.gate_engine` <= bound is enforced only "
                "under mode `overlap` (>= 2 cores). The committed win "
                "on this image is the reclaimed-share column: the "
                "boundary work that LEAVES the critical path once a "
                "second core exists.",
            ]
    return lines


def _chaos_lines() -> list[str]:
    """The 'Chaos campaigns' PERF.md section: static mechanism text plus
    the campaign summary from the committed CHAOS_campaign.json. One
    function so ``main()`` and the committed PERF.md cannot drift."""
    lines = [
        "",
        "## Chaos campaigns (randomized multi-site fault schedules)",
        "",
        "`surreal_tpu chaos <algo|all> [env] --seeds N` runs N seeded "
        "short REAL training runs, each under a deterministic multi-site "
        "fault schedule drawn by `chaos/schedule.py` over the "
        "`utils/faults.py` site registry (per-site kind vocabulary, "
        "kill/nan caps, exclusive co-fire groups, a per-schedule "
        "injected-delay budget). Every run is judged post-hoc by the "
        "`chaos/invariants.py` oracles — exactly-once row conservation "
        "at the quiesced close boundary, counted-never-silent (every "
        "delivered fault leaves a declared counter delta), monotone "
        "published/served param versions and cumulative counters, zero "
        "thread/shm/fd residue after teardown, newest-checkpoint finite "
        "restorability, spill-WAL re-read consistency, and fault "
        "surfacing (every delivered fault appears as a `fault` telemetry "
        "event). A failing schedule is greedily shrunk (drop one spec, "
        "re-run deterministically) to a 1-minimal repro and recorded "
        "with its `(profile, seed)` replay key. "
        "`perf_gate.gate_chaos` holds the committed campaign to >= 25 "
        "schedules over >= 10 distinct FIRED sites with zero violations.",
    ]
    try:
        with open("CHAOS_campaign.json") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return lines
    if not isinstance(data, dict) or data.get("kind") != "chaos_campaign":
        return lines
    g = data.get("gauges") or {}
    by_prof: dict[str, int] = {}
    for s in data.get("schedules") or ():
        by_prof[s.get("profile", "?")] = by_prof.get(
            s.get("profile", "?"), 0) + 1
    lines += [
        "",
        f"Committed campaign (`CHAOS_campaign.json`): "
        f"{int(g.get('chaos/schedules', 0))} schedules over profiles "
        + ", ".join(f"`{p}` ({n})" for p, n in sorted(by_prof.items()))
        + f"; {int(g.get('chaos/faults_injected', 0))} faults delivered "
        f"across {int(g.get('chaos/sites_covered', 0))} distinct sites; "
        f"{int(g.get('chaos/violations', 0))} invariant violations; "
        f"wall {float(g.get('chaos/run_ms', 0)) / 1e3:,.0f} s.",
        "",
        "Fired sites: "
        + ", ".join(f"`{s}`" for s in data.get("sites_covered") or ())
        + ".",
    ]
    return lines


def _autotuner_lines() -> list[str]:
    """The 'Program autotuner' PERF.md section: static mechanism text plus
    the measured table from the BENCH_tune.json artifact when one exists.
    One function so ``main()`` and the committed PERF.md cannot drift."""
    lines = [
        "",
        "## Program autotuner (searched scan-unroll + program geometry, "
        "persistent per-workload tuning cache)",
        "",
        "Every graded workload is latency-bound on long `lax.scan`s of "
        "tiny elementwise ops, yet scan-unroll factors and geometry "
        "choices (`gae_impl`, minibatch shuffle layout, update-loop "
        "shape) were hand-set defaults. `surreal_tpu/tune/` searches "
        "them instead (Stooke & Abbeel 1803.02811's measure-and-pick "
        "discipline): greedy coordinate descent over the declared "
        "candidate space (`tune/space.py` — rollout/SGD/update-loop "
        "`unroll`, `gae_impl` incl. the pallas kernel, `shuffle`), each "
        "candidate timed through the REAL trainer programs with bench.py's "
        "device_get-fenced chained-window discipline — the fused device "
        "iteration for `jax:*` envs, the jitted learn program alone for "
        "host-env (gym/dm_control/SEED) fingerprints, whose rollout is "
        "host python with no scan to unroll — winner "
        "persisted in a JSON tuning cache beside the compile cache "
        "(`session.tuning_cache_dir`), keyed by workload fingerprint "
        "(algo + model + geometry + backend + jax version, minus the "
        "searched knobs). Trainers consult the cache at build time "
        "(`algo.autotune='off'|'cache'|'search'`); a second `surreal_tpu "
        "tune` run on the same fingerprint is a pure cache hit (zero "
        "measurements), and decisions land in telemetry as `tune` events "
        "(`surreal_tpu diag` renders hit/miss + candidate timings). "
        "bench.py / perf_wallclock.py record the active decision per "
        "artifact row, so tuned and untuned arms can never silently mix.",
    ]
    tb = _load_tune_bench()
    if tb:
        lines += [
            "",
            f"Measured winners (`BENCH_tune.json`, platform "
            f"`{tb.get('platform')}`; adoption threshold 2% vs the "
            "static default — at or under it the default keeps the "
            "compile-cache-warm program):",
            "",
            "| Workload | Geometry | default ms/iter | tuned ms/iter | "
            "speedup | adopted knobs |",
            "|---|---|---|---|---|---|",
        ]
        for w in tb["workloads"]:
            chosen = w.get("config") or {}
            default = w.get("default") or {}
            diff = {
                k: v for k, v in chosen.items() if default.get(k) != v
            }
            lines.append(
                "| {wl} | {g} | {d:.1f} | {c:.1f} | {s:.2f}x | {k} |".format(
                    wl=w.get("workload", "?"),
                    g=w.get("geometry", "?"),
                    d=float(w.get("default_ms") or 0.0),
                    c=float(w.get("chosen_ms") or 0.0),
                    s=float(w.get("speedup") or 1.0),
                    k=", ".join(f"`{k}={v}`" for k, v in sorted(diff.items()))
                    or "(static defaults already optimal)",
                )
            )
    return lines


def _perf_observability_lines() -> list[str]:
    """The 'Performance observability' PERF.md section: static mechanism
    text plus an MFU-per-committed-BENCH-artifact table, so regeneration
    keeps the observability story and the measured MFU trail together.
    One function so ``main()`` and the committed PERF.md cannot drift."""
    lines = [
        "",
        "## Performance observability (in-graph cost/MFU accounting, "
        "trace correlation, on-demand profiling)",
        "",
        "The measurement layer under every number above "
        "(`session/costs.py`, `session/profile.py`, telemetry spine "
        "extensions): each driver registers its jitted hot programs with "
        "XLA's cost model at startup (per-program FLOPs / bytes accessed "
        "/ arithmetic intensity as `program_cost` telemetry events) and "
        "emits live `perf/mfu` + `perf/membw_util` gauges at the metrics "
        "cadence — pure host arithmetic over already-recorded phase "
        "windows, transfer-guard proven to add zero device->host syncs. "
        "The SEED data plane stamps a run-scoped trace id plus span ids "
        "into its control frames so `surreal_tpu diag` stitches a "
        "cross-process timeline (worker step -> frame in flight -> serve "
        "batch -> queue dwell -> learn) with p50/p90/p99 per hop, and "
        "`surreal_tpu profile <folder>` captures an on-demand "
        "`jax.profiler` window into `<folder>/telemetry/profiles/`. "
        "`perf_gate.py` turns the committed artifact trail below into a "
        "CI gate (>10% regression on the same workload fingerprint "
        "exits nonzero).",
        "",
        "MFU per committed BENCH artifact (XLA cost model / "
        f"{PEAK_FLOPS_BF16 / 1e12:.0f} TFLOP/s bf16 peak; 'n/a' predates "
        "the cost accounting or is a failed round). Geometry and arm ride "
        "every row because the trail is NOT one curve: a row measured at "
        "a different geometry, precision arm, or platform is a different "
        "workload, and reading it against the headline rows as a "
        "regression (or a win) is exactly the mistake this column "
        "exists to prevent — perf_gate fingerprints rows the same way:",
        "",
        "| Artifact | metric | geometry | arm (platform) | env steps/s | MFU |",
        "|---|---|---|---|---|---|",
    ]
    # one artifact parser for the gate and this table (perf_gate.py):
    # the CI gate and PERF.md must never classify the same row differently
    from perf_gate import load_rows

    for row in load_rows("."):
        if row.get("failed"):
            lines.append(
                f"| `{row['file']}` | (failed round) | n/a | n/a | n/a | n/a |"
            )
            continue
        mfu = row.get("mfu")
        arm_bits = [b for b in (row.get("arm"), row.get("platform")) if b]
        lines.append(
            "| `{p}` | {m} | {g} | {a} | {v:,.0f} | {mfu} |".format(
                p=row["file"], m=row.get("metric", "?"),
                g=row.get("geometry") or "not recorded",
                a=(
                    f"{row.get('arm') or '?'} (`{row.get('platform') or '?'}`)"
                    if arm_bits else "not recorded"
                ),
                v=row["value"],
                mfu=f"{float(mfu) * 100:.3f}%" if mfu is not None else "n/a",
            )
        )
    return lines


def _precision_lines() -> list[str]:
    """The 'Precision policy' PERF.md section: static mechanism text plus
    the per-policy wall-clock / bytes-accessed table from the newest
    committed artifact carrying a precision sweep (bench.py
    --sweep-precision -> BENCH_r06.json). One function so ``main()`` and
    the committed PERF.md cannot drift — the autotuner/observability
    sections' discipline."""
    lines = [
        "",
        "## Precision policy (f32 / mixed / bf16 / bf16+fp8, dynamic "
        "loss scaling, Pallas hot-kernel suite)",
        "",
        "`algo.precision` (ops/precision.py) is ONE knob governing model "
        "compute dtype, trajectory/SGD/replay staging dtype, and dynamic "
        "loss scaling, threaded through every learner and trainer with "
        "no per-driver forks — and a searched autotuner dimension "
        "(tune/space.py, searched FIRST so later unroll knobs re-measure "
        "under the adopted policy). Params and optimizer state stay f32 "
        "under every policy. 'bf16' stages obs-class arrays in bfloat16 "
        "(the epochs x minibatch gathers and the replay buffer move half "
        "the bytes) and wraps every optimizer chain in dynamic loss "
        "scaling: power-of-two scales make healthy steps EXACT, an "
        "overflow skips the step (Adam moments untouched) and backs the "
        "scale off, and the scale state rides the optimizer pytree next "
        "to PR-5's recovery_scale so a divergence that slips the skip "
        "logic still hits the existing guard + rollback. Checkpoint "
        "run-metadata records the policy; restore across a mismatch is "
        "a named PrecisionMismatchError, not an orbax structure "
        "traceback. The kernel suite grew past GAE: fused V-trace "
        "(ops/pallas_vtrace.py, `vtrace_impl`), the generic reverse "
        "recurrence + discounted returns (ops/pallas_returns.py), and "
        "scalar-prefetch replay gather/scatter row-DMA kernels "
        "(ops/pallas_replay.py, `replay_gather`) — all with interpret-"
        "mode fallbacks, validated against their XLA references on every "
        "backend, adopted per workload only when measured faster.",
    ]
    art = newest_bench_artifact()
    sweep = (art[1].get("precision_sweep") if art else None) or {}
    arms = sweep.get("arms") or []
    costs = sweep.get("headline_costs") or []
    if arms or costs:
        plat = arms[0].get("platform") if arms else None
        # the narrative must match the platform the artifact actually
        # recorded — the same branch gate_precision takes: on a
        # bf16-emulating host f32 outruns any bf16 arm by construction;
        # on TPU bf16 must win its keep against the true f32 baseline
        plat_note = (
            "this host emulates bf16, so f32 outruns any bf16-computing "
            "arm here; on TPU the MXU inverts that"
            if plat != "tpu"
            else "native bf16 MXU — the f32 arm is the true baseline"
        )
        lines += [
            "",
            f"Per-policy measurements (`{art[0]}`; platform "
            f"{plat} recorded honestly — {plat_note}. "
            "Bytes-accessed rows are the PR-6 cost accountant at the "
            "TRUE headline geometry, deterministic, no timed window):",
            "",
            "| policy | timed geometry | steps/s | headline bytes/iter |",
            "|---|---|---|---|",
        ]
        cost_by = {c.get("precision"): c for c in costs}
        for a in arms:
            c = cost_by.get(a.get("precision"), {})
            byts = c.get("bytes_accessed_per_iter")
            lines.append(
                "| {p} | {g} | {v:,.0f} | {b} |".format(
                    p=a.get("precision"),
                    g=f"{a.get('num_envs')}x{a.get('horizon')}",
                    v=a.get("value", 0),
                    b=f"{byts / 1e9:.2f} GB" if byts else "n/a",
                )
            )
        cf = cost_by.get("f32", {}).get("bytes_accessed_per_iter")
        cb = cost_by.get("bf16", {}).get("bytes_accessed_per_iter")
        if cf and cb:
            lines.append(
                f"\nbf16 policy: {(1 - cb / cf) * 100:.1f}% lower "
                "bytes-accessed per headline iteration than f32 "
                "(commitment >= 25%, gated by perf_gate.py as a tier-1 "
                "test)."
            )
    return lines


def _load_block_vs_row():
    """Load perf_curves.py's artifact if present — the comparison is a
    slow chip-bound campaign run separately; keeping it as a JSON artifact
    lets PERF.md regens preserve the section without re-running it."""
    try:
        with open("block_vs_row.json") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _block_vs_row_verdict(s) -> str:
    bm, rm = s["block"]["final_median"], s["row"]["final_median"]
    b_lo = min(s["block"]["final_returns"])
    b_hi = max(s["block"]["final_returns"])
    r_lo = min(s["row"]["final_returns"])
    r_hi = max(s["row"]["final_returns"])
    overlap = not (b_hi < r_lo or r_hi < b_lo)
    spread = max(b_hi - b_lo, r_hi - r_lo)
    benign = overlap and abs(bm - rm) <= spread
    if benign:
        return (
            "The per-seed final-return ranges OVERLAP "
            f"(block [{b_lo:,.0f}-{b_hi:,.0f}] vs row "
            f"[{r_lo:,.0f}-{r_hi:,.0f}]) and the median gap "
            f"({abs(bm - rm):,.0f}) is within the larger arm's seed "
            f"spread ({spread:,.0f}): at the real multi-minibatch "
            "geometry the block co-grouping is statistically benign — "
            "the direct evidence the round-4 docstring argument "
            "promised. 'row' stays selectable for exact reference "
            "semantics."
        )
    return (
        f"The arms separate (block median {bm:,.0f} vs row {rm:,.0f}; "
        f"ranges block [{b_lo:,.0f}-{b_hi:,.0f}] vs row "
        f"[{r_lo:,.0f}-{r_hi:,.0f}]): the block co-grouping has a "
        "measurable learning cost at this geometry — documented honestly "
        "here; weigh the 13x throughput win against it per workload, or "
        "set `algo.shuffle='row'` for exact reference semantics."
    )


def _capture_trace(trainer, state, carry, key) -> str | None:
    """Profiler window over two fused iters (SURVEY.md §5.1). MUST run
    after every measurement: see the axon post-trace-compilation note."""
    trace_dir = "/tmp/perf_lift/profile"
    try:
        with jax.profiler.trace(trace_dir):
            for _ in range(2):
                key, it_key = jax.random.split(key)
                state, carry, metrics = trainer._train_iter(state, carry, it_key)
            jax.device_get(metrics)  # real fence: trace must span execution
        return trace_dir
    except Exception:
        return None


def main(argv=None) -> None:
    import sys

    argv = sys.argv[1:] if argv is None else argv
    if "--sync-readme" in argv:
        # citation-only sync (no benchmarks, works off-chip) — see
        # sync_readme_artifact's docstring for why this exists
        sync_readme_artifact()
        return
    rows = []
    trace_fn = None
    for fn in (
        ppo_lift_headline, impala_pong, ddpg_prioritized_lift,
        ddpg_prioritized_lift_1m, ppo_cnn_nut_pixels,
        ppo_trajectory_pendulum, host_env_cheetah,
    ):
        r = fn()
        if r is None:
            continue
        trace_fn = r.pop("_trace_fn", None) or trace_fn  # not JSON-able
        rows.append(r)
        print(json.dumps(r, default=float))
    scaling = headline_scaling() if "--scaling" in argv else None
    # trace LAST: everything compiled after a trace window runs degraded
    rows[0]["trace_dir"] = trace_fn() if trace_fn else None

    dev = jax.devices()[0]
    lines = [
        "# PERF — measured utilization report",
        "",
        f"Device: `{dev.device_kind}` (1 chip; via the axon tunnel). "
        f"MFU denominator: {PEAK_FLOPS_BF16 / 1e12:.0f} TFLOP/s (TPU v5e "
        "public bf16 peak). FLOPs are XLA's own `cost_analysis()` of the "
        "compiled training iteration — model + env + optimizer, everything "
        "in the program.",
        "",
        "All timings are fenced by `jax.device_get` of a program output — "
        "`jax.block_until_ready` does not wait on this backend, which "
        "inflated pre-round-3 records ~1000x (bench.py module doc has the "
        "forensics). These workloads are LATENCY-BOUND on long scans of "
        "tiny elementwise env ops, not matmul-bound — MFU is expectedly "
        "tiny and reported for transparency; the graded metric stays env "
        "steps/s/chip.",
        "",
        "| Workload | Geometry | env steps/s/chip | iter ms | FLOP/s | MFU |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        fl = r.get("model_flops_per_s")
        mfu = r.get("mfu")
        lines.append(
            "| {w} | {g} | {s:,.0f} | {ms:.1f} | {fl} | {mfu} |".format(
                w=r["workload"],
                g=r["geometry"],
                s=r["env_steps_per_s"],
                ms=r["iter_ms"],
                fl=f"{fl / 1e12:.2f} TFLOP/s" if fl else "n/a",
                mfu=f"{mfu * 100:.2f}%" if mfu else "n/a",
            )
        )
    head = rows[0]
    parts_sum = head["rollout_only_ms"] + head["learn_only_ms"]
    if head["iter_ms"] < 0.9 * parts_sum:
        verdict = (
            "The fused iteration beats rollout+learn compiled separately "
            f"({head['iter_ms']:.2f} ms vs {parts_sum:.2f} ms summed): one "
            "program lets XLA overlap env stepping with learning work and "
            "keep intermediates in HBM/VMEM instead of round-tripping "
            "between dispatches — the reason the trainer fuses the whole "
            "iteration."
        )
    else:
        verdict = (
            "Rollout and learn compiled separately sum close to the fused "
            f"iteration ({parts_sum:.2f} ms vs {head['iter_ms']:.2f} ms): "
            "fusion is not load-bearing at this geometry; the split shows "
            "which half dominates."
        )
    lines += [
        "",
        "## Top-line breakdown (headline workload)",
        "",
        f"- fused train iteration: {head['iter_ms']:.2f} ms",
        f"- rollout-only program (policy forward + env step x 256): "
        f"{head['rollout_only_ms']:.2f} ms",
        f"- learn-only program (GAE + 4x4 minibatch SGD): "
        f"{head['learn_only_ms']:.2f} ms",
        "",
        verdict,
    ]
    at = head.get("attrib")
    if at:
        lines += [
            "",
            "## Learn-phase attribution (round-4 finding)",
            "",
            "Sub-programs compiled and timed separately at the headline "
            "geometry (device_get-fenced, chained):",
            "",
            "| Component | ms/iter |",
            "|---|---|",
            f"| learn-only, `algo.shuffle='row'` (reference semantics: per-epoch row reshuffle) | {at['learn_row_ms']:.1f} |",
            f"| learn-only, `algo.shuffle='block'` (default) | {head['learn_only_ms']:.1f} |",
            f"| value forwards (2x model.apply over [T, B]) | {at['value_forwards_ms']:.1f} |",
            f"| GAE recurrence | {at['gae_ms']:.1f} |",
            f"| ALL 16 grad steps (4 epochs x 4 minibatches), no shuffling/gathers | {at['gradsteps16_nogather_ms']:.1f} |",
            "",
            "With row shuffling, learn time was dominated NOT by training "
            "compute but by minibatch assembly: a ~1M-element argsort "
            "permutation per epoch plus random row gathers whose "
            "4-byte-row leaves (advantages, logps) walk the TPU scalar "
            "unit. `algo.shuffle='block'` (learners/ppo.py `_sgd_epochs`) "
            "permutes contiguous blocks instead — statistically benign "
            "here because a flat-layout block is a same-timestep slab of "
            "independent envs — and removes that cost wholesale; 'row' "
            "remains selectable for exact reference semantics.",
        ]
    pong = next((r for r in rows if r.get("pong_attrib")), None)
    if pong:
        pa = pong["pong_attrib"]
        fused = pong["iter_ms"]
        # decision logic rendered with the numbers: which phase owns the
        # iteration, and what (if anything) a kernel-level fix could buy
        dominant = max(
            ("env rendering+logic", pa["env_only_ms"]),
            ("CNN acting", max(pa["act_only_ms"], 0.0)),
            ("learn (V-trace + CNN fwd/bwd)", pa["learn_ms"]),
            key=lambda t: t[1],
        )
        B, T = pa.get("num_envs", "?"), pa.get("horizon", "?")
        lines += [
            "",
            "## Pixel-path attribution (pong, round-5)",
            "",
            "Sub-programs compiled and timed separately at the pong "
            f"geometry ({pong['geometry']}; device_get-fenced, chained):",
            "",
            "| Component | ms/iter |",
            "|---|---|",
            f"| fused train iteration | {fused:.1f} |",
            f"| rollout only (CNN act + env step x {T}) | {pa['rollout_ms']:.1f} |",
            f"| env only (random actions: pixel render + game logic x {T}) | {pa['env_only_ms']:.1f} |",
            f"| CNN acting only (NatureCNN forward x {T}, fixed frame) | {pa['act_only_ms']:.1f} |",
            f"| learn only (V-trace + CNN fwd/bwd over [{T}, {B}]) | {pa['learn_ms']:.1f} |",
            "",
            f"The iteration is owned by **{dominant[0]}** "
            f"({dominant[1]:.1f} ms of {fused:.1f}). "
            + (
                "The ~3% MFU on pixel workloads is a ROOFLINE property, "
                "not a missed optimization: the env scan writes uint8 "
                "frames elementwise (bandwidth, not MXU), and the "
                "NatureCNN on 42x42 frames does small-spatial convs whose "
                "im2col tiles underfill the 128x128 systolic array. "
                "Decision recorded: no pallas kernel for the conv path — "
                "the phase a kernel could accelerate is not where the "
                "milliseconds are; pixel-throughput work should target "
                "the env scan's frame writes if it ever becomes the "
                "bottleneck at larger batch."
                if dominant[0] == "env rendering+logic"
                else
                "The conv path owns the iteration at this geometry. "
                "Decision recorded after checking the stem: it already "
                "computes in bf16 (models/encoders.py NatureCNN), so the "
                "remaining kernel levers are channel-padded layouts or a "
                "fused pallas stem — NOT pursued, because the low MFU is "
                "structural at this shape (the first conv's C_in=2 "
                "underfills the 128-lane MXU regardless of kernel, and "
                "XLA already pads); a pallas conv would re-derive XLA's "
                "own schedule for single-digit-ms stakes. Revisit only "
                "if pixel workloads scale to larger frames/channels "
                "where the conv becomes tens of ms."
            ),
        ]
    bvr = _load_block_vs_row()
    if bvr and all(
        bvr["summary"][m]["final_returns"] for m in ("block", "row")
    ):
        s = bvr["summary"]
        lines += [
            "",
            "## Block-vs-row shuffle: direct learning-curve A/B "
            "(round-5 validation of the round-4 13x win)",
            "",
            f"Geometry {s['geometry']}, {s['n_iters']} iterations per run, "
            f"{len(s['block']['final_returns'])} seeds per arm, arms "
            "interleaved (perf_curves.py; artifact `block_vs_row.json`"
            + (
                f"; final performance = {s['final_estimator']}"
                if s.get("final_estimator") else ""
            )
            + ").",
            "",
            "| Shuffle mode | final returns (per seed, sorted) | median |",
            "|---|---|---|",
            "| `block` (TPU default) | "
            + ", ".join(f"{v:,.0f}" for v in s["block"]["final_returns"])
            + f" | {s['block']['final_median']:,.0f} |",
            "| `row` (reference semantics) | "
            + ", ".join(f"{v:,.0f}" for v in s["row"]["final_returns"])
            + f" | {s['row']['final_median']:,.0f} |",
            "",
            _block_vs_row_verdict(s),
        ]
    # static section: the dispatch-pipeline levers are mechanism-proven by
    # test (tier-1 is CPU); regenerating PERF.md on a measurement round
    # must not drop their documentation
    lines += [
        "",
        "## Dispatch pipeline (donation, persistent compile cache, "
        "prefetch staging)",
        "",
        "Three levers added by the dispatch-pipeline PR; mechanisms "
        "proven by test on this image (tier-1 runs on CPU — chip-side "
        "wall-clock numbers are for the next on-TPU measurement round to "
        "record):",
        "",
        "- **Donation** — every fused train/learn jit donates its "
        "loop-carried pytrees (`donate_argnums`): train state, env "
        "carry, replay shards. For the off-policy fused program the "
        "replay storage is the single largest HBM allocation, so "
        "donation halves its steady-state footprint (one live copy "
        "instead of input+output across each iteration) and removes the "
        "copy XLA otherwise schedules. Drivers commit carries to the "
        "mesh sharding at init so the aliasing holds from iteration 1 "
        "(an uncommitted input's donation is silently dropped by the "
        "reshard). Invariant enforced two ways: "
        "`tests/test_dispatch_pipeline.py` (donated inputs actually "
        "released; stale reuse raises) and the `test_import_hygiene` "
        "donation lint (every `jax.jit` in a learner/trainer step "
        "module must state its donation decision; the deliberate "
        "non-donations — SEED's live act closure, the host overlap "
        "collectors — are declared `donate_argnums=()` with the alias "
        "named).",
        "- **Persistent compile cache** — `session.compile_cache_dir` "
        "enables `jax_compilation_cache_dir` (+ relaxed eligibility "
        "thresholds, via `utils/compat.py` for the pinned jax, "
        "including the reset of jax's once-per-process cache-used "
        "latch). WALLCLOCK_r05 context: the pong 2.5-vs-4.5-minute "
        "spread was compile time, not train time — a warm cache "
        "converts that compile into executable deserialization. Measure "
        "with `python perf_wallclock.py --compile-cache /tmp/xla_cache` "
        "twice: run 1 (cold, empty dir) vs run 2 (warm) — compare "
        "`summary.seed0_compile_s`; per-row `compile_cache` hit/miss "
        "counters make the artifacts self-describing, and `surreal_tpu "
        "diag` reports the same counters for any training session.",
        "- **Prefetch staging** (`learners/prefetch.py`) — SEED: the "
        "staging thread waits on the chunk queue and pays the "
        "host→device transfer (with the committed dp sharding) for "
        "chunk k+1 while the learner runs chunk k, so steady-state "
        "iteration ≈ max(stage, learn) instead of stage+learn; on "
        "a tunneled chip the hidden transfer is the dominant term. "
        "Off-policy host loop: the whole exploration rollout + its "
        "single `device_put` runs on the staging thread while the "
        "device drains `updates_per_iter` SGD steps "
        "(`topology.overlap_rollouts`; the host-env caveat in the table "
        "below — one-core boxes see ~1x — applies to this overlap too). "
        "Transfer-guard tests prove staging adds zero device→host "
        "syncs.",
    ]
    # static section + artifact table: the autotuner is documented
    # unconditionally; the measured table rides the BENCH_tune.json
    # artifact so a regen without the search keeps the last measured run
    lines += _autotuner_lines()
    # static section + artifact table: the observability layer is
    # documented unconditionally; the MFU trail rides the committed
    # BENCH_r*.json artifacts
    lines += _perf_observability_lines()
    # static section + per-policy table riding the newest precision-sweep
    # artifact (BENCH_r06.json)
    lines += _precision_lines()
    host = next((r for r in rows if r.get("host_attrib")), None)
    if host:
        ha = host["host_attrib"]
        roll_ms = ha["rollout_projected_ms"]
        win = ha["alternate_iter_ms"] / ha["overlap_iter_ms"]
        lines += [
            "",
            "## Host-env data plane (BASELINE ② — the reference's operating shape)",
            "",
            "CPU MuJoCo envs (dm_control cheetah-run, 32 envs) feeding the "
            "chip per step — the reference's defining workload (actors + "
            "ZMQ replay, SURVEY.md §3.2-3.3). Three drive modes, measured "
            "end-to-end through the real trainers (wall-clock between "
            "metrics fences, first 3 iterations discarded as compile/warm):",
            "",
            "| Drive mode | env steps/s | iter ms |",
            "|---|---|---|",
            f"| strict alternation (`overlap_rollouts=false`) | {ha['alternate_sps']:,.0f} | {ha['alternate_iter_ms']:.0f} |",
            f"| overlapped collector (`overlap_rollouts=true`, default) | {ha['overlap_sps']:,.0f} | {ha['overlap_iter_ms']:.0f} |",
            f"| SEED (4 worker processes x 8 envs -> InferenceServer) | {ha['seed_sps']:,.0f} | {ha['seed_iter_ms']:.0f} |",
            "",
            "Per-phase attribution of one alternation iteration "
            f"(horizon {64}):",
            "",
            "| Phase | ms |",
            "|---|---|",
            f"| policy act, per env step (obs upload + forward + action download over the tunnel, fenced) | {ha['act_ms_per_step']:.2f} |",
            f"| env.step, per env step (32 serial MuJoCo steps on 1 host core) | {ha['env_ms_per_step']:.2f} |",
            f"| rollout projected (act+env) x 64 | {roll_ms:.0f} |",
            f"| learn, per iteration (4 epochs x 4 minibatches, fenced) | {ha['learn_ms_per_iter']:.0f} |",
            "",
            (
                f"The overlapped loop runs {win:.2f}x the strict "
                "alternation — hiding the learn phase behind the "
                "collector thread captures the available win."
                if win > 1.02
                else
                f"Overlap measured {win:.2f}x vs strict alternation — on "
                "THIS box it does not pay: the projected rollout "
                f"({roll_ms:.0f} ms) is ~"
                f"{roll_ms / max(ha['learn_ms_per_iter'], 1e-9):.0f}x the "
                f"learn phase ({ha['learn_ms_per_iter']:.0f} ms), so "
                "there is almost nothing to hide, and the collector "
                "thread's device round trips contend with the learner's "
                "on one host core. The feature targets the reference's "
                "balance (env+learn comparable); `overlap_rollouts="
                "false` is the right setting here."
            )
            + (
                " The SEED plane is the fastest mode measured here "
                f"({ha['seed_sps']:,.0f} steps/s vs "
                f"{max(ha['alternate_sps'], ha['overlap_sps']):,.0f} for "
                "the best in-process loop): workers step envs "
                "continuously instead of waiting for the learn, and the "
                "server coalesces the fleet into one batched forward per "
                f"round, so the ~{ha['act_ms_per_step']:.0f} ms per-act "
                "device round trip is paid once per SERVER step, not "
                "once per trainer env step."
                if ha["seed_sps"] >= max(ha["alternate_sps"], ha["overlap_sps"])
                else
                f" SEED measured {ha['seed_sps']:,.0f} steps/s vs "
                f"{max(ha['alternate_sps'], ha['overlap_sps']):,.0f} for "
                "the best in-process loop — on this box the in-process "
                "loop wins; see the attribution rows for where its time "
                "goes."
            )
            + " NOTE the absolute numbers carry two environment taxes a "
            "production host would not pay: this image tunnels every act "
            "round trip to a remote chip (the act row above is mostly "
            "tunnel latency — a local TPU host pays ~1 ms), and the host "
            "has ONE CPU core (`nproc`=1), so the 32 MuJoCo envs step "
            "serially and SEED's 4 worker processes time-slice one core. "
            "The numbers are honest for THIS box; the mode ranking the "
            "table records is the measured one.",
        ]
    # static section + artifact table: the host data-plane rebuild is
    # documented unconditionally (mechanism proven by test on this CPU
    # image); the measured table rides the BENCH_host.json artifact so a
    # regen without the campaign keeps the last measured numbers
    lines += _host_data_plane_lines()
    lines += _experience_plane_lines()
    lines += _act_path_lines()
    lines += _gateway_lines()
    lines += _ops_plane_lines()
    lines += _trace_lines()
    lines += _watchdog_lines()
    lines += _control_lines()
    lines += _replay_tiers_lines()
    lines += _engine_lines()
    lines += _chaos_lines()
    if scaling:
        lines += [
            "",
            "## Headline geometry scaling (`--scaling`)",
            "",
            "| Geometry (envs x horizon) | env steps/s/chip | iter ms |",
            "|---|---|---|",
        ]
        for r in scaling:
            lines.append(
                f"| {r['geometry']} | {r['env_steps_per_s']:,.0f} "
                f"| {r['iter_ms']:.2f} |"
            )
        lines += [
            "",
            "Horizon costs linearly (the env scan is sequential) and width "
            "costs linearly once elementwise env ops saturate, so "
            "throughput is flat-to-declining past the knee. bench.py "
            "records the headline at its own swept knee (4096 x 256 since "
            "the round-4 block-shuffle change); this sweep holds horizon "
            "at 256 to show the width axis in isolation.",
        ]
    if head.get("trace_dir"):
        lines += [
            "",
            f"A `jax.profiler` trace of two fused iterations was captured to "
            f"`{head['trace_dir']}` (TensorBoard profile plugin format; not "
            "committed — rerun `python perf_report.py` to regenerate).",
        ]
    lines += [
        "",
        "_Generated by `perf_report.py`; bench.py prints the headline line "
        "with `mfu` for the driver's BENCH artifact._",
        "",
    ]
    with open("PERF.md", "w") as f:
        f.write("\n".join(lines))
    print("wrote PERF.md")
    _update_readme(rows)


def newest_bench_artifact():
    """(basename, parsed-bench-line) of the newest BENCH_r*.json on disk,
    or None. The single source of truth for 'artifact of record' — used
    by the README regen, the ``--sync-readme`` mode, and the anti-drift
    test (tests/test_perf_docs.py)."""
    import glob
    import os

    bench_files = sorted(glob.glob("BENCH_r*.json"))
    for path in reversed(bench_files):
        try:
            with open(path) as f:
                data = json.load(f)
            # driver artifacts wrap the bench line under "parsed"; a
            # FAILED round writes "parsed": null — `or data` (not a
            # default) so null falls back too, and the isinstance guard
            # lets any non-dict artifact fall through to the newest
            # VALID bench file instead of raising TypeError (ADVICE r5)
            parsed = (data.get("parsed") or data) if isinstance(data, dict) else None
            if isinstance(parsed, dict) and "value" in parsed:
                return os.path.basename(path), parsed
        except (OSError, json.JSONDecodeError):
            continue
    return None


def sync_readme_artifact() -> bool:
    """Rewrite ONLY the 'Driver artifact of record' citation inside
    README's marked perf block to the newest BENCH_r*.json — no
    benchmarks run, so this works off-chip. Round-4 VERDICT weak #2: the
    regen-on-measure guard couldn't fire for an artifact captured AFTER
    the last measurement run (the driver writes BENCH_r{N} when the round
    ends); this mode + the suite's anti-drift test close that hole.
    Returns True if README changed."""
    import re

    art = newest_bench_artifact()
    if art is None:
        return False
    name, parsed = art
    vsb = parsed.get("vs_baseline", parsed["value"] / 1e5)
    # same qualification rules as _update_readme: significant digits for
    # sub-10x rows, platform/precision arms carried into the citation so
    # a CPU sweep row can never read like a chip record
    vsb_txt = f"{vsb:,.0f}x" if vsb >= 10 else f"{vsb:.3g}x"
    quals = [str(parsed[k]) for k in ("platform", "precision") if parsed.get(k)]
    qual_txt = f" ({', '.join(quals)} arm)" if quals else ""
    new_cite = (
        f"Driver artifact of record `{name}`: "
        f"{parsed['value']:,.0f} steps/s{qual_txt} ({vsb_txt} target)."
    )
    with open("README.md") as f:
        readme = f.read()
    out, n = re.subn(
        r"Driver artifact of record `BENCH_r\d+\.json`: [\d,]+ steps/s"
        r"(?: \([^)]*arm\))? \([\d.,]+x target\)\.",
        new_cite,
        readme,
    )
    if n and out != readme:
        with open("README.md", "w") as f:
            f.write(out)
        print(f"README artifact-of-record synced to {name}")
        return True
    if n == 0:
        print(
            "WARNING: README's 'Driver artifact of record' sentence did "
            "not match the expected format — nothing synced. Re-run "
            "`python perf_report.py` (full regen) or restore the "
            "footnote's wording.",
        )
    return False


def _update_readme(rows) -> None:
    """Regenerate README's measured-throughput table from THIS run plus
    the newest driver BENCH artifact on disk, so the three sources
    (README / PERF.md / BENCH_r0N.json) cannot drift (round-3 VERDICT
    weak #2). Rewrites only the marked block; wall-clock learning rows
    outside the markers are separate end-to-end runs and stay manual."""
    start, end = "<!-- PERF-TABLE-START -->", "<!-- PERF-TABLE-END -->"
    try:
        with open("README.md") as f:
            readme = f.read()
    except OSError:
        return
    if start not in readme or end not in readme:
        print("README markers not found; table not updated")
        return

    artifact = newest_bench_artifact()

    head = rows[0]
    art_txt = ""
    if artifact:
        vsb = artifact[1].get("vs_baseline", artifact[1]["value"] / 1e5)
        # sub-10x artifacts keep significant digits (same rule as the
        # table rows), and rows that record platform/precision arms
        # (bench.py --precision) carry them into the citation — a CPU
        # sweep row must never read like a chip record
        vsb_txt = f"{vsb:,.0f}x" if vsb >= 10 else f"{vsb:.3g}x"
        quals = [
            str(artifact[1][k])
            for k in ("platform", "precision")
            if artifact[1].get(k)
        ]
        qual_txt = f" ({', '.join(quals)} arm)" if quals else ""
        art_txt = (
            f" Driver artifact of record `{artifact[0]}`: "
            f"{artifact[1]['value']:,.0f} steps/s{qual_txt} "
            f"({vsb_txt} target)."
        )
    body = [
        "| Workload (BASELINE config class) | Geometry | env steps/s/chip | vs 100k north star |",
        "|---|---|---|---|",
    ]
    for r in rows:
        x = r["env_steps_per_s"] / 1e5
        body.append(
            "| {w} | {g} | **{s:,.0f}** | {x} |".format(
                w=r["workload"], g=r["geometry"],
                s=r["env_steps_per_s"],
                # sub-1x rows (the host-env plane pays the tunnel tax per
                # step) get significant digits instead of rounding to a
                # bogus "0x" — %g keeps tiny ratios visible (0.004x)
                x=f"{x:,.0f}x" if x >= 10 else f"{x:.3g}x",
            )
        )
    body += [
        "",
        f"_Table generated by `perf_report.py` (device_get-fenced, this "
        f"run's measurements; headline iter {head['iter_ms']:.1f} ms, "
        f"MFU {head.get('mfu', 0) * 100:.2f}%).{art_txt} Full breakdown "
        "and per-phase attributions: `PERF.md`._",
    ]
    new = (
        readme[: readme.index(start) + len(start)]
        + "\n"
        + "\n".join(body)
        + "\n"
        + readme[readme.index(end):]
    )
    with open("README.md", "w") as f:
        f.write(new)
    print("updated README.md perf table")


if __name__ == "__main__":
    main()
