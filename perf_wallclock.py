"""Multi-seed wall-clock campaign (round-5 VERDICT weak #1: every README
wall-clock row was a single-seed run; the pong 2.5-vs-4.5-min spread was
attributed to "compile + seed variance" without data).

Runs the two headline wall-clock workloads across seeds on the real chip,
separating COMPILE time (start -> first iteration's metrics fence) from
TRAIN time (first fence -> target reached):

- PPO on ``jax:lift`` to 1000 episode return (BASELINE north-star
  time-to-reward: < 10 min on a v5e-8; we run ONE chip);
- IMPALA+NatureCNN on pixel ``jax:pong`` to +5 return (the round-3 bar).

Seeds share one process per workload: seed 0 pays XLA compile, later
seeds reuse the jit cache — so the IN-PROCESS cold/warm split is measured
directly instead of estimated. Writes ``WALLCLOCK_r05.json``; README's
wall-clock rows cite its medians.

``--compile-cache DIR`` additionally enables the persistent XLA compile
cache (session.compile_cache_dir) for the CROSS-PROCESS split: the first
invocation against an empty DIR is the cold run (misses populate the
cache), a rerun of the same command is the warm run — its seed-0
``compile_to_first_iter_s`` now measures cache deserialization instead
of XLA compilation, which is the number the dispatch-pipeline PR's
compile-cache knob exists to shrink. Each row records the process-global
hit/miss counters so cold and warm artifacts are self-describing.

``--host-path`` switches to the host data-plane campaign instead: the
SEED trainer at the PERF.md dm_control geometry (4 process workers x 8
CPU MuJoCo envs x 64 horizon — the round-5 record of 288 env steps/s),
measured once per transport (shm, then the pickle fallback) so the
artifact carries the zero-copy split directly. Writes a
``BENCH_host.json`` artifact with the NEGOTIATED transport recorded
(server gauges, not the requested knob), reusing bench.py's bounded
retry/backoff on backend-init outages and its structured failed-round
artifact on exhaustion. Also reachable as ``python bench.py --host-path``.

Usage: python perf_wallclock.py [--seeds 3] [--compile-cache DIR] [--out F]
       python perf_wallclock.py --host-path [--out BENCH_host.json]
"""

from __future__ import annotations

import json
import time

import jax

COMPILE_CACHE_DIR = None  # set by --compile-cache; threaded into configs
AUTOTUNE = "off"          # set by --autotune (off|cache|search); every row
TUNING_CACHE_DIR = None   # records the ACTIVE tuner decision regardless, so
                          # artifacts can't silently mix tuned/untuned arms


def run_to_target(trainer_factory, target: float, seeds, max_minutes=12.0):
    """For each seed: fresh Trainer (same process -> warm jit cache after
    the first), run until rolling episode/return >= target. Returns a list
    of per-seed dicts."""
    out = []
    for i, seed in enumerate(seeds):
        trainer = trainer_factory(seed)
        t_start = time.perf_counter()
        marks = {"first_metric": None, "hit": None}

        def on_m(it, m, marks=marks, t_start=t_start):
            now = time.perf_counter()
            if marks["first_metric"] is None:
                marks["first_metric"] = now
            r = m.get("episode/return")
            if r is not None and r == r and r >= target:  # r==r: NaN guard
                marks["hit"] = now
                return True
            return (now - t_start) > max_minutes * 60

        trainer.run(on_metrics=on_m)
        total = (marks["hit"] or time.perf_counter()) - t_start
        compile_s = (marks["first_metric"] or time.perf_counter()) - t_start
        from surreal_tpu.utils.compat import compile_cache_counts

        row = {
            "seed": seed,
            "cold": i == 0,  # in-process jit-cache cold (cross-process
                             # cold/warm = empty vs populated --compile-cache)
            "reached_target": marks["hit"] is not None,
            "total_s": total,
            "compile_to_first_iter_s": compile_s,
            "train_s": total - compile_s,
            "compile_cache": dict(
                compile_cache_counts(), dir=COMPILE_CACHE_DIR
            ) if COMPILE_CACHE_DIR else None,
            # the active autotuner decision (surreal_tpu/tune/): mode,
            # cache hit/miss, applied config — tuned and untuned runs
            # must be distinguishable in the artifact
            "tuning": trainer.tune_decision.artifact()
            if hasattr(trainer, "tune_decision") else None,
        }
        out.append(row)
        print(json.dumps(row, default=float), flush=True)
    return out


def lift_trainer(seed: int):
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=128, epochs=4, num_minibatches=4,
                        autotune=AUTOTUNE),
        ),
        env_config=Config(name="jax:lift", num_envs=2048),
        session_config=Config(
            folder=f"/tmp/wallclock_lift_{seed}",
            compile_cache_dir=COMPILE_CACHE_DIR,
            tuning_cache_dir=TUNING_CACHE_DIR,
            seed=seed,
            total_env_steps=10**12,
            # metrics cadence matters on the tunneled chip: every_n_iters=1
            # forces a ~120 ms device_get sync per iteration (~5x slowdown
            # at a 30 ms iter). 5 matches the round-4 runs this campaign
            # multi-seeds, keeping the threshold-check cadence comparable.
            metrics=Config(every_n_iters=5, tensorboard=False, console=False),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    return Trainer(cfg)


def pong_trainer(seed: int):
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    cfg = Config(
        learner_config=Config(
            algo=Config(name="impala", horizon=32, autotune=AUTOTUNE),
            model=Config(cnn=Config(enabled=True)),
        ),
        env_config=Config(name="jax:pong", num_envs=1024),
        session_config=Config(
            folder=f"/tmp/wallclock_pong_{seed}",
            compile_cache_dir=COMPILE_CACHE_DIR,
            tuning_cache_dir=TUNING_CACHE_DIR,
            seed=seed,
            total_env_steps=10**12,
            # every 10, matching the round-4 pong run (see lift note)
            metrics=Config(every_n_iters=10, tensorboard=False, console=False),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    return Trainer(cfg)


# -- host data plane (--host-path) -------------------------------------------

HOST_BASELINE_SPS = 288.0  # PERF.md round-5 host-path record (best of
                           # alternate/overlap/SEED-4-proc at this geometry)
HOST_WORKERS = 4
HOST_WORKER_ENVS = 8
HOST_HORIZON = 64
HOST_WARM_ITERS = 3
HOST_MEAS_ITERS = 24


def _host_path_measure(transport: str) -> dict:
    """One SEED run at the PERF.md dm_control geometry; returns the row
    with the NEGOTIATED transport recorded (the server's gauges, not the
    requested knob — a denied shm grant must not masquerade)."""
    import shutil
    import tempfile

    from surreal_tpu.launch.seed_trainer import SEEDTrainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    folder = tempfile.mkdtemp(prefix="bench_host_")
    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=HOST_HORIZON, epochs=4,
                        num_minibatches=4),
        ),
        env_config=Config(
            name="dm_control:cheetah-run", num_envs=HOST_WORKER_ENVS
        ),
        session_config=Config(
            folder=folder,
            total_env_steps=10**12,
            metrics=Config(every_n_iters=1, tensorboard=False, console=False),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
            topology=Config(
                num_env_workers=HOST_WORKERS,
                worker_mode="process",
                transport=transport,
            ),
        ),
    ).extend(base_config())
    trainer = SEEDTrainer(cfg)
    marks: list[tuple[float, float]] = []  # (t, env_steps) per metrics fire
    last = {}

    def on_m(it, m):
        marks.append((time.perf_counter(), m["time/env_steps"]))
        last.update(m)
        return len(marks) >= HOST_WARM_ITERS + HOST_MEAS_ITERS

    try:
        trainer.run(on_metrics=on_m)
    finally:
        shutil.rmtree(folder, ignore_errors=True)
    t0, s0 = marks[HOST_WARM_ITERS - 1]
    t1, s1 = marks[-1]
    n = len(marks) - HOST_WARM_ITERS
    return {
        "requested_transport": transport,
        "env_steps_per_s": (s1 - s0) / (t1 - t0),
        "iter_ms": (t1 - t0) / n * 1e3,
        "pipeline_workers": trainer.pipeline_workers,
        # active autotuner decision ('off' here unless the config opts in)
        "tuning": trainer.tune_decision.artifact(),
        # negotiated reality, from the server gauges riding the metrics
        "transport": {
            k.split("/", 1)[1]: v
            for k, v in last.items()
            if k in (
                "server/shm_workers", "server/pickle_workers",
                "server/wire_bytes_per_step", "server/pipeline_occupancy",
            )
        },
    }


def host_path_main(argv) -> int:
    """--host-path driver: measure shm then the pickle fallback, write the
    BENCH_host.json-style artifact. Bounded retry/backoff on backend-init
    outages and a structured ``{"error": ..., "parsed": null}`` artifact
    on exhaustion come from bench.py (the PR-2 handling, reused)."""
    import sys

    from bench import RETRY_ATTEMPTS, RETRY_BACKOFF_S, _is_retryable, _reset_backends

    out_path = "BENCH_host.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    try:
        import dm_control  # noqa: F401
    except Exception as e:
        result = {"error": f"dm_control unavailable: {e}", "parsed": None}
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
        print(json.dumps(result))
        return 0
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            shm_row = _host_path_measure("shm")
            pickle_row = _host_path_measure("pickle")
            sps = shm_row["env_steps_per_s"]
            result = {
                "metric": "host_env_steps_per_sec_seed_cheetah",
                "value": round(sps, 1),
                "unit": "env_steps/s",
                "geometry": (
                    f"{HOST_WORKERS} process workers x {HOST_WORKER_ENVS} "
                    f"dm_control:cheetah-run envs x {HOST_HORIZON} horizon"
                ),
                "host_baseline_sps": HOST_BASELINE_SPS,
                "vs_host_baseline": round(sps / HOST_BASELINE_SPS, 2),
                "shm": shm_row,
                "pickle": pickle_row,
                # the device actually measured (bench.py discipline: a CPU
                # fallback must never masquerade as a chip number)
                "device": str(jax.devices()[0].device_kind),
                "platform": str(jax.devices()[0].platform),
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2, default=float)
            print(json.dumps(result, default=float))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"host-path attempt {attempt + 1}/{RETRY_ATTEMPTS} failed "
                    f"({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    result = {"error": err, "parsed": None}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


# -- sharded experience plane (--experience-plane) ----------------------------

XP_SHM_WIRE_RECORD = 5.8  # PR-3 slab record (wire B/step, BENCH_host.json)
XP_NUM_ENVS = 8
XP_HORIZON = 32
XP_UPDATES = 8
XP_BATCH = 128
XP_SHARDS = 2
XP_WARM = 4
XP_MEAS = 16


def _xp_trainer(kind: str, transport: str, folder: str, seed: int = 0,
                tiers=None):
    from surreal_tpu.launch.offpolicy_trainer import OffPolicyTrainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    replay = Config(
        kind="remote" if kind == "remote" else "uniform",
        remote_kind="uniform",
        capacity=16_384, start_sample_size=512, batch_size=XP_BATCH,
    )
    if tiers is not None:
        replay.tiers = Config(tiers)
    cfg = Config(
        learner_config=Config(
            algo=Config(
                name="ddpg", horizon=XP_HORIZON,
                updates_per_iter=XP_UPDATES,
                exploration=Config(warmup_steps=0),
            ),
            replay=replay,
        ),
        env_config=Config(name="gym:Pendulum-v1", num_envs=XP_NUM_ENVS),
        session_config=Config(
            folder=folder,
            seed=seed,
            total_env_steps=10**12,
            metrics=Config(every_n_iters=1, tensorboard=False, console=False),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
            topology=Config(
                experience_plane=Config(
                    num_shards=XP_SHARDS, shard_mode="thread",
                    transport=transport,
                ),
            ),
        ),
    ).extend(base_config())
    return OffPolicyTrainer(cfg)


def _xp_measure(kind: str, transport: str, tiers=None, arm=None) -> dict:
    """One off-policy run (remote plane arm, or the in-process reference)
    at the local-shards geometry; warm iterations discarded. Records the
    settled experience gauges and the fixed-seed reward trajectory so the
    remote-vs-in-process curves ride the artifact."""
    import shutil
    import tempfile

    folder = tempfile.mkdtemp(prefix="bench_xp_")
    trainer = _xp_trainer(kind, transport, folder, tiers=tiers)
    marks: list[tuple[float, float]] = []
    returns: list = []
    last: dict = {}

    def on_m(it, m):
        marks.append((time.perf_counter(), m["time/env_steps"]))
        r = m.get("episode/return")
        if r is not None and r == r:
            returns.append(round(float(r), 2))
        last.update(m)
        return len(marks) >= XP_WARM + XP_MEAS

    try:
        trainer.run(on_metrics=on_m)
    finally:
        shutil.rmtree(folder, ignore_errors=True)
    t0, s0 = marks[XP_WARM - 1]
    t1, s1 = marks[-1]
    n = len(marks) - XP_WARM
    row = {
        "arm": arm or (kind if kind != "remote" else f"remote-{transport}"),
        "env_steps_per_s": round((s1 - s0) / (t1 - t0), 1),
        "iter_ms": round((t1 - t0) / n * 1e3, 2),
        "episode_returns": returns,
        "final_return": returns[-1] if returns else None,
    }
    if kind == "remote":
        row.update({
            "wire_bytes_per_step": last.get("experience/wire_bytes_per_step"),
            "sample_wait_ms": last.get("experience/sample_wait_ms"),
            "shards_live": last.get("experience/shards_live"),
            "rows_ingested": last.get("experience/rows"),
            "dropped_rows": last.get("experience/dropped_rows"),
            "respawns": last.get("experience/respawns"),
        })
        tier = {k: v for k, v in last.items() if k.startswith("tier/")}
        if tier:
            row["tiers"] = tier
            row["env_steps"] = last.get("time/env_steps")
    return row


def experience_plane_main(argv) -> int:
    """--experience-plane driver (ISSUE 8 satellite): measure the remote
    plane per transport arm (shm / tcp / pickle, 2 local thread shards)
    against the in-process replay reference at the same fixed-seed
    geometry; write the BENCH_experience.json artifact perf_gate's
    experience gate and PERF.md's generated section consume. Platform is
    recorded honestly; the shm arm's wire-bytes and the learner
    sample-wait are the gated commitments."""
    import sys

    from bench import RETRY_ATTEMPTS, RETRY_BACKOFF_S, _is_retryable, _reset_backends

    out_path = "BENCH_experience.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    try:
        import gymnasium  # noqa: F401
    except Exception as e:
        result = {"error": f"gymnasium unavailable: {e}", "parsed": None}
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
        print(json.dumps(result))
        return 0
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            inproc = _xp_measure("inprocess", "auto")
            arms = {
                t: _xp_measure("remote", t) for t in ("shm", "tcp", "pickle")
            }
            shm = arms["shm"]
            result = {
                "metric": "experience_plane_env_steps_per_sec_ddpg_pendulum",
                "value": shm["env_steps_per_s"],
                "unit": "env_steps/s",
                "geometry": (
                    f"{XP_NUM_ENVS} gym:Pendulum-v1 envs x {XP_HORIZON} "
                    f"horizon x {XP_UPDATES} updates/iter (batch "
                    f"{XP_BATCH}) over {XP_SHARDS} local thread shards"
                ),
                "shards": XP_SHARDS,
                "shard_mode": "thread",
                "shm_wire_record_bps": XP_SHM_WIRE_RECORD,
                "inprocess": inproc,
                "shm": shm,
                "tcp": arms["tcp"],
                "pickle": arms["pickle"],
                # the device actually measured (bench.py discipline)
                "device": str(jax.devices()[0].device_kind),
                "platform": str(jax.devices()[0].platform),
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2, default=float)
            print(json.dumps(result, default=float))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"experience-plane attempt {attempt + 1}/{RETRY_ATTEMPTS}"
                    f" failed ({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    result = {"error": err, "parsed": None}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


# -- replay tiers (--replay-tiers) --------------------------------------------

def replay_tiers_main(argv) -> int:
    """--replay-tiers driver (ISSUE 18): the hierarchical-replay
    acceptance artifact. Two arms at the --experience-plane geometry
    (shm transport, 2 local thread shards):

      warm  replay.tiers absent — every update batch rides the PR-8
            shard fan-in (wire frame + spec.unpack + host->device put)
      hot   tiers on — steady-state batches drawn ON DEVICE from the
            hot ring at request time; the shards become the warm
            fallback and the spill WAL runs alongside ingest

    Committed figures: both arms' settled experience/sample_wait_ms
    (the acceptance criterion: hot below warm), the WAL's append
    bytes/env-step, and quantized vs raw cold bytes/transition.

    One-core honesty: on a single-core CPU box the hot arm's THROUGHPUT
    need not win — the same core still pays rollout + ingest + WAL
    encode; what the device-resident tier removes is the learner-side
    sample path (wait + transfer), which is exactly what sample_wait_ms
    isolates. The artifact records env_steps/s for both arms unmassaged.
    """
    import sys

    from bench import RETRY_ATTEMPTS, RETRY_BACKOFF_S, _is_retryable, _reset_backends

    out_path = "BENCH_tiers.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    try:
        import gymnasium  # noqa: F401
    except Exception as e:
        result = {"error": f"gymnasium unavailable: {e}", "parsed": None}
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
        print(json.dumps(result))
        return 0
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            warm = _xp_measure("remote", "shm", arm="warm")
            hot = _xp_measure(
                "remote", "shm",
                tiers={
                    "hot": {"enabled": True, "capacity": 4096},
                    "spill": {"enabled": True},
                },
                arm="hot",
            )
            tiers = hot.get("tiers", {})
            steps = float(hot.get("env_steps") or 1)
            # raw f32 row of the Pendulum transition spec — the
            # quantization denominator (obs 3 + next_obs 3 + action 1 +
            # reward 1 + discount 1 floats)
            raw_row = 9 * 4
            cold_row = tiers.get("tier/cold_bytes_per_row")
            result = {
                "metric": "replay_tiers_hot_sample_wait_ms",
                "value": hot.get("sample_wait_ms"),
                "unit": "ms",
                "geometry": (
                    f"{XP_NUM_ENVS} gym:Pendulum-v1 envs x {XP_HORIZON} "
                    f"horizon x {XP_UPDATES} updates/iter (batch "
                    f"{XP_BATCH}) over {XP_SHARDS} local thread shards, "
                    "shm transport; hot ring 4096"
                ),
                "warm": warm,
                "hot": hot,
                "hot_hits": tiers.get("tier/hot_hits"),
                "hot_misses": tiers.get("tier/hot_misses"),
                "wal_bytes_per_step": (
                    round(float(tiers.get("tier/spill_bytes", 0)) / steps, 2)
                ),
                "raw_bytes_per_transition": raw_row,
                "cold_bytes_per_transition": cold_row,
                "cold_vs_raw_ratio": (
                    round(float(cold_row) / raw_row, 3)
                    if cold_row else None
                ),
                "torn_segments": tiers.get("tier/torn_segments", 0),
                "notes": (
                    "one-core honesty: throughput parity expected on a "
                    "shared-core CPU box; the committed win is the "
                    "learner-side sample wait (hot draw dispatches "
                    "on-device at request time) and the quantized cold "
                    "row. Wait figures are settled EWMAs from the final "
                    "metrics row of each arm."
                ),
                "device": str(jax.devices()[0].device_kind),
                "platform": str(jax.devices()[0].platform),
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2, default=float)
            print(json.dumps(result, default=float))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"replay-tiers attempt {attempt + 1}/{RETRY_ATTEMPTS}"
                    f" failed ({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    result = {"error": err, "parsed": None}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


# -- autoscaling act-serving tier (--act-path) --------------------------------

ACT_WORKERS = 2
ACT_WORKER_ENVS = 8
ACT_HORIZON = 32
ACT_WARM = 3
ACT_MEAS = 12
ACT_REPLICAS = 2
# the one-core honesty bound gate_act enforces. On a box with ONE core
# the N-replica arm cannot win: the fleet splits each lockstep round's
# single coalesced forward into N SERIAL smaller forwards (per-dispatch
# overhead dominates a small CPU MLP act), and the extra serve thread
# contends with the learner for the same core — measured ~0.67x at this
# geometry. The local commitment is therefore "replication does not
# COLLAPSE throughput" (>= 0.5x single); the >= 1x scaling claim needs
# cores for the replicas to actually run on, recorded when a multi-core
# measurement round exists.
ACT_HONESTY_RATIO = 0.5
FANOUT_PUBLISHES = 12
FANOUT_HIDDEN = (256, 256)  # big enough that frame bytes dominate headers


def _act_measure(replicas: int) -> dict:
    """One SEED run at the act-path geometry with ``replicas`` inference
    servers; returns the row with serve p50/p99 from the session's own
    ``hops`` telemetry (the PR-1/PR-6 gauges, not a bench-side timer)."""
    import shutil
    import tempfile

    from surreal_tpu.launch.seed_trainer import SEEDTrainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config
    from surreal_tpu.session.telemetry import diag_summary

    folder = tempfile.mkdtemp(prefix="bench_act_")
    cfg = Config(
        learner_config=Config(
            algo=Config(name="impala", horizon=ACT_HORIZON),
        ),
        env_config=Config(name="gym:CartPole-v1", num_envs=ACT_WORKER_ENVS),
        session_config=Config(
            folder=folder,
            total_env_steps=10**12,
            metrics=Config(every_n_iters=1, tensorboard=False, console=False),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
            topology=Config(
                num_env_workers=ACT_WORKERS,
                inference_fleet=Config(replicas=replicas),
            ),
        ),
    ).extend(base_config())
    trainer = SEEDTrainer(cfg)
    marks: list[tuple[float, float]] = []
    last: dict = {}

    def on_m(it, m):
        marks.append((time.perf_counter(), m["time/env_steps"]))
        last.update(m)
        return len(marks) >= ACT_WARM + ACT_MEAS

    try:
        trainer.run(on_metrics=on_m)
        hops = (diag_summary(folder) or {}).get("hops") or {}
    finally:
        shutil.rmtree(folder, ignore_errors=True)
    t0, s0 = marks[ACT_WARM - 1]
    t1, s1 = marks[-1]
    n = len(marks) - ACT_WARM
    serve = hops.get("serve_batch_ms") or {}
    return {
        "replicas": replicas,
        "env_steps_per_s": round((s1 - s0) / (t1 - t0), 1),
        "iter_ms": round((t1 - t0) / n * 1e3, 2),
        "serve_ms_p50": serve.get("p50"),
        "serve_ms_p99": serve.get("p99"),
        "serve_ms_ewma": last.get("server/serve_ms"),
        "chunk_age_s": last.get("server/chunk_age_s"),
        "replicas_live": last.get("fleet/replicas_live"),
        "tuning": trainer.tune_decision.artifact(),
    }


def _fanout_measure() -> dict:
    """Bytes-per-publish across the fanout arms, against the
    point-to-point baseline (one full msgpack blob per fetch — what
    every subscriber used to cost PER CLIENT). Versions simulate SGD
    steps (small fixed-seed perturbations); the steady figure excludes
    the first (necessarily full) key frame."""
    import numpy as np

    from surreal_tpu.agents import make_agent
    from surreal_tpu.distributed.module_dict import dumps_pytree
    from surreal_tpu.distributed.param_fanout import (
        ParameterFanout,
        ParameterSubscriber,
    )
    from surreal_tpu.envs.base import ArraySpec, EnvSpecs
    from surreal_tpu.learners import build_learner
    from surreal_tpu.session.config import Config

    import jax

    specs = EnvSpecs(
        obs=ArraySpec(shape=(24,), dtype=np.dtype(np.float32)),
        action=ArraySpec(shape=(4,), dtype=np.dtype(np.float32)),
    )
    learner = build_learner(
        Config(algo=Config(name="ppo"),
               model=Config(actor_hidden=FANOUT_HIDDEN,
                            critic_hidden=FANOUT_HIDDEN)),
        specs,
    )
    state = learner.init(jax.random.key(0))
    view = make_agent(learner).acting_view(state)
    baseline_bytes = len(dumps_pytree(view))
    leaves = [np.asarray(l) for l in jax.device_get(jax.tree.leaves(view))]
    rng = np.random.default_rng(0)

    def version_stream():
        """Successive acting views one small SGD-sized step apart."""
        cur = [np.array(l) for l in leaves]
        treedef = jax.tree.structure(view)
        while True:
            yield jax.tree.unflatten(treedef, cur)
            cur = [
                (l + 1e-3 * rng.standard_normal(l.shape).astype(l.dtype))
                if np.issubdtype(l.dtype, np.floating) else l
                for l in cur
            ]

    arms = {}
    for name, wire, delta in (
        ("full_f32", "f32", False),
        ("delta", "f32", True),
        ("bf16", "bf16", False),
        ("delta_bf16", "bf16", True),
    ):
        fan = ParameterFanout(wire=wire, delta=delta)
        sub = ParameterSubscriber(fan.address, fan.ack_address, view)
        time.sleep(0.3)  # SUB join
        stream = version_stream()
        sizes = []
        err = 0.0
        params = None
        for k in range(FANOUT_PUBLISHES):
            params = next(stream)
            info = fan.publish(params)
            sizes.append(info["bytes"])
            deadline = time.time() + 5.0
            while sub.version < info["version"] and time.time() < deadline:
                sub.poll(timeout_ms=50)
            time.sleep(0.02)  # let the ack land before the next publish
        got = jax.tree.leaves(sub.params)
        want = jax.tree.leaves(params)
        err = max(
            float(np.max(np.abs(np.asarray(a, np.float64) - np.asarray(b, np.float64))))
            for a, b in zip(got, want)
        )
        arms[name] = {
            "wire": wire,
            "delta": delta,
            "first_frame_bytes": sizes[0],
            "bytes_per_publish": round(
                sum(sizes[1:]) / max(len(sizes) - 1, 1), 1
            ),
            "frames": dict(full=fan.full_frames, delta=fan.delta_frames,
                           rekeys=fan.rekeys),
            "reconstruct_abs_err_max": err,
            "subscriber_applied": sub.applied,
        }
        sub.close()
        fan.close()
    return {
        "pointtopoint_fetch_bytes": baseline_bytes,
        "publishes_per_arm": FANOUT_PUBLISHES,
        "model_hidden": list(FANOUT_HIDDEN),
        "arms": arms,
    }


def act_path_main(argv) -> int:
    """--act-path driver (ISSUE 10): the serving-tier campaign —
    1 vs N inference-server replicas through the real SEED trainer at a
    one-core-feasible geometry (serve p50/p99 + env steps/s), plus
    bytes-per-publish for the parameter-fanout arms (full f32 / delta /
    bf16 / delta+bf16) against the point-to-point fetch baseline.
    Writes BENCH_act.json (perf_gate.gate_act and PERF.md's generated
    section consume it), with bench.py's bounded retry/backoff and
    structured failed-round artifact."""
    import sys

    from bench import RETRY_ATTEMPTS, RETRY_BACKOFF_S, _is_retryable, _reset_backends

    out_path = "BENCH_act.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    try:
        import gymnasium  # noqa: F401
    except Exception as e:
        result = {"error": f"gymnasium unavailable: {e}", "parsed": None}
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
        print(json.dumps(result))
        return 0
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            single = _act_measure(1)
            fleet = _act_measure(ACT_REPLICAS)
            fanout = _fanout_measure()
            result = {
                "metric": "act_path_env_steps_per_sec_seed_cartpole",
                "value": fleet["env_steps_per_s"],
                "unit": "env_steps/s",
                "geometry": (
                    f"{ACT_WORKERS} thread workers x {ACT_WORKER_ENVS} "
                    f"gym:CartPole-v1 envs x {ACT_HORIZON} horizon, "
                    f"1 vs {ACT_REPLICAS} inference-server replicas"
                ),
                "act_honesty_ratio": ACT_HONESTY_RATIO,
                "single": single,
                "fleet": fleet,
                "fanout": fanout,
                # the device actually measured (bench.py discipline)
                "device": str(jax.devices()[0].device_kind),
                "platform": str(jax.devices()[0].platform),
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2, default=float)
            print(json.dumps(result, default=float))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"act-path attempt {attempt + 1}/{RETRY_ATTEMPTS} failed "
                    f"({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    result = {"error": err, "parsed": None}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


# -- session gateway (--gateway) ----------------------------------------------

GW_ATTACHES = 32        # attach-latency sample size
GW_ACTS = 150           # act-RTT sample size per arm
GW_DISTINCT_OBS = 12    # duplicated-obs workload: 12 distinct obs cycled
GW_OBS_BATCH = 16       # policy forward geometry — a real numpy MLP cost
GW_POLICY_DIM = 512     # (~17 MFLOP/forward) so the ratio measures gateway
                        # overhead on a policy-sized act, not on a no-op
# the one-core honesty bound gate_gateway enforces on act RTT: the
# gateway arm pays the tenant wire round-trip (DEALER->ROUTER->serve->
# reply) ON TOP of the same fleet forward the direct arm times
# in-process, and on a box with ONE core the client, the gateway loop,
# and the serving fleet all contend for it. The local commitment is
# therefore "the session tier does not DOUBLE the act latency"
# (p50 RTT <= 2x the direct in-process serve); sub-1.2x ratios need
# cores for the gateway loop to actually run on, recorded when a
# multi-core measurement round exists.
GW_RTT_RATIO_MAX = 2.0


def _gateway_policy():
    """A numpy MLP act closure sized so the FORWARD dominates framing —
    the honest denominator for the wire-overhead ratio."""
    import numpy as np

    rng = np.random.default_rng(0)
    w1 = rng.standard_normal((GW_POLICY_DIM, GW_POLICY_DIM)).astype(
        np.float32
    ) / np.sqrt(GW_POLICY_DIM)
    w2 = rng.standard_normal((GW_POLICY_DIM, 2)).astype(np.float32)

    def act_fn(obs):
        h = np.maximum(obs @ w1, 0.0)
        logits = h @ w2
        return np.argmax(logits, axis=-1), {}

    return act_fn


def _gateway_measure() -> dict:
    """The session-gateway campaign (standalone — no trainer): attach
    p50/p99, act RTT p50/p99 through the gateway wire vs the SAME fleet
    forward called in-process (cache disabled for the overhead arm), and
    the act-cache split on a duplicated-obs workload (hit rate + hit vs
    served latency)."""
    import numpy as np

    from surreal_tpu.distributed.fleet import InferenceFleet
    from surreal_tpu.gateway import GatewaySession, GatewayServer

    def pctl(samples_ms):
        arr = np.asarray(samples_ms)
        return {
            "p50": round(float(np.percentile(arr, 50)), 3),
            "p99": round(float(np.percentile(arr, 99)), 3),
        }

    obs_pool = [
        np.random.default_rng(i).standard_normal(
            (GW_OBS_BATCH, GW_POLICY_DIM)
        ).astype(np.float32)
        for i in range(GW_DISTINCT_OBS)
    ]
    fleet = InferenceFleet(
        _gateway_policy(), num_workers=2, replicas=2, unroll_length=4
    )
    try:
        # arm 1: direct-to-fleet — the same serve_act ingress the gateway
        # calls, timed in-process (the floor the wire overhead sits on)
        direct_ms = []
        for k in range(GW_ACTS):
            obs = obs_pool[k % GW_DISTINCT_OBS]
            t0 = time.perf_counter()
            fleet.serve_act(obs)
            direct_ms.append((time.perf_counter() - t0) * 1e3)

        # arm 2: through the gateway, cache OFF — every act pays the wire
        # AND the forward, so the ratio isolates the session tier's cost
        server = GatewayServer(fleet, act_cache=0)
        attach_ms = []
        for _ in range(GW_ATTACHES):
            t0 = time.perf_counter()
            s = GatewaySession(
                server.address, obs_shape=(GW_OBS_BATCH, GW_POLICY_DIM)
            )
            attach_ms.append((time.perf_counter() - t0) * 1e3)
            s.close()
        sess = GatewaySession(
            server.address, obs_shape=(GW_OBS_BATCH, GW_POLICY_DIM)
        )
        rtt_ms = []
        for k in range(GW_ACTS):
            obs = obs_pool[k % GW_DISTINCT_OBS]
            t0 = time.perf_counter()
            sess.act(obs)
            rtt_ms.append((time.perf_counter() - t0) * 1e3)
        sess.close()
        server.close()

        # arm 3: cache ON, duplicated-obs workload — hits must be
        # STRICTLY faster than served acts (they skip the forward)
        server = GatewayServer(fleet, act_cache=256)
        sess = GatewaySession(
            server.address, obs_shape=(GW_OBS_BATCH, GW_POLICY_DIM)
        )
        hit_ms, served_ms = [], []
        for k in range(GW_ACTS):
            obs = obs_pool[k % GW_DISTINCT_OBS]
            t0 = time.perf_counter()
            _, info = sess.act(obs)
            (hit_ms if info["cached"] else served_ms).append(
                (time.perf_counter() - t0) * 1e3
            )
        cache_hit_rate = server.event()["cache_hit_rate"]
        sess.close()
        server.close()
    finally:
        fleet.close()

    direct = pctl(direct_ms)
    rtt = pctl(rtt_ms)
    return {
        "attach_ms": pctl(attach_ms),
        "act_rtt_ms": rtt,
        "direct_ms": direct,
        "rtt_ratio_p50": round(rtt["p50"] / direct["p50"], 3),
        "cache": {
            "hit_rate": round(float(cache_hit_rate), 3),
            "hit_ms": pctl(hit_ms),
            "served_ms": pctl(served_ms),
            "distinct_obs": GW_DISTINCT_OBS,
            "acts": GW_ACTS,
        },
        "acts_per_arm": GW_ACTS,
        "policy": f"numpy MLP {GW_POLICY_DIM}x{GW_POLICY_DIM}x2, "
                  f"batch {GW_OBS_BATCH}",
    }


def gateway_main(argv) -> int:
    """--gateway driver (ISSUE 12): the session-gateway campaign —
    attach latency, act RTT through the gateway vs direct-to-fleet
    (one-core honesty ratio recorded), and the act-cache hit/served
    latency split at a duplicated-obs workload. Writes
    ``BENCH_gateway.json`` (perf_gate.gate_gateway and PERF.md's
    generated section consume it), with bench.py's bounded
    retry/backoff and structured failed-round artifact."""
    import sys

    from bench import RETRY_ATTEMPTS, RETRY_BACKOFF_S, _is_retryable, _reset_backends

    out_path = "BENCH_gateway.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            row = _gateway_measure()
            result = {
                "metric": "gateway_act_rtt_ms_p50",
                "value": row["act_rtt_ms"]["p50"],
                "unit": "ms",
                "geometry": (
                    f"2-replica fleet, {row['policy']}, "
                    f"{GW_ACTS} acts/arm, tcp loopback"
                ),
                "rtt_ratio_max": GW_RTT_RATIO_MAX,
                **row,
                # the device actually measured (bench.py discipline)
                "device": str(jax.devices()[0].device_kind),
                "platform": str(jax.devices()[0].platform),
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2, default=float)
            print(json.dumps(result, default=float))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"gateway attempt {attempt + 1}/{RETRY_ATTEMPTS} failed "
                    f"({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    result = {"error": err, "parsed": None}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


# -- ops plane (--ops-plane) --------------------------------------------------

OPS_SNAPSHOTS = 300     # snapshot-build sample size
OPS_PUSHES = 200        # per-tier push-cost sample size
OPS_WIRE_TIERS = 5      # gateway + 2 fleet replicas + 2 experience shards
OPS_ITER_TIMED = 10     # steady-state train iterations for the denominator
# the overhead commitment gate_ops enforces: building + writing one
# merged run snapshot (SLO evaluation included) costs <= 5% of one
# steady-state train iteration — observability must never become the
# workload
OPS_SNAPSHOT_FRAC_MAX = 0.05


def _ops_rows():
    """Representative per-tier rows at production shape: the gateway's
    tenant table + hops, per-replica queue stats, per-shard ring stats —
    what a live multi-tenant SEED run actually pushes each cadence."""
    gw_hops = {
        name: {"p50": 1.2, "p90": 3.4, "p99": 9.8, "n": 512}
        for name in ("gateway_act_ms", "gateway_transit_ms",
                     "gateway_attach_ms")
    }
    tenants = {
        f"tenant{i}": {"sessions": 3, "max_sessions": 8, "rate": 100.0,
                       "acts": 1000 + i, "queued": 2, "throttled": 5 * i,
                       "evicted": 0, "rejected": 1}
        for i in range(8)
    }
    gw_gauges = {f"gateway/{k}": float(v) for v, k in enumerate(
        ("sessions", "attaches", "reattaches", "detaches", "acts",
         "cache_hits", "cache_misses", "migrations", "catch_ups",
         "pinned_sessions", "dropped_replies", "bad_frames", "respawns")
    )}
    rows = [("gateway", dict(
        gauges=gw_gauges, hops=gw_hops,
        body={"tenants": tenants, "cache_hit_rate": 0.4, **gw_gauges},
    ))]
    for i in range(2):
        rows.append((f"fleet.replica{i}", dict(
            gauges={"server/requests": 5e4, "server/batches": 1e4,
                    "server/queue_depth": 3.0, "server/param_version": 40.0},
            hops={"serve_batch_ms": {"p50": 0.8, "p90": 1.1, "p99": 2.0,
                                     "n": 512}},
        )))
    for i in range(2):
        rows.append((f"experience.shard{i}", dict(
            gauges={"ingested_rows": 1e5, "sample_queue_depth": 4.0,
                    "ring_fill": 0.7},
            hops={"ingest_transit_ms": {"p50": 0.3, "p90": 0.6, "p99": 1.4,
                                        "n": 512}},
        )))
    return rows


def _ops_iter_ms() -> float:
    """The denominator: one steady-state fused train iteration at the
    committed headline geometry (BENCH_r06: PPO, 512 envs x 64 horizon),
    compile excluded — median of OPS_ITER_TIMED timed passes. epochs=1/
    num_minibatches=1 UNDERSTATES a production iteration, which makes
    the <= 5% commitment conservative, never flattering."""
    import tempfile

    from surreal_tpu.launch.rollout import init_device_carry
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    with tempfile.TemporaryDirectory() as folder:
        cfg = Config(
            learner_config=Config(
                algo=Config(name="ppo", horizon=64, epochs=1,
                            num_minibatches=1)
            ),
            env_config=Config(name="jax:cartpole", num_envs=512),
            session_config=Config(
                folder=folder, total_env_steps=0,
                metrics=Config(every_n_iters=0, tensorboard=False,
                               console=False),
                checkpoint=Config(every_n_iters=0),
                eval=Config(every_n_iters=0),
            ),
        ).extend(base_config())
        trainer = Trainer(cfg)
        key = jax.random.key(0)
        key, init_key, env_key = jax.random.split(key, 3)
        state = trainer.learner.init(init_key)
        carry = init_device_carry(trainer.env, env_key, trainer.num_envs)
        key, wk = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, wk)
        jax.block_until_ready(metrics)  # compile outside the timing
        samples = []
        for _ in range(OPS_ITER_TIMED):
            key, it_key = jax.random.split(key)
            t0 = time.perf_counter()
            state, carry, metrics = trainer._train_iter(state, carry, it_key)
            jax.block_until_ready(metrics)
            samples.append((time.perf_counter() - t0) * 1e3)
        samples.sort()
        return samples[len(samples) // 2]


def _ops_measure() -> dict:
    """The ops-plane campaign (standalone — no training run): per-tier
    push cost on the serve-loop side, snapshot-build cost (tier merge +
    SLO evaluation + flight-recorder append + atomic file write) on the
    learner side at a production tier census, and the steady-state
    iteration time the snapshot cost is judged against."""
    import tempfile

    import numpy as np

    from surreal_tpu.session.opsplane import OpsAggregator, OpsPusher

    def pctl(samples_ms):
        arr = np.asarray(samples_ms)
        return {
            "p50": round(float(np.percentile(arr, 50)), 4),
            "p99": round(float(np.percentile(arr, 99)), 4),
        }

    rows = _ops_rows()
    push_ms, snap_ms = [], []
    with tempfile.TemporaryDirectory() as folder:
        agg = OpsAggregator(
            folder, trace_id="bench",
            slo_cfg={"act_rtt_p99_ms": 50.0, "attach_p99_ms": 100.0,
                     "throttle_rate": 0.5, "staleness_updates": 10},
        )
        try:
            pushers = [
                OpsPusher(agg.address, tier, trace_id="bench",
                          min_interval_s=0.0)
                for tier, _ in rows
            ]
            for k in range(OPS_PUSHES):
                tier_row = rows[k % len(rows)][1]
                p = pushers[k % len(pushers)]
                t0 = time.perf_counter()
                p.push(force=True, **tier_row)
                push_ms.append((time.perf_counter() - t0) * 1e3)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len(agg._tiers) >= len(rows):
                    break
                time.sleep(0.01)
            # the learner-local tiers, at their real shapes
            agg.push_local("learner", gauges={
                f"perf/g{i}": float(i) for i in range(40)
            })
            agg.push_local("param_fanout", gauges={"version": 41.0})
            agg.push_local("fleet", body={"replicas": {
                str(i): {"alive": True, "param_version": 40}
                for i in range(2)
            }})
            for i in range(OPS_SNAPSHOTS):
                t0 = time.perf_counter()
                agg.snapshot(iteration=i, env_steps=i * 512)
                snap_ms.append((time.perf_counter() - t0) * 1e3)
            for p in pushers:
                p.close()
        finally:
            agg.close()
    iter_ms = _ops_iter_ms()
    snap = pctl(snap_ms)
    return {
        "snapshot_ms": snap,
        "push_ms": pctl(push_ms),
        "iter_ms": round(iter_ms, 3),
        "snapshot_frac_of_iter": round(snap["p50"] / iter_ms, 4),
        "tiers": len(rows) + 3,
        "snapshots": OPS_SNAPSHOTS,
        "workload": (
            f"{len(rows)} wire tiers + 3 learner-local rows, 8 tenants, "
            "4 SLO objectives; iter: PPO jax:cartpole 512x64 (1 epoch)"
        ),
    }


def ops_plane_main(argv) -> int:
    """--ops-plane driver (ISSUE 13): per-cadence cost of the live ops
    plane — tier push cost, snapshot build + SLO evaluation + atomic
    write, against the steady-state iteration time. Writes
    ``BENCH_ops.json`` (perf_gate.gate_ops and PERF.md's generated
    section consume it), with bench.py's bounded retry/backoff and
    structured failed-round artifact."""
    import sys

    from bench import RETRY_ATTEMPTS, RETRY_BACKOFF_S, _is_retryable, _reset_backends

    out_path = "BENCH_ops.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            row = _ops_measure()
            result = {
                "metric": "ops_snapshot_ms_p50",
                "value": row["snapshot_ms"]["p50"],
                "unit": "ms",
                "geometry": row["workload"],
                "snapshot_frac_max": OPS_SNAPSHOT_FRAC_MAX,
                **row,
                "device": str(jax.devices()[0].device_kind),
                "platform": str(jax.devices()[0].platform),
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2, default=float)
            print(json.dumps(result, default=float))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"ops-plane attempt {attempt + 1}/{RETRY_ATTEMPTS} "
                    f"failed ({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    result = {"error": err, "parsed": None}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


# -- causal tracing + lineage (--trace) ---------------------------------------

TRACE_SPANS = 2000       # span-emit microbench sample size
TRACE_LINEAGE_REPS = 50  # lineage-reduction sample size
TRACE_SAMPLE_N = 64      # the default head-sampling rate (telemetry.trace.*)
TRACE_WORKERS = 4        # worker streams at the headline SEED census
TRACE_HORIZON = 64       # requests per worker stream per iteration
# the overhead commitment gate_trace enforces: ALL per-iteration tracing
# work — every head-sampled span the serving + learner paths emit at the
# default 1-in-64 cadence plus the exact lineage reduction over the full
# 512x64 version column — costs <= 2% of one steady-state train
# iteration at the committed headline geometry
TRACE_OVERHEAD_FRAC_MAX = 0.02


def _trace_measure() -> dict:
    """The tracing/lineage campaign (standalone — no training run):
    span-emit cost + JSONL footprint from a live Tracer, the exact
    lineage reduction over one update's version column at the headline
    geometry (512 envs x 64 horizon), and the modeled per-iteration
    overhead against the steady-state iteration time.

    The span census is deliberately an UPPER bound: every head-sampled
    request is charged 2 spans (worker.step + replica.forward) and every
    sampled chunk 2 more (xplane.relay + learn.dispatch), all priced at
    the measured p99 emit cost — the real paths emit off the learner
    thread, so the commitment is conservative, never flattering."""
    import os
    import tempfile

    import numpy as np

    from surreal_tpu.session.telemetry import LineageReducer, Tracer

    def pctl(samples_ms):
        arr = np.asarray(samples_ms)
        return {
            "p50": round(float(np.percentile(arr, 50)), 5),
            "p99": round(float(np.percentile(arr, 99)), 5),
        }

    span_ms = []
    with tempfile.TemporaryDirectory() as folder:
        tracer = Tracer(folder, enabled=True, name="bench",
                        trace_sample_n=TRACE_SAMPLE_N)
        root = tracer.trace_context("bench:warm")
        tracer.emit_span("bench.span", root, tier="bench", dur_ms=0.1)
        bytes0 = os.path.getsize(tracer.path)  # line-buffered: current
        for k in range(TRACE_SPANS):
            ctx = tracer.trace_context(f"bench:{k}")
            child = ctx.child(tracer.next_span_id())
            t0 = time.perf_counter()
            tracer.emit_span("bench.span", ctx, tier="bench",
                             dur_ms=0.1, version=k)
            tracer.emit_span("bench.child", child, tier="bench",
                             dur_ms=0.1)
            span_ms.append((time.perf_counter() - t0) * 1e3 / 2.0)
        bytes_per_span = (os.path.getsize(tracer.path) - bytes0) / (
            2.0 * TRACE_SPANS
        )
        tracer.close()
    # one update's acting-version column at the headline geometry:
    # 512 x 64 transitions spread over 4 distinct policy versions
    # (a mid-run fanout publish mixing generations)
    n_rows = 512 * 64
    versions = np.repeat(
        np.asarray([37, 38, 39, 40], dtype=np.int32), n_rows // 4
    )
    reducer = LineageReducer()
    reducer.reduce(41, versions)  # warm (numpy dispatch outside timing)
    lineage_ms = []
    for _ in range(TRACE_LINEAGE_REPS):
        t0 = time.perf_counter()
        reducer.reduce(41, versions)
        lineage_ms.append((time.perf_counter() - t0) * 1e3)
    iter_ms = _ops_iter_ms()
    span = pctl(span_ms)
    lineage = pctl(lineage_ms)
    # the modeled per-iteration span census (upper bound, see docstring)
    sampled = max(1, TRACE_WORKERS * TRACE_HORIZON // TRACE_SAMPLE_N)
    spans_per_iter = 2 * sampled + 2
    trace_ms_per_iter = spans_per_iter * span["p99"] + lineage["p99"]
    return {
        "span_emit_ms": span,
        "spans_per_s": round(1000.0 / max(span["p50"], 1e-6), 1),
        "bytes_per_span": round(bytes_per_span, 1),
        "lineage_reduce_ms": lineage,
        "lineage_rows": n_rows,
        "iter_ms": round(iter_ms, 3),
        "spans_per_iter": spans_per_iter,
        "trace_ms_per_iter": round(trace_ms_per_iter, 4),
        "overhead_frac_of_iter": round(trace_ms_per_iter / iter_ms, 5),
        "sample_n": TRACE_SAMPLE_N,
        "workload": (
            f"{TRACE_WORKERS} worker streams x {TRACE_HORIZON} requests, "
            f"1-in-{TRACE_SAMPLE_N} head-sampled, 2 spans/request + "
            f"2 learner spans; lineage over {n_rows} rows / 4 versions; "
            "iter: PPO jax:cartpole 512x64 (1 epoch)"
        ),
    }


def trace_main(argv) -> int:
    """--trace driver (ISSUE 14): per-iteration cost of causal span
    exemplars + exact experience lineage — span emit rate/footprint,
    lineage reduction over the headline version column, modeled overhead
    fraction against the steady-state iteration. Writes
    ``BENCH_trace.json`` (perf_gate.gate_trace and PERF.md's generated
    section consume it), with bench.py's bounded retry/backoff and
    structured failed-round artifact."""
    import sys

    from bench import RETRY_ATTEMPTS, RETRY_BACKOFF_S, _is_retryable, _reset_backends

    out_path = "BENCH_trace.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            row = _trace_measure()
            result = {
                "metric": "trace_overhead_frac_of_iter",
                "value": row["overhead_frac_of_iter"],
                "unit": "frac",
                "geometry": row["workload"],
                "overhead_frac_max": TRACE_OVERHEAD_FRAC_MAX,
                **row,
                "device": str(jax.devices()[0].device_kind),
                "platform": str(jax.devices()[0].platform),
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2, default=float)
            print(json.dumps(result, default=float))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"trace attempt {attempt + 1}/{RETRY_ATTEMPTS} "
                    f"failed ({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    result = {"error": err, "parsed": None}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


# -- watchdog & incident engine (--watchdog) ----------------------------------

WATCHDOG_SWEEPS = 300    # detector-sweep sample size (steady state)
# the overhead commitment gate_watchdog enforces: one full detector sweep
# over a production-census snapshot (all breakout/saturation/growth/
# liveness/regression families armed) plus the incident engine's
# per-sweep observe() costs <= 1% of one steady-state train iteration —
# the watchdog judges the workload, it must never become one
WATCHDOG_EVAL_FRAC_MAX = 0.01


def _watchdog_snap(i: int, rows, anomalous: bool = False) -> dict:
    """One merged-snapshot dict at the ``_ops_rows`` production census
    (the same tier shapes ``--ops-plane`` prices), with every detector
    family's signals present; ``anomalous`` flips the fleet tier into
    the killed-replica shape (DEAD + serve/RTT breakout) so the
    incident-open path can be timed end-to-end."""
    tiers = {}
    for name, row in rows:
        tiers[name] = {
            "age_s": 0.2, "dead": False, "cadence_s": 1.0,
            "gauges": dict(row.get("gauges") or {}),
            "hops": dict(row.get("hops") or {}),
            "body": row.get("body"),
        }
    tiers["learner"] = {
        "age_s": 0.0, "dead": False, "cadence_s": 1.0,
        "gauges": {
            "time/env_steps_per_s": 5.0e4, "perf/mfu": 0.3,
            "experience/sample_wait_ms": 1.0,
            "fleet/serve_ms": 2.0, "fleet/respawns": 0.0,
            "lineage/staleness_p99": 2.0,
            "trace/dropped_spans": 0.0, "gateway/bad_frames": 0.0,
        },
    }
    gw_p99 = 9.8
    if anomalous:
        rep = tiers.get("fleet.replica0")
        if rep is not None:
            rep["age_s"], rep["dead"] = 9.0, True
        tiers["learner"]["gauges"]["fleet/serve_ms"] = 80.0
        gw_p99 = 250.0
    return {
        "type": "ops_snapshot", "t": 1000.0 + 0.1 * i, "seq": i,
        "iteration": i, "env_steps": i * 512, "trace": "bench",
        "tiers": tiers,
        "hops": {"gateway_act_ms": {"p50": 1.2, "p90": 3.4, "p99": gw_p99}},
        "slo": {}, "bad_frames": 0,
    }


def _watchdog_measure() -> dict:
    """The watchdog campaign (standalone — no training run): full
    detector sweep + incident-engine observe per snapshot at the
    production tier census, plus the incident-open end-to-end latency
    (anomalous snapshot in -> incident-1.json on disk), against the
    steady-state iteration time."""
    import tempfile

    import numpy as np

    from surreal_tpu.session.incidents import IncidentEngine
    from surreal_tpu.session.watchdog import Watchdog

    def pctl(samples_ms):
        arr = np.asarray(samples_ms)
        return {
            "p50": round(float(np.percentile(arr, 50)), 5),
            "p99": round(float(np.percentile(arr, 99)), 5),
        }

    rows = _ops_rows()
    eval_ms = []
    with tempfile.TemporaryDirectory() as folder:
        wd = Watchdog(
            # a synthetic baseline row arms the regression detector so
            # the priced sweep includes every family
            baseline_rows=[{
                "file": "BENCH_bench.json", "round": 0,
                "metric": "env_steps_per_sec_bench", "value": 9.0e4,
                "platform": None, "geometry": None, "mfu": 0.5,
                "arm": None, "failed": False,
            }],
        )
        eng = IncidentEngine(folder=folder, trace_id="bench")
        for i in range(WATCHDOG_SWEEPS):
            snap = _watchdog_snap(i, rows)
            t0 = time.perf_counter()
            firings = wd.evaluate(snap)
            eng.observe(firings, snap)
            eval_ms.append((time.perf_counter() - t0) * 1e3)
        # incident-open e2e: anomalous snapshot in -> record on disk.
        # Liveness fires on the FIRST anomalous sweep, so one sweep is
        # the whole open path (absorb + rank + atomic write included).
        i0 = WATCHDOG_SWEEPS
        t0 = time.perf_counter()
        snap = _watchdog_snap(i0, rows, anomalous=True)
        eng.observe(wd.evaluate(snap), snap)
        open_ms = (time.perf_counter() - t0) * 1e3
        import os as _os

        from surreal_tpu.session.incidents import INCIDENTS_DIR
        from surreal_tpu.session.telemetry import TELEMETRY_DIR

        rec = _os.path.join(
            folder, TELEMETRY_DIR, INCIDENTS_DIR, "incident-1.json"
        )
        if not _os.path.isfile(rec):
            raise RuntimeError(
                "anomalous snapshot did not open a persisted incident"
            )
    iter_ms = _ops_iter_ms()
    ev = pctl(eval_ms)
    return {
        "eval_ms": ev,
        "incident_open_ms": round(open_ms, 4),
        "iter_ms": round(iter_ms, 3),
        "eval_frac_of_iter": round(ev["p99"] / iter_ms, 5),
        "sweeps": WATCHDOG_SWEEPS,
        "workload": (
            f"{len(rows)} wire tiers + learner row, all 5 detector "
            "families armed (regression vs synthetic baseline); "
            "iter: PPO jax:cartpole 512x64 (1 epoch)"
        ),
    }


def watchdog_main(argv) -> int:
    """--watchdog driver (ISSUE 15): per-cadence cost of the watchdog
    detector sweep + incident engine, and the incident-open end-to-end
    latency. Writes ``BENCH_watchdog.json`` (perf_gate.gate_watchdog and
    PERF.md's generated section consume it), with bench.py's bounded
    retry/backoff and structured failed-round artifact."""
    import sys

    from bench import RETRY_ATTEMPTS, RETRY_BACKOFF_S, _is_retryable, _reset_backends

    out_path = "BENCH_watchdog.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            row = _watchdog_measure()
            result = {
                "metric": "watchdog_eval_frac_of_iter",
                "value": row["eval_frac_of_iter"],
                "unit": "frac",
                "geometry": row["workload"],
                "eval_frac_max": WATCHDOG_EVAL_FRAC_MAX,
                **row,
                "device": str(jax.devices()[0].device_kind),
                "platform": str(jax.devices()[0].platform),
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2, default=float)
            print(json.dumps(result, default=float))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"watchdog attempt {attempt + 1}/{RETRY_ATTEMPTS} "
                    f"failed ({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    result = {"error": err, "parsed": None}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


# -- remediation control loop & loadgen (--control) ---------------------------

CONTROL_SWEEPS = 300     # decision-sweep sample size (action in flight)
CONTROL_WARMUP = 40      # healthy sweeps to arm the watchdog baselines
CONTROL_LOADGEN_S = 1.5  # sustained-rate window against a live gateway
# the overhead commitment gate_control enforces: one remediation decision
# sweep (verification tick for the in-flight action + open-incident
# mapping guards) costs <= 1% of one steady-state train iteration — the
# control loop steers the workload, it must never become one
CONTROL_DECIDE_FRAC_MAX = 0.01


def _control_snap(i: int, rows, anomalous: bool = False) -> dict:
    """``_watchdog_snap`` plus the per-replica ``fleet/serve_ms`` gauge
    the remediation counter-detector reads as its fleet objective, so
    verification samples are real values rather than skipped Nones."""
    snap = _watchdog_snap(i, rows, anomalous=anomalous)
    serve = 80.0 if anomalous else 2.0
    for name, tier in snap["tiers"].items():
        if name.startswith("fleet"):
            tier["gauges"]["fleet/serve_ms"] = serve
    return snap


def _control_measure() -> dict:
    """The control campaign (standalone — no training run): incident ->
    journaled-action end-to-end latency (anomalous snapshot in ->
    action-1.json on disk), per-sweep remediation decision cost with an
    action in verification flight, and the tenant load generator's
    sustained act rate against a live fleet + gateway."""
    import tempfile

    import numpy as np

    from surreal_tpu.session.incidents import IncidentEngine
    from surreal_tpu.session.remediate import ACTIONS_DIR, RemediationEngine
    from surreal_tpu.session.telemetry import TELEMETRY_DIR
    from surreal_tpu.session.watchdog import Watchdog

    def pctl(samples_ms):
        arr = np.asarray(samples_ms)
        return {
            "p50": round(float(np.percentile(arr, 50)), 5),
            "p99": round(float(np.percentile(arr, 99)), 5),
        }

    class _BenchFleet:
        """Bounded fake actuator: the engine's fleet_scale_up target."""

        def __init__(self):
            self.n = 2

        def scale_up(self):
            self.n += 1
            return self.n - 1

        def scale_down(self, replica=None):
            self.n -= 1
            return True

    rows = _ops_rows()
    decide_ms = []
    with tempfile.TemporaryDirectory() as folder:
        wd = Watchdog(
            baseline_rows=[{
                "file": "BENCH_bench.json", "round": 0,
                "metric": "env_steps_per_sec_bench", "value": 9.0e4,
                "platform": None, "geometry": None, "mfu": 0.5,
                "arm": None, "failed": False,
            }],
        )
        eng = IncidentEngine(folder=folder, trace_id="bench")
        rem = RemediationEngine(
            folder=folder, incidents=eng, trace_id="bench",
            cfg={
                # keep the one action verifying for the whole timed
                # phase, and never re-act: the priced sweep is the
                # steady in-flight state (verify tick + guards)
                "verify_windows": CONTROL_SWEEPS + CONTROL_WARMUP + 4,
                "cooldown_s": 1e9,
            },
        )
        rem.bind_actuators(fleet=_BenchFleet())
        for i in range(CONTROL_WARMUP):
            snap = _control_snap(i, rows)
            firings = wd.evaluate(snap)
            eng.observe(firings, snap)
            rem.step(firings, snap)
        # incident -> action e2e: anomalous snapshot in -> incident
        # opens (liveness fires on the FIRST anomalous sweep) -> the
        # engine maps its top cause to fleet_scale_up and journals
        # action-1.json, all inside one decision sweep.
        i0 = CONTROL_WARMUP
        t0 = time.perf_counter()
        snap = _control_snap(i0, rows, anomalous=True)
        firings = wd.evaluate(snap)
        eng.observe(firings, snap)
        rem.step(firings, snap)
        act_e2e_ms = (time.perf_counter() - t0) * 1e3
        import os as _os

        rec = _os.path.join(folder, TELEMETRY_DIR, ACTIONS_DIR,
                            "action-1.json")
        if not _os.path.isfile(rec):
            raise RuntimeError(
                "anomalous snapshot did not produce a journaled action"
            )
        # steady decision sweeps with the action in verification flight:
        # the incident stays open, the engine samples the objective and
        # declines to stack a second action — the per-cadence cost the
        # frac gate prices.
        for i in range(i0 + 1, i0 + 1 + CONTROL_SWEEPS):
            snap = _control_snap(i, rows, anomalous=True)
            firings = wd.evaluate(snap)
            eng.observe(firings, snap)
            t0 = time.perf_counter()
            rem.step(firings, snap)
            decide_ms.append((time.perf_counter() - t0) * 1e3)
        if rem.executed != 1:
            raise RuntimeError(
                f"expected exactly one executed action, got {rem.executed}"
            )
    loadgen = _control_loadgen()
    iter_ms = _ops_iter_ms()
    dec = pctl(decide_ms)
    return {
        "decide_ms": dec,
        "incident_to_action_ms": round(act_e2e_ms, 4),
        "iter_ms": round(iter_ms, 3),
        "decide_frac_of_iter": round(dec["p99"] / iter_ms, 5),
        "sweeps": CONTROL_SWEEPS,
        "loadgen": loadgen,
        "workload": (
            f"{len(rows)} wire tiers + learner row, open incident with "
            "fleet_scale_up in verification flight; "
            "iter: PPO jax:cartpole 512x64 (1 epoch)"
        ),
    }


def _control_loadgen() -> dict:
    """Sustained tenant act rate: two steady tenants against a live
    InferenceFleet + GatewayServer for ``CONTROL_LOADGEN_S`` seconds —
    achieved acts/s vs the offered rate, plus the client-side RTT."""
    import numpy as np

    from surreal_tpu.distributed.fleet import InferenceFleet
    from surreal_tpu.gateway import GatewayServer
    from surreal_tpu.gateway.loadgen import LoadGenerator

    def act_fn(obs):
        b = obs.shape[0]
        return (
            np.zeros(b, np.int32),
            {"logp": np.full(b, -np.log(2), np.float32)},
        )

    offered_hz = 100.0  # 2 tenants x 50 Hz
    fleet = InferenceFleet(act_fn, num_workers=2, replicas=2,
                           unroll_length=4)
    server = GatewayServer(fleet, lease_s=30.0)
    gen = LoadGenerator(
        server.address,
        tenants=[
            {"tenant": "steady-0", "profile": "steady", "rate_hz": 50.0},
            {"tenant": "steady-1", "profile": "steady", "rate_hz": 50.0},
        ],
        obs_shape=(1, 4), timeout_s=5.0, retries=2,
    )
    try:
        gen.start()
        t0 = time.perf_counter()
        time.sleep(CONTROL_LOADGEN_S)
        elapsed = time.perf_counter() - t0
        rep = gen.stop()
    finally:
        server.close()
        fleet.close()
    errors = [t["error"] for t in rep["tenants"].values() if t["error"]]
    if errors:
        raise RuntimeError(f"loadgen tenant died: {errors[0]}")
    return {
        "offered_hz": offered_hz,
        "acts_per_s": round(rep["loadgen/acts"] / elapsed, 2),
        "act_rtt_ms": round(rep["loadgen/act_rtt_ms"], 4),
        "window_s": round(elapsed, 3),
    }


def control_main(argv) -> int:
    """--control driver (ISSUE 16): per-cadence cost of the remediation
    decision sweep, the incident -> journaled-action latency, and the
    load generator's sustained rate. Writes ``BENCH_control.json``
    (perf_gate.gate_control and PERF.md's generated section consume
    it), with bench.py's bounded retry/backoff and structured failed-
    round artifact."""
    import sys

    from bench import RETRY_ATTEMPTS, RETRY_BACKOFF_S, _is_retryable, _reset_backends

    out_path = "BENCH_control.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            row = _control_measure()
            result = {
                "metric": "control_decide_frac_of_iter",
                "value": row["decide_frac_of_iter"],
                "unit": "frac",
                "geometry": row["workload"],
                "decide_frac_max": CONTROL_DECIDE_FRAC_MAX,
                **row,
                "device": str(jax.devices()[0].device_kind),
                "platform": str(jax.devices()[0].platform),
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2, default=float)
            print(json.dumps(result, default=float))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"control attempt {attempt + 1}/{RETRY_ATTEMPTS} "
                    f"failed ({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    result = {"error": err, "parsed": None}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


# -- elastic learner group (--learner-group) ----------------------------------

LGROUP_OBS_DIM = 64
LGROUP_ACT_DIM = 8
LGROUP_BATCH = 1024  # rows per SGD update: a learn-bound geometry
LGROUP_WARM = 2
LGROUP_MEAS = 15
LGROUP_REPEATS = 3
LGROUP_MEMBERS = (1, 2, 4)
# M=1 parity (ISSUE 17 acceptance): the one-member group dispatches the
# SAME jitted single-learn program; its Python wrapper must stay within
# 2% of the single learner's updates/s.
LGROUP_PARITY_TOL = 0.02
# the multichip scaling commitment WHEN real cores back the simulated
# devices (mode='scaling'): learn-bound updates/s at M=2 >= 1.6x M=1.
# On one core the 8-device CPU sim time-slices a single core, so the
# artifact reports the honesty ratio under mode='honesty' instead —
# never a fabricated speedup (the act-path precedent).
LGROUP_SCALE_MIN_M2 = 1.6


def _lgroup_learner():
    import numpy as np

    from surreal_tpu.envs.base import ArraySpec, EnvSpecs
    from surreal_tpu.learners import build_learner
    from surreal_tpu.session.config import Config

    specs = EnvSpecs(
        obs=ArraySpec(shape=(LGROUP_OBS_DIM,), dtype=np.dtype(np.float32)),
        action=ArraySpec(shape=(LGROUP_ACT_DIM,), dtype=np.dtype(np.float32)),
    )
    learner = build_learner(Config(algo=Config(name="ddpg")), specs)
    return learner, learner.init(jax.random.key(0))


def _lgroup_batch(key):
    import jax.numpy as jnp

    ks = jax.random.split(key, 4)
    B = LGROUP_BATCH
    return {
        "obs": jax.random.normal(ks[0], (B, LGROUP_OBS_DIM)),
        "next_obs": jax.random.normal(ks[1], (B, LGROUP_OBS_DIM)),
        "action": jnp.clip(
            jax.random.normal(ks[2], (B, LGROUP_ACT_DIM)), -1, 1
        ),
        "reward": jax.random.normal(ks[3], (B,)),
        "discount": jnp.full((B,), 0.99),
    }


def _lgroup_time_learn(learn, state, batch) -> float:
    """updates/s of one jitted learn program at the committed geometry
    (state threaded through so every call does real optimizer work).
    Best of ``LGROUP_REPEATS`` timed windows: the parity bound is 2%,
    one-core scheduler jitter alone exceeds that in a single window."""
    key = jax.random.key(7)
    s = state
    for _ in range(LGROUP_WARM):
        key, k = jax.random.split(key)
        s, m = learn(s, batch, k)
    jax.block_until_ready(s)
    best = 0.0
    for _ in range(LGROUP_REPEATS):
        t0 = time.perf_counter()
        for _ in range(LGROUP_MEAS):
            key, k = jax.random.split(key)
            s, m = learn(s, batch, k)
        jax.block_until_ready(s)
        best = max(best, LGROUP_MEAS / (time.perf_counter() - t0))
    return best


class _LgroupStubPlane:
    """Just the surface LearnerGroup reads for the learn-path overhead
    measurement (no live shards: the bench times the LEARN dispatch,
    sampling is the experience-plane campaign's business)."""

    num_shards = 4
    _backoff_base = 0.05
    _backoff_cap = 1.0

    def sampler_factory(self, shard_ids, batch_size, base_key):
        class _S:
            sample_wait_ms = 0.0

            def request_iteration(self, wm, beta):
                pass

            def close(self):
                pass

        return _S()


def _lgroup_measure() -> dict:
    """In-process arms (devices as the session sees them — ONE on this
    box): the single learner, the M=1 group (parity), and the M in
    {2, 4} concat fallback (the same mean-gradient update, counted
    honestly as fallback_learns)."""
    from surreal_tpu.parallel.learner_group import LearnerGroup

    learner, state = _lgroup_learner()
    batch = _lgroup_batch(jax.random.key(1))
    single = jax.jit(learner.learn, donate_argnums=())
    single_ups = _lgroup_time_learn(single, state, batch)
    rows = {}
    for m in LGROUP_MEMBERS:
        group = LearnerGroup(
            learner=learner, plane=_LgroupStubPlane(),
            batch_size=LGROUP_BATCH, members=m,
            base_key=jax.random.key(2), single_learn=single,
        )
        ups = _lgroup_time_learn(group.learn, state, batch)
        rows[str(m)] = {
            "updates_per_s": round(ups, 3),
            "rows_per_s": round(ups * LGROUP_BATCH, 1),
            "vs_single": round(ups / single_ups, 4),
            "allreduce_learns": group.allreduce_learns,
            "fallback_learns": group.fallback_learns,
        }
        group.close()
    return {
        "single_updates_per_s": round(single_ups, 3),
        "parity_ratio": rows["1"]["vs_single"],
        "members": rows,
    }


_LGROUP_MULTICHIP_SCRIPT = r"""
import json, os, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

import perf_wallclock as pw
from surreal_tpu.parallel.learner_group import group_learn

assert jax.device_count() >= 8, jax.device_count()
learner, state = pw._lgroup_learner()
batch = pw._lgroup_batch(jax.random.key(1))
rounds = {}
base = None
for m in pw.LGROUP_MEMBERS:
    mesh = Mesh(np.asarray(jax.devices()[:m]), ("lg",))
    learn = group_learn(learner, mesh)
    ups = pw._lgroup_time_learn(learn, state, batch)
    if base is None:
        base = ups
    rounds[str(m)] = {
        "updates_per_s": round(ups, 3),
        "speedup_vs_m1": round(ups / base, 4),
        "devices": m,
    }
print(json.dumps({"n_devices": jax.device_count(), "rounds": rounds}))
"""


def _lgroup_multichip(out_path: str) -> dict:
    """The 8-device CPU-sim round (MULTICHIP_r06.json): the REAL
    shard_map all-reduce learn at M in {1, 2, 4} simulated members.
    cores < 2 means the sim devices time-slice one core — recorded as
    mode='honesty' with the measured (flat or worse) ratios."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", _LGROUP_MULTICHIP_SCRIPT],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    tail = (proc.stdout.strip().splitlines() or [""])[-1]
    cores = os.cpu_count() or 1
    result = {
        "n_devices": 8,
        "rc": proc.returncode,
        "ok": proc.returncode == 0,
        "skipped": False,
        "tail": "" if proc.returncode == 0 else
                (proc.stderr or proc.stdout)[-2000:],
        "cores": cores,
        "mode": "scaling" if cores >= 2 else "honesty",
        "scale_min_m2": LGROUP_SCALE_MIN_M2,
    }
    if proc.returncode == 0:
        result.update(json.loads(tail))
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, default=float)
    return result


def learner_group_main(argv) -> int:
    """--learner-group driver (ISSUE 17): the M=1 parity bound, the
    per-M learn arms (in-process fallback + 8-device-sim all-reduce),
    writing ``BENCH_lgroup.json`` and ``MULTICHIP_r06.json`` for
    ``perf_gate.gate_learner_group`` and PERF.md's scaling table."""
    import os
    import sys

    from bench import RETRY_ATTEMPTS, RETRY_BACKOFF_S, _is_retryable, _reset_backends

    out_path = "BENCH_lgroup.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    mc_path = os.path.join(os.path.dirname(out_path) or ".",
                           "MULTICHIP_r06.json")
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            row = _lgroup_measure()
            mc = _lgroup_multichip(mc_path)
            result = {
                "metric": "learner_group_m1_parity_ratio",
                "value": row["parity_ratio"],
                "unit": "ratio",
                "geometry": (
                    f"ddpg learn, batch {LGROUP_BATCH} x obs "
                    f"{LGROUP_OBS_DIM}, {LGROUP_MEAS} timed updates; "
                    f"members M in {list(LGROUP_MEMBERS)}"
                ),
                "parity_tol": LGROUP_PARITY_TOL,
                "scale_min_m2": LGROUP_SCALE_MIN_M2,
                "mode": mc["mode"],
                "cores": mc["cores"],
                **row,
                "multichip": {
                    k: mc[k] for k in ("ok", "rounds") if k in mc
                },
                "device": str(jax.devices()[0].device_kind),
                "platform": str(jax.devices()[0].platform),
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2, default=float)
            print(json.dumps(result, default=float))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"learner-group attempt {attempt + 1}/{RETRY_ATTEMPTS} "
                    f"failed ({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    result = {"error": err, "parsed": None}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


# -- loop-engine campaign (ISSUE 19) -----------------------------------------

ENGINE_WARM_ITERS = 2    # jit compile + cache warmup land here
ENGINE_MEAS_ITERS = 6    # median over these
ENGINE_TOL = 0.05        # pipelined iter-time must be <= legacy * (1+tol)
ENGINE_HEADLINE = (512, 64)  # device drivers run the 512x64 geometry


def _engine_cfgs():
    """One (name, geometry, make_cfg) per driver loop the engine ports.

    Device drivers (fused PPO, fused DDPG) run the 512x64 headline
    geometry; host and SEED drivers run reduced geometries — each row
    records its own, so the artifact can't silently mix scales."""
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    ne, hz = ENGINE_HEADLINE

    def session(folder, pipeline, **extra):
        return Config(
            folder=folder,
            total_env_steps=10**12,  # stopped by the on_metrics budget
            metrics=Config(every_n_iters=1, tensorboard=False, console=False),
            # a real checkpoint rides every other boundary, so the
            # pipelined arm defers actual side-band work, not empty calls
            checkpoint=Config(every_n_iters=2),
            eval=Config(every_n_iters=0),
            engine=Config(pipeline_sidebands=pipeline),
            **extra,
        )

    def ppo_device(folder, pipeline):
        return Config(
            learner_config=Config(algo=Config(name="ppo", horizon=hz)),
            env_config=Config(name="jax:cartpole", num_envs=ne),
            session_config=session(folder, pipeline, seed=7),
        ).extend(base_config())

    def ppo_host(overlap):
        def make(folder, pipeline):
            return Config(
                learner_config=Config(
                    algo=Config(name="ppo", horizon=64, epochs=2)
                ),
                env_config=Config(name="gym:CartPole-v1", num_envs=8),
                session_config=session(
                    folder, pipeline, seed=7,
                    topology=Config(overlap_rollouts=overlap),
                ),
            ).extend(base_config())

        return make

    def ddpg_device(folder, pipeline):
        return Config(
            learner_config=Config(
                algo=Config(
                    name="ddpg", horizon=hz, updates_per_iter=4,
                    exploration=Config(warmup_steps=0),
                ),
                replay=Config(
                    kind="uniform", capacity=131072,
                    start_sample_size=8192, batch_size=256,
                ),
            ),
            env_config=Config(name="jax:pendulum", num_envs=ne),
            session_config=session(folder, pipeline, seed=7),
        ).extend(base_config())

    def ddpg_host(folder, pipeline):
        return Config(
            learner_config=Config(
                algo=Config(
                    name="ddpg", horizon=32, n_step=3, updates_per_iter=2,
                    exploration=Config(warmup_steps=0),
                ),
                replay=Config(
                    kind="uniform", capacity=4096,
                    start_sample_size=64, batch_size=32,
                ),
            ),
            env_config=Config(name="gym:Pendulum-v1", num_envs=4),
            session_config=session(folder, pipeline, seed=7),
        ).extend(base_config())

    def seed(folder, pipeline):
        return Config(
            learner_config=Config(algo=Config(name="impala", horizon=8)),
            env_config=Config(name="gym:CartPole-v1", num_envs=4),
            session_config=session(
                folder, pipeline, seed=7,
                topology=Config(num_env_workers=2),
            ),
        ).extend(base_config())

    return [
        ("ppo_device", f"jax:cartpole {ne}x{hz}", ppo_device),
        ("ppo_host_alternate", "gym:CartPole 8x64 (overlap off)",
         ppo_host(False)),
        ("ppo_host_overlap", "gym:CartPole 8x64 (overlap on)",
         ppo_host(True)),
        ("ddpg_device", f"jax:pendulum {ne}x{hz}, 4 updates/iter",
         ddpg_device),
        ("ddpg_host", "gym:Pendulum 4x32, n_step 3", ddpg_host),
        ("seed", "impala gym:CartPole 4x8, 2 thread workers", seed),
    ]


def _engine_arm(name: str, make_cfg, pipeline: bool) -> dict:
    """One driver run at one engine mode; median steady-state iter time
    plus the engine's own gauges from the last metrics row."""
    import shutil
    import tempfile

    from surreal_tpu.main.launch import select_trainer

    folder = tempfile.mkdtemp(prefix=f"bench_engine_{name}_")
    trainer = select_trainer(make_cfg(folder, pipeline))
    marks: list[float] = []
    last: dict = {}

    def on_m(it, m):
        marks.append(time.perf_counter())
        last.update(m)
        return len(marks) >= ENGINE_WARM_ITERS + ENGINE_MEAS_ITERS

    try:
        trainer.run(on_metrics=on_m)
    finally:
        shutil.rmtree(folder, ignore_errors=True)
    tail = marks[ENGINE_WARM_ITERS - 1:]
    diffs = sorted(b - a for a, b in zip(tail, tail[1:]))
    iter_ms = diffs[len(diffs) // 2] * 1e3
    return {
        "iter_ms": round(iter_ms, 3),
        "iters_measured": len(diffs),
        "boundary_p50_ms": last.get("engine/stage_p50_ms"),
        "occupancy": last.get("engine/occupancy"),
        "deferred_boundaries": last.get("engine/deferred_boundaries"),
        "skipped_boundaries": last.get("engine/skipped_boundaries"),
    }


def _engine_measure() -> dict:
    """Every ported driver, pipelining off then on. The off arm IS the
    legacy loop (the engine runs the boundary inline); the on arm defers
    publish/checkpoint/observe to the staging worker. reclaimed_frac is
    the inline boundary's share of the legacy iteration — the fraction
    of the critical path the pipelined arm moves off it."""
    import sys

    drivers = {}
    for name, geometry, make_cfg in _engine_cfgs():
        off = _engine_arm(name, make_cfg, False)
        on = _engine_arm(name, make_cfg, True)
        ratio = (
            on["iter_ms"] / off["iter_ms"] if off["iter_ms"] else None
        )
        reclaimed = (
            float(off["boundary_p50_ms"]) / off["iter_ms"]
            if off.get("boundary_p50_ms") and off["iter_ms"] else None
        )
        drivers[name] = {
            "geometry": geometry,
            "off": off,
            "on": on,
            "iter_ratio_on_vs_off": round(ratio, 4) if ratio else None,
            "reclaimed_frac": (
                round(reclaimed, 4) if reclaimed is not None else None
            ),
        }
        print(
            f"engine bench {name}: off {off['iter_ms']:.1f} ms, "
            f"on {on['iter_ms']:.1f} ms (ratio {ratio:.3f})",
            file=sys.stderr,
        )
    return drivers


def engine_main(argv) -> int:
    """--loop-engine driver (ISSUE 19): per-driver iteration time with
    boundary pipelining off (the legacy inline loop) vs on, plus the
    off-critical-path fraction the deferral reclaims. Writes
    ``BENCH_engine.json`` for ``perf_gate.gate_engine`` and PERF.md's
    loop-engine table. On a one-core box the staging worker time-slices
    the compute thread, so the arms are recorded in mode='honesty' — the
    <= bound is only enforced under mode='overlap' (>= 2 cores)."""
    import os
    import sys

    from bench import RETRY_ATTEMPTS, RETRY_BACKOFF_S, _is_retryable, _reset_backends

    out_path = "BENCH_engine.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    cores = os.cpu_count() or 1
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            drivers = _engine_measure()
            headline = drivers["ppo_device"]
            result = {
                "metric": "engine_pipelined_iter_ratio_ppo_device",
                "value": headline["iter_ratio_on_vs_off"],
                "unit": "ratio (pipelined / legacy iteration time)",
                "geometry": (
                    f"device drivers at {ENGINE_HEADLINE[0]}x"
                    f"{ENGINE_HEADLINE[1]}; host/SEED reduced geometries "
                    "recorded per row"
                ),
                "tol": ENGINE_TOL,
                "cores": cores,
                "mode": "overlap" if cores >= 2 else "honesty",
                "warm_iters": ENGINE_WARM_ITERS,
                "meas_iters": ENGINE_MEAS_ITERS,
                "drivers": drivers,
                "device": str(jax.devices()[0].device_kind),
                "platform": str(jax.devices()[0].platform),
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2, default=float)
            print(json.dumps(result, default=float))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"loop-engine attempt {attempt + 1}/{RETRY_ATTEMPTS} "
                    f"failed ({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    result = {"error": err, "parsed": None}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0


def chaos_main(argv) -> int:
    """--chaos driver (ISSUE 20): the randomized chaos-campaign artifact.
    Thin delegate over ``surreal_tpu chaos`` — N seeded short real runs
    under generated multi-site fault schedules, every run judged by the
    invariant oracles, failures shrunk to minimal repros. Writes
    ``CHAOS_campaign.json`` for ``perf_gate.gate_chaos`` and PERF.md's
    chaos section. rc 1 when any schedule recorded a violation (the
    committed artifact must be a clean campaign)."""
    import sys
    import tempfile

    from surreal_tpu.chaos.campaign import run_campaign, write_artifact

    out_path = "CHAOS_campaign.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    seeds = 25
    if "--seeds" in argv:
        seeds = int(argv[argv.index("--seeds") + 1])
    base_dir = (
        argv[argv.index("--dir") + 1] if "--dir" in argv
        else tempfile.mkdtemp(prefix="surreal_chaos_")
    )
    artifact = run_campaign(seeds, base_dir)
    write_artifact(out_path, artifact)
    print(json.dumps(artifact["gauges"]))
    if artifact["failures"]:
        print(
            f"chaos: {len(artifact['failures'])} failing schedule(s) — see "
            f"{out_path} failures[] for the shrunk minimal repros",
            file=sys.stderr,
        )
        return 1
    return 0


def main(argv=None) -> None:
    import os
    import sys

    argv = sys.argv[1:] if argv is None else argv
    if "--host-path" in argv:
        sys.exit(host_path_main(argv))
    if "--experience-plane" in argv:
        sys.exit(experience_plane_main(argv))
    if "--act-path" in argv:
        sys.exit(act_path_main(argv))
    if "--gateway" in argv:
        sys.exit(gateway_main(argv))
    if "--ops-plane" in argv:
        sys.exit(ops_plane_main(argv))
    if "--trace" in argv:
        sys.exit(trace_main(argv))
    if "--watchdog" in argv:
        sys.exit(watchdog_main(argv))
    if "--control" in argv:
        sys.exit(control_main(argv))
    if "--learner-group" in argv:
        sys.exit(learner_group_main(argv))
    if "--chaos" in argv:
        sys.exit(chaos_main(argv))
    n = 3
    if "--seeds" in argv:
        n = int(argv[argv.index("--seeds") + 1])
    seeds = list(range(n))
    out_path = "WALLCLOCK_r05.json"
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    global COMPILE_CACHE_DIR, AUTOTUNE, TUNING_CACHE_DIR
    if "--autotune" in argv:
        AUTOTUNE = argv[argv.index("--autotune") + 1]
    if "--tuning-cache" in argv:
        TUNING_CACHE_DIR = os.path.abspath(
            argv[argv.index("--tuning-cache") + 1]
        )
    cache_was_cold = None
    if "--compile-cache" in argv:
        COMPILE_CACHE_DIR = os.path.abspath(
            argv[argv.index("--compile-cache") + 1]
        )
        # cold vs warm is a property of the DIR, not the flag: record it
        # before any compilation touches the cache
        cache_was_cold = not (
            os.path.isdir(COMPILE_CACHE_DIR) and os.listdir(COMPILE_CACHE_DIR)
        )

    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    results = {
        "device": str(jax.devices()[0].device_kind),
        "compile_cache_dir": COMPILE_CACHE_DIR,
        "compile_cache_was_cold": cache_was_cold,
        "autotune": AUTOTUNE,
        "tuning_cache_dir": TUNING_CACHE_DIR,
        "lift_to_1000": run_to_target(lift_trainer, 1000.0, seeds),
        "pong_to_plus5": run_to_target(pong_trainer, 5.0, seeds),
    }

    def stats(rows, key="total_s"):
        import statistics

        # medians over REACHED runs only — a timed-out run's total_s is a
        # censored cap, and mixing it in would recreate the single-seed
        # honesty problem this script exists to fix
        reached = [r for r in rows if r["reached_target"]]
        if not reached:
            return {"n_reached": 0, "n": len(rows)}
        vals = sorted(r[key] for r in reached)
        return {
            "median_s": statistics.median(vals),
            "min_s": vals[0],
            "max_s": vals[-1],
            "n_reached": len(vals),
            "n": len(rows),
        }

    results["summary"] = {
        "lift_to_1000": stats(results["lift_to_1000"]),
        "lift_train_only": stats(results["lift_to_1000"], "train_s"),
        "pong_to_plus5": stats(results["pong_to_plus5"]),
        "pong_train_only": stats(results["pong_to_plus5"], "train_s"),
        # the cross-process compile split: seed-0 compile time under a
        # warm --compile-cache vs a cold one is the persistent-cache win
        "seed0_compile_s": {
            "lift": results["lift_to_1000"][0]["compile_to_first_iter_s"]
            if results["lift_to_1000"] else None,
            "pong": results["pong_to_plus5"][0]["compile_to_first_iter_s"]
            if results["pong_to_plus5"] else None,
        },
    }
    with open(out_path, "w") as f:
        json.dump(results, f, indent=2, default=float)
    print(json.dumps(results["summary"], indent=2, default=float))


if __name__ == "__main__":
    main()
