"""Headline benchmark: env steps/sec/chip for fused on-device PPO on the
BlockLifting-class workload (the graded metric: BASELINE.json defines
"Robosuite env steps/sec/chip" on BlockLifting state-obs PPO; the
``jax:lift`` env is this repo's TPU-native BlockLifting — see
surreal_tpu/envs/jax/lift.py for the robosuite/MJX-availability note).

Workload: PPO with a large vmapped env batch — rollout + GAE + minibatched
SGD all in one compiled program per iteration, dispatched asynchronously so
tunnel/dispatch latency overlaps device compute (the steps counted are real
policy-driven env steps inside the training loop, not a bare env-step
microbenchmark).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is value / 100_000 — the north-star ">=100k env steps/sec/chip"
from BASELINE.json (the reference itself published no numbers; SURVEY.md §6).
"""

from __future__ import annotations

import json
import sys
import time

import jax

# Throughput-optimal batch geometry, measured on one v5lite chip (sweep in
# round 2): steps/s scales ~linearly with envs*horizon up to >=16k envs
# (the small-config ceiling is dispatch latency, not compute); 4096x256 is
# the knee where per-iter dispatch overhead is fully amortized while the
# program is still a config a user would actually train (PPO learns lift
# with these shapes — see tests/test_envs.py::test_ppo_learns_on_lift and
# the 1024x128 time-to-reward config in README.md).
NUM_ENVS = 4096
HORIZON = 256
WARMUP_ITERS = 2
MEASURE_ITERS = 10
NORTH_STAR = 100_000.0
# TPU v5e (v5lite) public peak: 197 TFLOP/s bf16 per chip — the MFU
# denominator. RL env-step workloads are NOT matmul-bound (tiny MLPs, env
# physics, data movement), so MFU here is an honesty metric, not a target:
# it says what fraction of the chip the headline steps/s actually uses.
PEAK_FLOPS_BF16 = 197e12


def _iter_flops(jitted, *args) -> float | None:
    """Analytic FLOPs of one compiled training iteration, from XLA's own
    cost model (compiled.cost_analysis()); None when the backend doesn't
    report it."""
    try:
        ca = jitted.lower(*args).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):  # some backends wrap per-device
            ca = ca[0]
        return float(ca["flops"]) if ca and "flops" in ca else None
    except Exception:
        return None


def main() -> None:
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=HORIZON, epochs=4, num_minibatches=4),
        ),
        env_config=Config(name="jax:lift", num_envs=NUM_ENVS),
        session_config=Config(
            folder="/tmp/bench_lift",
            metrics=Config(every_n_iters=10_000),  # no host syncs mid-bench
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())

    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    from surreal_tpu.launch.rollout import init_device_carry

    carry = init_device_carry(trainer.env, env_key, NUM_ENVS)

    # warmup (compile) -- not measured
    for _ in range(WARMUP_ITERS):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.block_until_ready(metrics)
    flops_per_iter = _iter_flops(trainer._train_iter, state, carry, key)

    t0 = time.perf_counter()
    for _ in range(MEASURE_ITERS):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0

    steps = MEASURE_ITERS * NUM_ENVS * HORIZON
    sps = steps / dt
    result = {
        "metric": "env_steps_per_sec_per_chip_ppo_fused_blocklift",
        "value": round(sps, 1),
        "unit": "env_steps/s/chip",
        "vs_baseline": round(sps / NORTH_STAR, 3),
    }
    if flops_per_iter is not None:
        achieved = flops_per_iter * MEASURE_ITERS / dt
        result["model_flops_per_s"] = round(achieved, 1)
        result["mfu"] = round(achieved / PEAK_FLOPS_BF16, 6)
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
