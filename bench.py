"""Headline benchmark: env steps/sec/chip for fused on-device PPO on the
BlockLifting-class workload (the graded metric: BASELINE.json defines
"Robosuite env steps/sec/chip" on BlockLifting state-obs PPO; the
``jax:lift`` env is this repo's TPU-native BlockLifting — see
surreal_tpu/envs/jax/lift.py for the robosuite/MJX-availability note).

Workload: PPO with a large vmapped env batch — rollout + GAE + minibatched
SGD all in one compiled program per iteration. The steps counted are real
policy-driven env steps inside the training loop, not a bare env-step
microbenchmark.

MEASUREMENT INTEGRITY (round-3 correction): on this image's tunneled
backend, ``jax.block_until_ready`` RETURNS WITHOUT WAITING for program
completion, which silently inflated earlier recorded numbers (BENCH_r01/
r02 and round-2 README claims in the billions) by ~1000x. The only
trustworthy fence is ``jax.device_get`` of a program OUTPUT — verified by
linearity in iteration count and by FLOP sanity (the old numbers implied
>100% MXU utilization on CNN workloads, a physical impossibility). This
bench times a CHAINED loop (each iteration consumes the previous state)
fenced by ``device_get``. Honest throughput on one v5lite chip is ~34M
env steps/s — ~340x the 100k north-star, measured with the same
device_get fence, linearity check, and FLOP-sanity discipline as the
round-3 correction. (Round 3 recorded ~3.5M; round 4's attribution found
~70% of the learn phase was minibatch row-gather/permutation cost and
replaced it with block-shuffled minibatching — learners/ppo.py
``_sgd_epochs``, PERF.md.)

The workload is latency-bound on the env scan (hundreds of sequential
tiny elementwise ops per step), not matmul-bound: MFU is reported for
transparency and is expectedly tiny; steps/s is the graded metric.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
vs_baseline is value / 100_000 — the north-star ">=100k env steps/sec/chip"
from BASELINE.json (the reference itself published no numbers; SURVEY.md §6).
"""

from __future__ import annotations

import json
import sys
import time

import jax

# Throughput-optimal batch geometry from the round-4 sweep
# (device_get-fenced, one v5lite chip, block-shuffled minibatches —
# round 4 found the old learn phase was ~70% row-gather/permutation
# cost and removed it, moving the knee to a much larger batch):
# 2048x256 24.3M, 4096x128 27.0M, 4096x256 34-38M (knee), 8192x128
# 33.8M, 8192x256 32.4M, 16384x256 30.4M, 8192x512 29.8M steps/s.
# (Round-3 knee for comparison: 2048x128 at 3.2-3.5M with row shuffling.)
NUM_ENVS = 4096
HORIZON = 256
WARMUP_ITERS = 2
MEASURE_ITERS = 10
NORTH_STAR = 100_000.0
# program autotuner arm (surreal_tpu/tune/): --autotune cache|search and
# --tuning-cache DIR select it; the artifact ALWAYS records the active
# decision so a record can't silently mix tuned and untuned arms
AUTOTUNE = "off"
TUNING_CACHE_DIR = None
# precision-policy arm (surreal_tpu/ops/precision.py): --precision
# f32|mixed|bf16|bf16_fp8 selects the policy the measured program runs
# under; the row records it (plus per-iteration FLOPs / bytes accessed
# from the PR-6 cost accountant) so policy arms can never silently mix.
# --sweep-precision measures the listed arms back-to-back into one
# artifact ({"parsed": <headline arm>, "precision": {...}}), and
# --cost-only skips the timed window (cost model only — how the TRUE
# headline geometry gets per-policy bytes rows on hosts too slow to time
# it).
PRECISION = "mixed"
# TPU v5e (v5lite) public peak: 197 TFLOP/s bf16 per chip — the MFU
# denominator. This workload is latency-bound on the env scan, so MFU is
# an honesty metric (expectedly tiny), not a target.
PEAK_FLOPS_BF16 = 197e12


def _iter_costs(jitted, *args) -> dict | None:
    """Per-iteration FLOPs + bytes accessed from the PR-6 cost
    accountant's path (``lower().cost_analysis()`` — host-side trace +
    HLO cost pass, no compile; the same numbers the driver's
    ``program_cost`` telemetry events record); None when the backend
    reports nothing."""
    from surreal_tpu.session.costs import program_costs

    return program_costs(jitted, *args)


def _iter_flops(jitted, *args) -> float | None:
    """FLOPs-only view of :func:`_iter_costs` (perf_report.py's
    attribution harnesses import this)."""
    costs = _iter_costs(jitted, *args)
    return costs["flops"] if costs else None


def _measure(
    precision: str | None = None,
    num_envs: int | None = None,
    horizon: int | None = None,
    iters: int | None = None,
    cost_only: bool = False,
) -> dict:
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    precision = precision or PRECISION
    num_envs = num_envs or NUM_ENVS
    horizon = horizon or HORIZON
    iters = iters or MEASURE_ITERS
    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=horizon, epochs=4,
                        num_minibatches=4, autotune=AUTOTUNE,
                        precision=precision),
        ),
        env_config=Config(name="jax:lift", num_envs=num_envs),
        session_config=Config(
            folder="/tmp/bench_lift",
            tuning_cache_dir=TUNING_CACHE_DIR,
            metrics=Config(every_n_iters=10_000),  # no host syncs mid-bench
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())

    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    from surreal_tpu.launch.rollout import init_device_carry

    carry = init_device_carry(trainer.env, env_key, num_envs)

    result = {
        "metric": "env_steps_per_sec_per_chip_ppo_fused_blocklift",
        "unit": "env_steps/s/chip",
        # the device actually measured: jax can silently fall back to CPU
        # when the TPU backend fails to init mid-outage, and a CPU number
        # must never masquerade as the per-chip record
        "device": str(jax.devices()[0].device_kind),
        "platform": str(jax.devices()[0].platform),
        # the active autotuner decision (mode, cache hit/miss, applied
        # config): a bench record must never silently mix tuned and
        # untuned arms (surreal_tpu/tune/)
        "tuning": trainer.tune_decision.artifact(),
        # the active precision policy + geometry: policy arms must never
        # silently mix either (ops/precision.py)
        "precision": precision,
        "num_envs": num_envs,
        "horizon": horizon,
    }
    costs = _iter_costs(trainer._train_iter, state, carry, key)
    if costs is not None:
        result["flops_per_iter"] = costs["flops"]
        result["bytes_accessed_per_iter"] = costs["bytes_accessed"]
    if cost_only:
        result["cost_only"] = True
        return result

    # warmup (compile) -- not measured. device_get, NOT block_until_ready:
    # the latter returns without waiting on this backend (see module doc)
    for _ in range(WARMUP_ITERS):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)

    # throwaway timed window: the first timed window of a freshly
    # compiled program can carry a ~10x one-time tunnel artifact even
    # after the compile warmup above
    for _ in range(2):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)

    t0 = time.perf_counter()
    for _ in range(iters):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)  # the only trustworthy completion fence
    dt = time.perf_counter() - t0

    steps = iters * num_envs * horizon
    sps = steps / dt
    result["value"] = round(sps, 1)
    result["vs_baseline"] = round(sps / NORTH_STAR, 3)
    result["iter_ms"] = round(dt / iters * 1e3, 2)
    if costs is not None:
        achieved = costs["flops"] * iters / dt
        result["model_flops_per_s"] = round(achieved, 1)
        result["mfu"] = round(achieved / PEAK_FLOPS_BF16, 6)
    return result


def _sweep_precision(
    num_envs: int | None, horizon: int | None, iters: int | None
) -> dict:
    """The precision-policy campaign (ISSUE 7): time the f32 and bf16
    arms back-to-back at the given geometry, and pull COST-ONLY per-policy
    rows at the true headline geometry (4096x256 — the accountant's
    ``lower().cost_analysis()`` needs no timed window, so the bytes
    comparison stays anchored to the headline workload even on hosts too
    slow to time it). The bf16 arm is the top-level row (what perf_gate's
    cross-round fingerprint sees); the f32 arm and the headline cost rows
    ride under ``precision_sweep`` for the intra-artifact gate."""
    arms = [
        _measure(precision=p, num_envs=num_envs, horizon=horizon, iters=iters)
        for p in ("f32", "mixed", "bf16")
    ]
    headline_costs = [
        _measure(precision=p, cost_only=True)
        for p in ("f32", "mixed", "bf16")
    ]
    headline = dict(arms[-1])  # bf16 is the policy under test
    headline["precision_sweep"] = {
        "arms": arms,
        "headline_costs": headline_costs,
    }
    return headline


# error signatures of a TPU backend-init outage (the round-5 event: the
# tunneled backend refused to come up and bench died rc=1 with a raw
# traceback, leaving NO artifact for the round). Word-bounded regex, not
# bare substrings: 'tpu' must not match inside 'output', or a
# deterministic shape error would burn three compile cycles before the
# artifact lands. Deterministic failures (bad import, config typo, shape
# error) match none of these and are NOT retried — they would repeat.
_BACKEND_INIT_RETRYABLE = (
    r"\btpu\b", r"backend", r"\bunavailable\b", r"deadline.?exceeded",
    r"failed to (connect|initialize)", r"connection (refused|reset)",
    r"no visible device", r"\bplugin\b",
)
RETRY_ATTEMPTS = 3
RETRY_BACKOFF_S = 10.0


def _is_retryable(err: BaseException) -> bool:
    import re

    msg = f"{type(err).__name__}: {err}".lower()
    return any(re.search(sig, msg) for sig in _BACKEND_INIT_RETRYABLE)


def _reset_backends() -> None:
    """Drop jax's cached backend-discovery result so a retry genuinely
    re-attempts TPU init — xla_bridge latches _backends/_backend_errors
    on first use and short-circuits every later call, so without this a
    'retry' either re-raises the cached error instantly or silently
    measures on the CPU fallback. Best-effort across jax pins."""
    try:
        from jax._src import xla_bridge

        xla_bridge._clear_backends()
    except Exception:
        try:
            jax.clear_backends()
        except Exception:
            pass


def main() -> int:
    """Measure with bounded retry/backoff on backend-init outages; on
    ``--host-path``, delegate to the host data-plane campaign
    (perf_wallclock.host_path_main — SEED trainer at the PERF.md
    dm_control geometry, BENCH_host.json artifact) instead; otherwise on
    exhaustion (or a non-retryable failure) print the driver's structured
    failed-round artifact ({"error": ..., "parsed": null} — the shape
    perf_report.newest_bench_artifact already skips over) and exit 0, so
    an outage yields a parseable record instead of a raw-traceback rc=1."""
    if "--host-path" in sys.argv:
        from perf_wallclock import host_path_main

        return host_path_main(sys.argv[1:])
    if "--experience-plane" in sys.argv:
        # sharded experience plane campaign (ISSUE 8): remote shm/tcp/
        # pickle arms vs the in-process replay reference — writes
        # BENCH_experience.json (perf_gate's experience gate consumes it)
        from perf_wallclock import experience_plane_main

        return experience_plane_main(sys.argv[1:])
    if "--act-path" in sys.argv:
        # serving-tier campaign (ISSUE 10): 1 vs N inference replicas +
        # parameter-fanout bytes-per-publish arms — writes BENCH_act.json
        # (perf_gate's act gate consumes it)
        from perf_wallclock import act_path_main

        return act_path_main(sys.argv[1:])
    if "--gateway" in sys.argv:
        # session-gateway campaign (ISSUE 12): attach latency, act RTT
        # through the gateway vs direct-to-fleet, act-cache hit/served
        # split — writes BENCH_gateway.json (perf_gate's gateway gate
        # consumes it)
        from perf_wallclock import gateway_main

        return gateway_main(sys.argv[1:])
    if "--ops-plane" in sys.argv:
        # ops-plane campaign (ISSUE 13): per-cadence tier push +
        # snapshot-build/SLO cost against steady-state iteration time —
        # writes BENCH_ops.json (perf_gate's ops gate consumes it)
        from perf_wallclock import ops_plane_main

        return ops_plane_main(sys.argv[1:])
    if "--trace" in sys.argv:
        # causal tracing + lineage campaign (ISSUE 14): span emit
        # rate/footprint, exact lineage reduction, modeled per-iteration
        # overhead fraction — writes BENCH_trace.json (perf_gate's trace
        # gate consumes it)
        from perf_wallclock import trace_main

        return trace_main(sys.argv[1:])
    if "--watchdog" in sys.argv:
        # watchdog/incident campaign (ISSUE 15): detector sweep +
        # incident-engine observe cost per snapshot, incident-open e2e
        # latency — writes BENCH_watchdog.json (perf_gate's watchdog
        # gate consumes it)
        from perf_wallclock import watchdog_main

        return watchdog_main(sys.argv[1:])
    if "--control" in sys.argv:
        # closed-loop control campaign (ISSUE 16): remediation decision
        # sweep cost, incident -> journaled-action latency, loadgen
        # sustained rate — writes BENCH_control.json (perf_gate's
        # control gate consumes it)
        from perf_wallclock import control_main

        return control_main(sys.argv[1:])
    if "--replay-tiers" in sys.argv:
        # replay-tiers campaign (ISSUE 18): hot-tier sample wait vs the
        # warm shard fan-in, WAL append bytes/step, quantized vs raw
        # cold bytes/transition — writes BENCH_tiers.json (perf_gate's
        # replay-tiers gate consumes it)
        from perf_wallclock import replay_tiers_main

        return replay_tiers_main(sys.argv[1:])
    if "--learner-group" in sys.argv:
        # elastic learner-group campaign (ISSUE 17): M=1 parity vs the
        # single learner, per-M learn arms (in-process fallback + the
        # 8-device-sim all-reduce round) — writes BENCH_lgroup.json +
        # MULTICHIP_r06.json (perf_gate's learner-group gate consumes
        # them)
        from perf_wallclock import learner_group_main

        return learner_group_main(sys.argv[1:])
    if "--chaos" in sys.argv:
        # chaos campaign (ISSUE 20): N seeded short real runs under
        # generated multi-site fault schedules, judged by the invariant
        # oracles, failures shrunk to minimal repros — writes
        # CHAOS_campaign.json (perf_gate's chaos gate consumes it)
        from perf_wallclock import chaos_main

        return chaos_main(sys.argv[1:])
    if "--loop-engine" in sys.argv:
        # loop-engine campaign (ISSUE 19): per-driver iteration time with
        # boundary pipelining off (the legacy inline loop) vs on, plus the
        # off-critical-path fraction the deferral reclaims — writes
        # BENCH_engine.json (perf_gate's engine gate consumes it)
        from perf_wallclock import engine_main

        return engine_main(sys.argv[1:])
    global AUTOTUNE, TUNING_CACHE_DIR, PRECISION
    if "--autotune" in sys.argv:
        AUTOTUNE = sys.argv[sys.argv.index("--autotune") + 1]
    if "--tuning-cache" in sys.argv:
        import os

        TUNING_CACHE_DIR = os.path.abspath(
            sys.argv[sys.argv.index("--tuning-cache") + 1]
        )
    if "--precision" in sys.argv:
        PRECISION = sys.argv[sys.argv.index("--precision") + 1]
    arg = lambda name, cast, default: (
        cast(sys.argv[sys.argv.index(name) + 1])
        if name in sys.argv else default
    )
    num_envs = arg("--num-envs", int, None)
    horizon = arg("--horizon", int, None)
    iters = arg("--iters", int, None)
    cost_only = "--cost-only" in sys.argv
    sweep = "--sweep-precision" in sys.argv
    err = None
    for attempt in range(RETRY_ATTEMPTS):
        try:
            if sweep:
                print(json.dumps(_sweep_precision(num_envs, horizon, iters)))
            else:
                print(json.dumps(_measure(
                    num_envs=num_envs, horizon=horizon, iters=iters,
                    cost_only=cost_only,
                )))
            return 0
        except Exception as e:  # noqa: BLE001 — the artifact records it
            err = f"{type(e).__name__}: {e}"
            if attempt < RETRY_ATTEMPTS - 1 and _is_retryable(e):
                wait = RETRY_BACKOFF_S * 2**attempt
                print(
                    f"bench attempt {attempt + 1}/{RETRY_ATTEMPTS} failed "
                    f"({err}); retrying in {wait:.0f}s",
                    file=sys.stderr,
                )
                time.sleep(wait)
                _reset_backends()
                continue
            break
    print(json.dumps({"error": err, "parsed": None}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
