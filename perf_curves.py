"""Direct block-vs-row shuffle validation at the REAL headline geometry
(round-5 VERDICT weak #3: the 13x block-shuffle win was backed by a
degenerate single-minibatch equivalence test plus thresholded learning
tests; this runs the actual A/B).

Trains the headline workload (PPO+MLP on ``jax:lift``, 4096 envs x 256
horizon, 4 epochs x 4 minibatches) under ``algo.shuffle='block'`` (the
TPU default) and ``'row'`` (exact reference semantics: per-epoch row
reshuffle) for N_ITERS iterations x 3 seeds each, recording the
episode-return curve. Writes ``block_vs_row.json``; perf_report.py
renders the comparison into PERF.md from that artifact, so the (slow,
chip-bound) measurement survives PERF.md regens.

Usage: python perf_curves.py [--iters 150] [--seeds 3]
"""

from __future__ import annotations

import json
import time


N_ITERS = 150
SAMPLE_EVERY = 5
TAIL_SAMPLES = 5  # final-performance estimate = mean of the last 5 samples


def run_one(mode: str, seed: int, n_iters: int):
    from surreal_tpu.launch.trainer import Trainer
    from surreal_tpu.session.config import Config
    from surreal_tpu.session.default_configs import base_config

    cfg = Config(
        learner_config=Config(
            algo=Config(name="ppo", horizon=256, epochs=4,
                        num_minibatches=4, shuffle=mode),
        ),
        env_config=Config(name="jax:lift", num_envs=4096),
        session_config=Config(
            folder=f"/tmp/curves_{mode}_{seed}",
            seed=seed,
            total_env_steps=10**12,
            # cadence = the sampling stride: every_n_iters=1 would force a
            # ~120 ms device_get sync per iteration on the tunneled chip
            # (~5x slowdown) for samples on_m would discard anyway
            metrics=Config(every_n_iters=SAMPLE_EVERY, tensorboard=False,
                           console=False),
            checkpoint=Config(every_n_iters=0),
            eval=Config(every_n_iters=0),
        ),
    ).extend(base_config())
    trainer = Trainer(cfg)
    curve = []
    t0 = time.perf_counter()

    def on_m(it, m):
        r = m.get("episode/return")
        if it % SAMPLE_EVERY == 0 and r is not None and r == r:
            curve.append({"iteration": it, "return": float(r)})
        return it >= n_iters

    trainer.run(on_metrics=on_m)
    out = {
        "mode": mode,
        "seed": seed,
        "wall_s": time.perf_counter() - t0,
        "curve": curve,
    }
    print(json.dumps({k: v for k, v in out.items() if k != "curve"}
                     | {"final_return": curve[-1]["return"] if curve else None},
                     default=float), flush=True)
    return out


def main(argv=None) -> None:
    import sys

    argv = sys.argv[1:] if argv is None else argv
    n_iters = N_ITERS
    n_seeds = 3
    if "--iters" in argv:
        n_iters = int(argv[argv.index("--iters") + 1])
    if "--seeds" in argv:
        n_seeds = int(argv[argv.index("--seeds") + 1])

    runs = []
    # interleave modes so any slow tunnel drift hits both arms equally
    for seed in range(n_seeds):
        for mode in ("block", "row"):
            runs.append(run_one(mode, seed, n_iters))

    def mode_stats(mode):
        import statistics

        # tail MEAN over the last few sampled iterations, not the single
        # final point: episode/return is a per-iteration mean over only
        # the episodes that finished in that iteration, so one-iteration
        # point estimates carry episode noise straight into the verdict
        finals = [
            statistics.fmean(p["return"] for p in r["curve"][-TAIL_SAMPLES:])
            for r in runs
            if r["mode"] == mode and r["curve"]
        ]
        finals.sort()
        return {
            "final_returns": finals,
            "final_median": statistics.median(finals) if finals else None,
        }

    summary = {
        "geometry": "jax:lift 4096x256, 4 epochs x 4 minibatches",
        "n_iters": n_iters,
        "block": mode_stats("block"),
        "row": mode_stats("row"),
    }
    with open("block_vs_row.json", "w") as f:
        json.dump({"summary": summary, "runs": runs}, f, indent=2,
                  default=float)
    print(json.dumps(summary, indent=2, default=float))


if __name__ == "__main__":
    main()
