"""Perf regression gate (ISSUE 6 satellite): compare the newest committed
``BENCH_*.json`` row against the previous committed baseline with the
same workload fingerprint and exit nonzero on a >10% throughput
regression.

Fingerprint = the artifact's ``metric`` string plus the recorded
platform/device (a CPU-fallback row must never gate against a chip
record, and vice versa — bench.py records both fields since PR 2; older
artifacts recorded neither, which this gate treats as a distinct
"unrecorded" fingerprint rather than guessing).

Tolerances (CI must stay green through environment noise, red only on a
real regression):

- no artifacts at all, only one artifact per fingerprint, or a newest
  artifact from a FAILED round (``parsed: null`` — the round-5 backend
  outage shape): rc 0 with a note. A missing measurement is a campaign
  problem, not a regression.
- improvement or regression within ``--threshold`` (default 10%): rc 0.
- newest value < (1 - threshold) x baseline value for the same
  fingerprint: rc 1, with both rows printed.

Usage:
    python perf_gate.py                  # gate the repo's committed rows
    python perf_gate.py --threshold 0.2 --dir /path/to/artifacts
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys


def load_rows(art_dir: str) -> list[dict]:
    """All parseable ``BENCH_r*.json`` rows, oldest -> newest by round
    number — the ONE parser for the committed headline-artifact trail
    (this gate AND perf_report.py's observability table import it, so
    the CI gate and PERF.md can never classify the same artifact
    differently).

    Each row: {file, round, metric, value, platform, device, mfu,
    failed}. Files without a numeric round suffix (BENCH_host.json,
    BENCH_tune.json) carry workload tables, not one gated headline row —
    skipped entirely."""
    rows = []
    for path in sorted(glob.glob(os.path.join(art_dir, "BENCH_r*.json"))):
        name = os.path.basename(path)
        m = re.match(r"BENCH_r(\d+)\.json$", name)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(data, dict):
            continue
        # driver artifacts wrap the bench line under "parsed"; a failed
        # round writes "parsed": null — `or` lets it fall through to the
        # raw dict shape (standalone bench.py output)
        parsed = data.get("parsed") or data
        if (
            not isinstance(parsed, dict)
            or parsed.get("value") is None
        ):
            rows.append({"file": name, "round": int(m.group(1)),
                         "failed": True})
            continue
        ne, hz = parsed.get("num_envs"), parsed.get("horizon")
        rows.append({
            "file": name,
            "round": int(m.group(1)),
            "metric": str(parsed.get("metric")),
            "value": float(parsed["value"]),
            "platform": parsed.get("platform"),
            "device": parsed.get("device"),
            "mfu": parsed.get("mfu"),
            # measurement geometry + arm (bench.py records both since
            # ISSUE 7): rows from different geometries/policy arms must
            # never silently read as comparable — the r06 row is a bf16
            # 512x64 CPU arm, not a headline regression. None = the
            # artifact predates the fields.
            "geometry": f"{ne}x{hz}" if ne and hz else None,
            "arm": parsed.get("precision"),
            "failed": False,
        })
    rows.sort(key=lambda r: r["round"])
    return rows


def fingerprint(row: dict) -> tuple:
    # geometry + precision arm joined the fingerprint with the ISSUE-10
    # table fix: a row measured at a different geometry or policy arm is
    # a different workload, and gating it against the headline rows is
    # exactly the cross-geometry misread the per-row fields exist to
    # prevent (rows predating the fields compare among themselves via
    # the 'unrecorded' bucket, as platform/device already did)
    return (
        row.get("metric"),
        row.get("platform") or "unrecorded",
        row.get("device") or "unrecorded",
        row.get("geometry") or "unrecorded",
        row.get("arm") or "unrecorded",
    )


def gate_precision(art_dir: str, newest_file: str, threshold: float,
                   out=sys.stdout) -> int:
    """Intra-artifact precision gate (ISSUE 7): when the newest artifact
    carries a ``precision_sweep`` (bench.py --sweep-precision), enforce
    the low-precision pipeline's two commitments on the SAME image the
    artifact was measured on:

    - wall-clock: the bf16 arm is no slower than the f32 baseline beyond
      ``threshold`` (same steps/s metric, same geometry, back-to-back);
    - bytes: the headline-geometry cost rows show >= 25% lower
      bytes-accessed per iteration under bf16 than f32 (the XLA cost
      model is deterministic — no tolerance needed).

    rc 0 with a note when the artifact carries no sweep (older rounds).
    """
    try:
        with open(os.path.join(art_dir, newest_file)) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return 0
    parsed = (data.get("parsed") or data) if isinstance(data, dict) else {}
    sweep = parsed.get("precision_sweep") if isinstance(parsed, dict) else None
    if not sweep:
        print(f"perf_gate: {newest_file} carries no precision sweep — "
              "nothing to gate per-policy (rc 0)", file=out)
        return 0
    rc = 0
    by_pol = {r.get("precision"): r for r in sweep.get("arms", [])}
    f32, bf16 = by_pol.get("f32"), by_pol.get("bf16")
    mixed = by_pol.get("mixed")
    # the wall-clock baseline is the INCUMBENT policy for the platform:
    # 'mixed' (bf16 compute — the repo's shipped default since the seed)
    # on hosts without native low-precision units, where an f32 arm
    # outruns ANY bf16-computing program by emulation overhead alone and
    # gating against it would flag the pre-existing default as a
    # regression; the true f32 arm on TPU, where bf16 must actually win
    # its keep. Both arms are always RECORDED either way.
    plat = (bf16 or {}).get("platform")
    baseline_arm, base_name = (
        (f32, "f32") if plat == "tpu" else (mixed, "mixed (incumbent)")
    )
    if f32 and bf16 and plat != "tpu" and f32.get("value"):
        print(
            f"perf_gate: note — f32 arm {f32['value']:,.1f} vs bf16 "
            f"{bf16['value']:,.1f} steps/s on platform {plat} (recorded, "
            "not gated: this host emulates bf16; the shipped default "
            "already computes in bf16)", file=out,
        )
    if baseline_arm and bf16 and baseline_arm.get("value") and bf16.get("value"):
        ratio = bf16["value"] / baseline_arm["value"]
        line = (
            f"perf_gate: precision wall-clock bf16 {bf16['value']:,.1f} vs "
            f"{base_name} {baseline_arm['value']:,.1f} steps/s "
            f"(ratio {ratio:.3f}, threshold {1.0 - threshold:.2f}, "
            f"platform {plat})"
        )
        if ratio < 1.0 - threshold:
            print(line + " — REGRESSION", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    costs = {
        r.get("precision"): r for r in sweep.get("headline_costs", [])
    }
    cf, cb = costs.get("f32"), costs.get("bf16")
    if (
        cf and cb
        and cf.get("bytes_accessed_per_iter") and cb.get("bytes_accessed_per_iter")
    ):
        reduction = 1.0 - cb["bytes_accessed_per_iter"] / cf["bytes_accessed_per_iter"]
        line = (
            f"perf_gate: precision bytes-accessed/iter (headline "
            f"{cb.get('num_envs')}x{cb.get('horizon')}) bf16 "
            f"{cb['bytes_accessed_per_iter']:.3e} vs f32 "
            f"{cf['bytes_accessed_per_iter']:.3e} "
            f"({reduction * 100:.1f}% lower; commitment >= 25%)"
        )
        if reduction < 0.25:
            print(line + " — BELOW COMMITMENT", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    return rc


def gate_experience(art_dir: str, out=sys.stdout) -> int:
    """Experience-plane gate (ISSUE 8 satellite): when a committed
    ``BENCH_experience.json`` exists (``bench.py --experience-plane``),
    enforce the plane's two commitments on the image it was measured on:

    - the shm arm's wire bytes per ingested transition stay within 2x of
      the PR-3 slab record (``shm_wire_record_bps`` in the artifact) —
      the control-frames-only contract;
    - the learner's sample-wait EWMA stays under 10% of the iteration
      time (floored at 2 ms for sub-20ms iterations) — the
      "learner never waits on experience ingest" contract.

    rc 0 with a note when the artifact is absent or from a failed round
    (a missing campaign is not a regression).
    """
    path = os.path.join(art_dir, "BENCH_experience.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no BENCH_experience.json — experience plane not "
              "measured (rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or data.get("value") is None:
        print("perf_gate: BENCH_experience.json is from a FAILED campaign "
              "(rc 0)", file=out)
        return 0
    rc = 0
    shm = data.get("shm") or {}
    record = float(data.get("shm_wire_record_bps", 5.8))
    wire = shm.get("wire_bytes_per_step")
    if wire is not None:
        line = (
            f"perf_gate: experience shm wire {float(wire):.1f} B/step vs "
            f"PR-3 slab record {record:.1f} (commitment <= {2 * record:.1f})"
        )
        if float(wire) > 2.0 * record:
            print(line + " — ABOVE COMMITMENT", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    wait = shm.get("sample_wait_ms")
    iter_ms = shm.get("iter_ms")
    if wait is not None and iter_ms:
        budget = max(0.10 * float(iter_ms), 2.0)
        line = (
            f"perf_gate: experience learner sample-wait "
            f"{float(wait):.2f} ms of a {float(iter_ms):.1f} ms iteration "
            f"(commitment <= {budget:.2f} ms)"
        )
        if float(wait) > budget:
            print(line + " — LEARNER WAITS ON INGEST", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    return rc


def gate_act(art_dir: str, out=sys.stdout) -> int:
    """Act-serving-tier gate (ISSUE 10): when a committed
    ``BENCH_act.json`` exists (``bench.py --act-path``), enforce the
    tier's two commitments on the image it was measured on:

    - replication does not collapse throughput: the N-replica arm's env
      steps/s stay >= ``act_honesty_ratio`` x the single-replica arm.
      The bound is the artifact's own (0.5 on a one-core box, where the
      fleet's N serve threads run SERIALLY — each round pays N small
      forwards instead of one coalesced one, and the serve threads
      contend with the learner for the core; the >= 1x SCALING claim is
      cross-core and waits on a multi-core measurement round);
    - fanout bytes: the delta AND bf16 arms' steady bytes-per-publish
      sit BELOW the full-f32 broadcast frame (which itself replaces N
      per-client fetch blobs with one encode).

    rc 0 with a note when the artifact is absent or from a failed round.
    """
    path = os.path.join(art_dir, "BENCH_act.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no BENCH_act.json — act-serving tier not "
              "measured (rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or data.get("value") is None:
        print("perf_gate: BENCH_act.json is from a FAILED campaign (rc 0)",
              file=out)
        return 0
    rc = 0
    single = data.get("single") or {}
    fleet = data.get("fleet") or {}
    # default mirrors the producer's bound (perf_wallclock.py
    # ACT_HONESTY_RATIO) so a field-less artifact can't flip the verdict
    honesty = float(data.get("act_honesty_ratio", 0.5))
    s_sps, f_sps = single.get("env_steps_per_s"), fleet.get("env_steps_per_s")
    # `is not None`, not truthiness: a MEASURED 0.0 (total collapse) must
    # gate red, not silently skip the check
    if s_sps is not None and f_sps is not None and float(s_sps) > 0:
        ratio = float(f_sps) / float(s_sps)
        line = (
            f"perf_gate: act-path {fleet.get('replicas')}-replica "
            f"{float(f_sps):,.1f} vs single {float(s_sps):,.1f} steps/s "
            f"(ratio {ratio:.3f}, commitment >= {honesty:.2f} on a "
            f"one-core box)"
        )
        if ratio < honesty:
            print(line + " — TIER COLLAPSES THROUGHPUT", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    arms = (data.get("fanout") or {}).get("arms") or {}
    full = (arms.get("full_f32") or {}).get("bytes_per_publish")
    if full is not None:
        for arm in ("delta", "bf16"):
            got = (arms.get(arm) or {}).get("bytes_per_publish")
            if got is None:
                continue
            line = (
                f"perf_gate: fanout {arm} {float(got):,.1f} B/publish vs "
                f"full-f32 {float(full):,.1f} (commitment: below)"
            )
            if float(got) >= float(full):
                print(line + " — NOT BELOW", file=out)
                rc = 1
            else:
                print(line + " — ok", file=out)
    return rc


def gate_gateway(art_dir: str, out=sys.stdout) -> int:
    """Session-gateway gate (ISSUE 12): when a committed
    ``BENCH_gateway.json`` exists (``bench.py --gateway``), enforce the
    tier's two commitments on the image it was measured on:

    - the session tier does not double act latency: gateway act RTT p50
      stays <= ``rtt_ratio_max`` x the direct in-process ``serve_act``
      p50 (2.0 on a one-core box, where the client, the gateway loop,
      and the fleet contend for the same core — the wire round-trip
      rides on top of the SAME policy forward the direct arm times);
    - a cache hit is strictly faster than a served act: the act cache's
      value claim is skipping the forward, so hit p50 must sit BELOW
      served p50 at the duplicated-obs workload.

    rc 0 with a note when the artifact is absent or from a failed round.
    """
    path = os.path.join(art_dir, "BENCH_gateway.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no BENCH_gateway.json — session gateway not "
              "measured (rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or data.get("value") is None:
        print("perf_gate: BENCH_gateway.json is from a FAILED campaign "
              "(rc 0)", file=out)
        return 0
    rc = 0
    # default mirrors the producer's bound (perf_wallclock.py
    # GW_RTT_RATIO_MAX) so a field-less artifact can't flip the verdict
    bound = float(data.get("rtt_ratio_max", 2.0))
    rtt = (data.get("act_rtt_ms") or {}).get("p50")
    direct = (data.get("direct_ms") or {}).get("p50")
    # `is not None`, not truthiness: a MEASURED 0.0 direct p50 means the
    # ratio is meaningless — skip with a note rather than divide
    if rtt is not None and direct is not None and float(direct) > 0:
        ratio = float(rtt) / float(direct)
        line = (
            f"perf_gate: gateway act RTT p50 {float(rtt):.3f} ms vs "
            f"direct {float(direct):.3f} ms (ratio {ratio:.3f}, "
            f"commitment <= {bound:.1f}x on a one-core box)"
        )
        if ratio > bound:
            print(line + " — GATEWAY DOUBLES ACT LATENCY", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    cache = data.get("cache") or {}
    hit = (cache.get("hit_ms") or {}).get("p50")
    served = (cache.get("served_ms") or {}).get("p50")
    if hit is not None and served is not None:
        line = (
            f"perf_gate: gateway cache hit p50 {float(hit):.3f} ms vs "
            f"served {float(served):.3f} ms at hit-rate "
            f"{float(cache.get('hit_rate', 0)):.2f} "
            "(commitment: strictly below)"
        )
        if float(hit) >= float(served):
            print(line + " — HIT NOT FASTER THAN A FORWARD", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    return rc


def gate_ops(art_dir: str, out=sys.stdout) -> int:
    """The ops-plane overhead commitments (ISSUE 13), from
    ``BENCH_ops.json`` (``python bench.py --ops-plane``):

    - observability must never become the workload: building + writing
      one merged run snapshot (SLO evaluation included) costs <=
      ``snapshot_frac_max`` (5%) of one steady-state train iteration at
      the committed headline geometry;
    - a tier's push must stay serve-loop cheap: push p99 under 1 ms
      (non-blocking send of one JSON row — anything slower would tax
      every gateway/replica/shard loop pass).

    rc 0 with a note when the artifact is absent or from a failed round.
    """
    path = os.path.join(art_dir, "BENCH_ops.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no BENCH_ops.json — ops plane not measured "
              "(rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or data.get("value") is None:
        print("perf_gate: BENCH_ops.json is from a FAILED campaign "
              "(rc 0)", file=out)
        return 0
    rc = 0
    # default mirrors the producer's bound (perf_wallclock.py
    # OPS_SNAPSHOT_FRAC_MAX) so a field-less artifact can't flip the verdict
    frac_max = float(data.get("snapshot_frac_max", 0.05))
    snap = (data.get("snapshot_ms") or {}).get("p50")
    iter_ms = data.get("iter_ms")
    if snap is not None and iter_ms is not None and float(iter_ms) > 0:
        frac = float(snap) / float(iter_ms)
        line = (
            f"perf_gate: ops snapshot build p50 {float(snap):.3f} ms vs "
            f"iteration {float(iter_ms):.1f} ms ({frac:.2%} of the "
            f"iteration, commitment <= {frac_max:.0%})"
        )
        if frac > frac_max:
            print(line + " — OBSERVABILITY BECAME THE WORKLOAD", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    push = (data.get("push_ms") or {}).get("p99")
    if push is not None:
        line = (
            f"perf_gate: ops tier push p99 {float(push):.4f} ms "
            "(commitment < 1 ms on the serve loop)"
        )
        if float(push) >= 1.0:
            print(line + " — PUSH TAXES THE SERVE LOOP", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    return rc


def gate_trace(art_dir: str, out=sys.stdout) -> int:
    """The causal-tracing overhead commitment (ISSUE 14), from
    ``BENCH_trace.json`` (``python bench.py --trace``): every span the
    head-sampled serving + learner paths emit per iteration (priced at
    the measured p99 emit cost) PLUS the exact lineage reduction over
    the full 512x64 version column must cost <= ``overhead_frac_max``
    (2%) of one steady-state train iteration at the committed headline
    geometry — tracing the workload must never become the workload.

    rc 0 with a note when the artifact is absent or from a failed round.
    """
    path = os.path.join(art_dir, "BENCH_trace.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no BENCH_trace.json — tracing not measured "
              "(rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or data.get("value") is None:
        print("perf_gate: BENCH_trace.json is from a FAILED campaign "
              "(rc 0)", file=out)
        return 0
    # default mirrors the producer's bound (perf_wallclock.py
    # TRACE_OVERHEAD_FRAC_MAX) so a field-less artifact can't flip the
    # verdict
    frac_max = float(data.get("overhead_frac_max", 0.02))
    frac = data.get("overhead_frac_of_iter", data.get("value"))
    iter_ms = data.get("iter_ms")
    line = (
        f"perf_gate: trace+lineage {float(frac):.3%} of the iteration"
        + (f" ({float(iter_ms):.1f} ms)" if iter_ms is not None else "")
        + f", commitment <= {frac_max:.0%}"
    )
    if float(frac) > frac_max:
        print(line + " — TRACING BECAME THE WORKLOAD", file=out)
        return 1
    print(line + " — ok", file=out)
    return 0


def gate_watchdog(art_dir: str, out=sys.stdout) -> int:
    """The watchdog overhead commitment (ISSUE 15), from
    ``BENCH_watchdog.json`` (``python bench.py --watchdog``): one full
    detector sweep (all five families armed at the production tier
    census) plus the incident engine's per-sweep observe, priced at the
    measured p99, must cost <= ``eval_frac_max`` (1%) of one
    steady-state train iteration at the committed headline geometry —
    the watchdog judges the workload, it must never become one.

    rc 0 with a note when the artifact is absent or from a failed round.
    """
    path = os.path.join(art_dir, "BENCH_watchdog.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no BENCH_watchdog.json — watchdog not measured "
              "(rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or data.get("value") is None:
        print("perf_gate: BENCH_watchdog.json is from a FAILED campaign "
              "(rc 0)", file=out)
        return 0
    # default mirrors the producer's bound (perf_wallclock.py
    # WATCHDOG_EVAL_FRAC_MAX) so a field-less artifact can't flip the
    # verdict
    frac_max = float(data.get("eval_frac_max", 0.01))
    frac = data.get("eval_frac_of_iter", data.get("value"))
    iter_ms = data.get("iter_ms")
    line = (
        f"perf_gate: watchdog sweep+incident p99 {float(frac):.3%} of "
        "the iteration"
        + (f" ({float(iter_ms):.1f} ms)" if iter_ms is not None else "")
        + f", commitment <= {frac_max:.0%}"
    )
    if float(frac) > frac_max:
        print(line + " — THE WATCHDOG BECAME THE WORKLOAD", file=out)
        return 1
    print(line + " — ok", file=out)
    return 0


def gate_control(art_dir: str, out=sys.stdout) -> int:
    """The control-loop overhead commitment (ISSUE 16), from
    ``BENCH_control.json`` (``python bench.py --control``): one
    remediation decision sweep (verification tick for the in-flight
    action plus the open-incident mapping guards), priced at the
    measured p99, must cost <= ``decide_frac_max`` (1%) of one
    steady-state train iteration at the committed headline geometry —
    the control loop steers the workload, it must never become one.

    rc 0 with a note when the artifact is absent or from a failed round.
    """
    path = os.path.join(art_dir, "BENCH_control.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no BENCH_control.json — control loop not "
              "measured (rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or data.get("value") is None:
        print("perf_gate: BENCH_control.json is from a FAILED campaign "
              "(rc 0)", file=out)
        return 0
    # default mirrors the producer's bound (perf_wallclock.py
    # CONTROL_DECIDE_FRAC_MAX) so a field-less artifact can't flip the
    # verdict
    frac_max = float(data.get("decide_frac_max", 0.01))
    frac = data.get("decide_frac_of_iter", data.get("value"))
    iter_ms = data.get("iter_ms")
    line = (
        f"perf_gate: remediation decision sweep p99 {float(frac):.3%} "
        "of the iteration"
        + (f" ({float(iter_ms):.1f} ms)" if iter_ms is not None else "")
        + f", commitment <= {frac_max:.0%}"
    )
    if float(frac) > frac_max:
        print(line + " — THE CONTROL LOOP BECAME THE WORKLOAD", file=out)
        return 1
    print(line + " — ok", file=out)
    return 0


def gate_learner_group(art_dir: str, out=sys.stdout) -> int:
    """The elastic learner-group commitments (ISSUE 17), from
    ``BENCH_lgroup.json`` + ``MULTICHIP_r06.json`` (``bench.py
    --learner-group``):

    - M=1 parity: the one-member group's updates/s within ``parity_tol``
      (2%) of the single learner — the group abstraction is free when
      unused;
    - scaling honesty: under mode='scaling' (>= 2 real cores behind the
      simulated devices) the M=2 all-reduce arm must reach
      ``scale_min_m2`` (1.6x) over M=1; under mode='honesty' (one core
      time-slicing the sim) the measured ratios are recorded as-is and
      only their PRESENCE is enforced — a fabricated speedup can't pass
      because the mode rides the artifact.

    rc 0 with a note when the artifact is absent or from a failed round.
    """
    path = os.path.join(art_dir, "BENCH_lgroup.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no BENCH_lgroup.json — learner group not "
              "measured (rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or data.get("value") is None:
        print("perf_gate: BENCH_lgroup.json is from a FAILED campaign "
              "(rc 0)", file=out)
        return 0
    rc = 0
    parity = float(data["value"])
    tol = float(data.get("parity_tol", 0.02))
    line = (f"perf_gate: learner-group M=1 parity {parity:.4f}x the "
            f"single learner, commitment >= {1 - tol:.2f}x")
    if parity < 1.0 - tol:
        print(line + " — THE GROUP ABSTRACTION TAXES THE SINGLE-LEARNER "
              "PATH", file=out)
        rc = 1
    else:
        print(line + " — ok", file=out)
    # the multichip round: scaling bound in scaling mode, honesty rows
    # otherwise
    mc_path = os.path.join(art_dir, "MULTICHIP_r06.json")
    try:
        with open(mc_path) as f:
            mc = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no MULTICHIP_r06.json — the 8-device-sim "
              "all-reduce round was not measured (rc 0)", file=out)
        return rc
    if not mc.get("ok") or not mc.get("rounds"):
        print("perf_gate: MULTICHIP_r06.json records a failed sim round "
              "(rc 0 — the BENCH_lgroup parity verdict stands)", file=out)
        return rc
    rounds = mc["rounds"]
    m2 = rounds.get("2", {}).get("speedup_vs_m1")
    mode = str(mc.get("mode", data.get("mode", "honesty")))
    scale_min = float(mc.get("scale_min_m2", 1.6))
    if m2 is None:
        print("perf_gate: MULTICHIP_r06.json has no M=2 round — the "
              "scaling claim is unmeasured", file=out)
        return max(rc, 1)
    if mode == "scaling":
        line = (f"perf_gate: learner-group M=2 all-reduce {float(m2):.2f}x "
                f"M=1 on the sim mesh, commitment >= {scale_min:.1f}x")
        if float(m2) < scale_min:
            print(line + " — GROUP SCALING COLLAPSED", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    else:
        print(
            f"perf_gate: learner-group sim round ran on "
            f"{mc.get('cores', '?')} core(s) — honesty mode, measured "
            f"M=2 ratio {float(m2):.2f}x recorded, scaling bound "
            "deferred to a multi-core round", file=out,
        )
    return rc


def gate_replay_tiers(art_dir: str, out=sys.stdout) -> int:
    """Replay-tiers gate (ISSUE 18): when a committed
    ``BENCH_tiers.json`` exists (``bench.py --replay-tiers``), enforce
    the hierarchy's two commitments on the image it was measured on:

    - the hot arm's learner sample-wait EWMA sits at or below the warm
      arm's — the device-resident tier must never be slower to serve
      than the shard fan-in it fronts (the acceptance criterion: hot-hit
      ``experience/sample_wait_ms`` measurably below the committed warm
      figure);
    - the quantized cold row is >= 25% smaller than the raw f32
      transition (``cold_vs_raw_ratio <= 0.75``) — the HEPPO-GAE
      quantization actually pays for itself on disk.

    rc 0 with a note when the artifact is absent or from a failed round
    (a missing campaign is not a regression).
    """
    path = os.path.join(art_dir, "BENCH_tiers.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no BENCH_tiers.json — replay tiers not measured "
              "(rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or data.get("value") is None:
        print("perf_gate: BENCH_tiers.json is from a FAILED campaign "
              "(rc 0)", file=out)
        return 0
    rc = 0
    warm_wait = (data.get("warm") or {}).get("sample_wait_ms")
    hot_wait = (data.get("hot") or {}).get("sample_wait_ms")
    if warm_wait is not None and hot_wait is not None:
        line = (
            f"perf_gate: replay-tiers hot sample-wait "
            f"{float(hot_wait):.3f} ms vs warm {float(warm_wait):.3f} ms "
            "(commitment: hot <= warm)"
        )
        if float(hot_wait) > float(warm_wait):
            print(line + " — HOT TIER SLOWER THAN WARM", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    ratio = data.get("cold_vs_raw_ratio")
    if ratio is not None:
        line = (
            f"perf_gate: replay-tiers cold row {float(ratio):.3f}x the raw "
            "f32 transition (commitment <= 0.75)"
        )
        if float(ratio) > 0.75:
            print(line + " — QUANTIZATION NOT PAYING", file=out)
            rc = 1
        else:
            print(line + " — ok", file=out)
    return rc


def gate_engine(art_dir: str, out=sys.stdout) -> int:
    """Loop-engine gate (ISSUE 19), from ``BENCH_engine.json``
    (``bench.py --loop-engine``): per ported driver, the pipelined arm's
    steady-state iteration time must sit at or below the legacy inline
    arm's within ``tol`` (5%) — deferring the boundary must never tax
    the critical path — and the pipelined arm must actually have
    deferred boundaries (a no-op 'on' arm reading as parity would be a
    fabricated win).

    One-core honesty: under mode='honesty' (< 2 cores, the staging
    worker time-slices the compute thread) the measured ratios are
    recorded as-is and only their presence is enforced — the <= bound
    waits for a box where overlap is physically possible, and the mode
    rides the artifact so a one-core run can't masquerade as a
    measured speedup. rc 0 with a note when the artifact is absent or
    from a failed campaign."""
    path = os.path.join(art_dir, "BENCH_engine.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no BENCH_engine.json — loop engine not "
              "measured (rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or not data.get("drivers"):
        print("perf_gate: BENCH_engine.json is from a FAILED campaign "
              "(rc 0)", file=out)
        return 0
    rc = 0
    tol = float(data.get("tol", 0.05))
    mode = str(data.get("mode", "honesty"))
    enforce = mode == "overlap"
    for name, row in sorted(data["drivers"].items()):
        ratio = row.get("iter_ratio_on_vs_off")
        if ratio is None:
            print(f"perf_gate: engine driver {name} has no measured "
                  "ratio — the arm did not complete", file=out)
            rc = 1
            continue
        deferred = float((row.get("on") or {}).get(
            "deferred_boundaries") or 0.0)
        if deferred <= 0.0:
            print(f"perf_gate: engine driver {name} pipelined arm "
                  "deferred ZERO boundaries — pipelining never engaged",
                  file=out)
            rc = 1
            continue
        line = (f"perf_gate: engine {name} pipelined/legacy iter ratio "
                f"{float(ratio):.3f}, commitment <= {1 + tol:.2f}")
        if enforce and float(ratio) > 1.0 + tol:
            print(line + " — PIPELINING TAXES THE CRITICAL PATH", file=out)
            rc = 1
        elif enforce:
            print(line + " — ok", file=out)
        else:
            print(line + f" — recorded (mode={mode}, "
                  f"{data.get('cores', '?')} core(s); bound deferred to "
                  "a multi-core round)", file=out)
    return rc


def gate_tier1(art_dir: str, out=sys.stdout) -> int:
    """The tier-1 wall-clock budget guard (ISSUE 13 satellite): the
    committed ``BENCH_tier1.json`` audit (one real ``--durations=15``
    run: wall_s, passed/failed, worst offenders) must stay inside the
    budget its ROADMAP note claims, and the note must cite the SAME
    budget the verify command enforces — the "runtime is a real
    constraint" sentence can never silently go stale.

    rc 0 with a note when no audit is committed."""
    path = os.path.join(art_dir, "BENCH_tier1.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no BENCH_tier1.json — tier-1 runtime not "
              "audited (rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or data.get("wall_s") is None:
        print("perf_gate: BENCH_tier1.json carries no wall_s (rc 0)",
              file=out)
        return 0
    rc = 0
    wall = float(data["wall_s"])
    budget = float(data.get("budget_s", 870))
    line = (
        f"perf_gate: tier-1 suite {wall:.0f} s of the {budget:.0f} s "
        f"budget ({data.get('passed', '?')} passed, "
        f"{data.get('failed', '?')} failed)"
    )
    if wall > budget:
        print(line + " — OVER BUDGET (mark offenders slow or raise the "
              "budget WITH the ROADMAP note)", file=out)
        rc = 1
    elif wall > 0.95 * budget:
        print(line + " — ok, but within 5% of the ceiling", file=out)
    else:
        print(line + " — ok", file=out)
    if int(data.get("failed", 0) or 0) > 0:
        print("perf_gate: the committed tier-1 audit records FAILURES — "
              "an audit of a red suite must not be the committed record",
              file=out)
        rc = 1
    # the honesty half: ROADMAP's verify command must enforce the same
    # budget the audit was judged against
    try:
        with open(os.path.join(art_dir, "ROADMAP.md")) as f:
            roadmap = f.read()
    except OSError:
        roadmap = ""
    if roadmap and f"timeout -k 10 {int(budget)}" not in roadmap:
        print(
            f"perf_gate: BENCH_tier1.json budget_s={int(budget)} but "
            "ROADMAP.md's tier-1 command enforces a DIFFERENT timeout — "
            "the wall-clock note went stale", file=out,
        )
        rc = 1
    return rc


def gate_chaos(art_dir: str, out=sys.stdout) -> int:
    """Chaos-campaign gate (ISSUE 20): the committed
    ``CHAOS_campaign.json`` (``surreal_tpu chaos all --seeds N --out``)
    must record a campaign broad enough to mean something and clean
    enough to ship:

    - >= 25 seeded schedules actually ran (``chaos/schedules``);
    - >= 10 DISTINCT fault sites fired (``sites_covered`` counts sites
      whose faults were delivered, not merely drawn — a schedule whose
      faults never reach their call counts proves nothing);
    - ZERO invariant violations and zero recorded failures — a failing
      schedule ships as a shrunk minimal repro in ``failures``, and a
      repo with a known-failing chaos seed must gate red until the bug
      (or the oracle) is fixed.

    rc 0 with a note when the artifact is absent (a missing campaign is
    a campaign problem, not a regression)."""
    path = os.path.join(art_dir, "CHAOS_campaign.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        print("perf_gate: no CHAOS_campaign.json — chaos campaign not "
              "run (rc 0)", file=out)
        return 0
    if not isinstance(data, dict) or data.get("kind") != "chaos_campaign":
        print("perf_gate: CHAOS_campaign.json is not a campaign artifact "
              "(rc 0)", file=out)
        return 0
    rc = 0
    g = data.get("gauges") or {}
    n_sched = int(g.get("chaos/schedules", 0))
    n_sites = int(g.get("chaos/sites_covered",
                        len(data.get("sites_covered") or ())))
    n_viol = int(g.get("chaos/violations", 0))
    n_fail = len(data.get("failures") or ())
    line = (
        f"perf_gate: chaos campaign {n_sched} schedules, {n_sites} "
        f"distinct fired sites, {n_viol} violations "
        f"(commitments >= 25 schedules, >= 10 sites, 0 violations)"
    )
    if n_sched < 25:
        print(line + " — CAMPAIGN TOO SMALL", file=out)
        rc = 1
    elif n_sites < 10:
        print(line + " — SITE COVERAGE TOO NARROW", file=out)
        rc = 1
    elif n_viol > 0 or n_fail > 0:
        print(line + " — INVARIANT VIOLATIONS ON RECORD", file=out)
        for fail in (data.get("failures") or ())[:5]:
            print(
                f"perf_gate:   chaos repro profile={fail.get('profile')} "
                f"seed={fail.get('seed')} minimal_plan="
                f"{len(fail.get('minimal_plan') or ())} spec(s)", file=out,
            )
        rc = 1
    else:
        print(line + " — ok", file=out)
    return rc


def gate(art_dir: str, threshold: float, out=sys.stdout) -> int:
    # the experience-plane, act-path, gateway, ops-plane, trace,
    # watchdog, control, and tier-1 budget gates are independent of the
    # BENCH_r* trail: run them first and fold their verdicts into every
    # return path
    xp_rc = max(
        gate_experience(art_dir, out=out), gate_act(art_dir, out=out),
        gate_gateway(art_dir, out=out), gate_ops(art_dir, out=out),
        gate_trace(art_dir, out=out), gate_watchdog(art_dir, out=out),
        gate_control(art_dir, out=out), gate_learner_group(art_dir, out=out),
        gate_replay_tiers(art_dir, out=out), gate_engine(art_dir, out=out),
        gate_tier1(art_dir, out=out), gate_chaos(art_dir, out=out),
    )
    rows = load_rows(art_dir)
    valid = [r for r in rows if not r.get("failed")]
    if not rows:
        print("perf_gate: no BENCH_*.json artifacts found — nothing to "
              "gate (rc 0)", file=out)
        return xp_rc
    newest = rows[-1]
    if newest.get("failed"):
        print(
            f"perf_gate: newest artifact {newest['file']} is from a FAILED "
            "round (no parsed row) — a missing measurement is a campaign "
            "problem, not a regression (rc 0)", file=out,
        )
        return xp_rc
    # intra-artifact precision gate rides every verdict below: the
    # cross-round compare and the per-policy commitments are independent
    prec_rc = gate_precision(art_dir, newest["file"], threshold, out=out)
    baseline = None
    for r in valid[:-1][::-1]:
        if fingerprint(r) == fingerprint(newest):
            baseline = r
            break
    if baseline is None:
        print(
            f"perf_gate: {newest['file']} ({newest['metric']}) has no "
            "earlier committed artifact with the same fingerprint — "
            "nothing to compare (rc 0)", file=out,
        )
        return max(prec_rc, xp_rc)
    ratio = newest["value"] / baseline["value"] if baseline["value"] else 1.0
    verdict = (
        f"perf_gate: {newest['file']} {newest['value']:,.1f} vs baseline "
        f"{baseline['file']} {baseline['value']:,.1f} "
        f"({newest['metric']}; ratio {ratio:.3f}, threshold "
        f"{1.0 - threshold:.2f})"
    )
    if ratio < 1.0 - threshold:
        print(verdict + " — REGRESSION", file=out)
        return 1
    print(verdict + " — ok", file=out)
    return max(prec_rc, xp_rc)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="gate the newest BENCH_*.json against the committed "
                    "baseline for the same workload fingerprint"
    )
    ap.add_argument("--dir", default=".", help="artifact directory")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="allowed fractional regression (default 0.10)")
    args = ap.parse_args(argv)
    return gate(args.dir, args.threshold)


if __name__ == "__main__":
    sys.exit(main())
