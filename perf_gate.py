"""Perf regression gate (ISSUE 6 satellite): compare the newest committed
``BENCH_*.json`` row against the previous committed baseline with the
same workload fingerprint and exit nonzero on a >10% throughput
regression.

Fingerprint = the artifact's ``metric`` string plus the recorded
platform/device (a CPU-fallback row must never gate against a chip
record, and vice versa — bench.py records both fields since PR 2; older
artifacts recorded neither, which this gate treats as a distinct
"unrecorded" fingerprint rather than guessing).

Tolerances (CI must stay green through environment noise, red only on a
real regression):

- no artifacts at all, only one artifact per fingerprint, or a newest
  artifact from a FAILED round (``parsed: null`` — the round-5 backend
  outage shape): rc 0 with a note. A missing measurement is a campaign
  problem, not a regression.
- improvement or regression within ``--threshold`` (default 10%): rc 0.
- newest value < (1 - threshold) x baseline value for the same
  fingerprint: rc 1, with both rows printed.

Usage:
    python perf_gate.py                  # gate the repo's committed rows
    python perf_gate.py --threshold 0.2 --dir /path/to/artifacts
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys


def load_rows(art_dir: str) -> list[dict]:
    """All parseable ``BENCH_r*.json`` rows, oldest -> newest by round
    number — the ONE parser for the committed headline-artifact trail
    (this gate AND perf_report.py's observability table import it, so
    the CI gate and PERF.md can never classify the same artifact
    differently).

    Each row: {file, round, metric, value, platform, device, mfu,
    failed}. Files without a numeric round suffix (BENCH_host.json,
    BENCH_tune.json) carry workload tables, not one gated headline row —
    skipped entirely."""
    rows = []
    for path in sorted(glob.glob(os.path.join(art_dir, "BENCH_r*.json"))):
        name = os.path.basename(path)
        m = re.match(r"BENCH_r(\d+)\.json$", name)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(data, dict):
            continue
        # driver artifacts wrap the bench line under "parsed"; a failed
        # round writes "parsed": null — `or` lets it fall through to the
        # raw dict shape (standalone bench.py output)
        parsed = data.get("parsed") or data
        if (
            not isinstance(parsed, dict)
            or parsed.get("value") is None
        ):
            rows.append({"file": name, "round": int(m.group(1)),
                         "failed": True})
            continue
        rows.append({
            "file": name,
            "round": int(m.group(1)),
            "metric": str(parsed.get("metric")),
            "value": float(parsed["value"]),
            "platform": parsed.get("platform"),
            "device": parsed.get("device"),
            "mfu": parsed.get("mfu"),
            "failed": False,
        })
    rows.sort(key=lambda r: r["round"])
    return rows


def fingerprint(row: dict) -> tuple:
    return (
        row.get("metric"),
        row.get("platform") or "unrecorded",
        row.get("device") or "unrecorded",
    )


def gate(art_dir: str, threshold: float, out=sys.stdout) -> int:
    rows = load_rows(art_dir)
    valid = [r for r in rows if not r.get("failed")]
    if not rows:
        print("perf_gate: no BENCH_*.json artifacts found — nothing to "
              "gate (rc 0)", file=out)
        return 0
    newest = rows[-1]
    if newest.get("failed"):
        print(
            f"perf_gate: newest artifact {newest['file']} is from a FAILED "
            "round (no parsed row) — a missing measurement is a campaign "
            "problem, not a regression (rc 0)", file=out,
        )
        return 0
    baseline = None
    for r in valid[:-1][::-1]:
        if fingerprint(r) == fingerprint(newest):
            baseline = r
            break
    if baseline is None:
        print(
            f"perf_gate: {newest['file']} ({newest['metric']}) has no "
            "earlier committed artifact with the same fingerprint — "
            "nothing to compare (rc 0)", file=out,
        )
        return 0
    ratio = newest["value"] / baseline["value"] if baseline["value"] else 1.0
    verdict = (
        f"perf_gate: {newest['file']} {newest['value']:,.1f} vs baseline "
        f"{baseline['file']} {baseline['value']:,.1f} "
        f"({newest['metric']}; ratio {ratio:.3f}, threshold "
        f"{1.0 - threshold:.2f})"
    )
    if ratio < 1.0 - threshold:
        print(verdict + " — REGRESSION", file=out)
        return 1
    print(verdict + " — ok", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="gate the newest BENCH_*.json against the committed "
                    "baseline for the same workload fingerprint"
    )
    ap.add_argument("--dir", default=".", help="artifact directory")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="allowed fractional regression (default 0.10)")
    args = ap.parse_args(argv)
    return gate(args.dir, args.threshold)


if __name__ == "__main__":
    sys.exit(main())
