"""Host-side observation wrappers (parity: reference
``surreal/env/wrapper.py`` — FrameStackWrapper, GrayscaleWrapper,
TransposeWrapper, FilterWrapper/obs-concat, max-step; SURVEY.md §2.1).

These run on the CPU host on numpy batches *before* ``device_put`` so the
device-bound payload is final (e.g. grayscale before shipping cuts DCN
bytes 3x). Channel convention is channels-last [..., H, W, C] to match TPU
conv layouts; TransposeWrapper exists for sources that produce [C, H, W].
"""

from __future__ import annotations

import dataclasses

import numpy as np

from surreal_tpu.envs.base import ArraySpec, HostEnv, HostWrapper, StepOutput


class FrameStackWrapper(HostWrapper):
    """Stack the last k obs along the channel (last) axis."""

    def __init__(self, env: HostEnv, k: int):
        super().__init__(env)
        self.k = k
        inner = env.specs.obs
        shape = (*inner.shape[:-1], inner.shape[-1] * k)
        self.specs = dataclasses.replace(
            env.specs, obs=dataclasses.replace(inner, shape=shape)
        )
        self._frames: np.ndarray | None = None  # [B, ..., C*k]

    def reset(self, seed: int | None = None) -> np.ndarray:
        obs = self.env.reset(seed)
        self._frames = np.concatenate([obs] * self.k, axis=-1)
        return self._frames.copy()

    def step(self, actions: np.ndarray) -> StepOutput:
        out = self.env.step(actions)
        c = out.obs.shape[-1]
        info = dict(out.info)
        if "terminal_obs" in info:
            # terminal_obs must match THIS wrapper's obs spec: the episode's
            # final stack = previous frames shifted + the terminal frame.
            info["terminal_obs"] = np.concatenate(
                [self._frames[..., c:], info["terminal_obs"]], axis=-1
            )
        self._frames = np.concatenate([self._frames[..., c:], out.obs], axis=-1)
        # reset stacks for finished envs: repeat the fresh reset obs
        if out.done.any():
            idx = np.nonzero(out.done)[0]
            self._frames[idx] = np.concatenate([out.obs[idx]] * self.k, axis=-1)
        return StepOutput(
            obs=self._frames.copy(), reward=out.reward, done=out.done, info=info
        )


class GrayscaleWrapper(HostWrapper):
    """RGB [..., H, W, 3] -> grayscale [..., H, W, 1] (ITU-R 601 luma)."""

    _LUMA = np.asarray([0.299, 0.587, 0.114], np.float32)

    def __init__(self, env: HostEnv):
        super().__init__(env)
        inner = env.specs.obs
        self.specs = dataclasses.replace(
            env.specs, obs=dataclasses.replace(inner, shape=(*inner.shape[:-1], 1))
        )

    def _convert(self, obs: np.ndarray) -> np.ndarray:
        gray = obs.astype(np.float32) @ self._LUMA
        return gray[..., None].astype(self.specs.obs.dtype)

    def reset(self, seed: int | None = None) -> np.ndarray:
        return self._convert(self.env.reset(seed))

    def step(self, actions: np.ndarray) -> StepOutput:
        out = self.env.step(actions)
        info = dict(out.info)
        if "terminal_obs" in info:
            info["terminal_obs"] = self._convert(info["terminal_obs"])
        return StepOutput(
            obs=self._convert(out.obs), reward=out.reward, done=out.done, info=info
        )


class TransposeWrapper(HostWrapper):
    """Permute obs axes (after the batch axis), e.g. CHW -> HWC."""

    def __init__(self, env: HostEnv, perm: tuple[int, ...]):
        super().__init__(env)
        self.perm = perm
        inner = env.specs.obs
        shape = tuple(inner.shape[p] for p in perm)
        self.specs = dataclasses.replace(
            env.specs, obs=dataclasses.replace(inner, shape=shape)
        )
        self._batch_perm = (0, *(p + 1 for p in perm))

    def reset(self, seed: int | None = None) -> np.ndarray:
        return np.transpose(self.env.reset(seed), self._batch_perm)

    def step(self, actions: np.ndarray) -> StepOutput:
        out = self.env.step(actions)
        info = dict(out.info)
        if "terminal_obs" in info:
            info["terminal_obs"] = np.transpose(info["terminal_obs"], self._batch_perm)
        return StepOutput(
            obs=np.transpose(out.obs, self._batch_perm),
            reward=out.reward,
            done=out.done,
            info=info,
        )


class ActionRepeatWrapper(HostWrapper):
    """Repeat each action k times, summing rewards (dm_control-style).

    Batched caveat: the inner env auto-resets, so an env that finishes on an
    inner step keeps stepping its *new* episode for the remaining repeats
    (per-env pausing isn't possible through a batched host adapter). Rewards
    after the boundary are excluded and the FIRST done's terminal_obs /
    truncated are the ones reported, so bootstrapping stays correct; the
    returned obs for such envs is up to k-1 steps into the new episode.
    """

    def __init__(self, env: HostEnv, k: int):
        super().__init__(env)
        self.k = k

    def step(self, actions: np.ndarray) -> StepOutput:
        total = np.zeros(self.num_envs, np.float32)
        done = np.zeros(self.num_envs, bool)
        terminal_obs = None
        truncated = np.zeros(self.num_envs, bool)
        out = None
        for _ in range(self.k):
            out = self.env.step(actions)
            total += out.reward * ~done  # stop accumulating past the boundary
            inner_term = out.info.get("terminal_obs")
            if inner_term is not None:
                if terminal_obs is None:
                    terminal_obs = np.zeros_like(inner_term)
                first_done = out.done & ~done  # envs finishing on THIS inner step
                terminal_obs[first_done] = inner_term[first_done]
                truncated |= np.asarray(out.info.get("truncated", False)) & first_done
            done |= out.done
        info = dict(out.info)
        if terminal_obs is not None:
            info["terminal_obs"] = terminal_obs
            info["truncated"] = truncated
        return StepOutput(obs=out.obs, reward=total, done=done, info=info)


class PixelObsWrapper(HostWrapper):
    """Replace state obs with rendered RGB frames (the pixel-obs path for
    backends whose native obs is a state vector; parity with the reference's
    camera-pixel Robosuite configs, SURVEY.md §2.1 env-adapter row).

    Uses nearest-neighbor resize (pure numpy — no cv2 in this image) to
    ``image_size``. uint8 output keeps host->device bytes small.
    """

    def __init__(self, env: HostEnv, image_size: tuple[int, int] = (84, 84)):
        super().__init__(env)
        self.image_size = tuple(image_size)
        h, w = self.image_size
        self.specs = dataclasses.replace(
            env.specs,
            obs=ArraySpec(shape=(h, w, 3), dtype=np.dtype(np.uint8), name="pixels"),
        )
        # capture the TRUE terminal frame while the episode's last state is
        # still live: the adapter fires this right before its auto-reset
        # (time-limit-truncated pixel episodes bootstrap off this frame; a
        # post-reset render would be the NEXT episode's first frame).
        # Install on the innermost adapter — an instance attribute on an
        # intermediate wrapper would shadow nothing (the adapter checks its
        # OWN attribute) and the hook would silently never fire.
        self._terminal_frames: dict[int, np.ndarray] = {}
        adapter = env
        while isinstance(adapter, HostWrapper):
            adapter = adapter.env
        adapter.pre_reset_hook = self._capture_terminal

    def _render_one(self, env) -> np.ndarray:
        frame = np.asarray(env.render())
        return _nn_resize(frame, self.image_size).astype(np.uint8)

    def _capture_terminal(self, i: int, env) -> None:
        self._terminal_frames[i] = self._render_one(env)

    def _grab(self) -> np.ndarray:
        return np.stack([self._render_one(env) for env in self.env.envs])

    def reset(self, seed: int | None = None) -> np.ndarray:
        self.env.reset(seed)
        self._terminal_frames.clear()
        return self._grab()

    def step(self, actions: np.ndarray) -> StepOutput:
        self._terminal_frames.clear()
        out = self.env.step(actions)
        pixels = self._grab()
        info = dict(out.info)
        terminal = pixels
        if self._terminal_frames:
            terminal = pixels.copy()
            for i, frame in self._terminal_frames.items():
                terminal[i] = frame
        info["terminal_obs"] = terminal
        return StepOutput(obs=pixels, reward=out.reward, done=out.done, info=info)


def _nn_resize(img: np.ndarray, size: tuple[int, int]) -> np.ndarray:
    h, w = size
    ys = (np.arange(h) * img.shape[0] / h).astype(np.intp)
    xs = (np.arange(w) * img.shape[1] / w).astype(np.intp)
    return img[ys][:, xs]


class EpisodeStatsWrapper(HostWrapper):
    """Track per-env episode return/length; finished episodes surface in
    ``info['episode_returns']``/``info['episode_lengths']`` (parity: the
    stats the reference's agents pushed to tensorplex, SURVEY.md §5.5).
    """

    def __init__(self, env: HostEnv):
        super().__init__(env)
        self._ret = np.zeros(env.num_envs, np.float64)
        self._len = np.zeros(env.num_envs, np.int64)

    def reset(self, seed: int | None = None) -> np.ndarray:
        self._ret[:] = 0.0
        self._len[:] = 0
        return self.env.reset(seed)

    def step(self, actions: np.ndarray) -> StepOutput:
        out = self.env.step(actions)
        self._ret += out.reward
        self._len += 1
        info = dict(out.info)
        if out.done.any():
            idx = np.nonzero(out.done)[0]
            info["episode_returns"] = self._ret[idx].copy()
            info["episode_lengths"] = self._len[idx].copy()
            self._ret[idx] = 0.0
            self._len[idx] = 0
        return StepOutput(obs=out.obs, reward=out.reward, done=out.done, info=info)
