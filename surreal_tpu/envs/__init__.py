"""Environment layer (parity: reference ``surreal/env/``, SURVEY.md §2.1
L3): make_env factory, host adapters (gymnasium/dm_control), obs wrappers,
video recording, plus the TPU-native on-device env family in ``jax/``.
"""

from surreal_tpu.envs.base import (
    ArraySpec,
    DiscreteSpec,
    EnvSpecs,
    HostEnv,
    HostWrapper,
    StepOutput,
)
from surreal_tpu.envs.factory import is_jax_env, make_env, register_jax_env

__all__ = [
    "ArraySpec",
    "DiscreteSpec",
    "EnvSpecs",
    "HostEnv",
    "HostWrapper",
    "StepOutput",
    "is_jax_env",
    "make_env",
    "register_jax_env",
]
