"""``make_env`` factory (parity: reference ``surreal/env/__init__.py``
dispatch on name prefix — ``gym:*``, ``dm_control:*``, ``robosuite:*``;
SURVEY.md §2.1). New prefix ``jax:*`` selects pure on-device envs.

Host path returns a wrapped :class:`HostEnv`; ``jax:`` path returns an
:class:`AutoReset`-wrapped functional env — callers branch on
:func:`is_jax_env` (the trainer runs different collection loops for the
two families).
"""

from __future__ import annotations

from typing import Union

from surreal_tpu.envs.base import HostEnv
from surreal_tpu.envs.jax.base import AutoReset, JaxEnv
from surreal_tpu.envs.wrappers import (
    ActionRepeatWrapper,
    EpisodeStatsWrapper,
    FrameStackWrapper,
    GrayscaleWrapper,
    PixelObsWrapper,
)

AnyEnv = Union[HostEnv, AutoReset]

_JAX_ENVS = {}
_BUILTINS_LOADED = False


def register_jax_env(name: str, cls) -> None:
    _JAX_ENVS[name] = cls


def _builtin_jax_envs():
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:
        return
    _BUILTINS_LOADED = True
    from surreal_tpu.envs.jax.cartpole import CartPole
    from surreal_tpu.envs.jax.pendulum import Pendulum

    # all first-party pure-JAX modules (jax/numpy only — no optional
    # deps): import unconditionally so a broken module surfaces instead
    # of silently unregistering its envs
    from surreal_tpu.envs.jax.lift import BlockLift
    from surreal_tpu.envs.jax.nut_assembly import NutAssembly
    from surreal_tpu.envs.jax.pixels import BlockLiftPixels, NutAssemblyPixels
    from surreal_tpu.envs.jax.pong import Pong, PongSmall

    _JAX_ENVS.setdefault("cartpole", CartPole)
    _JAX_ENVS.setdefault("pendulum", Pendulum)
    _JAX_ENVS.setdefault("lift", BlockLift)
    _JAX_ENVS.setdefault("pong", Pong)
    _JAX_ENVS.setdefault("pong16", PongSmall)
    _JAX_ENVS.setdefault("nut", NutAssembly)
    _JAX_ENVS.setdefault("lift_pixels", BlockLiftPixels)
    _JAX_ENVS.setdefault("nut_pixels", NutAssemblyPixels)


def is_jax_env(env: AnyEnv) -> bool:
    return isinstance(env, (JaxEnv, AutoReset))


def make_env(env_config) -> AnyEnv:
    """Build the configured environment from an ``env_config`` tree."""
    name = env_config.name
    if ":" not in name:
        raise ValueError(
            f"env name {name!r} needs a backend prefix (jax:, gym:, dm_control:, robosuite:)"
        )
    backend, _, env_id = name.partition(":")

    if backend == "jax":
        _builtin_jax_envs()
        if env_id not in _JAX_ENVS:
            raise ValueError(f"unknown jax env {env_id!r}; have {sorted(_JAX_ENVS)}")
        env = _JAX_ENVS[env_id]()
        return AutoReset(env, time_limit=env_config.time_limit)

    if backend == "gym":
        from surreal_tpu.envs.gym_adapter import GymAdapter

        kwargs = {}
        if env_config.pixel_obs or env_config.video.enabled:
            # both pixel obs and video recording need rendered frames
            kwargs["render_mode"] = "rgb_array"
        env: HostEnv = GymAdapter(
            env_id, num_envs=env_config.num_envs, seed=env_config.seed, **kwargs
        )
    elif backend == "dm_control":
        from surreal_tpu.envs.dm_control_adapter import DmControlAdapter

        domain, _, task = env_id.partition("-")
        env = DmControlAdapter(
            domain, task, num_envs=env_config.num_envs, seed=env_config.seed
        )
    elif backend == "robosuite":
        try:
            import robosuite  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "robosuite is not installed in this image (SURVEY.md §7); "
                "use the on-device BlockLifting-class env 'jax:lift' for "
                "Robosuite-class workloads"
            ) from e
        from surreal_tpu.envs.robosuite_adapter import RobosuiteAdapter

        env = RobosuiteAdapter(
            env_id,
            num_envs=env_config.num_envs,
            seed=env_config.seed,
            renderable=bool(env_config.pixel_obs or env_config.video.enabled),
        )
    else:
        raise ValueError(f"unknown env backend {backend!r}")

    if env_config.pixel_obs:
        env = PixelObsWrapper(env, image_size=tuple(env_config.image_size or (84, 84)))
    if env_config.grayscale:
        env = GrayscaleWrapper(env)
    if env_config.frame_stack > 1:
        env = FrameStackWrapper(env, env_config.frame_stack)
    if env_config.action_repeat > 1:
        env = ActionRepeatWrapper(env, env_config.action_repeat)
    env = EpisodeStatsWrapper(env)
    if env_config.video.enabled and env_config.video.dir:
        from surreal_tpu.envs.video import VideoWrapper

        env = VideoWrapper(env, env_config.video.dir, env_config.video.every_n_episodes)
    return env
