"""Robosuite host adapter (parity: reference robosuite wrapper in
``surreal/env/``, SURVEY.md §2.1 env-adapter row — state obs via
robot-state + object-state concat, shaped rewards, horizon truncation).

robosuite is NOT installed in this image (SURVEY.md §7), so this adapter
import-gates at construction: with robosuite present it is one more
``make_env`` backend (``robosuite:Lift`` etc.); without it the factory's
error points at the on-device BlockLifting-class task ``jax:lift``, which
is the path the north-star benchmarks use. The adapter is exercised in
tests against a faked robosuite module implementing the same surface
(``make``, dict obs, 4-tuple step, ``action_spec``, ``horizon``).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from surreal_tpu.envs.base import (
    ArraySpec,
    EnvSpecs,
    HostEnv,
    StepOutput,
    rescale_canonical_action,
)

# the reference's FilterWrapper kept these obs-dict keys, concatenated
_STATE_KEYS = ("robot-state", "object-state")


def _flatten_state(obs_dict: dict) -> np.ndarray:
    parts = [
        np.asarray(obs_dict[k], np.float32).ravel()
        for k in _STATE_KEYS
        if k in obs_dict
    ]
    if not parts:  # newer robosuite: per-robot prefixed keys
        parts = [
            np.asarray(v, np.float32).ravel()
            for k, v in sorted(obs_dict.items())
            if k.endswith(("-state", "_state"))
        ]
    if not parts:
        raise ValueError(
            f"no state keys found in robosuite obs dict: {sorted(obs_dict)}"
        )
    return np.concatenate(parts)


class _RenderableEnv:
    """Gym-style ``.render()`` facade over a robosuite env: PixelObsWrapper
    and VideoWrapper call ``env.render()`` on each inner env, while
    robosuite renders offscreen through ``env.sim.render`` (and returns the
    frame bottom-up, as MuJoCo offscreen buffers do)."""

    def __init__(self, env, camera: str = "agentview", height: int = 256, width: int = 256):
        self._env = env
        self._camera = camera
        self._height = height
        self._width = width

    def __getattr__(self, name: str) -> Any:
        return getattr(self._env, name)

    def render(self) -> np.ndarray:
        frame = self._env.sim.render(
            camera_name=self._camera, height=self._height, width=self._width
        )
        return np.asarray(frame)[::-1]


class RobosuiteAdapter(HostEnv):
    """B independent robosuite envs behind the batched HostEnv API
    (state observations; pixel obs ride PixelObsWrapper like any host env —
    pass ``renderable=True`` so the offscreen renderer is enabled and each
    env exposes a gym-style ``render()``).
    """

    def __init__(
        self,
        env_id: str,
        num_envs: int = 1,
        seed: int = 0,
        robots: str = "Sawyer",
        renderable: bool = False,
        camera: str = "agentview",
        **make_kwargs: Any,
    ):
        import robosuite

        kwargs = dict(
            robots=robots,
            has_renderer=False,
            has_offscreen_renderer=renderable,
            use_camera_obs=False,
            use_object_obs=True,
            reward_shaping=True,  # the reference trained on shaped rewards
        )
        kwargs.update(make_kwargs)
        self.envs = [robosuite.make(env_id, **kwargs) for _ in range(num_envs)]
        if renderable:
            self.envs = [_RenderableEnv(e, camera=camera) for e in self.envs]
        self.num_envs = num_envs
        self._seed = seed
        # robosuite draws reset randomness from the GLOBAL numpy RNG; keep
        # a per-instance stream and swap it in around robosuite calls so
        # two adapters (e.g. training + eval envs) can't clobber each
        # other's determinism through the shared global state
        self._np_state = np.random.RandomState(seed).get_state()

        proto = self.envs[0]
        obs0 = self._isolated_reset(proto)
        obs_dim = _flatten_state(obs0).shape[0]
        low, high = proto.action_spec
        self._act_low = np.asarray(low, np.float32)
        self._act_high = np.asarray(high, np.float32)
        self.horizon = int(getattr(proto, "horizon", 1000))
        self._t = np.zeros(num_envs, np.int64)
        self.specs = EnvSpecs(
            obs=ArraySpec(shape=(obs_dim,), dtype=np.dtype(np.float32), name="state"),
            action=ArraySpec(
                shape=self._act_low.shape, dtype=np.dtype(np.float32), name="action"
            ),
        )

    def _isolated_reset(self, env) -> dict:
        """Run ``env.reset()`` under this adapter's private numpy stream."""
        outer = np.random.get_state()
        np.random.set_state(self._np_state)
        try:
            return env.reset()
        finally:
            self._np_state = np.random.get_state()
            np.random.set_state(outer)

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._np_state = np.random.RandomState(seed).get_state()
        self._t[:] = 0
        return np.stack(
            [_flatten_state(self._isolated_reset(env)) for env in self.envs]
        )

    def step(self, actions: np.ndarray) -> StepOutput:
        native = rescale_canonical_action(actions, self._act_low, self._act_high)
        obs_b, rew_b, done_b = [], [], []
        terminal_obs = np.zeros((self.num_envs, *self.specs.obs.shape), np.float32)
        truncated_b = np.zeros(self.num_envs, bool)
        for i, env in enumerate(self.envs):
            obs_dict, reward, done, _ = env.step(native[i])
            obs = _flatten_state(obs_dict)
            self._t[i] += 1
            truncated = self._t[i] >= self.horizon
            done = bool(done) or truncated
            if done:
                terminal_obs[i] = obs
                # robosuite ends episodes at the horizon; task "success"
                # does not terminate the MDP, so a done here is truncation
                # unless the env says otherwise before the horizon
                truncated_b[i] = truncated
                if self.pre_reset_hook is not None:
                    self.pre_reset_hook(i, env)
                obs = _flatten_state(self._isolated_reset(env))
                self._t[i] = 0
            obs_b.append(obs)
            rew_b.append(float(reward))
            done_b.append(done)
        return StepOutput(
            obs=np.stack(obs_b),
            reward=np.asarray(rew_b, np.float32),
            done=np.asarray(done_b, bool),
            info={"terminal_obs": terminal_obs, "truncated": truncated_b},
        )

    def close(self) -> None:
        for env in self.envs:
            env.close()
