"""Environment layer core (parity: reference ``surreal/env/base.py`` —
``Env``/``Wrapper`` ABC and obs/action specs, SURVEY.md §2.1).

Two env families, reflecting the TPU split:

- :class:`HostEnv` — stateful, **batched** numpy envs on the CPU host
  (gymnasium / dm_control adapters). The batched step API is the rebuild's
  answer to the reference's one-process-per-env actor pool: one host
  process steps B envs and ships one contiguous obs batch to the device
  (SEED-RL pattern, SURVEY.md §3.2).
- :class:`JaxEnv` (``envs/jax/base.py``) — pure-functional envs that run
  *on device* under vmap/scan: zero host traffic, the north-star
  throughput path.

All continuous action spaces are canonicalized to [-1, 1]; adapters own
the rescaling to native bounds.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any

import numpy as np


@dataclasses.dataclass(frozen=True)
class ArraySpec:
    """Shape/dtype contract for one obs or action array (unbatched)."""

    shape: tuple[int, ...]
    dtype: np.dtype
    name: str = ""

    def zeros(self, batch: int | None = None) -> np.ndarray:
        shape = self.shape if batch is None else (batch, *self.shape)
        return np.zeros(shape, self.dtype)


@dataclasses.dataclass(frozen=True)
class DiscreteSpec(ArraySpec):
    """Discrete action spec: scalar int action in [0, n)."""

    n: int = 0


@dataclasses.dataclass(frozen=True)
class EnvSpecs:
    obs: ArraySpec
    action: ArraySpec

    @property
    def discrete(self) -> bool:
        return isinstance(self.action, DiscreteSpec)


class StepOutput(dict):
    """Batched step result: obs [B,...], reward [B], done [B], info dict.

    ``done`` marks episode boundaries *after which the obs is already the
    reset obs* (auto-reset semantics — what on-device pipelines need so
    trajectories stay fixed-shape; the pre-reset terminal obs is available
    as ``info['terminal_obs']`` for algorithms that bootstrap off it).
    """

    @property
    def obs(self) -> np.ndarray:
        return self["obs"]

    @property
    def reward(self) -> np.ndarray:
        return self["reward"]

    @property
    def done(self) -> np.ndarray:
        return self["done"]

    @property
    def info(self) -> dict[str, Any]:
        return self.get("info", {})


def rescale_canonical_action(
    actions: np.ndarray, low: np.ndarray, high: np.ndarray
) -> np.ndarray:
    """Map canonical [-1, 1] actions to native [low, high] bounds (the one
    place this arithmetic lives; both host adapters call it)."""
    a = np.clip(actions, -1.0, 1.0)
    return low + (a + 1.0) * 0.5 * (high - low)


class HostEnv(abc.ABC):
    """Batched, auto-resetting host environment.

    ``pre_reset_hook`` — optional callable ``(i, env) -> None`` that
    adapters invoke for env ``i`` immediately before its auto-reset, while
    the terminal state is still live. This is the seam wrappers that derive
    observations from live env state (e.g. rendered pixels) use to capture
    the TRUE terminal observation; without it a render after ``step`` sees
    the next episode's first frame.
    """

    specs: EnvSpecs
    num_envs: int
    pre_reset_hook = None

    @abc.abstractmethod
    def reset(self, seed: int | None = None) -> np.ndarray:
        """Reset all envs; returns obs batch [B, ...]."""

    @abc.abstractmethod
    def step(self, actions: np.ndarray) -> StepOutput:
        """Step all envs with actions [B, ...]; auto-resets finished envs."""

    def close(self) -> None:  # pragma: no cover - trivial default
        pass


class HostWrapper(HostEnv):
    """Base wrapper delegating to an inner env (parity: reference
    ``surreal/env/wrapper.py`` Wrapper base)."""

    def __init__(self, env: HostEnv):
        self.env = env
        self.specs = env.specs
        self.num_envs = env.num_envs

    def reset(self, seed: int | None = None) -> np.ndarray:
        return self.env.reset(seed)

    def step(self, actions: np.ndarray) -> StepOutput:
        return self.env.step(actions)

    def close(self) -> None:
        self.env.close()

    def __getattr__(self, name: str) -> Any:
        return getattr(self.env, name)
