"""On-device pixel Pong (BASELINE config ⑤'s workload class: "IMPALA/V-trace
256-env Atari Pong"). The ALE and its ROMs are not in this image (SURVEY.md
§7 flagged this), so — consistent with the BlockLifting answer in
``lift.py`` — the TPU-native substitute is the game itself re-implemented
as a pure-JAX functional env: paddle-vs-paddle Pong with PIXEL
observations rendered on device, jit/vmap/scan-able, so 256+ envs step in
HBM next to the CNN policy.

Game (Atari-Pong-shaped):
- Court is the unit square; the agent's paddle is the LEFT edge, a
  tracking opponent (capped speed, slightly slower than the ball) is the
  RIGHT edge. Actions: Discrete(3) = stay / up / down.
- Ball bounces off top/bottom walls and paddles; paddle hits deflect the
  ball with a vertical angle proportional to the hit offset (classic Pong
  control surface), and speed up slightly toward a cap.
- A miss scores the point: reward +1 when the opponent misses, -1 when
  the agent misses; the ball re-serves toward the scored-against side.
  Like Atari Pong the episode runs many points; it ends by time limit
  (AutoReset truncation) or when either side reaches 21
  (``info['score']`` tracks agent minus opponent).

Observation: [42, 42, 2] uint8 pixels — channel 0 is the current frame
(paddles + ball as bright blocks), channel 1 the previous frame, giving
the CNN the motion information Atari setups get from frame-stacking
(rendered in-env, so no host wrapper is needed on the device path).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from surreal_tpu.envs.base import ArraySpec, DiscreteSpec, EnvSpecs
from surreal_tpu.envs.jax.base import JaxEnv

_RES = 42                 # render resolution (square)
_PADDLE_HALF = 0.08       # paddle half-height (court units)
_PADDLE_SPEED = 0.04      # agent paddle speed per step
_OPP_SPEED = 0.03         # opponent tracking speed (beatable: < ball |vy| cap)
_BALL_SPEED0 = 0.03       # serve speed
_BALL_SPEED_MAX = 0.06
_SPEEDUP = 1.05           # per paddle hit
_AGENT_X = 0.04           # paddle plane x positions
_OPP_X = 0.96
_DEFLECT = 0.04           # max |vy| added by hit offset
_WIN_SCORE = 21


class PongState(NamedTuple):
    ball: jax.Array        # [2] position
    vel: jax.Array         # [2] velocity
    agent_y: jax.Array     # [] agent paddle center
    opp_y: jax.Array       # [] opponent paddle center
    agent_score: jax.Array # [] int32 points won by the agent
    opp_score: jax.Array   # [] int32 points won by the opponent
    prev_frame: jax.Array  # [_RES, _RES] uint8
    key: jax.Array         # serve randomness


def _serve(key: jax.Array, toward_agent: jax.Array):
    """Ball from center toward the scored-against side, random angle."""
    vy = jax.random.uniform(key, (), jnp.float32, -0.02, 0.02)
    vx = jnp.where(toward_agent, -_BALL_SPEED0, _BALL_SPEED0)
    return jnp.asarray([0.5, 0.5], jnp.float32), jnp.stack([vx, vy])


def _render(ball, agent_y, opp_y, res: int = _RES) -> jax.Array:
    """[res, res] uint8 frame: rows = y (top=0), cols = x. The court is
    normalized, so resolution is render-only — the 16x16 variant plays the
    identical game."""
    grid = (jnp.arange(res, dtype=jnp.float32) + 0.5) / res
    ys = grid[:, None]  # [R, 1]
    xs = grid[None, :]  # [1, R]
    cell = 1.0 / res
    ball_px = (jnp.abs(ys - ball[1]) <= cell) & (jnp.abs(xs - ball[0]) <= cell)
    agent_px = (jnp.abs(ys - agent_y) <= _PADDLE_HALF) & (
        jnp.abs(xs - _AGENT_X) <= cell
    )
    opp_px = (jnp.abs(ys - opp_y) <= _PADDLE_HALF) & (jnp.abs(xs - _OPP_X) <= cell)
    return jnp.where(ball_px | agent_px | opp_px, 255, 0).astype(jnp.uint8)


class Pong(JaxEnv):
    max_episode_steps = 2048
    res = _RES  # render resolution; physics is resolution-independent

    specs = EnvSpecs(
        obs=ArraySpec(shape=(_RES, _RES, 2), dtype=np.dtype(np.uint8), name="pixels"),
        action=DiscreteSpec(shape=(), dtype=np.dtype(np.int32), name="action", n=3),
    )

    def reset(self, key: jax.Array):
        key, serve_key, side_key = jax.random.split(key, 3)
        ball, vel = _serve(serve_key, jax.random.bernoulli(side_key))
        state = PongState(
            ball=ball,
            vel=vel,
            agent_y=jnp.asarray(0.5, jnp.float32),
            opp_y=jnp.asarray(0.5, jnp.float32),
            agent_score=jnp.zeros((), jnp.int32),
            opp_score=jnp.zeros((), jnp.int32),
            prev_frame=_render(ball, 0.5, 0.5, self.res),
            key=key,
        )
        return state, self._obs(state)

    def step(self, state: PongState, action: jax.Array):
        # paddles
        move = jnp.asarray([0.0, -_PADDLE_SPEED, _PADDLE_SPEED], jnp.float32)[action]
        agent_y = jnp.clip(state.agent_y + move, _PADDLE_HALF, 1.0 - _PADDLE_HALF)
        opp_y = jnp.clip(
            state.opp_y
            + jnp.clip(state.ball[1] - state.opp_y, -_OPP_SPEED, _OPP_SPEED),
            _PADDLE_HALF,
            1.0 - _PADDLE_HALF,
        )

        # ball flight + wall bounce
        ball = state.ball + state.vel
        vy = jnp.where((ball[1] < 0.0) | (ball[1] > 1.0), -state.vel[1], state.vel[1])
        ball = ball.at[1].set(jnp.clip(ball[1], 0.0, 1.0))
        vel = state.vel.at[1].set(vy)

        def paddle_bounce(ball, vel, paddle_y, plane_x, left: bool):
            # `left` is a STATIC side selector (which paddle); the traced
            # part is whether the ball is moving toward that side
            toward = (vel[0] < 0) if left else (vel[0] > 0)
            plane = (ball[0] <= plane_x) if left else (ball[0] >= plane_x)
            crossed = plane & toward
            hit = crossed & (jnp.abs(ball[1] - paddle_y) <= _PADDLE_HALF)
            offset = (ball[1] - paddle_y) / _PADDLE_HALF  # [-1, 1]
            speed = jnp.minimum(jnp.abs(vel[0]) * _SPEEDUP, _BALL_SPEED_MAX)
            new_vx = speed if left else -speed
            # vy capped like vx: without the clamp, deflections random-walk
            # |vy| up within a rally, and the opponent's beatability rests
            # on its tracking speed staying below this cap
            new_vy = jnp.clip(
                vel[1] + offset * _DEFLECT, -_BALL_SPEED_MAX, _BALL_SPEED_MAX
            )
            new_vel = jnp.stack([new_vx, new_vy])
            vel = jnp.where(hit, new_vel, vel)
            ball = jnp.where(hit, ball.at[0].set(plane_x), ball)
            return ball, vel, hit, crossed

        ball, vel, hit_a, crossed_a = paddle_bounce(ball, vel, agent_y, _AGENT_X, True)
        ball, vel, hit_o, crossed_o = paddle_bounce(ball, vel, opp_y, _OPP_X, False)
        agent_missed = crossed_a & ~hit_a
        opp_missed = crossed_o & ~hit_o
        reward = jnp.where(
            opp_missed, 1.0, jnp.where(agent_missed, -1.0, 0.0)
        ).astype(jnp.float32)
        agent_score = state.agent_score + opp_missed.astype(jnp.int32)
        opp_score = state.opp_score + agent_missed.astype(jnp.int32)

        # re-serve after a point, toward whoever was scored against
        key, serve_key = jax.random.split(state.key)
        serve_ball, serve_vel = _serve(serve_key, agent_missed)
        point = agent_missed | opp_missed
        ball = jnp.where(point, serve_ball, ball)
        vel = jnp.where(point, serve_vel, vel)

        frame = _render(ball, agent_y, opp_y, self.res)
        new_state = PongState(
            ball=ball,
            vel=vel,
            agent_y=agent_y,
            opp_y=opp_y,
            agent_score=agent_score,
            opp_score=opp_score,
            prev_frame=frame,
            key=key,
        )
        # like Atari Pong: game over when EITHER side reaches 21 points
        done = (agent_score >= _WIN_SCORE) | (opp_score >= _WIN_SCORE)
        info = {"score": agent_score - opp_score, "point": point}
        obs = jnp.stack([frame, state.prev_frame], axis=-1)
        return new_state, obs, reward, done, info

    @staticmethod
    def _obs(state: PongState) -> jax.Array:
        return jnp.stack([state.prev_frame, state.prev_frame], axis=-1)


class PongSmall(Pong):
    """16x16 Pong (``jax:pong16``): the same court, physics, and opponent —
    resolution is render-only — at a size whose CNN forward is cheap enough
    for the CPU-sim suite to LEARN on (the in-suite pixel-learning guard,
    round-3 VERDICT missing #5; the real-chip result stays the 42x42 env)."""

    res = 16
    specs = EnvSpecs(
        obs=ArraySpec(shape=(16, 16, 2), dtype=np.dtype(np.uint8), name="pixels"),
        action=DiscreteSpec(shape=(), dtype=np.dtype(np.int32), name="action", n=3),
    )
