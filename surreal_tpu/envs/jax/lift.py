"""On-device BlockLifting-class manipulation task (BASELINE configs ③④ and
the north-star workload: "Robosuite BlockLifting, state obs, PPO").

Parity note (SURVEY.md §2.2 robosuite row, §7): robosuite is not installed
in this image and neither is MJX (`mujoco` 3.10 here ships only the C
bindings — ``mujoco.mjx`` is a separate package that is absent; verified at
build time, no network to fetch it). The reference ran Block Lifting on
host-side MuJoCo C physics behind robosuite. The TPU-native answer is this
module: the lifting task re-implemented as a pure-JAX functional env —
elementwise math only, jit/vmap/scan-able, so the whole rollout lives in
HBM next to the policy. Physics is a rigid-grasp-limit model in the spirit
of Brax's positional/spring backends rather than a full LCP contact solve:

- **Gripper**: a position-actuated parallel-jaw hand on a 3-DoF gantry
  (x, y, z) with a 1-DoF finger opening, the minimal abstraction of the
  reference's position-controlled Sawyer + two-finger gripper. Action is
  4-dim canonical [-1, 1]: commanded xyz velocity + close/open rate.
- **Block**: a cube on a table under gravity, inelastic table contact with
  sliding friction decay.
- **Grasp**: fingers straddling the block produce a squeeze force
  F_n = k * penetration (capped); Coulomb condition mu*F_n >= m*g decides
  whether the grasp supports the block. A supporting grasp enters the
  rigid-grasp limit (block velocity-matched to the hand — the stable,
  solver-free limit of stiction); a partial grasp slips with reduced
  effective gravity and drag toward the hand's motion.

Reward (dense, robosuite-Lift-shaped): reach term (1 - tanh(10*dist)),
a continuous squeeze term, and a lifting term that dominates — max
6.0/step over the 200-step episode, scaled so a policy that grasps within
the first ~2 s and holds the block at the 10 cm target scores >1000,
matching the paper's "1k reward" scale that BASELINE.json's wall-clock
target is defined on (travel time makes the theoretical max ~1150; a
mediocre hoverer that never lifts stays under 300). ``info['success']``
marks block-at-target steps.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from surreal_tpu.envs.base import ArraySpec, EnvSpecs
from surreal_tpu.envs.jax.base import JaxEnv

# -- geometry / physics constants (SI units; table top is z = 0) ------------
_DT = 0.01                 # physics substep [s]
_N_SUB = 2                 # substeps per control step (control dt = 0.02 s)
_BLOCK_HALF = 0.02         # 4 cm cube
_BLOCK_MASS = 0.1          # kg
_G = 9.81
_GRIP_V_MAX = 0.35         # gantry speed limit [m/s]
_GRIP_W_MAX = 0.10         # max finger opening [m]
_GRIP_W_SPEED = 0.25       # finger open/close rate [m/s]
_PAD = 0.004               # finger-pad compliance margin [m]
_PAD_HALF_H = 0.025        # finger-pad half-height (z grasp-overlap gate) [m]
_K_SQUEEZE = 300.0         # squeeze stiffness [N/m]
_PEN_MAX = 0.012           # squeeze penetration cap [m]
_MU = 1.0                  # finger-block friction coefficient
_SLIP_DRAG = 6.0           # horizontal drag toward hand motion in partial grasp
_TABLE_FRICTION = 8.0      # exponential sliding-decay rate on the table [1/s]
_WS_XY = 0.25              # gripper workspace half-extent in x, y
_WS_Z_MAX = 0.35           # gripper workspace ceiling
_TABLE_XY = 0.30           # block stays on the table within +-this
_LIFT_TARGET = 0.10        # lift height defining full reward / success [m]
_BLOCK_SPAWN = 0.10        # block spawn half-range in x, y


class LiftState(NamedTuple):
    grip_pos: jax.Array    # [3] gripper (hand) center
    grip_vel: jax.Array    # [3] realized hand velocity (for obs)
    grip_width: jax.Array  # [] finger opening
    block_pos: jax.Array   # [3] block center
    block_vel: jax.Array   # [3]


def _grasp_force(state: LiftState):
    """Squeeze normal force and geometric-alignment gate.

    Fingers travel along x at grip_pos.x +- width/2; a squeeze exists when
    the hand straddles the block (centers aligned within the block
    half-extent on every axis) and the commanded opening is tighter than
    block width + pad compliance.
    """
    d = jnp.abs(state.grip_pos - state.block_pos)
    # finger pads are taller than the block half-extent, so the z gate is
    # looser than x/y (center-to-center overlap with 3 cm pads)
    aligned = jnp.all(d < jnp.array([_BLOCK_HALF, _BLOCK_HALF, _PAD_HALF_H]))
    pen = jnp.clip(
        2.0 * _BLOCK_HALF + 2.0 * _PAD - state.grip_width, 0.0, _PEN_MAX
    )
    f_n = jnp.where(aligned, _K_SQUEEZE * pen, 0.0)
    return f_n, aligned & (pen > 0.0)


class BlockLift(JaxEnv):
    """Block lifting with state observations (17-dim) and 4-dim continuous
    actions; factory name ``jax:lift``."""

    max_episode_steps = 200

    specs = EnvSpecs(
        obs=ArraySpec(shape=(17,), dtype=np.dtype(np.float32), name="state"),
        action=ArraySpec(shape=(4,), dtype=np.dtype(np.float32), name="hand"),
    )

    def reset(self, key: jax.Array):
        k_block, k_grip = jax.random.split(key)
        block_xy = jax.random.uniform(
            k_block, (2,), jnp.float32, -_BLOCK_SPAWN, _BLOCK_SPAWN
        )
        k_grip, k_w = jax.random.split(k_grip)
        grip_xy = jax.random.uniform(k_grip, (2,), jnp.float32, -0.02, 0.02)
        # randomized initial opening: some episodes begin nearly closed, so
        # the squeeze->lift phase is reachable by exploration before the
        # policy has learned a deliberate closing motion
        width0 = jax.random.uniform(
            k_w, (), jnp.float32, 2.0 * _BLOCK_HALF - 0.005, _GRIP_W_MAX
        )
        state = LiftState(
            grip_pos=jnp.concatenate(
                [grip_xy, jnp.full((1,), 0.20, jnp.float32)]
            ),
            grip_vel=jnp.zeros((3,), jnp.float32),
            grip_width=width0,
            block_pos=jnp.concatenate(
                [block_xy, jnp.full((1,), _BLOCK_HALF, jnp.float32)]
            ),
            block_vel=jnp.zeros((3,), jnp.float32),
        )
        return state, self._obs(state)

    def step(self, state: LiftState, action: jax.Array):
        a = jnp.clip(action, -1.0, 1.0)
        v_cmd = a[:3] * _GRIP_V_MAX
        w_rate = -a[3] * _GRIP_W_SPEED  # action[3] > 0 closes the fingers

        def substep(s: LiftState, _):
            # hand: kinematic position actuation inside the workspace box
            new_gpos = jnp.clip(
                s.grip_pos + v_cmd * _DT,
                jnp.array([-_WS_XY, -_WS_XY, 0.0], jnp.float32),
                jnp.array([_WS_XY, _WS_XY, _WS_Z_MAX], jnp.float32),
            )
            gvel = (new_gpos - s.grip_pos) / _DT
            new_w = jnp.clip(s.grip_width + w_rate * _DT, 0.0, _GRIP_W_MAX)
            s = s._replace(grip_pos=new_gpos, grip_vel=gvel, grip_width=new_w)

            f_n, contact = _grasp_force(s)
            support = _MU * f_n / (_BLOCK_MASS * _G)  # >=1 -> holds weight
            held = contact & (support >= 1.0)

            # rigid-grasp limit: block velocity-matched to the hand
            held_vel = gvel
            # partial grasp: slips under reduced gravity, dragged along
            slip_acc = (
                jnp.array([0.0, 0.0, -_G], jnp.float32)
                * (1.0 - jnp.minimum(support, 1.0))
                + (gvel - s.block_vel) * _SLIP_DRAG * jnp.minimum(support, 1.0)
            )
            free_acc = jnp.array([0.0, 0.0, -_G], jnp.float32)
            bvel = jnp.where(
                held,
                held_vel,
                s.block_vel
                + jnp.where(contact, slip_acc, free_acc) * _DT,
            )
            bpos = s.block_pos + bvel * _DT

            # table: inelastic normal contact + sliding-friction decay
            on_table = bpos[2] <= _BLOCK_HALF
            bpos = bpos.at[2].set(jnp.maximum(bpos[2], _BLOCK_HALF))
            bvel = bvel.at[2].set(
                jnp.where(on_table, jnp.maximum(bvel[2], 0.0), bvel[2])
            )
            decay = jnp.exp(-_TABLE_FRICTION * _DT)
            bvel = bvel.at[:2].multiply(
                jnp.where(on_table & ~held, decay, 1.0)
            )
            bpos = bpos.at[:2].set(jnp.clip(bpos[:2], -_TABLE_XY, _TABLE_XY))
            return s._replace(block_pos=bpos, block_vel=bvel), None

        state, _ = jax.lax.scan(substep, state, None, length=_N_SUB)

        f_n, _ = _grasp_force(state)
        support = _MU * f_n / (_BLOCK_MASS * _G)
        grasped = support >= 1.0
        dist = jnp.linalg.norm(state.grip_pos - state.block_pos)
        height = jnp.clip(
            (state.block_pos[2] - _BLOCK_HALF) / _LIFT_TARGET, 0.0, 1.0
        )
        reward = (
            (1.0 - jnp.tanh(10.0 * dist))
            + 0.5 * jnp.minimum(support, 1.0)  # continuous squeeze shaping
            + 4.5 * height
        ).astype(jnp.float32)
        success = height >= 0.95
        done = jnp.asarray(False)  # time-limit truncation only (AutoReset)
        info = {
            "success": success,
            "grasped": grasped,
            "block_height": state.block_pos[2] - _BLOCK_HALF,
        }
        return state, self._obs(state), reward, done, info

    @staticmethod
    def _obs(state: LiftState) -> jax.Array:
        return jnp.concatenate(
            [
                state.grip_pos,
                state.grip_vel,
                state.grip_width[None],
                state.block_pos,
                state.block_vel,
                state.block_pos - state.grip_pos,
                (state.block_pos[2] - _BLOCK_HALF)[None],
            ]
        ).astype(jnp.float32)
