"""On-device NutAssembly-class manipulation task (BASELINE config ④'s
workload: "PPO Robosuite NutAssembly pixels" — the pixel variant lives in
``envs/jax/pixels.py``; this module is the task itself, state obs).

Parity note (same provenance as ``lift.py``): robosuite and MJX are absent
from this image, so the reference's NutAssembly (grasp a nut, thread it
onto its peg) is re-implemented as a pure-JAX functional env sharing
``lift.py``'s rigid-grasp-limit physics (SURVEY.md §2.2 robosuite row, §7).
The task extends lifting with the insertion objective that makes
NutAssembly the harder benchmark: a staged reach -> grasp -> carry ->
place problem.

Model:
- **Gripper**: identical to ``lift.py`` — position-actuated parallel-jaw
  hand on a 3-DoF gantry + 1-DoF opening; 4-dim canonical [-1, 1] action.
- **Nut**: a square nut, block-sized for the grasp model, spawning on the
  left half of the table.
- **Peg**: a fixed vertical post on the right. When the nut is released
  (or slips) with its center inside the peg's capture radius and below
  the peg top, it THREADS: it slides down the post (xy clamped to the peg
  axis) and rests at the base — robosuite's success condition.

Reward (dense, staged, max ~6/step over the 200-step episode — the same
scale as ``lift.py`` so wall-clock targets compare): reach term toward
the nut, continuous squeeze shaping, a carry term toward the hover point
above the peg, and a dominant threaded bonus. ``info['success']`` marks
threaded-at-rest steps.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from surreal_tpu.envs.base import ArraySpec, EnvSpecs
from surreal_tpu.envs.jax.base import JaxEnv
from surreal_tpu.envs.jax.lift import (
    _BLOCK_HALF,
    _BLOCK_MASS,
    _G,
    _GRIP_V_MAX,
    _GRIP_W_MAX,
    _GRIP_W_SPEED,
    _MU,
    _N_SUB,
    _SLIP_DRAG,
    _TABLE_FRICTION,
    _TABLE_XY,
    _WS_XY,
    _WS_Z_MAX,
    _DT,
    LiftState,
    _grasp_force,
)

# peg geometry (table top is z = 0). Plain numpy: module import must stay
# device-free (VERDICT r2 item 1 — jnp at import latches the backend)
PEG_XY = np.array([0.15, 0.15], dtype=np.float32)  # post axis position
PEG_HEIGHT = 0.10          # post top [m]
PEG_CAPTURE_R = 0.018      # nut-center capture radius for threading [m]
_NUT_SPAWN_X = (-0.20, 0.0)  # nut spawns left of the peg
_NUT_SPAWN_Y = 0.15
_HOVER = 0.03              # carry target height above the peg top


class NutState(NamedTuple):
    hand: LiftState        # gripper + nut as the "block" of the grasp model
    threaded: jax.Array    # [] bool — nut is on the peg


class NutAssembly(JaxEnv):
    """Nut threading with state observations (20-dim) and the 4-dim
    continuous gripper action; factory name ``jax:nut``."""

    max_episode_steps = 200

    specs = EnvSpecs(
        obs=ArraySpec(shape=(20,), dtype=np.dtype(np.float32), name="state"),
        action=ArraySpec(shape=(4,), dtype=np.dtype(np.float32), name="hand"),
    )

    def reset(self, key: jax.Array):
        k_nut, k_grip, k_w = jax.random.split(key, 3)
        nut_x = jax.random.uniform(
            k_nut, (), jnp.float32, _NUT_SPAWN_X[0], _NUT_SPAWN_X[1]
        )
        nut_y = jax.random.uniform(
            jax.random.fold_in(k_nut, 1), (), jnp.float32,
            -_NUT_SPAWN_Y, _NUT_SPAWN_Y,
        )
        grip_xy = jax.random.uniform(k_grip, (2,), jnp.float32, -0.02, 0.02)
        width0 = jax.random.uniform(
            k_w, (), jnp.float32, 2.0 * _BLOCK_HALF - 0.005, _GRIP_W_MAX
        )
        hand = LiftState(
            grip_pos=jnp.concatenate(
                [grip_xy, jnp.full((1,), 0.20, jnp.float32)]
            ),
            grip_vel=jnp.zeros((3,), jnp.float32),
            grip_width=width0,
            block_pos=jnp.stack([nut_x, nut_y, jnp.asarray(_BLOCK_HALF)]),
            block_vel=jnp.zeros((3,), jnp.float32),
        )
        state = NutState(hand=hand, threaded=jnp.asarray(False))
        return state, self._obs(state)

    def step(self, state: NutState, action: jax.Array):
        a = jnp.clip(action, -1.0, 1.0)
        v_cmd = a[:3] * _GRIP_V_MAX
        w_rate = -a[3] * _GRIP_W_SPEED

        def substep(carry, _):
            s, threaded = carry
            new_gpos = jnp.clip(
                s.grip_pos + v_cmd * _DT,
                jnp.array([-_WS_XY, -_WS_XY, 0.0], jnp.float32),
                jnp.array([_WS_XY, _WS_XY, _WS_Z_MAX], jnp.float32),
            )
            gvel = (new_gpos - s.grip_pos) / _DT
            new_w = jnp.clip(s.grip_width + w_rate * _DT, 0.0, _GRIP_W_MAX)
            s = s._replace(grip_pos=new_gpos, grip_vel=gvel, grip_width=new_w)

            f_n, contact = _grasp_force(s)
            support = _MU * f_n / (_BLOCK_MASS * _G)
            held = contact & (support >= 1.0)
            # a firm regrasp pulls the nut back OFF the peg
            threaded = threaded & ~held

            slip_acc = (
                jnp.array([0.0, 0.0, -_G], jnp.float32)
                * (1.0 - jnp.minimum(support, 1.0))
                + (gvel - s.block_vel) * _SLIP_DRAG * jnp.minimum(support, 1.0)
            )
            free_acc = jnp.array([0.0, 0.0, -_G], jnp.float32)
            bvel = jnp.where(
                held,
                gvel,
                s.block_vel + jnp.where(contact, slip_acc, free_acc) * _DT,
            )
            bpos = s.block_pos + bvel * _DT

            # threading: released inside the capture radius below the peg
            # top -> the nut is on the post and slides down it. The
            # airborne gate (z above table rest height) means the nut must
            # come DOWN over the post — sliding it along the table into
            # the capture radius cannot thread it.
            over_peg = (
                (jnp.linalg.norm(bpos[:2] - PEG_XY) < PEG_CAPTURE_R)
                & (bpos[2] < PEG_HEIGHT + _BLOCK_HALF)
                & (bpos[2] > _BLOCK_HALF + 1e-3)
            )
            threaded = threaded | (over_peg & ~held)
            # on the post: xy clamped to the axis; falls to rest at base
            bpos = jnp.where(
                threaded, bpos.at[:2].set(PEG_XY), bpos
            )
            bvel = jnp.where(
                threaded, bvel.at[:2].set(0.0), bvel
            )

            on_table = bpos[2] <= _BLOCK_HALF
            bpos = bpos.at[2].set(jnp.maximum(bpos[2], _BLOCK_HALF))
            bvel = bvel.at[2].set(
                jnp.where(on_table, jnp.maximum(bvel[2], 0.0), bvel[2])
            )
            decay = jnp.exp(-_TABLE_FRICTION * _DT)
            bvel = bvel.at[:2].multiply(
                jnp.where(on_table & ~held, decay, 1.0)
            )
            bpos = bpos.at[:2].set(jnp.clip(bpos[:2], -_TABLE_XY, _TABLE_XY))
            return (s._replace(block_pos=bpos, block_vel=bvel), threaded), None

        (hand, threaded), _ = jax.lax.scan(
            substep, (state.hand, state.threaded), None, length=_N_SUB
        )
        state = NutState(hand=hand, threaded=threaded)

        f_n, _ = _grasp_force(hand)
        support = _MU * f_n / (_BLOCK_MASS * _G)
        grasped = support >= 1.0
        dist_reach = jnp.linalg.norm(hand.grip_pos - hand.block_pos)
        hover = jnp.concatenate(
            [PEG_XY, jnp.full((1,), PEG_HEIGHT + _BLOCK_HALF + _HOVER)]
        )
        dist_carry = jnp.linalg.norm(hand.block_pos - hover)
        at_rest = hand.block_pos[2] <= _BLOCK_HALF + 1e-4
        success = threaded & at_rest
        reward = (
            (1.0 - jnp.tanh(10.0 * dist_reach))
            + 0.5 * jnp.minimum(support, 1.0)
            + 2.0 * (1.0 - jnp.tanh(5.0 * dist_carry))
            + 2.5 * threaded.astype(jnp.float32)
        ).astype(jnp.float32)
        done = jnp.asarray(False)  # time-limit truncation only (AutoReset)
        info = {
            "success": success,
            "grasped": grasped,
            "threaded": threaded,
            "nut_height": hand.block_pos[2] - _BLOCK_HALF,
        }
        return state, self._obs(state), reward, done, info

    @staticmethod
    def _obs(state: NutState) -> jax.Array:
        hand = state.hand
        peg_top = jnp.concatenate(
            [PEG_XY, jnp.full((1,), PEG_HEIGHT, jnp.float32)]
        )
        return jnp.concatenate(
            [
                hand.grip_pos,
                hand.grip_vel,
                hand.grip_width[None],
                hand.block_pos,
                hand.block_vel,
                hand.block_pos - hand.grip_pos,
                peg_top - hand.block_pos,
                state.threaded.astype(jnp.float32)[None],
            ]
        ).astype(jnp.float32)
