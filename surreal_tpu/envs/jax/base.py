"""Pure-functional on-device environments.

No counterpart exists in the reference — its envs were host-side C physics
behind Python (SURVEY.md §2.3 MuJoCo row). This is the TPU-native addition
that makes the north-star throughput possible: envs as jittable pure
functions, vmapped over a batch axis, scanned over time, living entirely in
HBM next to the policy.

API (gymnax-style functional):
    state, obs = env.reset(key, params)
    state, obs, reward, done, info = env.step(state, action, params)

``state`` is a pytree carrying everything including a PRNG key; auto-reset
is composed on top via :class:`AutoReset` so trajectories stay fixed-shape
under ``lax.scan``.
"""

from __future__ import annotations

import abc
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from surreal_tpu.envs.base import EnvSpecs


class JaxEnv(abc.ABC):
    """Single-env functional definition; batching is ``vmap``, not a loop."""

    specs: EnvSpecs
    max_episode_steps: int | None = None

    @abc.abstractmethod
    def reset(self, key: jax.Array):
        """-> (state pytree, obs [obs_dim...])"""

    @abc.abstractmethod
    def step(self, state, action: jax.Array):
        """-> (state, obs, reward scalar, done scalar bool, info dict)"""


class AutoResetState(NamedTuple):
    env_state: Any
    key: jax.Array
    step_count: jax.Array  # int32 scalar


class AutoReset:
    """Auto-reset + time-limit composition (parity: the reference's
    max-step/time-limit wrapper, SURVEY.md §2.1 obs wrappers row), done the
    functional way: on done, the returned obs IS the reset obs and the
    episode's terminal obs is surfaced in ``info['terminal_obs']`` so
    bootstrapping stays correct.
    """

    def __init__(self, env: JaxEnv, time_limit: int | None = None):
        self.env = env
        self.specs = env.specs
        self.time_limit = time_limit or env.max_episode_steps

    def reset(self, key: jax.Array):
        key, sub = jax.random.split(key)
        env_state, obs = self.env.reset(sub)
        return AutoResetState(env_state, key, jnp.zeros((), jnp.int32)), obs

    def step(self, state: AutoResetState, action: jax.Array):
        env_state, obs, reward, done, info = self.env.step(state.env_state, action)
        steps = state.step_count + 1
        # genuine termination takes precedence: a step that both terminates
        # and hits the limit is terminated, NOT truncated (else bootstrapping
        # would wrongly credit gamma*V(terminal) to a real failure state)
        truncated = (
            jnp.asarray(False)
            if self.time_limit is None
            else jnp.logical_and(steps >= self.time_limit, jnp.logical_not(done))
        )
        done = jnp.logical_or(done, truncated)

        key, sub = jax.random.split(state.key)
        reset_state, reset_obs = self.env.reset(sub)

        def pick(reset_leaf, cont_leaf):
            return jnp.where(
                jnp.reshape(done, (1,) * reset_leaf.ndim) if reset_leaf.ndim else done,
                reset_leaf,
                cont_leaf,
            )

        new_env_state = jax.tree.map(pick, reset_state, env_state)
        new_obs = pick(reset_obs, obs)
        new_steps = jnp.where(done, 0, steps)
        info = dict(info)
        info["terminal_obs"] = obs
        info["truncated"] = truncated
        return AutoResetState(new_env_state, key, new_steps), new_obs, reward, done, info


def batch_reset(env, keys: jax.Array):
    """vmap reset over a leading batch of keys."""
    return jax.vmap(env.reset)(keys)


def batch_step(env, state, actions: jax.Array):
    """vmap step over the batch axis of state/actions."""
    return jax.vmap(env.step)(state, actions)
