"""Device-rendered pixel variants of the manipulation envs (BASELINE
config ④: "PPO Robosuite NutAssembly pixels (CNN, frame-stack)").

The reference rendered robosuite camera frames on the host (MuJoCo
offscreen GL) and shipped them through frame-stack wrappers (SURVEY.md
§2.1 obs-wrappers row). The TPU-native answer renders ON DEVICE, like
``jax:pong``: the scene is rasterized from env state with elementwise
masks — jit/vmap/scan-able, so 1000+ pixel envs step and render in HBM
next to the CNN policy with zero host traffic.

Camera model: two orthographic views, each ``RES x RES``:
- channel 0: SIDE view (x right, z up) — the lifting/threading axis;
- channel 1: TOP view (x right, y down) — the tabletop reach plane.
Objects draw at distinct intensities (fingers 255, object 170, peg 110,
table line 60) so a grayscale channel still separates them. The previous
two-view frame is carried in env state and concatenated (pong-style
motion channels), giving obs ``[RES, RES, 4] uint8`` — the frame-stack
role, rendered in-env so no host wrapper is needed on the device path.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from surreal_tpu.envs.base import ArraySpec, EnvSpecs
from surreal_tpu.envs.jax.base import JaxEnv
from surreal_tpu.envs.jax.lift import (
    _BLOCK_HALF,
    _PAD_HALF_H,
    _WS_XY,
    _WS_Z_MAX,
    BlockLift,
)
from surreal_tpu.envs.jax.nut_assembly import (
    PEG_HEIGHT,
    PEG_XY,
    NutAssembly,
)

RES = 64
_FINGER_HALF_X = 0.006   # finger pad half-thickness along the travel axis
_FINGER_HALF_Y = 0.010
_PEG_HALF_R = 0.012

# world extents mapped onto the image square
_X_LO, _X_HI = -_WS_XY - 0.02, _WS_XY + 0.02
_Y_LO, _Y_HI = -_WS_XY - 0.02, _WS_XY + 0.02
_Z_LO, _Z_HI = -0.02, _WS_Z_MAX + 0.02


def _axis(lo: float, hi: float) -> jax.Array:
    """Pixel-center world coordinates along one image axis."""
    return lo + (jnp.arange(RES, dtype=jnp.float32) + 0.5) * ((hi - lo) / RES)


def _boxes_view(u, v, boxes) -> jax.Array:
    """Rasterize axis-aligned boxes onto a [RES, RES] uint8 view.

    ``u``/``v``: world coordinates of pixel columns/rows. ``boxes``:
    sequence of (cu, cv, hu, hv, intensity) — center/half-extent along
    each image axis. Overlaps resolve by max intensity.
    """
    img = jnp.zeros((RES, RES), jnp.uint8)
    for cu, cv, hu, hv, val in boxes:
        mask = (jnp.abs(u[None, :] - cu) <= hu) & (jnp.abs(v[:, None] - cv) <= hv)
        img = jnp.maximum(img, jnp.where(mask, jnp.uint8(val), jnp.uint8(0)))
    return img


def _render_hand_scene(hand, extra_side=(), extra_top=()) -> jax.Array:
    """[RES, RES, 2] uint8: side + top orthographic views of the gripper
    and its object, plus per-view extra boxes (e.g. the peg)."""
    xs = _axis(_X_LO, _X_HI)
    ys = _axis(_Y_LO, _Y_HI)
    zs = _axis(_Z_HI, _Z_LO)  # rows top-down: high z at row 0
    gx, gy, gz = hand.grip_pos[0], hand.grip_pos[1], hand.grip_pos[2]
    half_w = hand.grip_width / 2.0
    bx, by, bz = hand.block_pos[0], hand.block_pos[1], hand.block_pos[2]

    side = _boxes_view(
        xs,
        zs,
        [
            # two finger pads straddling the travel axis
            (gx - half_w, gz, _FINGER_HALF_X, _PAD_HALF_H, 255),
            (gx + half_w, gz, _FINGER_HALF_X, _PAD_HALF_H, 255),
            # palm bar joining the fingers
            (gx, gz + _PAD_HALF_H, half_w, _FINGER_HALF_X, 255),
            (bx, bz, _BLOCK_HALF, _BLOCK_HALF, 170),
            # table surface line at z = 0
            (0.0, 0.0, _X_HI, 0.004, 60),
            *extra_side,
        ],
    )
    top = _boxes_view(
        xs,
        ys,
        [
            (gx - half_w, gy, _FINGER_HALF_X, _FINGER_HALF_Y, 255),
            (gx + half_w, gy, _FINGER_HALF_X, _FINGER_HALF_Y, 255),
            (bx, by, _BLOCK_HALF, _BLOCK_HALF, 170),
            *extra_top,
        ],
    )
    return jnp.stack([side, top], axis=-1)


def render_lift(state) -> jax.Array:
    return _render_hand_scene(state)


def render_nut(state) -> jax.Array:
    return _render_hand_scene(
        state.hand,
        extra_side=[(PEG_XY[0], PEG_HEIGHT / 2.0, _PEG_HALF_R, PEG_HEIGHT / 2.0, 110)],
        extra_top=[(PEG_XY[0], PEG_XY[1], _PEG_HALF_R, _PEG_HALF_R, 110)],
    )


class _PixelState(NamedTuple):
    inner: object
    prev: jax.Array  # [RES, RES, 2] previous two-view frame


class _DevicePixels(JaxEnv):
    """Pixel wrapper over a state-obs device env: same dynamics/reward,
    observations become current+previous two-view frames."""

    inner: JaxEnv       # set by subclasses (stateless pure-fn env)
    render = None       # staticmethod(state) -> [RES, RES, 2] uint8

    def reset(self, key: jax.Array):
        s, _ = self.inner.reset(key)
        frame = type(self).render(s)
        return _PixelState(s, frame), jnp.concatenate([frame, frame], axis=-1)

    def step(self, state: _PixelState, action: jax.Array):
        s, _, reward, done, info = self.inner.step(state.inner, action)
        frame = type(self).render(s)
        obs = jnp.concatenate([frame, state.prev], axis=-1)
        return _PixelState(s, frame), obs, reward, done, info


_PIXEL_SPECS = lambda inner: EnvSpecs(  # noqa: E731
    obs=ArraySpec(shape=(RES, RES, 4), dtype=np.dtype(np.uint8), name="pixels"),
    action=inner.specs.action,
)


class BlockLiftPixels(_DevicePixels):
    """Factory name ``jax:lift_pixels``."""

    inner = BlockLift()
    render = staticmethod(render_lift)
    max_episode_steps = BlockLift.max_episode_steps
    specs = _PIXEL_SPECS(BlockLift)


class NutAssemblyPixels(_DevicePixels):
    """Factory name ``jax:nut_pixels`` — BASELINE config ④'s shape."""

    inner = NutAssembly()
    render = staticmethod(render_nut)
    max_episode_steps = NutAssembly.max_episode_steps
    specs = _PIXEL_SPECS(NutAssembly)


# -- eval-video frame rendering ---------------------------------------------

def _views_to_rgb(views, upscale: int = 3):
    """[R, R, 2] two-view uint8 -> side-by-side RGB [R*u, 2*R*u + u, 3]
    (host numpy; per-frame eval-video work, not a device op)."""
    import numpy as np

    v = np.asarray(views)
    sep = np.full((v.shape[0], 1), 40, np.uint8)  # thin divider column
    panel = np.concatenate([v[..., 0], sep, v[..., 1]], axis=1)
    panel = panel.repeat(upscale, axis=0).repeat(upscale, axis=1)
    return np.stack([panel] * 3, axis=-1)


def frame_renderer(env):
    """Optional eval-video renderer for a device env: returns
    ``state -> [H, W, 3] uint8`` or None when the env has no visual form
    (the reference recorded eval videos via VideoWrapper; device envs
    render from state instead of a GL context)."""
    from surreal_tpu.envs.jax.pong import Pong

    if isinstance(env, _DevicePixels):
        render = jax.jit(type(env).render)  # one dispatch per frame, not per op
        return lambda s: _views_to_rgb(render(s.inner))
    if isinstance(env, BlockLift):
        render = jax.jit(render_lift)
        return lambda s: _views_to_rgb(render(s))
    if isinstance(env, NutAssembly):
        render = jax.jit(render_nut)
        return lambda s: _views_to_rgb(render(s))
    if isinstance(env, Pong):
        import numpy as np

        def pong_frame(s):
            f = np.asarray(s.prev_frame).repeat(4, axis=0).repeat(4, axis=1)
            return np.stack([f] * 3, axis=-1)

        return pong_frame
    return None
