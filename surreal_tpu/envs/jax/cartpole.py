"""On-device CartPole-v1 (dynamics per Barto-Sutton-Anderson / the gymnasium
implementation's constants). BASELINE config ① workload, runnable either via
the gymnasium host adapter (``gym:CartPole-v1``) or fully on device as
``jax:cartpole`` — both expose identical specs so configs are swappable.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from surreal_tpu.envs.base import ArraySpec, DiscreteSpec, EnvSpecs
from surreal_tpu.envs.jax.base import JaxEnv

_GRAVITY = 9.8
_CART_MASS = 1.0
_POLE_MASS = 0.1
_TOTAL_MASS = _CART_MASS + _POLE_MASS
_POLE_HALF_LEN = 0.5
_POLEMASS_LEN = _POLE_MASS * _POLE_HALF_LEN
_FORCE_MAG = 10.0
_TAU = 0.02
_THETA_LIMIT = 12 * 2 * jnp.pi / 360
_X_LIMIT = 2.4


class CartPoleState(NamedTuple):
    x: jax.Array
    x_dot: jax.Array
    theta: jax.Array
    theta_dot: jax.Array


class CartPole(JaxEnv):
    max_episode_steps = 500  # CartPole-v1 limit

    specs = EnvSpecs(
        obs=ArraySpec(shape=(4,), dtype=np.dtype(np.float32), name="state"),
        action=DiscreteSpec(shape=(), dtype=np.dtype(np.int32), name="action", n=2),
    )

    def reset(self, key: jax.Array):
        vals = jax.random.uniform(key, (4,), jnp.float32, -0.05, 0.05)
        state = CartPoleState(vals[0], vals[1], vals[2], vals[3])
        return state, self._obs(state)

    def step(self, state: CartPoleState, action: jax.Array):
        force = jnp.where(action == 1, _FORCE_MAG, -_FORCE_MAG).astype(jnp.float32)
        cos_t = jnp.cos(state.theta)
        sin_t = jnp.sin(state.theta)
        temp = (force + _POLEMASS_LEN * state.theta_dot**2 * sin_t) / _TOTAL_MASS
        theta_acc = (_GRAVITY * sin_t - cos_t * temp) / (
            _POLE_HALF_LEN * (4.0 / 3.0 - _POLE_MASS * cos_t**2 / _TOTAL_MASS)
        )
        x_acc = temp - _POLEMASS_LEN * theta_acc * cos_t / _TOTAL_MASS

        new = CartPoleState(
            x=state.x + _TAU * state.x_dot,
            x_dot=state.x_dot + _TAU * x_acc,
            theta=state.theta + _TAU * state.theta_dot,
            theta_dot=state.theta_dot + _TAU * theta_acc,
        )
        done = (
            (jnp.abs(new.x) > _X_LIMIT) | (jnp.abs(new.theta) > _THETA_LIMIT)
        )
        reward = jnp.ones((), jnp.float32)
        return new, self._obs(new), reward, done, {}

    @staticmethod
    def _obs(state: CartPoleState) -> jax.Array:
        return jnp.stack(
            [state.x, state.x_dot, state.theta, state.theta_dot]
        ).astype(jnp.float32)
