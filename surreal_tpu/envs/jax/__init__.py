"""Pure-functional on-device environments (TPU-native; no reference
counterpart — replaces host C physics for the north-star throughput path).
"""

from surreal_tpu.envs.jax.base import AutoReset, AutoResetState, JaxEnv, batch_reset, batch_step

__all__ = ["AutoReset", "AutoResetState", "JaxEnv", "batch_reset", "batch_step"]
