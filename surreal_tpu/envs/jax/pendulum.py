"""On-device Pendulum-v1 (continuous control smoke workload for the
PPO/DDPG continuous paths before MuJoCo-class envs; same functional API as
``jax:cartpole``). Dynamics/constants match gymnasium's Pendulum-v1 with
the canonical [-1, 1] action box scaled to +-2 torque internally.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from surreal_tpu.envs.base import ArraySpec, EnvSpecs
from surreal_tpu.envs.jax.base import JaxEnv

_MAX_SPEED = 8.0
_MAX_TORQUE = 2.0
_DT = 0.05
_G = 10.0
_M = 1.0
_L = 1.0


class PendulumState(NamedTuple):
    theta: jax.Array
    theta_dot: jax.Array


def _angle_normalize(x: jax.Array) -> jax.Array:
    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi


class Pendulum(JaxEnv):
    max_episode_steps = 200

    specs = EnvSpecs(
        obs=ArraySpec(shape=(3,), dtype=np.dtype(np.float32), name="state"),
        action=ArraySpec(shape=(1,), dtype=np.dtype(np.float32), name="torque"),
    )

    def reset(self, key: jax.Array):
        k1, k2 = jax.random.split(key)
        theta = jax.random.uniform(k1, (), jnp.float32, -jnp.pi, jnp.pi)
        theta_dot = jax.random.uniform(k2, (), jnp.float32, -1.0, 1.0)
        state = PendulumState(theta, theta_dot)
        return state, self._obs(state)

    def step(self, state: PendulumState, action: jax.Array):
        u = jnp.clip(action[0], -1.0, 1.0) * _MAX_TORQUE
        cost = (
            _angle_normalize(state.theta) ** 2
            + 0.1 * state.theta_dot**2
            + 0.001 * u**2
        )
        new_theta_dot = state.theta_dot + (
            3.0 * _G / (2.0 * _L) * jnp.sin(state.theta) + 3.0 / (_M * _L**2) * u
        ) * _DT
        new_theta_dot = jnp.clip(new_theta_dot, -_MAX_SPEED, _MAX_SPEED)
        new = PendulumState(
            theta=state.theta + new_theta_dot * _DT,
            theta_dot=new_theta_dot,
        )
        done = jnp.asarray(False)  # time-limit only (via AutoReset)
        return new, self._obs(new), -cost.astype(jnp.float32), done, {}

    @staticmethod
    def _obs(state: PendulumState) -> jax.Array:
        return jnp.stack(
            [jnp.cos(state.theta), jnp.sin(state.theta), state.theta_dot]
        ).astype(jnp.float32)
