"""Gymnasium host adapter (parity: reference gym adapter in
``surreal/env/``, SURVEY.md §2.1 env-adapter row).

Differences from the reference, by design: the adapter is *batched* — one
adapter steps B envs and returns contiguous arrays ready for a single
``device_put`` — because the rebuild replaces the 1-process-per-env actor
pool with SEED-style central inference (SURVEY.md §3.2).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from surreal_tpu.envs.base import (
    ArraySpec,
    DiscreteSpec,
    EnvSpecs,
    HostEnv,
    StepOutput,
    rescale_canonical_action,
)


class GymAdapter(HostEnv):
    """B independent gymnasium envs behind the batched HostEnv API."""

    def __init__(self, env_id: str, num_envs: int = 1, seed: int = 0, **make_kwargs: Any):
        import gymnasium

        self.envs = [gymnasium.make(env_id, **make_kwargs) for _ in range(num_envs)]
        self.num_envs = num_envs
        self._seed = seed
        self._seeded = False

        proto = self.envs[0]
        obs_space = proto.observation_space
        act_space = proto.action_space
        obs_spec = ArraySpec(
            shape=tuple(obs_space.shape), dtype=np.dtype(obs_space.dtype), name="obs"
        )
        if hasattr(act_space, "n"):  # Discrete
            act_spec = DiscreteSpec(
                shape=(), dtype=np.dtype(np.int32), name="action", n=int(act_space.n)
            )
            self._act_low = self._act_high = None
        else:  # Box -> canonical [-1, 1]
            act_spec = ArraySpec(
                shape=tuple(act_space.shape), dtype=np.dtype(np.float32), name="action"
            )
            self._act_low = np.asarray(act_space.low, np.float32)
            self._act_high = np.asarray(act_space.high, np.float32)
        self.specs = EnvSpecs(obs=obs_spec, action=act_spec)

    def reset(self, seed: int | None = None) -> np.ndarray:
        # Seed each env's RNG stream only on the first reset (or when the
        # caller passes an explicit seed); plain reset() afterwards keeps the
        # streams advancing so repeated resets don't replay identical episodes.
        if seed is None and self._seeded:
            obs = [env.reset()[0] for env in self.envs]
        else:
            base = self._seed if seed is None else seed
            obs = [env.reset(seed=base + i)[0] for i, env in enumerate(self.envs)]
            self._seeded = True
        return np.stack(obs).astype(self.specs.obs.dtype)

    def step(self, actions: np.ndarray) -> StepOutput:
        if self._act_low is not None:
            actions = rescale_canonical_action(actions, self._act_low, self._act_high)
        obs_b, rew_b, done_b = [], [], []
        terminal_obs = np.zeros((self.num_envs, *self.specs.obs.shape), self.specs.obs.dtype)
        truncated_b = np.zeros(self.num_envs, bool)
        for i, env in enumerate(self.envs):
            act = actions[i]
            if isinstance(self.specs.action, DiscreteSpec):
                act = int(act)
            obs, reward, terminated, truncated, _ = env.step(act)
            done = terminated or truncated
            if done:
                terminal_obs[i] = obs
                truncated_b[i] = truncated and not terminated
                if self.pre_reset_hook is not None:
                    self.pre_reset_hook(i, env)
                obs, _ = env.reset()
            obs_b.append(obs)
            rew_b.append(reward)
            done_b.append(done)
        return StepOutput(
            obs=np.stack(obs_b).astype(self.specs.obs.dtype),
            reward=np.asarray(rew_b, np.float32),
            done=np.asarray(done_b, bool),
            info={"terminal_obs": terminal_obs, "truncated": truncated_b},
        )

    def close(self) -> None:
        for env in self.envs:
            env.close()
