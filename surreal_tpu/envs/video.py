"""Eval-episode video recording (parity: reference
``surreal/env/video_env.py`` VideoWrapper, SURVEY.md §2.1).

Records env-0's frames every N episodes. Encodes mp4 when imageio+ffmpeg
are importable, else falls back to ``.npz`` frame dumps (this image has no
guaranteed encoder; do not add dependencies).
"""

from __future__ import annotations

import os

import numpy as np

from surreal_tpu.envs.base import HostEnv, HostWrapper, StepOutput


class VideoWrapper(HostWrapper):
    def __init__(self, env: HostEnv, out_dir: str, every_n_episodes: int = 50):
        super().__init__(env)
        self.out_dir = out_dir
        self.every_n = max(1, every_n_episodes)
        self._episode = 0
        self._frames: list[np.ndarray] = []
        os.makedirs(out_dir, exist_ok=True)

    def _render(self) -> np.ndarray | None:
        render = getattr(self.env, "render", None)
        if render is None and hasattr(self.env, "envs"):
            env0 = self.env.envs[0]
            render = getattr(env0, "render", None)
            if render is None and hasattr(env0, "physics"):  # dm_control
                return self.env.envs[0].physics.render(height=240, width=320)
        if render is None:
            return None
        frame = render()
        return None if frame is None else np.asarray(frame)

    @property
    def _recording(self) -> bool:
        return self._episode % self.every_n == 0

    def reset(self, seed: int | None = None) -> np.ndarray:
        obs = self.env.reset(seed)
        self._frames = []
        if self._recording:
            frame = self._render()
            if frame is not None:
                self._frames.append(frame)
        return obs

    def step(self, actions: np.ndarray) -> StepOutput:
        out = self.env.step(actions)
        if self._recording:
            frame = self._render()
            if frame is not None:
                self._frames.append(frame)
        if out.done[0]:
            if self._recording and self._frames:
                self._save()
            self._episode += 1
            self._frames = []
        return out

    def _save(self) -> None:
        save_episode_frames(self._frames, self.out_dir, self._episode)


def save_episode_frames(frames, out_dir: str, episode_idx: int) -> str:
    """Write one episode's frame stack (mp4 when an encoder exists, else
    .npz). Shared by the host VideoWrapper and the device-env eval
    recorder. Returns the file stem."""
    os.makedirs(out_dir, exist_ok=True)
    stem = os.path.join(out_dir, f"episode_{episode_idx:06d}")
    arr = np.stack([np.asarray(f) for f in frames])
    try:
        import imageio.v2 as imageio

        imageio.mimwrite(stem + ".mp4", arr, fps=30)
    except Exception:
        np.savez_compressed(stem + ".npz", frames=arr)
    return stem
