"""dm_control host adapter (parity: reference dm_control adapter in
``surreal/env/``, SURVEY.md §2.1): flattens the suite's ordered obs dict
into one float vector, canonicalizes actions to [-1, 1], batched like the
gym adapter. BASELINE config ② (cheetah-run) runs through this.
"""

from __future__ import annotations

import numpy as np

from surreal_tpu.envs.base import (
    ArraySpec,
    EnvSpecs,
    HostEnv,
    StepOutput,
    rescale_canonical_action,
)


def _flatten_obs(obs_dict) -> np.ndarray:
    return np.concatenate(
        [np.asarray(v, np.float32).ravel() for v in obs_dict.values()]
    )


class DmControlAdapter(HostEnv):
    def __init__(self, domain: str, task: str, num_envs: int = 1, seed: int = 0):
        from dm_control import suite

        self.envs = [
            suite.load(domain, task, task_kwargs={"random": seed + i})
            for i in range(num_envs)
        ]
        self.num_envs = num_envs

        proto = self.envs[0]
        ts = proto.reset()
        obs_dim = _flatten_obs(ts.observation).shape[0]
        act_spec = proto.action_spec()
        self._act_low = np.asarray(act_spec.minimum, np.float32)
        self._act_high = np.asarray(act_spec.maximum, np.float32)
        self.specs = EnvSpecs(
            obs=ArraySpec(shape=(obs_dim,), dtype=np.dtype(np.float32), name="obs"),
            action=ArraySpec(
                shape=tuple(act_spec.shape), dtype=np.dtype(np.float32), name="action"
            ),
        )

    def reset(self, seed: int | None = None) -> np.ndarray:
        del seed  # dm_control seeding is fixed at construction
        return np.stack(
            [_flatten_obs(env.reset().observation) for env in self.envs]
        )

    def step(self, actions: np.ndarray) -> StepOutput:
        native = rescale_canonical_action(actions, self._act_low, self._act_high)
        obs_b, rew_b, done_b = [], [], []
        terminal_obs = np.zeros((self.num_envs, *self.specs.obs.shape), np.float32)
        truncated_b = np.zeros(self.num_envs, bool)
        for i, env in enumerate(self.envs):
            ts = env.step(native[i])
            done = ts.last()
            obs = _flatten_obs(ts.observation)
            if done:
                terminal_obs[i] = obs
                # dm_control suite episodes end by time limit (discount==1.0
                # at the boundary means truncation, not termination)
                truncated_b[i] = ts.discount is None or ts.discount > 0.0
                if self.pre_reset_hook is not None:
                    self.pre_reset_hook(i, env)
                obs = _flatten_obs(env.reset().observation)
            obs_b.append(obs)
            rew_b.append(0.0 if ts.reward is None else ts.reward)
            done_b.append(done)
        return StepOutput(
            obs=np.stack(obs_b),
            reward=np.asarray(rew_b, np.float32),
            done=np.asarray(done_b, bool),
            info={"terminal_obs": terminal_obs, "truncated": truncated_b},
        )
