"""Anomaly detectors over the live ops-plane snapshot (ISSUE 15).

PRs 13-14 collect; this module *interprets*. ``Watchdog.evaluate(snap)``
runs once per ops-plane snapshot (the metrics cadence) over the merged
snapshot dict the :class:`~surreal_tpu.session.opsplane.OpsAggregator`
just built — pure host arithmetic on already-synced floats, so the
transfer-guard proof that covers the snapshot path covers the detectors
too (zero device->host syncs added).

Detector families (each firing is a plain dict the incident engine
consumes):

- **breakout** — robust EWMA/median + MAD deviation on the latency and
  throughput signals: derived iteration time, env steps/s, the learner's
  sample-wait, the gateway act-RTT p99 hop, the fleet serve EWMA. A
  value ``mad_k`` MADs AND ``min_rel`` relative off the window median,
  in the bad direction, for ``sustain`` consecutive snapshots, fires.
- **saturation** — absolute ceilings on queue depths / backpressure
  (fleet chunk queue, shard sample queue, gateway act queue) and on the
  respawn *rate* (fleet/experience/gateway respawns per history window).
- **growth** — monotonic-growth on every ``*dropped*`` / ``*bad_frames``
  counter found anywhere in the snapshot (they are all
  counted-never-silent failure counters: sustained growth is never
  benign), and on ``lineage/staleness_p99`` once it exceeds
  ``staleness_floor`` (a staleness ramp past pipeline-depth scale means
  the param path is falling behind; the startup climb toward steady
  state stays below the floor and never fires).
- **liveness** — any tier the aggregator marked DEAD (silent for 3x its
  own declared cadence).
- **regression** — live env steps/s and MFU against the committed BENCH
  baseline rows for the same fingerprint (``perf_gate.load_rows``): the
  bench-time win must *stay* won during live runs.

Every evaluation honors the ``watchdog.eval`` chaos site: ``drop_eval``
skips the sweep (counted in ``ops/watchdog_dropped_evals``, never
silent), ``delay`` sleeps first. Knobs: ``session_config.watchdog.*``
(session/default_configs.py).
"""

from __future__ import annotations

import time

from surreal_tpu.utils import faults

# breakout signal specs: (name, tier blamed, direction). 'high' fires on
# values above the window median, 'low' below (throughput collapses down).
# Values are pulled from the snapshot by key — gauges/body of any tier
# row for plain keys, hop percentiles for ('hop', name, pctl) specs,
# 'derived' for snapshot-to-snapshot derivations done here.
BREAKOUT_SIGNALS = (
    ("iter_ms", "learner", "high", ("derived", "iter_ms")),
    ("env_steps_per_s", "learner", "low", ("gauge", "time/env_steps_per_s")),
    ("sample_wait_ms", "learner", "high",
     ("gauge", "experience/sample_wait_ms")),
    ("act_rtt_p99_ms", "gateway", "high", ("hop", "gateway_act_ms", "p99")),
    ("fleet_serve_ms", "fleet", "high", ("gauge", "fleet/serve_ms")),
)

# saturation ceilings: gauge key -> tier blamed (threshold from config)
QUEUE_SIGNALS = {
    "fleet/queue_depth": "fleet",
    "experience/sample_queue_depth": "experience",
    "gateway/queued_acts": "gateway",
}
RESPAWN_COUNTERS = {
    "fleet/respawns": "fleet",
    "experience/respawns": "experience",
    "gateway/respawns": "gateway",
}

# growth counters are attributed to the tier their family belongs to
# (the dataflow graph in session/incidents.py then walks upstream)
_PREFIX_TIER = {
    "gateway": "gateway",
    "fleet": "fleet",
    "experience": "experience",
    "param": "param_fanout",
    "lineage": "param_fanout",
    "ops": "learner",
    "trace": "learner",
    "replay": "learner",
    "perf": "learner",
    "slo": "gateway",
}


def _family_tier(key: str) -> str:
    return _PREFIX_TIER.get(str(key).split("/", 1)[0], "learner")


def base_tier(name: str) -> str:
    """Collapse a per-instance tier row name to its dataflow-graph node:
    ``fleet.replica1`` -> ``fleet``, ``experience.shard0`` ->
    ``experience``."""
    return str(name).split(".", 1)[0]


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class _Breakout:
    """One robust-deviation detector: rolling window median + MAD, fires
    after ``sustain`` consecutive bad-direction outliers past warmup."""

    def __init__(self, name, tier, direction, cfg):
        self.name = name
        self.tier = tier
        self.direction = direction
        self.window = int(cfg["window"])
        self.warmup = int(cfg["warmup"])
        self.mad_k = float(cfg["mad_k"])
        self.min_rel = float(cfg["min_rel"])
        self.sustain = max(1, int(cfg["sustain"]))
        self._hist: list[float] = []
        self._streak = 0

    def observe(self, value) -> dict | None:
        if value is None:
            # a signal that stopped reporting is the liveness detector's
            # job; breakouts only judge values that arrived
            self._streak = 0
            return None
        v = float(value)
        hist = self._hist
        firing = None
        if len(hist) >= self.warmup:
            med = _median(hist)
            mad = _median([abs(x - med) for x in hist])
            # MAD floor: a perfectly flat warmup window (synthetic rigs,
            # quantized ms readings) must not make every jitter an outlier
            floor = max(mad, 1e-9, abs(med) * 0.01)
            dev = (v - med) if self.direction == "high" else (med - v)
            rel = dev / max(abs(med), 1e-9)
            if dev > self.mad_k * floor and rel > self.min_rel:
                self._streak += 1
            else:
                self._streak = 0
            if self._streak >= self.sustain:
                firing = {
                    "detector": "breakout",
                    "signal": self.name,
                    "tier": self.tier,
                    "value": round(v, 4),
                    "baseline": round(med, 4),
                    "direction": self.direction,
                    "deviation_mads": round(dev / floor, 2),
                }
        else:
            self._streak = 0
        hist.append(v)
        if len(hist) > self.window:
            del hist[0]
        return firing


class _Counter:
    """Rolling history of a monotonic counter; reports the per-window
    deltas so growth/rate detectors share one bookkeeping shape."""

    def __init__(self, window: int):
        self.window = max(2, int(window))
        self._vals: list[float] = []

    def observe(self, value: float) -> list[float]:
        self._vals.append(float(value))
        if len(self._vals) > self.window:
            del self._vals[0]
        return [
            self._vals[i + 1] - self._vals[i]
            for i in range(len(self._vals) - 1)
        ]


class Watchdog:
    """The detector sweep. Construct once per run (launch/hooks.py),
    call :meth:`evaluate` with each merged ops snapshot; returns the
    list of firing dicts for the incident engine."""

    def __init__(self, cfg=None, baseline_rows=None, platform=None,
                 geometry=None):
        cfg = cfg or {}
        get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: d
        self.enabled = bool(get("enabled", True))
        bo = {
            "window": int(get("window", 32)),
            "warmup": int(get("warmup", 8)),
            "mad_k": float(get("mad_k", 6.0)),
            "min_rel": float(get("min_rel", 0.25)),
            "sustain": int(get("sustain", 2)),
        }
        self._breakouts = [
            _Breakout(name, tier, direction, bo)
            for name, tier, direction, _ in BREAKOUT_SIGNALS
        ]
        self._specs = {s[0]: s[3] for s in BREAKOUT_SIGNALS}
        self.queue_depth_max = float(get("queue_depth_max", 512.0))
        self.respawn_burst = max(1, int(get("respawn_burst", 2)))
        self.growth_windows = max(1, int(get("growth_windows", 2)))
        self.staleness_growth_windows = max(
            2, int(get("staleness_growth_windows", 4))
        )
        # absolute floor before a staleness ramp counts as growth: live
        # runs legitimately climb from 0 toward steady-state pipeline
        # depth at startup (the sample queue still holds early-version
        # experience); a stalled fanout grows one version per update
        # without bound and crosses any depth-scale floor quickly.
        self.staleness_floor = float(get("staleness_floor", 64.0))
        self._queue_streaks: dict[str, int] = {}
        self._counters: dict[str, _Counter] = {}
        self._counter_window = bo["window"]
        # online regression vs the committed BENCH trail: rows from
        # perf_gate.load_rows for THIS platform (+ geometry when the live
        # run declares one). None/empty disarms the detector — a dev-box
        # run at a toy geometry has no committed fingerprint to regress
        # against.
        self.regression_frac = float(get("regression_frac", 0.5))
        self.regression_sustain = max(1, int(get("regression_sustain", 3)))
        self._regression_streaks = {"throughput": 0, "mfu": 0}
        self._baseline = self._match_baseline(
            baseline_rows, platform, geometry
        )
        # snapshot-to-snapshot derivations (iteration time)
        self._last_t: float | None = None
        self._last_iter: int | None = None
        self.evals = 0
        self.dropped_evals = 0
        self.firings = 0

    @staticmethod
    def _match_baseline(rows, platform, geometry) -> dict:
        """Pick the committed headline numbers matching the live
        fingerprint out of the ``perf_gate.load_rows`` row dicts."""
        best: dict = {}
        for row in rows or ():
            if row.get("failed") or row.get("value") is None:
                continue
            if not str(row.get("metric", "")).startswith("env_steps_per_sec"):
                continue
            if platform and row.get("platform") not in (None, platform):
                continue
            if geometry and row.get("geometry") not in (None, geometry):
                continue
            if float(row["value"]) > float(best.get("throughput", 0.0)):
                best["throughput"] = float(row["value"])
                best["file"] = row.get("file")
                if row.get("mfu") is not None:
                    best["mfu"] = float(row["mfu"])
        return best

    @staticmethod
    def load_baseline(art_dir: str):
        """Committed BENCH rows via ``perf_gate.load_rows`` — guarded:
        perf_gate lives at the repo root, not in the package, so an
        installed tree without the bench trail simply disarms the
        regression detector."""
        try:
            from perf_gate import load_rows
        except ImportError:
            return None
        try:
            return load_rows(art_dir)
        except Exception:
            return None

    # -- snapshot value extraction (pure dict walks) -------------------------
    @staticmethod
    def _find_gauge(snap: dict, key: str):
        for row in (snap.get("tiers") or {}).values():
            for src in (row.get("gauges"), row.get("body")):
                if src and key in src:
                    v = src[key]
                    if isinstance(v, (int, float)):
                        return float(v)
        return None

    def _signal_value(self, name: str, snap: dict):
        spec = self._specs[name]
        if spec[0] == "gauge":
            return self._find_gauge(snap, spec[1])
        if spec[0] == "hop":
            st = (snap.get("hops") or {}).get(spec[1])
            if isinstance(st, dict) and st.get(spec[2]) is not None:
                return float(st[spec[2]])
            return None
        # derived: wall seconds per iteration between snapshots
        t, it = snap.get("t"), snap.get("iteration")
        out = None
        if (t is not None and it is not None
                and self._last_t is not None and self._last_iter is not None
                and int(it) > int(self._last_iter)):
            out = (
                (float(t) - self._last_t)
                / (int(it) - self._last_iter) * 1e3
            )
        if t is not None and it is not None:
            self._last_t, self._last_iter = float(t), int(it)
        return out

    # -- the sweep -----------------------------------------------------------
    def evaluate(self, snap: dict | None) -> list[dict]:
        """One detector sweep over one merged snapshot. Returns the
        firings (possibly empty). Honors the ``watchdog.eval`` chaos
        site: ``drop_eval`` is counted, never silent."""
        if not self.enabled or not snap:
            return []
        spec = faults.fire("watchdog.eval")
        if spec is not None:
            kind = spec.get("kind")
            if kind == "drop_eval":
                self.dropped_evals += 1
                return []
            if kind == "delay":
                faults.sleep_ms(spec)
        self.evals += 1
        firings: list[dict] = []
        tiers = snap.get("tiers") or {}

        # liveness: the aggregator already applied the 3x-cadence rule
        for name, row in sorted(tiers.items()):
            if row.get("dead"):
                firings.append({
                    "detector": "liveness",
                    "signal": name,
                    "tier": base_tier(name),
                    "value": float(row.get("age_s", 0.0)),
                    "baseline": 3.0 * float(row.get("cadence_s", 0.0)),
                    "direction": "high",
                })

        # breakouts
        for det in self._breakouts:
            firing = det.observe(self._signal_value(det.name, snap))
            if firing is not None:
                firings.append(firing)

        # saturation: queue ceilings (sustained 2 windows) + respawn rate
        for key, tier in QUEUE_SIGNALS.items():
            v = self._find_gauge(snap, key)
            if v is not None and v >= self.queue_depth_max:
                self._queue_streaks[key] = self._queue_streaks.get(key, 0) + 1
            else:
                self._queue_streaks[key] = 0
            if self._queue_streaks.get(key, 0) >= 2:
                firings.append({
                    "detector": "saturation",
                    "signal": key,
                    "tier": tier,
                    "value": round(float(v), 2),
                    "baseline": self.queue_depth_max,
                    "direction": "high",
                })
        for key, tier in RESPAWN_COUNTERS.items():
            v = self._find_gauge(snap, key)
            if v is None:
                continue
            deltas = self._counters.setdefault(
                key, _Counter(self._counter_window)
            ).observe(v)
            burst = sum(d for d in deltas if d > 0)
            if burst >= self.respawn_burst:
                firings.append({
                    "detector": "saturation",
                    "signal": key,
                    "tier": tier,
                    "value": burst,
                    "baseline": self.respawn_burst,
                    "direction": "high",
                })

        # monotonic growth: every counted-never-silent failure counter
        # found anywhere in the snapshot, plus the snapshot-level
        # aggregator drop count and the lineage staleness ramp
        growth: dict[str, float] = {}
        for row in tiers.values():
            for src in (row.get("gauges"), row.get("body")):
                for key, v in (src or {}).items():
                    if not isinstance(v, (int, float)):
                        continue
                    k = str(key)
                    if "dropped" in k or "bad_frames" in k:
                        growth[k] = max(growth.get(k, 0.0), float(v))
        if snap.get("bad_frames") is not None:
            growth["ops/bad_frames"] = max(
                growth.get("ops/bad_frames", 0.0),
                float(snap["bad_frames"]),
            )
        for key in sorted(growth):
            deltas = self._counters.setdefault(
                key, _Counter(self._counter_window)
            ).observe(growth[key])
            recent = deltas[-self.growth_windows:]
            if (len(recent) >= self.growth_windows
                    and all(d > 0 for d in recent)):
                firings.append({
                    "detector": "growth",
                    "signal": key,
                    "tier": _family_tier(key),
                    "value": growth[key],
                    "baseline": growth[key] - sum(recent),
                    "direction": "high",
                })
        stale = self._find_gauge(snap, "lineage/staleness_p99")
        if stale is not None:
            deltas = self._counters.setdefault(
                "lineage/staleness_p99", _Counter(self._counter_window)
            ).observe(stale)
            recent = deltas[-self.staleness_growth_windows:]
            if (stale > self.staleness_floor
                    and len(recent) >= self.staleness_growth_windows
                    and all(d > 0 for d in recent)):
                firings.append({
                    "detector": "growth",
                    "signal": "lineage/staleness_p99",
                    "tier": "param_fanout",
                    "value": stale,
                    "baseline": stale - sum(recent),
                    "direction": "high",
                })

        # online regression vs the committed BENCH fingerprint
        if self._baseline.get("throughput"):
            for name, live_key, base in (
                ("throughput", "time/env_steps_per_s",
                 self._baseline.get("throughput")),
                ("mfu", "perf/mfu", self._baseline.get("mfu")),
            ):
                if not base:
                    continue
                live = self._find_gauge(snap, live_key)
                if live is None:
                    continue
                if live < self.regression_frac * float(base):
                    self._regression_streaks[name] += 1
                else:
                    self._regression_streaks[name] = 0
                if self._regression_streaks[name] >= self.regression_sustain:
                    firings.append({
                        "detector": "regression",
                        "signal": name,
                        "tier": "learner",
                        "value": round(float(live), 4),
                        "baseline": round(float(base), 4),
                        "direction": "low",
                        "bench": self._baseline.get("file"),
                    })

        self.firings += len(firings)
        for f in firings:
            f["t"] = time.time()
        return firings

    def gauges(self) -> dict[str, float]:
        """The watchdog's own ``ops/*`` counters (GAUGE_REGISTRY
        documents each); merged into the learner's metrics row."""
        return {
            "ops/watchdog_evals": float(self.evals),
            "ops/watchdog_dropped_evals": float(self.dropped_evals),
            "ops/watchdog_firings": float(self.firings),
        }
