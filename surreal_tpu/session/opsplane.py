"""Run-wide live ops plane (ISSUE 13): cross-tier metrics aggregation,
per-tenant SLO evaluation, and a fault flight recorder.

Everything before this PR was post-hoc — per-process JSONL that
``surreal_tpu diag`` replays after the fact. This module gives a running
multi-tier session (gateway, inference fleet, experience shards,
parameter fanout, learner) ONE live merged view:

- **OpsPusher** — one per pushing thread (zmq sockets are not
  thread-safe, so every tier thread owns its own PUSH socket — the
  control-wire discipline the data planes already follow). Pushes are
  cadence-bounded and non-blocking; a full queue DROPS the row and
  counts it, never stalls a serve loop. Process tiers (experience
  shards, fleet replicas) inherit the aggregator address through their
  spawn kwargs exactly like the PR-6 trace id.
- **OpsAggregator** — the learner-side PULL collector. A dedicated
  receiver thread keeps the latest row per tier; ``snapshot()`` (called
  at the metrics cadence by SessionHooks) merges them with the learner's
  own rows into one trace-id-stamped run snapshot, evaluates per-tenant
  SLOs (session/slo.py), feeds the flight recorder, and atomically
  replaces ``<folder>/telemetry/ops_snapshot.json`` — the file
  ``surreal_tpu top`` renders live, with no full-log replay.
- **FlightRecorder** — a bounded in-memory ring of the last K snapshots
  plus fault/recovery events, dumped to
  ``<folder>/telemetry/flightrec/<trigger>/`` when the RecoveryManager
  trips, a chaos fault fires, or an SLO budget exhausts — post-mortems
  see the minutes *before* the incident, not just the trip itself.

Tier liveness reuses the heartbeat rule: each pushed row carries its own
``cadence_s``; a tier whose newest row is older than 3x its cadence is
rendered DEAD instead of silently looking fine.

Pure host python on the snapshot path — no jax imports, no device
syncs (the transfer-guard test runs end_iteration, snapshot included,
under a zero-transfer assertion). ``zmq`` is imported lazily inside the
pusher/aggregator so ``top``/``load_snapshot`` stay importable off-chip
with no messaging stack at all.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from surreal_tpu.session.slo import SLOTracker
from surreal_tpu.session.telemetry import TELEMETRY_DIR
from surreal_tpu.utils import faults
from surreal_tpu.utils.net import alloc_address

SNAPSHOT_FILE = "ops_snapshot.json"
FLIGHTREC_DIR = "flightrec"
# a row with no self-declared cadence is judged against this one
DEFAULT_CADENCE_S = 10.0


def snapshot_path(folder: str) -> str:
    return os.path.join(folder, TELEMETRY_DIR, SNAPSHOT_FILE)


def load_snapshot(folder: str) -> dict | None:
    """Read the aggregator's snapshot file, tolerating the hostile shapes
    a live/killed run leaves behind: missing file, a torn half-written
    JSON text (the writer is atomic via os.replace, but a copied or
    truncated folder is not), or bytes cut inside a UTF-8 sequence."""
    try:
        with open(snapshot_path(folder), errors="replace") as f:
            snap = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    return snap if isinstance(snap, dict) else None


class OpsPusher:
    """One tier thread's PUSH half of the ops wire.

    ``push`` is cadence-bounded (at most one row per ``min_interval_s``
    unless forced) and never blocks: the socket runs a small send
    high-water mark and a full queue or closed peer drops the row,
    counted in ``dropped``. The ``ops.push`` chaos site lets tests drop
    or delay rows deterministically.
    """

    def __init__(self, address: str, tier: str, trace_id: str | None = None,
                 min_interval_s: float = 1.0):
        import zmq

        self.tier = str(tier)
        self.trace_id = trace_id
        self.min_interval_s = float(min_interval_s)
        self._zmq = zmq
        self._sock = zmq.Context.instance().socket(zmq.PUSH)
        self._sock.setsockopt(zmq.LINGER, 0)
        self._sock.setsockopt(zmq.SNDHWM, 8)  # stats, not data: drop early
        self._sock.connect(address)
        self._last = 0.0
        self.pushes = 0
        self.dropped = 0

    def push(self, gauges: dict | None = None, hops: dict | None = None,
             body: dict | None = None, force: bool = False) -> bool:
        """Send one row ``{tier, t, trace, cadence_s, gauges, hops,
        body}``; returns whether it left this process."""
        now = time.monotonic()
        if not force and now - self._last < self.min_interval_s:
            return False  # cadence bound, not a drop
        spec = faults.fire("ops.push")
        if spec is not None:
            if spec["kind"] == "drop_frame":
                self.dropped += 1  # counted, never silent
                return False
            if spec["kind"] == "delay":
                faults.sleep_ms(spec)
        row = {
            "tier": self.tier, "t": time.time(), "trace": self.trace_id,
            "cadence_s": self.min_interval_s,
            "gauges": gauges or {}, "hops": hops or {},
        }
        if body is not None:
            row["body"] = body
        try:
            self._sock.send(
                json.dumps(row, default=float).encode(),
                flags=self._zmq.NOBLOCK,
            )
        except (self._zmq.ZMQError, TypeError, ValueError):
            self.dropped += 1  # full HWM / closed ctx / unserializable row
            return False
        self._last = now
        self.pushes += 1
        return True

    def close(self) -> None:
        try:
            self._sock.close(0)
        except Exception:  # noqa: BLE001 — ctx may already be terminated
            pass


class FlightRecorder:
    """Bounded ring of snapshots + fault/recovery events with cooldown-
    limited dumps (a chaos storm must not turn the recorder into an IO
    fault of its own: at most one dump per trigger per
    ``min_dump_interval_s``; the dump directory for a trigger is
    overwritten by a later incident — the last incident wins, the ring
    inside it covers the minutes before)."""

    def __init__(self, folder: str | None, ring: int = 64,
                 min_dump_interval_s: float = 5.0, on_event=None):
        self.folder = folder
        self._snaps: deque = deque(maxlen=max(1, int(ring)))
        self._events: deque = deque(maxlen=max(4, int(ring) * 4))
        self._min_dump_interval_s = float(min_dump_interval_s)
        self._last_dump: dict[str, float] = {}
        self._on_event = on_event
        self.dumps = 0
        # callable returning the tracer's last-K exemplar span trees
        # (list of {exemplar, spans}); dumped as exemplars.jsonl so a
        # post-mortem sees WHAT the system was doing per-request, not
        # just aggregate gauges. None == tracing absent, nothing written
        self.exemplar_source = None

    def record_snapshot(self, snap: dict) -> None:
        self._snaps.append(snap)

    def record_event(self, kind: str, ev: dict) -> None:
        row = dict(ev)
        # a fault spec's own "kind" (kill/delay/...) must not clobber
        # the recorder's event kind — it rides as the detail field
        if "kind" in row:
            row["detail"] = row.pop("kind")
        self._events.append({"kind": kind, "t": time.time(), **row})

    def dump(self, trigger: str) -> str | None:
        """Write the rings to ``telemetry/flightrec/<trigger>/`` and
        return the directory (None when throttled/disabled/unwritable)."""
        if self.folder is None:
            return None
        now = time.monotonic()
        last = self._last_dump.get(trigger)
        if last is not None and now - last < self._min_dump_interval_s:
            return None
        self._last_dump[trigger] = now
        out = os.path.join(self.folder, TELEMETRY_DIR, FLIGHTREC_DIR, trigger)
        try:
            os.makedirs(out, exist_ok=True)
            with open(os.path.join(out, "snapshots.jsonl"), "w") as f:
                for snap in self._snaps:
                    f.write(json.dumps(snap, default=float) + "\n")
            with open(os.path.join(out, "events.jsonl"), "w") as f:
                for ev in self._events:
                    f.write(json.dumps(ev, default=float) + "\n")
            exemplars = []
            if self.exemplar_source is not None:
                try:
                    exemplars = list(self.exemplar_source() or ())
                except Exception:  # noqa: BLE001 — tracer must not kill a dump
                    exemplars = []
            if exemplars:
                with open(os.path.join(out, "exemplars.jsonl"), "w") as f:
                    for ex in exemplars:
                        f.write(json.dumps(ex, default=float) + "\n")
            meta = {
                "trigger": trigger, "t": time.time(),
                "snapshots": len(self._snaps), "events": len(self._events),
                "exemplars": len(exemplars),
            }
            with open(os.path.join(out, "meta.json"), "w") as f:
                json.dump(meta, f, indent=2)
        except OSError:
            return None  # telemetry must never kill training
        self.dumps += 1
        if self._on_event is not None:
            self._on_event(
                "ops_flightrec", trigger=trigger, dir=out,
                snapshots=len(self._snaps), events=len(self._events),
            )
        return out


class OpsAggregator:
    """The run-scoped collector: PULL socket on a dedicated receiver
    thread (latest row per tier), snapshot merge + SLO + flight recorder
    on the learner thread at the metrics cadence."""

    def __init__(self, folder: str | None, trace_id: str | None = None,
                 cfg=None, slo_cfg=None, on_event=None):
        cfg = cfg or {}
        get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: d
        self.enabled = bool(get("enabled", True))
        self.folder = folder
        self.trace_id = trace_id
        self._on_event = on_event
        self._lock = threading.Lock()
        self._tiers: dict[str, dict] = {}  # tier -> {row, t_recv}
        self._stop = threading.Event()
        self._thread = None
        self.address = None
        self.bad_frames = 0
        self.snapshots = 0
        self._seq = 0
        self._write_ok = folder is not None
        self.slo = SLOTracker(slo_cfg, on_event=on_event)
        self.flightrec = FlightRecorder(
            folder,
            ring=int(get("ring", 64)),
            min_dump_interval_s=float(get("min_dump_interval_s", 5.0)),
            on_event=on_event,
        )
        if self.enabled:
            # fixed address allocated up front (utils/net.py discipline)
            # so process tiers can inherit it through spawn kwargs before
            # the receiver thread has bound
            self.address = alloc_address()
            self._thread = threading.Thread(
                target=self._recv_loop, name="ops-aggregator", daemon=True
            )
            self._thread.start()

    # -- receive (dedicated thread, owns the PULL socket) --------------------
    def _recv_loop(self) -> None:
        import zmq

        sock = zmq.Context.instance().socket(zmq.PULL)
        sock.setsockopt(zmq.LINGER, 0)
        sock.setsockopt(zmq.RCVHWM, 64)
        try:
            sock.bind(self.address)
            poller = zmq.Poller()
            poller.register(sock, zmq.POLLIN)
            while not self._stop.is_set():
                try:
                    if not dict(poller.poll(100)):
                        continue
                    raw = sock.recv(zmq.NOBLOCK)
                except zmq.ZMQError:
                    if self._stop.is_set():
                        break
                    continue
                try:
                    row = json.loads(raw.decode(errors="replace"))
                    tier = row["tier"]
                    if not isinstance(tier, str):
                        raise TypeError("tier must be a string")
                except (ValueError, KeyError, TypeError):
                    with self._lock:
                        self.bad_frames += 1  # counted, never silent
                    continue
                with self._lock:
                    self._tiers[tier] = {
                        "row": row, "t_recv": time.monotonic()
                    }
        finally:
            sock.close(0)

    # -- local rows (learner-thread tiers skip the wire) ---------------------
    def push_local(self, tier: str, gauges: dict | None = None,
                   hops: dict | None = None, body: dict | None = None,
                   cadence_s: float | None = None) -> None:
        """Store a row for a tier that lives on the learner thread (the
        learner loop itself, the merged fleet/experience/fanout views) —
        same schema as the wire, no socket round-trip."""
        row = {
            "tier": tier, "t": time.time(), "trace": self.trace_id,
            "cadence_s": float(cadence_s or DEFAULT_CADENCE_S),
            "gauges": gauges or {}, "hops": hops or {},
        }
        if body is not None:
            row["body"] = body
        with self._lock:
            self._tiers[tier] = {"row": row, "t_recv": time.monotonic()}

    # -- incidents -----------------------------------------------------------
    def record_fault(self, ev: dict) -> None:
        self.flightrec.record_event("fault", dict(ev))

    def record_recovery(self, ev: dict) -> None:
        self.flightrec.record_event("recovery", dict(ev))

    def dump(self, trigger: str) -> str | None:
        return self.flightrec.dump(trigger)

    # -- snapshot (learner thread, metrics cadence) --------------------------
    def _derived(self, tiers: dict) -> dict:
        """Cross-tier derived measurements. Staleness prefers the
        learner's exact per-update lineage reduction (``lineage/
        staleness_p99`` — measured over the versions that actually
        entered the gradient) and only falls back to the PR-13
        approximation (newest published version minus the oldest version
        any fleet replica still serves) when lineage is disabled or the
        learner has not reported yet. ``staleness_source`` records which
        path fed the SLO evaluation."""
        learner = tiers.get("learner", {}).get("row", {})
        exact = (learner.get("gauges") or {}).get("lineage/staleness_p99")
        if exact is not None:
            return {
                "staleness_updates": max(0, int(exact)),
                "staleness_source": "lineage",
            }
        fanout = tiers.get("param_fanout", {}).get("row", {})
        published = (fanout.get("gauges") or {}).get("version")
        if published is None:
            return {}
        held = []
        fleet = tiers.get("fleet", {}).get("row", {}).get("body") or {}
        for rep in (fleet.get("replicas") or {}).values():
            v = rep.get("param_version")
            if v is not None:
                held.append(int(v))
        if not held:
            return {}
        return {
            "staleness_updates": max(0, int(published) - min(held)),
            "staleness_source": "derived",
        }

    def snapshot(self, iteration: int | None = None,
                 env_steps: int | None = None) -> dict:
        """Merge the latest per-tier rows into one run snapshot, evaluate
        SLOs, feed the flight recorder, atomically replace the snapshot
        file, and return the snapshot dict."""
        now_mono = time.monotonic()
        with self._lock:
            tiers = {k: dict(v) for k, v in self._tiers.items()}
            bad = self.bad_frames
        rows: dict[str, dict] = {}
        merged_hops: dict[str, dict] = {}
        for tier, rec in tiers.items():
            row = rec["row"]
            cadence = float(row.get("cadence_s") or DEFAULT_CADENCE_S)
            age = now_mono - rec["t_recv"]
            out = dict(row)
            out["age_s"] = round(age, 3)
            # the heartbeat rule: silent for 3x your own cadence == DEAD
            out["dead"] = age > 3.0 * cadence
            rows[tier] = out
            for hop, st in (row.get("hops") or {}).items():
                if isinstance(st, dict):
                    merged_hops[hop] = st
        gw = rows.get("gateway", {}).get("body") or {}
        derived = self._derived(tiers)
        slo_table, newly_exhausted = self.slo.evaluate(
            gw.get("tenants") or {}, merged_hops, derived
        )
        self._seq += 1
        snap = {
            "type": "ops_snapshot", "t": time.time(),
            "trace": self.trace_id, "seq": self._seq,
            "iteration": iteration, "env_steps": env_steps,
            "tiers": rows, "hops": merged_hops, "slo": slo_table,
            "slo_counters": self.slo.gauges(), "bad_frames": bad,
            "derived": derived,
        }
        self.flightrec.record_snapshot(snap)
        self._write(snap)
        self.snapshots += 1
        if self._on_event is not None:
            # bounded by the metrics cadence, like ``phases`` events
            self._on_event(
                "ops_snapshot", seq=self._seq, tiers=len(rows),
                dead=sum(1 for r in rows.values() if r["dead"]),
                breaches=self.slo.breaches, bad_frames=bad,
            )
        for tenant, objective in newly_exhausted:
            self.dump("slo")
            break  # one incident dump covers every pair this window
        return snap

    def _write(self, snap: dict) -> None:
        if not self._write_ok:
            return
        path = snapshot_path(self.folder)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f, default=float)
            os.replace(tmp, path)  # readers never see a torn file
        except OSError:
            self._write_ok = False  # telemetry must never kill training

    def gauges(self) -> dict[str, float]:
        with self._lock:
            bad = float(self.bad_frames)
            tiers = float(len(self._tiers))
        return {
            "ops/tiers": tiers,
            "ops/bad_frames": bad,
            "ops/snapshots": float(self.snapshots),
            "ops/flightrec_dumps": float(self.flightrec.dumps),
            **self.slo.gauges(),
        }

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.enabled = False


# -- top ----------------------------------------------------------------------


def top_report(snap: dict | None, folder: str | None = None) -> str:
    """Render one merged snapshot as the ``surreal_tpu top`` view:
    per-tier health, per-tenant SLO/budget table, hop latencies, MFU —
    reusing diag's section renderers over the snapshot's tier bodies
    instead of a full event-log replay."""
    from surreal_tpu.session.telemetry import (
        _engine_lines,
        _experience_plane_lines,
        _gateway_lines,
        _performance_lines,
        _serving_tier_lines,
    )

    if snap is None:
        return (
            f"surreal_tpu top — no ops snapshot"
            + (f" under {folder}" if folder else "")
            + "\n(the run has not written telemetry/ops_snapshot.json yet,"
            " or the file is torn — retrying helps for a live run)"
        )
    age = time.time() - float(snap.get("t", 0.0))
    lines = [
        "surreal_tpu top — run snapshot"
        + (f" #{snap.get('seq')}" if snap.get("seq") is not None else "")
        + (f", trace {snap['trace']}" if snap.get("trace") else ""),
        f"  written {age:.1f} s ago"
        + (
            f", iteration {snap['iteration']}"
            if snap.get("iteration") is not None else ""
        )
        + (
            f", env_steps {snap['env_steps']}"
            if snap.get("env_steps") is not None else ""
        )
        + (
            f", {snap['bad_frames']} bad frame(s) dropped"
            if snap.get("bad_frames") else ""
        ),
        "",
        "Tiers",
    ]
    tiers = snap.get("tiers") or {}
    if tiers:
        lines.append(f"  {'tier':<24} {'age s':>8} {'cadence':>8}  status")
        for name in sorted(tiers):
            row = tiers[name]
            dead = bool(row.get("dead"))
            lines.append(
                f"  {name:<24} {float(row.get('age_s', 0.0)):>8.1f} "
                f"{float(row.get('cadence_s', 0.0)):>8.1f}  "
                + ("DEAD (> 3x cadence)" if dead else "alive")
            )
        dead_tiers = [n for n, r in sorted(tiers.items()) if r.get("dead")]
        if dead_tiers:
            lines.append(
                f"  !! tier(s) {', '.join(dead_tiers)} stopped pushing — "
                "wedged, killed, or respawning"
            )
    else:
        lines.append("  (no tier has pushed a row yet)")
    lines += _slo_lines(snap)
    # diag's renderers, fed from the snapshot's tier bodies
    eng_body = (tiers.get("engine") or {}).get("body")
    eng_lines = _engine_lines({"engine": eng_body}) if eng_body else []
    if eng_lines:
        lines += ["", "Loop engine"] + eng_lines
    gw_body = (tiers.get("gateway") or {}).get("body")
    gw_lines = _gateway_lines({"gateway": gw_body}) if gw_body else []
    if gw_lines:
        lines += ["", "Gateway"] + gw_lines
    fleet_body = (tiers.get("fleet") or {}).get("body")
    tier_lines = _serving_tier_lines({"serving": fleet_body}) if fleet_body else []
    if tier_lines:
        lines += ["", "Serving tier"] + tier_lines
    xp_body = (tiers.get("experience") or {}).get("body")
    xp_lines = _experience_plane_lines({"experience": xp_body}) if xp_body else []
    if xp_lines:
        lines += ["", "Experience plane"] + xp_lines
    learner = tiers.get("learner") or {}
    perf_lines = _performance_lines({
        "perf": {
            k: v for k, v in (learner.get("gauges") or {}).items()
            if k.startswith("perf/")
        },
        "hops": snap.get("hops") or {},
    })
    if perf_lines:
        lines += ["", "Performance"] + perf_lines
    # watchdog incidents (ISSUE 15): same brief as diag's Incidents
    # section — pure file reading under <folder>/telemetry/incidents/,
    # so a live `top` shows an opened incident within one refresh
    if folder:
        try:
            from surreal_tpu.session.incidents import incidents_brief

            inc_lines = incidents_brief(folder)
        except Exception:
            inc_lines = []
        if inc_lines:
            lines += [
                "", "Incidents (surreal_tpu why for the full report)",
            ] + inc_lines
        # live remediation state (ISSUE 16): the newest journaled actions
        # under <folder>/telemetry/actions/ — an executing/verifying
        # action shows up within one refresh, same pure-file-read rule
        try:
            from surreal_tpu.session.remediate import actions_brief

            act_lines = actions_brief(folder)
        except Exception:
            act_lines = []
        if act_lines:
            lines += ["", "Remediation"] + act_lines
    return "\n".join(lines)


def _slo_lines(snap: dict) -> list[str]:
    table = snap.get("slo") or {}
    counters = snap.get("slo_counters") or {}
    if not table and not counters.get("slo/objectives"):
        return []
    lines = [
        "",
        "SLOs — {b:g} breach(es), {e:g} budget exhaustion(s)".format(
            b=float(counters.get("slo/breaches", 0)),
            e=float(counters.get("slo/exhaustions", 0)),
        ),
    ]
    if table:
        lines.append(
            f"  {'tenant':<12} {'objective':<20} {'measured':>10} "
            f"{'target':>10} {'budget':>8}  status"
        )
        for tenant in sorted(table):
            for name in sorted(table[tenant]):
                o = table[tenant][name]
                status = (
                    "EXHAUSTED" if o.get("exhausted")
                    else "BREACH" if o.get("breached") else "ok"
                )
                lines.append(
                    f"  {tenant:<12} {name:<20} "
                    f"{float(o.get('measured', 0)):>10.3f} "
                    f"{float(o.get('target', 0)):>10.3f} "
                    f"{float(o.get('budget_used', 0)):>7.0%}  {status}"
                )
    else:
        lines.append("  (objectives declared; no tenant data this window)")
    return lines
