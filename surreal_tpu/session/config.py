"""Layered experiment configuration.

Capability parity with the reference's ``surreal/session/config.py`` +
``default_configs.py`` (SURVEY.md §5.6): attribute-access nested dicts, an
``extend()`` that recursively merges user overrides onto a base tree while
enforcing required keys, and the three-tree split the whole framework is
organised around:

- ``learner_config`` — algorithm + model hyperparameters
- ``env_config``     — environment name, obs pipeline, action repeat …
- ``session_config`` — folders, schedules, and (new here) the ``topology``
  block that selects the TPU mesh instead of the reference's process-group
  port wiring.

Unlike the reference there is no port/host section: components that used to
be separate processes are modules inside one SPMD program.
"""

from __future__ import annotations

import copy
import json
from typing import Any, Iterator, Mapping

# Sentinel for keys the user MUST supply (the reference used the string
# '_req_' inside its default config trees for the same purpose).
REQUIRED = "_req_"
# Sentinel for keys that are optional-with-no-default.
OPTIONAL = "_opt_"


class ConfigError(Exception):
    pass


class Config(dict):
    """Nested dict with attribute access and base-extend semantics."""

    def __init__(self, data: Mapping | None = None, **kwargs: Any):
        super().__init__()
        merged = dict(data or {})
        merged.update(kwargs)
        for key, value in merged.items():
            self[key] = value

    # -- dict behaviour -----------------------------------------------------
    def __setitem__(self, key: str, value: Any) -> None:
        if isinstance(value, Mapping) and not isinstance(value, Config):
            value = Config(value)
        super().__setitem__(key, value)

    def __getattr__(self, key: str) -> Any:
        try:
            return self[key]
        except KeyError:
            raise AttributeError(
                f"Config has no key {key!r}; available: {sorted(self.keys())}"
            ) from None

    def __setattr__(self, key: str, value: Any) -> None:
        self[key] = value

    def __delattr__(self, key: str) -> None:
        try:
            del self[key]
        except KeyError:
            raise AttributeError(key) from None

    def __deepcopy__(self, memo: dict) -> "Config":
        return Config({k: copy.deepcopy(v, memo) for k, v in self.items()})

    # -- extend / validate --------------------------------------------------
    def extend(self, base: Mapping) -> "Config":
        """Merge ``self`` (overrides) onto ``base`` (defaults); validate.

        Returns a new Config. Keys present only in ``base`` keep their
        defaults; keys present in both are overridden by ``self``; nested
        dicts merge recursively; REQUIRED placeholders left unfilled raise.
        Unknown override keys are allowed (the reference permitted ad-hoc
        additions) but nested dict/scalar mismatches raise.
        """
        out = _merge(Config(base), self, path="")
        _check_required(out, path="")
        return out

    def flatten(self, sep: str = ".") -> dict[str, Any]:
        flat: dict[str, Any] = {}

        def rec(node: "Config", prefix: str) -> None:
            for k, v in node.items():
                full = f"{prefix}{sep}{k}" if prefix else str(k)
                if isinstance(v, Config):
                    rec(v, full)
                else:
                    flat[full] = v

        rec(self, "")
        return flat

    def override_from_dotlist(self, items: Iterator[str]) -> "Config":
        """Apply ``a.b.c=value`` CLI-style overrides in place (values parsed
        as JSON when possible, else kept as strings)."""
        for item in items:
            if "=" not in item:
                raise ConfigError(f"override {item!r} is not of form key=value")
            dotted, raw = item.split("=", 1)
            try:
                value = json.loads(raw)
            except json.JSONDecodeError:
                value = raw
            node = self
            *parents, leaf = dotted.split(".")
            for p in parents:
                if p not in node or not isinstance(node[p], Config):
                    node[p] = Config()
                node = node[p]
            node[leaf] = value
        return self

    def to_dict(self) -> dict:
        return {
            k: (v.to_dict() if isinstance(v, Config) else v) for k, v in self.items()
        }

    def dumps(self) -> str:
        return json.dumps(self.to_dict(), indent=2, default=str)


def _merge(base: Config, override: Mapping, path: str) -> Config:
    out = Config(copy.deepcopy(base))
    for key, value in override.items():
        full = f"{path}.{key}" if path else str(key)
        if (
            isinstance(value, str)
            and value in (REQUIRED, OPTIONAL)
            and key in out
            and not (isinstance(out[key], str) and out[key] in (REQUIRED, OPTIONAL))
        ):
            # an unfilled placeholder carried in an override tree never
            # stomps a real base value (comes up when a partially-filled
            # bundle is re-extended onto per-algorithm defaults)
            continue
        if key in out and isinstance(out[key], Config):
            if isinstance(value, Mapping):
                out[key] = _merge(out[key], value, full)
            elif value is None:
                out[key] = None  # explicit disable of a subtree
            else:
                raise ConfigError(f"{full}: cannot override dict with {type(value).__name__}")
        else:
            out[key] = copy.deepcopy(value)
    return out


def _check_required(node: Config, path: str) -> None:
    for key, value in node.items():
        full = f"{path}.{key}" if path else str(key)
        if isinstance(value, Config):
            _check_required(value, full)
        elif isinstance(value, str) and value == REQUIRED:
            raise ConfigError(f"required config key {full} was not provided")
        elif isinstance(value, str) and value == OPTIONAL:
            node[key] = None
