"""Session layer (parity: reference ``surreal/session/`` + observability
deps, SURVEY.md §2.1 / §5.4-5.6): config trees, trackers, checkpointing,
metrics/logging."""

from surreal_tpu.session.config import REQUIRED, Config
from surreal_tpu.session.checkpoint import CheckpointManager, make_checkpoint_manager
from surreal_tpu.session.metrics import MetricsWriter, get_logger, make_metrics_writer
from surreal_tpu.session.tracker import (
    MetricAggregator,
    PeriodicTimeTracker,
    PeriodicTracker,
)

__all__ = [
    "REQUIRED",
    "Config",
    "CheckpointManager",
    "make_checkpoint_manager",
    "MetricsWriter",
    "get_logger",
    "make_metrics_writer",
    "MetricAggregator",
    "PeriodicTimeTracker",
    "PeriodicTracker",
]
