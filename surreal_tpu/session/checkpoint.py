"""Checkpoint/resume (parity: reference ``surreal/utils/checkpoint.py`` —
``PeriodicCheckpoint`` with keep-last-N / keep-best retention and a
``restore_folder`` path through learner setup; SURVEY.md §2.1 Checkpoint
row and §5.4), built on orbax.

What is checkpointed: the **learner state pytree** (params, optimizer
state, obs-normalizer stats, adaptive scalars) plus run metadata
(iteration, env_steps). Environment/rollout carries are NOT checkpointed —
on resume, envs reset and refill, exactly as the reference's actors
restarted stateless and re-fetched parameters (SURVEY.md §5.3/§5.4
"agents don't checkpoint"). That makes resume trivially correct for both
the on-policy fused path and the replay path (the replay warms back up
past ``start_sample_size`` before learning resumes).

Layout under ``<session folder>/checkpoints/``:
    <step>/            orbax step dirs, pruned to ``keep_last``
    best/              overwritten copy of the best-metric state (keep_best)
    best_metric.json   the best metric value + the step it came from
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import orbax.checkpoint as ocp


class PrecisionMismatchError(ValueError):
    """A checkpoint was saved under a different precision policy than the
    one trying to restore it.

    The policy decides whether a ``LossScaleState`` leaf lives in the
    optimizer pytree (ops/precision.py) and which dtypes the trained
    numerics used — restoring across a mismatch either fails as a cryptic
    orbax structure error or, worse, silently resumes f32-trained
    numerics under a different policy. This error names both policies and
    the fix instead (the PR-5 ``recovery_scale`` pytree-break lesson,
    made a first-class check)."""


def check_precision_metadata(recorded: dict | None, active: dict | None) -> None:
    """Raise :class:`PrecisionMismatchError` when a checkpoint's recorded
    precision metadata disagrees with the active policy. Missing metadata
    (pre-ISSUE-7 sessions) or an unknown active policy passes — the guard
    never blocks legacy restores, it explains the breaks that WOULD
    happen."""
    if not recorded or not active:
        return
    mismatched = {
        k: (recorded.get(k), active.get(k))
        for k in (
            "policy", "param_dtype", "loss_scaling", "compute_dtype",
            "data_dtype", "fp8",
        )
        if k in recorded and recorded.get(k) != active.get(k)
    }
    if mismatched:
        detail = ", ".join(
            f"{k}: checkpoint={a!r} vs active={b!r}"
            for k, (a, b) in sorted(mismatched.items())
        )
        raise PrecisionMismatchError(
            "checkpoint was saved under a different precision policy "
            f"({detail}). Set algo.precision (and optimizer.loss_scaling) "
            "to match the checkpoint to resume it, or point "
            "session.folder at a fresh directory to train under the new "
            "policy from scratch."
        )


class CheckpointManager:
    """Save/restore learner state with keep-last-N + keep-best retention."""

    def __init__(
        self,
        folder: str,
        keep_last: int = 3,
        keep_best: bool = True,
        best_key: str = "episode/return",
        on_event=None,
    ):
        # on_event(type_str, **fields): optional telemetry sink (the
        # session tracer's .event) — restore-fallback decisions must be
        # visible in `surreal_tpu diag`, not only in a log file
        self._on_event = on_event
        self.directory = os.path.join(os.path.abspath(folder), "checkpoints")
        os.makedirs(self.directory, exist_ok=True)
        self.keep_best = keep_best
        self.best_key = best_key
        # Checkpointing is single-controller BY DESIGN, even under
        # jax.distributed: the multi-host driver passes host-local numpy
        # state and only rank 0 ever constructs a manager
        # (launch/multihost_trainer.py). Orbax would otherwise detect
        # process_count > 1 and block every save on a cross-process barrier
        # that the other ranks never join. active_processes pins all
        # coordination to the constructing process.
        mp_options = ocp.options.MultiprocessingOptions(
            primary_host=jax.process_index(),
            active_processes={jax.process_index()},
            barrier_sync_key_prefix=f"surreal_tpu_{jax.process_index()}",
        )
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep_last,
                # create=False: orbax refuses create+active_processes; the
                # makedirs above already guarantees the root exists
                create=False,
                # best/ is handled by hand below so keep-last and keep-best
                # retention compose instead of competing in one policy
                multiprocessing_options=mp_options,
            ),
        )
        self._best_dir = os.path.join(self.directory, "best")
        self._best_meta_path = os.path.join(self.directory, "best_metric.json")
        # run-scoped metadata sidecar (precision policy etc.): one file
        # per checkpoint root, not per step — the policy is a build-time
        # constant of the session writing here
        self._run_meta_path = os.path.join(self.directory, "run_meta.json")
        self._best_ckptr = ocp.StandardCheckpointer(
            multiprocessing_options=mp_options
        )
        self._mp_options = mp_options
        self._keep_last = keep_last
        self._extra_mgr: ocp.CheckpointManager | None = None

    def _extra(self) -> ocp.CheckpointManager:
        """Lazy manager for auxiliary step-aligned state (the replay
        buffer) — a SEPARATE tree under ``extra/`` so the main payload's
        shape stays stable across configs and old sessions restore fine."""
        if self._extra_mgr is None:
            root = os.path.join(self.directory, "extra")
            os.makedirs(root, exist_ok=True)
            self._extra_mgr = ocp.CheckpointManager(
                root,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self._keep_last,
                    create=False,
                    multiprocessing_options=self._mp_options,
                ),
            )
        return self._extra_mgr

    # -- save ----------------------------------------------------------------
    def save(
        self,
        step: int,
        state: Any,
        *,
        env_steps: int = 0,
        metrics: dict[str, float] | None = None,
    ) -> None:
        """Persist ``state`` at ``step``; update best/ when the tracked
        metric improves."""
        payload = {
            "state": state,
            "meta": {"iteration": step, "env_steps": env_steps},
        }
        self._mgr.save(step, args=ocp.args.StandardSave(payload))
        self._mgr.wait_until_finished()

        if not (self.keep_best and metrics):
            return
        value = metrics.get(self.best_key)
        if value is None or value != value:  # absent or NaN
            return
        best = self.best_metric()
        if best is not None and value <= best["value"]:
            return
        # orbax's own tmp-dir + rename makes the overwrite atomic
        self._best_ckptr.save(self._best_dir, payload, force=True)
        self._best_ckptr.wait_until_finished()
        # tmp + rename: a SIGKILL mid-write (kill-and-resume is a supported
        # flow) must never leave a truncated meta that crashes the relaunch
        tmp = self._best_meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"value": float(value), "step": int(step)}, f)
        os.replace(tmp, self._best_meta_path)

    def save_extra(self, step: int, tree: Any) -> None:
        """Persist auxiliary state aligned to ``step`` (see ``_extra``)."""
        mgr = self._extra()
        mgr.save(step, args=ocp.args.StandardSave(tree))
        mgr.wait_until_finished()

    def restore_extra(self, template: Any, step: int):
        """Restore the auxiliary tree saved at EXACTLY ``step`` (the step
        the main state restored from); None when absent — callers fall
        back to a fresh buffer, same as resuming an old session."""
        if step not in self._extra().all_steps():
            return None
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        return self._extra().restore(step, args=ocp.args.StandardRestore(abstract))

    # -- run metadata (precision policy sidecar) -----------------------------
    def save_run_metadata(self, meta: dict) -> None:
        """Persist run-scoped metadata (the active precision policy —
        ops/precision.py ``PrecisionPolicy.meta()``) beside the step dirs.
        Atomic (tmp + rename): relaunch pollers race this write."""
        tmp = self._run_meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, self._run_meta_path)

    def run_metadata(self) -> dict | None:
        """The recorded run metadata, or None (pre-ISSUE-7 sessions /
        torn writes read as absent — the guard must never turn a legacy
        resume into a crash about metadata bookkeeping)."""
        if not os.path.exists(self._run_meta_path):
            return None
        try:
            with open(self._run_meta_path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def check_precision(self, active_meta: dict | None) -> None:
        """Fail restore LOUDLY on a precision-policy mismatch (see
        :class:`PrecisionMismatchError`); callers run this BEFORE orbax
        touches the step dirs so the user sees the policy diff, not a
        structure traceback."""
        check_precision_metadata(self.run_metadata(), active_meta)

    # -- restore -------------------------------------------------------------
    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def steps(self) -> list[int]:
        """All retained step numbers, ascending (includes steps whose dirs
        may be damaged — restore() is where damage is discovered)."""
        return sorted(int(s) for s in self._mgr.all_steps())

    def best_metric(self) -> dict | None:
        if not os.path.exists(self._best_meta_path):
            return None
        with open(self._best_meta_path) as f:
            try:
                return json.load(f)
            except json.JSONDecodeError:
                # legacy non-atomic write interrupted by a kill: treat as
                # "no best yet" rather than poisoning every future save
                return None

    def restore(self, template_state: Any, step: int | None = None,
                validate=None):
        """Restore (state, meta) at ``step`` (default latest).

        ``template_state`` supplies the pytree structure/shardings to
        restore into — call sites pass a freshly ``init()``-ed state.
        Returns None when no checkpoint exists.

        Damage fallback: without an explicit ``step``, a latest step dir
        that fails to restore (truncated/corrupt — a SIGKILL mid-save is
        a supported failure, and relaunch-after-kill is exactly when this
        path runs) falls back to the next-older retained step instead of
        crashing the relaunch, emitting a ``recovery`` telemetry event
        (kind ``checkpoint_fallback``). ``validate(state) -> bool`` lets
        callers reject restorable-but-unusable steps (the divergence
        layer passes a finiteness check so a save that raced the NaN
        detection window never becomes the resume point); rejected steps
        emit kind ``skipped_nonfinite_checkpoint`` and the walk continues.
        If steps exist but NONE restores (every dir raised), the walk
        raises the NEWEST step's error — an every-step failure is
        systemic (e.g. the template's optimizer layout changed) and a
        silent fresh start would overwrite the very progress the caller
        asked to resume. All-rejected-by-validate returns None (poison
        everywhere is genuinely unresumable; callers fall back to fresh
        init). An explicit ``step`` is a caller decision and propagates
        its error directly.
        """
        template = {
            "state": template_state,
            "meta": {"iteration": 0, "env_steps": 0},
        }
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        if step is not None:
            payload = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
            return payload["state"], payload["meta"]
        candidates = sorted(self.steps(), reverse=True)
        first_exc: Exception | None = None
        for i, s in enumerate(candidates):
            try:
                payload = self._mgr.restore(
                    s, args=ocp.args.StandardRestore(abstract)
                )
            except Exception as e:  # orbax raises a zoo of types per damage mode
                if first_exc is None:
                    first_exc = e
                if self._on_event is not None and i < len(candidates) - 1:
                    self._on_event(
                        "recovery", kind="checkpoint_fallback",
                        bad_step=int(s), next_step=int(candidates[i + 1]),
                        error=f"{type(e).__name__}: {e}"[:200],
                    )
                continue
            if validate is not None and not validate(payload["state"]):
                if self._on_event is not None:
                    self._on_event(
                        "recovery", kind="skipped_nonfinite_checkpoint",
                        step=int(s),
                    )
                continue
            return payload["state"], payload["meta"]
        if first_exc is not None:
            raise first_exc  # nothing restored at all: systemic, be loud
        return None

    def restore_best(self, template_state: Any):
        """Restore the keep-best snapshot; None when absent."""
        if self.best_metric() is None:
            return None
        template = {
            "state": template_state,
            "meta": {"iteration": 0, "env_steps": 0},
        }
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        payload = self._best_ckptr.restore(self._best_dir, abstract)
        return payload["state"], payload["meta"]

    def close(self) -> None:
        self._mgr.close()
        self._best_ckptr.close()
        if self._extra_mgr is not None:
            self._extra_mgr.close()


def make_checkpoint_manager(session_config, on_event=None) -> CheckpointManager | None:
    """Build from ``session_config.checkpoint``; None when disabled
    (``every_n_iters`` <= 0)."""
    ck = session_config.checkpoint
    if not ck.every_n_iters or ck.every_n_iters <= 0:
        return None
    return CheckpointManager(
        session_config.folder,
        keep_last=ck.keep_last,
        keep_best=ck.keep_best,
        on_event=on_event,
    )
