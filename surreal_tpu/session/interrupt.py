"""Preemption-safe shutdown sentinel (the robustness layer's first leg:
a TPU preemption delivers SIGTERM with a short grace window, and the
reference fleet treated actor/learner death as routine — SURVEY.md §5.3;
RollArt-class systems checkpoint on the preemption signal rather than
losing everything since the last periodic save).

Design constraints, in order:

- **No handler races with orbax async saves.** The signal handler does ONE
  thing: latch a flag. All real work (the emergency checkpoint, session
  close) happens at the next ITERATION BOUNDARY on the thread that owns
  the checkpoint manager — a handler that called ``ckpt.save`` could fire
  mid-``wait_until_finished`` and corrupt the very checkpoint a relaunch
  needs.
- **Second signal escalates.** A wedged run (e.g. a collective that will
  never complete) must still be killable: the second SIGTERM/SIGINT raises
  ``KeyboardInterrupt`` from the handler, unwinding through the drivers'
  ``finally`` blocks (hooks/plane close) instead of waiting for a boundary
  that may never come.
- **Main-thread only, restore on close.** ``signal.signal`` is illegal off
  the main thread; constructed there, the sentinel stays disabled (tests
  that run drivers on worker threads keep working). ``close()`` restores
  the previous handlers so nested/sequential sessions in one process
  (tests, notebooks) do not leak handler state.

Wiring: ``SessionHooks`` owns one sentinel per run and ORs ``fired`` into
``end_iteration``'s stop flag, so every single-host driver exits its loop
at the next boundary and writes its normal final checkpoint — which IS the
emergency checkpoint, at most one iteration behind the preemption. The
multi-host drivers ride the same path on rank 0; the stop is broadcast by
the existing metrics-cadence agreement (``_maybe_agree_stop``), so the
whole group leaves the collective schedule together — interrupt latency
there is bounded by ``metrics.every_n_iters`` iterations. Ranks > 0
install their own latch-only sentinel so a fleet-wide SIGTERM cannot kill
them mid-collective while rank 0 still needs their participation.
"""

from __future__ import annotations

import signal
import threading


class InterruptSentinel:
    """Latch SIGTERM/SIGINT into a flag polled at iteration boundaries."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, enabled: bool = True):
        self._fired = threading.Event()
        self.signum: int | None = None
        self._count = 0
        self._prev: dict[int, object] = {}
        self.installed = False
        if not enabled:
            return
        if threading.current_thread() is not threading.main_thread():
            return  # signal.signal would raise; stay a disabled no-op
        try:
            for s in self.SIGNALS:
                self._prev[s] = signal.signal(s, self._handle)
            self.installed = True
        except (ValueError, OSError):  # exotic embedding; stay disabled
            self._prev.clear()

    def _handle(self, signum, frame):
        # async-signal context: latch and return — never touch locks,
        # logging, or the checkpoint manager from here (module docstring)
        self._count += 1
        self.signum = signum
        self._fired.set()
        if self._count >= 2:
            raise KeyboardInterrupt(
                f"second interrupt (signal {signum}): forcing teardown"
            )

    @property
    def fired(self) -> bool:
        return self._fired.is_set()

    def trigger(self, signum: int = signal.SIGTERM) -> None:
        """In-process latch (tests / the chaos harness's non-signal path)."""
        self.signum = signum
        self._fired.set()

    def close(self) -> None:
        """Restore the previous handlers (idempotent)."""
        if not self.installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):  # off-main-thread close; leave as-is
                pass
        self._prev.clear()
        self.installed = False
