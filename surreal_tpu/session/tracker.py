"""Periodic gating + metric aggregation (parity: ``surreal/session/tracker.py``
and tensorplex's averaging groups, SURVEY.md §5.5).

The reference shipped scalars from many processes to a tensorplex service
that averaged per group. Here there is one program, so aggregation is a
local ``MetricAggregator``; the writer side lives in
``surreal_tpu.session.metrics``.
"""

from __future__ import annotations

import time
from collections import defaultdict


class PeriodicTracker:
    """True every N increments (reference: PeriodicTracker)."""

    def __init__(self, period: int, init_count: int = 0):
        if period < 1:
            raise ValueError("period must be >= 1")
        self.period = period
        self._count = init_count

    def track_increment(self, n: int = 1) -> bool:
        prev = self._count // self.period
        self._count += n
        return self._count // self.period > prev

    @property
    def count(self) -> int:
        return self._count


class PeriodicTimeTracker:
    """True at most once every ``interval`` seconds."""

    def __init__(self, interval_s: float):
        self.interval_s = interval_s
        self._last = None

    def track(self) -> bool:
        now = time.monotonic()
        if self._last is None or now - self._last >= self.interval_s:
            self._last = now
            return True
        return False


class MetricAggregator:
    """Accumulate scalars between flushes; mean per key (tensorplex's
    per-group averaging, collapsed into one process)."""

    def __init__(self):
        self._sums: dict[str, float] = defaultdict(float)
        self._counts: dict[str, int] = defaultdict(int)

    def add(self, metrics: dict[str, float]) -> None:
        for key, value in metrics.items():
            self._sums[key] += float(value)
            self._counts[key] += 1

    def flush(self) -> dict[str, float]:
        out = {k: self._sums[k] / self._counts[k] for k in self._sums}
        self._sums.clear()
        self._counts.clear()
        return out

    def __len__(self) -> int:
        return len(self._sums)
