"""Remediation engine: root-caused incidents -> bounded actions on the
existing actuator surfaces (ISSUE 16).

PR 15's incident engine says *what probably caused it*; this module
closes the loop and says *what was done about it*. Once per ops
snapshot — after the watchdog sweep and the incident observe — the
engine reads the open incident's top-ranked cause tier and maps it to
ONE bounded action on an actuator the system already has:

    cause tier   action             actuator                    revert
    ----------   ----------------   -------------------------   --------------
    fleet        fleet_scale_up     InferenceFleet.scale_up     scale_down
    gateway      tenant_throttle    AdmissionController          restore the
                 (budget-burning     .set_quota (runtime)        previous quota
                 tenant)
    DEAD tier    targeted_restart   the tier's supervise()       (irreversible)
                                    (RespawnSchedule-backed)
    learner      learner_downshift  the config overrides path    restore the
    (regression)                    (batch/precision)            prior values
    learner      learner_scale_up   LearnerGroup.scale_up        scale_down
    (saturated/                     (parallel/learner_group.py:  (remove the
    lagging)                        join a member, rebalance)    joined member)

Discipline (the PR-15 false-positive guard, extended to actuation):

- **Journaled, first-class evidence** — every action is a counted
  ``remediation`` telemetry event, a ``remediation/*`` gauge bump, an
  atomic ``telemetry/actions/action-<n>.json`` record, AND an entry in
  the open incident's evidence (``surreal_tpu why`` renders
  cause -> action -> verdict).
- **Bounded** — per-action-kind cooldowns and a global ``max_actions``
  budget; a suppressed action is loud (``remediation/suppressed`` +
  event), never a silent retry loop.
- **Counter-detected** — each action watches its triggering objective
  for ``verify_windows`` post-action sweeps; if the objective regresses
  further, the action is marked ineffective, reverted where reversible
  (re-add the drained replica, restore the quota), and counted.

Pure host arithmetic over the snapshot dict (the same transfer-guard
that covers the watchdog covers this); persistence mirrors the incident
records (atomic tmp+replace, a failed write disables itself — the
control plane must never kill training). The report helpers at the
bottom are pure file reading, reused by ``why`` and ``top``.
"""

from __future__ import annotations

import json
import os
import time

ACTIONS_DIR = "actions"  # <folder>/telemetry/actions/

# verification objectives preferred when choosing which breached SLO row
# an action answers (latency/staleness contracts recover when the action
# works; throttle_rate on the throttled tenant moves the WRONG way under
# a shed, so it is last)
_SLO_PREFERENCE = (
    "act_rtt_p99_ms", "attach_p99_ms", "staleness_updates", "throttle_rate",
)


def _mean(xs) -> float | None:
    xs = [float(x) for x in xs if x is not None]
    return sum(xs) / len(xs) if xs else None


class RemediationEngine:
    """Owns incident-driven actuation for one run (constructed by
    SessionHooks next to the IncidentEngine, stepped once per metrics
    cadence after ``incidents.observe``).

    Actuators are bound AFTER construction (``bind_actuators``) because
    the fleet/gateway exist only inside the driver's run(); an unbound
    surface simply makes its actions unmappable — counted, never an
    error."""

    def __init__(self, folder=None, cfg=None, incidents=None, on_event=None,
                 trace_id=None):
        cfg = cfg or {}
        get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: d
        self.folder = folder
        self.trace_id = trace_id
        self.enabled = bool(get("enabled", True))
        self.max_actions = int(get("max_actions", 8))
        self.cooldown_s = float(get("cooldown_s", 30.0))
        self.verify_windows = max(1, int(get("verify_windows", 4)))
        # "regressed further": post-action mean beyond baseline by this
        # relative margin (plus a tiny absolute floor for ~0 baselines)
        self.regress_margin = float(get("regress_margin", 0.1))
        self.throttle_factor = float(get("throttle_factor", 0.5))
        self.min_rate = float(get("min_rate", 1.0))
        # rate applied when shedding a tenant whose quota was unlimited
        # (rate=0 disables the bucket, so a multiplicative throttle has
        # nothing to scale)
        self.shed_rate = float(get("shed_rate", 50.0))
        self._incidents = incidents
        self._on_event = on_event
        # bound actuator surfaces (None/empty until bind_actuators)
        self._fleet = None
        self._admission = None
        self._restart: dict = {}
        self._learner_downshift = None
        self._learner_restore = None
        self._learner_group = None
        # bookkeeping
        self._next_id = 1
        self._active: list[dict] = []   # actions still under verification
        self._last_t: dict[str, float] = {}  # action kind -> last exec time
        self.executed = 0
        self.suppressed = 0
        self.unmapped = 0
        self.reverted = 0
        self.ineffective = 0
        self.effective = 0
        self.errors = 0
        self._write_ok = folder is not None

    def bind_actuators(self, fleet=None, admission=None, restart=None,
                       learner_downshift=None, learner_restore=None,
                       learner_group=None) -> None:
        """Hand the engine its actuator surfaces: ``fleet`` duck-types
        ``scale_up()/scale_down()`` (InferenceFleet), ``admission``
        duck-types ``quota_of()/set_quota()`` (AdmissionController),
        ``restart`` maps tier name -> zero-arg supervise callable (the
        RespawnSchedule-backed supervisors), the learner pair
        implements the overrides downshift (downshift() -> revert
        payload or None; restore(payload)), and ``learner_group``
        duck-types ``scale_up() -> member_id / scale_down(member_id)``
        (the elastic LearnerGroup — ROADMAP's "scale the named tier"
        reservation for learners)."""
        if fleet is not None:
            self._fleet = fleet
        if admission is not None:
            self._admission = admission
        if restart:
            self._restart.update(restart)
        if learner_downshift is not None:
            self._learner_downshift = learner_downshift
        if learner_restore is not None:
            self._learner_restore = learner_restore
        if learner_group is not None:
            self._learner_group = learner_group

    # -- the per-cadence decision sweep --------------------------------------
    def step(self, firings: list[dict] | None, snap: dict | None) -> None:
        """One decision sweep: verify the active actions against this
        snapshot, then map the open incident's top cause to at most one
        new bounded action. Pure host work; every non-action outcome is
        counted."""
        if not self.enabled:
            return
        now = time.time()
        snap = snap or {}
        self._verify(snap, now)
        inc = (
            self._incidents.open_incident
            if self._incidents is not None else None
        )
        if inc is None or not inc.get("causes"):
            return
        if any(a["incident"] == inc["id"] for a in self._active):
            return  # an answer is already under verification — wait
        tier = str(inc["causes"][0].get("tier"))
        plan = self._map_action(tier, inc, firings or [], snap)
        if plan is None:
            self.unmapped += 1
            return
        kind = plan["kind"]
        if self.executed >= self.max_actions:
            self._suppress(kind, inc, now,
                           f"action budget exhausted "
                           f"({self.executed}/{self.max_actions})")
            return
        last = self._last_t.get(kind)
        if last is not None and now - last < self.cooldown_s:
            self._suppress(
                kind, inc, now,
                f"cooldown ({now - last:.1f} s of {self.cooldown_s:.1f} s)",
            )
            return
        self._execute(plan, tier, inc, snap, now)

    def _suppress(self, kind: str, inc: dict, now: float,
                  reason: str) -> None:
        """A would-be action stopped by a bound — loud, never a silent
        retry loop."""
        self.suppressed += 1
        if self._on_event is not None:
            self._on_event("remediation", status="suppressed", kind=kind,
                           incident=inc["id"], reason=reason)

    # -- cause tier -> action plan -------------------------------------------
    def _map_action(self, tier: str, inc: dict, firings: list[dict],
                    snap: dict) -> dict | None:
        """The action table. Returns ``{kind, detail, run, revert_info,
        reversible, objective fields...}`` or None (no bound actuator /
        no actionable target — counted unmapped by the caller)."""
        dead = [
            str(n) for n in inc.get("evidence", {}).get("dead_tiers", ())
            if str(n).split(".", 1)[0] == tier
        ]
        if tier == "fleet" and self._fleet is not None:
            return {
                "kind": "fleet_scale_up",
                "detail": (
                    f"re-arm/add a replica (dead: {', '.join(dead)})"
                    if dead else "add a serving replica"
                ),
                "objective": "fleet_serve_ms",
            }
        if tier == "gateway" and self._admission is not None:
            target = self._burning_tenant(snap)
            if target is None:
                return None
            tenant, objective = target
            return {
                "kind": "tenant_throttle",
                "detail": f"throttle tenant {tenant!r} "
                          f"(burning {objective} budget)",
                "objective": "slo_budget_used",
                "tenant": tenant,
                "slo_objective": objective,
            }
        if dead and tier in self._restart:
            return {
                "kind": "targeted_restart",
                "detail": f"supervise/restart {', '.join(dead)}",
                "objective": "tier_dead",
                "tier": tier,
            }
        regression = any(
            f.get("detector") == "regression" for f in firings
        ) or any(
            str(k).startswith("regression:learner")
            for k in (inc.get("detector_counts") or {})
        )
        if tier == "learner" and regression and (
            self._learner_downshift is not None
        ):
            return {
                "kind": "learner_downshift",
                "detail": "batch/precision downshift via config overrides",
                "objective": "throughput",
            }
        if tier == "learner" and not regression and (
            self._learner_group is not None
        ):
            # non-regression learner causes (saturation/growth/liveness
            # naming the learner tier = it can't keep up, not that its
            # update got slower): add a group member under the same
            # cooldown + max-actions + counter-detection discipline;
            # revert = remove the joined member
            return {
                "kind": "learner_scale_up",
                "detail": "join a learner-group member "
                          "(shard rebalance + fanout re-key)",
                "objective": "throughput",
            }
        return None

    def _burning_tenant(self, snap: dict) -> tuple[str, str] | None:
        """(tenant, objective) burning the most error budget in this
        snapshot's SLO table — the throttle target. Latency/staleness
        objectives are preferred for verification (see _SLO_PREFERENCE)."""
        best = None
        for tenant, row in (snap.get("slo") or {}).items():
            for objective, o in (row or {}).items():
                if not (isinstance(o, dict) and (o.get("breached")
                                                 or o.get("exhausted"))):
                    continue
                pref = (
                    _SLO_PREFERENCE.index(objective)
                    if objective in _SLO_PREFERENCE else len(_SLO_PREFERENCE)
                )
                score = (float(o.get("budget_used", 0.0)), -pref)
                if best is None or score > best[0]:
                    best = (score, str(tenant), str(objective))
        return (best[1], best[2]) if best else None

    # -- execution + journal -------------------------------------------------
    def _execute(self, plan: dict, tier: str, inc: dict, snap: dict,
                 now: float) -> None:
        kind = plan["kind"]
        reversible = True
        revert_info: dict = {}
        try:
            if kind == "fleet_scale_up":
                revert_info["replica"] = int(self._fleet.scale_up())
            elif kind == "tenant_throttle":
                tenant = plan["tenant"]
                old = self._admission.quota_of(tenant)
                new = dict(old)
                rate = float(old.get("rate", 0.0))
                new["rate"] = (
                    max(self.min_rate, rate * self.throttle_factor)
                    if rate > 0 else self.shed_rate
                )
                burst = float(old.get("burst", 1.0))
                new["burst"] = max(1.0, burst * self.throttle_factor)
                self._admission.set_quota(tenant, new)
                revert_info = {"tenant": tenant, "quota": old,
                               "applied": new}
            elif kind == "targeted_restart":
                self._restart[plan["tier"]]()
                reversible = False  # a restart cannot be un-run
            elif kind == "learner_downshift":
                payload = self._learner_downshift()
                if payload is None:
                    self.unmapped += 1  # nothing left to downshift
                    return
                revert_info = {"payload": payload}
                reversible = self._learner_restore is not None
            elif kind == "learner_scale_up":
                revert_info["member"] = int(self._learner_group.scale_up())
            else:  # pragma: no cover — _map_action emits only the above
                raise ValueError(f"unknown action kind {kind}")
        except Exception as e:  # noqa: BLE001 — actuation must never
            # kill training; the failure is journaled and counted
            self.errors += 1
            if self._on_event is not None:
                self._on_event("remediation", status="error", kind=kind,
                               incident=inc["id"],
                               reason=f"{type(e).__name__}: {e}")
            return
        n = self._next_id
        self._next_id += 1
        self.executed += 1
        self._last_t[kind] = now
        act = {
            "action": n, "t": now, "status": "verifying", "verdict": None,
            "trace": self.trace_id, "incident": int(inc["id"]),
            "cause_tier": tier, "cause_score": inc["causes"][0].get("score"),
            "kind": kind, "detail": plan["detail"],
            "objective": plan["objective"],
            "tenant": plan.get("tenant"),
            "slo_objective": plan.get("slo_objective"),
            "tier": plan.get("tier"),
            "baseline": self._objective_value(plan, snap),
            "samples": [], "verify_left": int(self.verify_windows),
            "reversible": reversible, "revert_info": revert_info,
            "reverted": False,
            "iteration": snap.get("iteration"),
        }
        self._active.append(act)
        self._write(act)
        if self._on_event is not None:
            self._on_event(
                "remediation", status="executed", action=n, kind=kind,
                incident=inc["id"], cause_tier=tier, detail=plan["detail"],
                baseline=act["baseline"],
            )
        self._attach(act)

    def _attach(self, act: dict) -> None:
        """Mirror the action into the incident it answered (first-class
        evidence; no-op once that incident is no longer the open one)."""
        if self._incidents is None:
            return
        inc = self._incidents.open_incident
        if inc is None or int(inc["id"]) != int(act["incident"]):
            return
        self._incidents.attach_action({
            "action": act["action"], "t": act["t"],
            "cause_tier": act["cause_tier"], "kind": act["kind"],
            "detail": act["detail"], "verdict": act["verdict"],
            "reverted": act["reverted"],
        })

    # -- the counter-detector ------------------------------------------------
    def _objective_value(self, act: dict, snap: dict) -> float | None:
        """The triggering objective's value in this snapshot (None = no
        data this sweep — never a verdict input). Lower is better for
        every objective except throughput."""
        obj = act.get("objective")
        tiers = snap.get("tiers") or {}
        if obj == "fleet_serve_ms":
            vals = [
                (row.get("gauges") or {}).get("fleet/serve_ms")
                for name, row in tiers.items()
                if str(name).split(".", 1)[0] == "fleet"
            ]
            return _mean(vals)
        if obj == "slo_budget_used":
            row = (snap.get("slo") or {}).get(act.get("tenant")) or {}
            o = row.get(act.get("slo_objective"))
            if isinstance(o, dict) and o.get("budget_used") is not None:
                return float(o["budget_used"])
            # tenant gone quiet: its budget stopped burning by definition
            return None
        if obj == "tier_dead":
            rows = [
                row for name, row in tiers.items()
                if str(name).split(".", 1)[0] == act.get("tier")
            ]
            if not rows:
                return None
            return _mean([1.0 if r.get("dead") else 0.0 for r in rows])
        if obj == "throughput":
            v = (
                (tiers.get("learner") or {}).get("gauges") or {}
            ).get("time/env_steps_per_s")
            return float(v) if v is not None else None
        return None

    def _verify(self, snap: dict, now: float) -> None:
        """One verification tick for every active action; verdicts after
        ``verify_windows`` sweeps, reverting what regressed further."""
        for act in list(self._active):
            v = self._objective_value(act, snap)
            if v is not None:
                act["samples"].append(round(float(v), 6))
            act["verify_left"] -= 1
            if act["verify_left"] > 0:
                continue
            self._active.remove(act)
            act["status"] = "done"
            act["verdict"] = self._judge(act)
            if act["verdict"] == "ineffective":
                self.ineffective += 1
                if act["reversible"]:
                    self._revert(act)
            elif act["verdict"] == "effective":
                self.effective += 1
            self._write(act)
            if self._on_event is not None:
                self._on_event(
                    "remediation_verdict", action=act["action"],
                    kind=act["kind"], verdict=act["verdict"],
                    incident=act["incident"], baseline=act["baseline"],
                    post_mean=_mean(act["samples"]),
                    reverted=act["reverted"],
                )
            self._attach(act)

    def _judge(self, act: dict) -> str:
        """ineffective = the objective regressed FURTHER past its
        at-action baseline; effective otherwise; unverified when either
        side carried no data (no data is never a revert trigger)."""
        baseline = act.get("baseline")
        post = _mean(act["samples"])
        if baseline is None or post is None:
            return "unverified"
        baseline = float(baseline)
        floor = 1e-6  # ~0 baselines: relative margin alone is a tautology
        if act.get("objective") == "throughput":  # higher is better
            return (
                "ineffective"
                if post < baseline * (1.0 - self.regress_margin) - floor
                else "effective"
            )
        return (
            "ineffective"
            if post > baseline * (1.0 + self.regress_margin) + floor
            else "effective"
        )

    def _revert(self, act: dict) -> None:
        kind = act["kind"]
        info = act.get("revert_info") or {}
        try:
            if kind == "fleet_scale_up":
                self._fleet.scale_down()
            elif kind == "tenant_throttle":
                self._admission.set_quota(info["tenant"], info["quota"])
            elif kind == "learner_downshift":
                self._learner_restore(info["payload"])
            elif kind == "learner_scale_up":
                self._learner_group.scale_down(info.get("member"))
            else:
                return
        except Exception as e:  # noqa: BLE001 — a failed revert is
            # journaled evidence, not a crash
            self.errors += 1
            act["revert_error"] = f"{type(e).__name__}: {e}"
            return
        act["reverted"] = True
        self.reverted += 1

    # -- teardown + persistence ----------------------------------------------
    def close(self) -> None:
        """Session teardown: flush still-verifying actions as-is (a run
        ending mid-verification is itself evidence)."""
        for act in self._active:
            self._write(act)

    def _write(self, act: dict) -> None:
        if not self._write_ok:
            return
        from surreal_tpu.session.telemetry import TELEMETRY_DIR

        folder = os.path.join(self.folder, TELEMETRY_DIR, ACTIONS_DIR)
        path = os.path.join(folder, f"action-{act['action']}.json")
        try:
            os.makedirs(folder, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(act, f, default=float)
            os.replace(tmp, path)  # readers never see a torn record
        except OSError:
            self._write_ok = False  # actuation telemetry must never
            # kill training

    def gauges(self) -> dict[str, float]:
        """The engine's ``remediation/*`` counters (GAUGE_REGISTRY
        documents each); merged into the learner's metrics row."""
        return {
            "remediation/actions": float(self.executed),
            "remediation/suppressed": float(self.suppressed),
            "remediation/unmapped": float(self.unmapped),
            "remediation/reverted": float(self.reverted),
            "remediation/ineffective": float(self.ineffective),
            "remediation/effective": float(self.effective),
            "remediation/errors": float(self.errors),
            "remediation/active": float(len(self._active)),
        }


# -- report helpers (pure file reading, like why/top/trace) -------------------


def load_actions(folder: str) -> list[dict]:
    """Every persisted action record under ``<folder>/telemetry/actions/``,
    id order. Hostile-tolerant: a torn/foreign file is skipped."""
    from surreal_tpu.session.telemetry import TELEMETRY_DIR

    act_dir = os.path.join(folder, TELEMETRY_DIR, ACTIONS_DIR)
    out = []
    try:
        names = os.listdir(act_dir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("action-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(act_dir, name)) as f:
                rec = json.load(f)
            if isinstance(rec, dict) and rec.get("action") is not None:
                out.append(rec)
        except (OSError, json.JSONDecodeError):
            continue
    out.sort(key=lambda r: int(r["action"]))
    return out


def _action_line(a: dict) -> str:
    verdict = a.get("verdict") or a.get("status", "?")
    return (
        f"  #{a.get('action')} incident {a.get('incident')} "
        f"{a.get('cause_tier', '?'):<12} -> {a.get('kind', '?'):<18} "
        f"{a.get('detail', '')} -> {verdict}"
        + (" (reverted)" if a.get("reverted") else "")
    )


def actions_report_lines(folder: str,
                         incident: int | None = None) -> list[str]:
    """The ``surreal_tpu why`` Actions section: the remediation journal
    rendered cause -> action -> verdict (empty when no action was ever
    taken — the section simply doesn't appear)."""
    actions = load_actions(folder)
    if incident is not None:
        actions = [
            a for a in actions if int(a.get("incident", -1)) == int(incident)
        ]
    if not actions:
        return []
    n_rev = sum(1 for a in actions if a.get("reverted"))
    lines = [
        f"Actions — {len(actions)} remediation action(s), "
        f"{n_rev} reverted (journal: telemetry/actions/)"
    ]
    for a in actions:
        lines.append(_action_line(a))
    return lines


def actions_brief(folder: str, limit: int = 4) -> list[str]:
    """The ``top`` live-action section: newest ``limit`` actions, one
    line each (same renderer as ``why``'s Actions section)."""
    actions = load_actions(folder)
    if not actions:
        return []
    active = sum(1 for a in actions if a.get("status") == "verifying")
    lines = [
        f"  {len(actions)} action(s) taken, {active} verifying "
        "(full journal: `surreal_tpu why <folder>`)"
    ]
    for a in actions[-limit:]:
        lines.append("  " + _action_line(a))
    return lines
