"""Base config trees (parity: reference ``surreal/session/default_configs.py``
plus ``surreal/main/ppo_configs.py`` / ``ddpg_configs.py``, SURVEY.md §5.6).

Three trees: learner / env / session. Algorithm-specific defaults live next
to each learner (``surreal_tpu.learners.ppo.PPO_LEARNER_CONFIG`` etc.) and
are ``extend()``-ed onto these bases.

New relative to the reference: ``session.topology`` selects the device mesh
(the reference instead wired ZMQ ports between processes), and
``session.backend`` selects tpu/cpu.
"""

from __future__ import annotations

from surreal_tpu.session.config import REQUIRED, Config

BASE_LEARNER_CONFIG = Config(
    algo=Config(
        name=REQUIRED,  # 'ppo' | 'ddpg' | 'impala'
        gamma=0.99,
        n_step=1,
        use_obs_filter=True,  # ZFilter running obs normalization
        # SEED topology only: drop trajectory chunks whose oldest transition
        # was acted more than this many updates ago (None = train on all;
        # V-trace absorbs bounded staleness, PPO-over-SEED should bound it)
        max_staleness=None,
        # program autotuner (surreal_tpu/tune/): 'off' = hand-set knobs
        # below; 'cache' = apply the tuning cache's winner for this
        # workload fingerprint (falls back to defaults on a miss, never
        # pays search cost); 'search' = on a miss, measure the candidate
        # space at trainer build time and persist the winner (device
        # jax:* envs only). `surreal_tpu tune <algo> <env>` runs the
        # search standalone against the same cache.
        autotune="off",
        # searched scan-unroll knobs (tune/space.py declares the candidate
        # values; every hot lax.scan states its decision explicitly —
        # enforced by the test_import_hygiene unroll lint):
        rollout_unroll=1,  # device rollout scan over the horizon
        gae_unroll=1,      # time recurrences: PPO's xla GAE scan,
                           # IMPALA's V-trace scan, ops/returns estimators
        # precision policy (ops/precision.py) — ONE knob governing model
        # compute dtype, trajectory/SGD/replay staging dtype, and dynamic
        # loss scaling, threaded through every learner and trainer (and a
        # searched autotuner dimension, tune/space.py):
        #   'f32'      compute f32, staging f32 (numerics baseline)
        #   'mixed'    compute bf16, staging f32 (the pre-ISSUE-7 default
        #              — kept default so existing configs/checkpoints
        #              reproduce exactly; no loss-scale state in the
        #              optimizer pytree)
        #   'bf16'     compute bf16 AND staging bf16 (obs-class arrays
        #              move half the bytes) + dynamic loss scaling
        #   'bf16_fp8' 'bf16' plus the experimental fp8 matmul path in
        #              Dense layers — behind this knob only, never
        #              auto-searched
        precision="mixed",
    ),
    model=Config(
        actor_hidden=(64, 64),
        critic_hidden=(64, 64),
        activation="tanh",
        encoder=Config(
            # policy/critic trunk family: 'auto' = CNN stem when
            # model.cnn.enabled else MLP (the reference's two shapes);
            # 'trajectory' = causal trajectory transformer
            # (models/attention.py) whose attention rides ring attention
            # over an `sp` mesh axis when one is bound — the long-context
            # seam as a config knob (on-policy learners: ppo AND impala,
            # device envs; ddpg fails fast rather than silently ignore it)
            kind="auto",
            features=64,
            num_layers=2,
            num_heads=4,
            head_dim=16,
            # trajectory acting: 'kv' (incremental decode against a K/V
            # cache — O(T) per step) | 'padded' (re-run the full padded
            # segment each step — O(T^2), the simple reference form)
            act_impl="kv",
            # pos_embed capacity; the sequence learn pass uses horizon+1
            # positions, validated at learner build (seq_policy.py)
            max_len=4096,
        ),
        cnn=Config(
            enabled=False,          # pixel observations -> Nature-CNN stem
            channels=(32, 64, 64),
            kernels=(8, 4, 3),
            strides=(4, 2, 1),
            dense=512,
        ),
        # 'auto' resolves BOTH dtypes from algo.precision (the unified
        # policy knob above — ops/precision.py); an explicit dtype string
        # here overrides the policy for this model alone (the pre-ISSUE-7
        # spelling, kept honored for old configs)
        dtype="auto",           # parameter dtype ('auto' -> float32)
        compute_dtype="auto",   # activations dtype ('auto' -> per policy)
    ),
    optimizer=Config(
        name="adam",
        lr=3e-4,
        max_grad_norm=0.5,
        lr_schedule="constant",  # 'constant' | 'linear'
        # dynamic loss scaling (ops/precision.py::dynamic_loss_scaling):
        # 'auto' enables it exactly when the precision policy stages in
        # bf16 ('bf16'/'bf16_fp8'); True/False force it. All factors are
        # powers of two, so scaling is exact on healthy steps; an
        # overflow skips the step (Adam moments untouched) and backs the
        # scale off. NOTE: enabling adds a LossScaleState leaf to the
        # optimizer pytree — checkpoints do not restore across a
        # loss-scaling flip (the run-metadata guard makes that a clear
        # error, session/checkpoint.py).
        loss_scaling=Config(
            enabled="auto",
            init=2.0**15,
            growth_interval=2000,
            growth_factor=2.0,
            backoff_factor=0.5,
            min=1.0,
            max=2.0**24,
        ),
    ),
    replay=Config(
        # 'fifo' | 'uniform' | 'prioritized' (algo defaults override), or
        # 'remote' — the sharded experience plane (surreal_tpu/experience/):
        # replay lives in ReplayShardServer processes fed by an
        # ExperienceSender and drained by a prefetched ShardedSampler, so
        # actor fleets on other hosts can feed one learner group. Host
        # off-policy path only; shard geometry/transport under
        # session.topology.experience_plane.
        kind="fifo",
        # remote only: the shard servers' sampling discipline
        remote_kind="uniform",   # 'uniform' | 'prioritized'
        capacity=100_000,
        start_sample_size=1_000,
        batch_size=256,
        # prioritized-replay knobs (ignored by other kinds)
        priority_alpha=0.6,
        priority_beta0=0.4,
        priority_eps=1e-6,
    ),
)

BASE_ENV_CONFIG = Config(
    name=REQUIRED,        # 'jax:cartpole', 'gym:CartPole-v1', 'dm_control:cheetah-run', ...
    num_envs=1,           # batched envs (vmap width on device, workers on host)
    action_repeat=1,
    frame_stack=1,
    grayscale=False,
    image_size=None,      # (H, W) resize for pixel obs
    pixel_obs=False,
    flatten_obs=True,     # adapters always flatten dict obs to one vector;
                          # kept for config parity (FilterWrapper/concat role)
    time_limit=None,      # None -> backend default
    video=Config(enabled=False, dir=None, every_n_episodes=50),
    seed=0,
)

BASE_SESSION_CONFIG = Config(
    folder=REQUIRED,  # experiment directory (checkpoints, metrics, logs)
    backend="tpu",    # 'tpu' | 'cpu' (cpu = host-simulated devices for tests)
    topology=Config(
        # mesh axes for the SPMD program; product must divide device count.
        # dp = data parallel (gradient psum), tp = tensor parallel seam.
        mesh=Config(dp=-1, tp=1),  # -1 -> use all remaining devices
        # host-side env worker processes (0 = in-process); each worker
        # steps its own env_config.num_envs-wide batch, so total host envs
        # = num_env_workers * num_envs
        num_env_workers=0,
        # 'thread' (fine for gym classic-control) | 'process' (OS workers,
        # spawn ctx — MuJoCo-heavy stepping holds the GIL, so real
        # deployments fork like the reference's actor pool did)
        worker_mode="thread",
        # SEED host data plane (distributed/shm_transport.py):
        # - transport: 'auto' negotiates per-worker zero-copy shared-memory
        #   slabs for process workers against the local server (pickle for
        #   thread mode and remote workers); 'shm' forces the slab grant;
        #   'pickle' keeps the original serialized wire everywhere.
        # - pipeline_workers: each worker splits its env slice into two
        #   sub-slices and steps one while the other's actions are in
        #   flight (double-buffered acting, Stooke & Abbeel 1803.02811) —
        #   hides the server round trip; needs an even num_envs (auto-
        #   disabled otherwise, and under a dp mesh whose width the
        #   sub-slice would not divide).
        # - worker_silence_s: per-step server-liveness budget in the
        #   worker (was hard-coded 120 s; the first replies legitimately
        #   wait out XLA compiles on a tunneled TPU).
        transport="auto",
        pipeline_workers=True,
        worker_silence_s=120.0,
        # SEED worker supervision: a dead worker respawns immediately the
        # first time, then exponentially backed off (base * 2^k, capped) —
        # a worker that dies AT STARTUP must not respawn-loop hot. The
        # streak resets once a respawn survives its probation window; the
        # current backoff is exported as the workers/respawn_backoff_s
        # gauge.
        respawn_backoff_s=0.5,
        respawn_backoff_cap_s=30.0,
        # inference server: sanitize nonfinite observation payloads
        # (np.nan_to_num + a server/sanitized_requests gauge) instead of
        # letting one corrupt slab slot poison the micro-batch, the acting
        # policy, and every trajectory in flight
        sanitize_obs=True,
        # sharded experience plane (surreal_tpu/experience/): the
        # cross-host replay tier behind replay.kind='remote' (off-policy
        # host path) and, with enabled=true, the SEED trainer's chunk
        # relay (trajectory chunks route server -> shard -> learner over
        # the negotiated wire — the cross-host seam for actor fleets on
        # other machines). Transport negotiates per peer: shm slabs
        # same-host, the length-framed tcp codec cross-host, pickle as
        # the fallback.
        experience_plane=Config(
            enabled=False,           # SEED chunk-relay arm only; the
                                     # off-policy plane keys off replay.kind
            num_shards=2,
            shard_mode="thread",     # 'thread' | 'process' (spawn ctx;
                                     # shards pin themselves to CPU — a
                                     # replay shard must never grab a chip)
            transport="auto",        # 'auto' | 'shm' | 'tcp' | 'pickle'
            insert_slots=4,          # sender backpressure window (shm:
                                     # slab slots; tcp/pickle: unacked
                                     # frames)
            watermark_timeout_s=5.0, # shard-side bound on sample deferral
                                     # (a respawned-empty shard must not
                                     # deadlock the learner)
            ack_timeout_s=5.0,       # sender per-attempt ack budget
            sample_timeout_s=10.0,   # sampler per-attempt reply budget
            fifo_depth=64,           # SEED arm: chunks held per shard
            # shard respawn schedule (the SEED worker supervisor's rule:
            # immediate first respawn, then base * 2^k capped)
            respawn_backoff_s=0.5,
            respawn_backoff_cap_s=30.0,
        ),
        # autoscaling act-serving tier (distributed/fleet.py): replicas>1
        # (or autoscale=true) replaces the single InferenceServer with an
        # InferenceFleet — N replicas behind session-affinity routing
        # (workers rendezvous-hash to a replica at spawn and stay there,
        # so trajectory streams and shm slabs keep one owner), each with
        # its OWN coalescing budget (min_batch = its affinity share of
        # the worker fleet; auto_tune tracks per-replica liveness).
        # Lifecycle is the SEED respawn schedule: a dead replica respawns
        # in place (fixed address) under base * 2^k backoff while its
        # workers re-hello to survivors. Autoscaling adds/drains replicas
        # off the serve-latency EWMA (the PR-1 gauge), cooldown-bounded,
        # within [min_replicas, max_replicas].
        inference_fleet=Config(
            replicas=1,               # 1 = the original single server
            min_replicas=1,
            max_replicas=4,
            autoscale=False,
            scale_up_serve_ms=40.0,   # fleet-mean serve EWMA above: add
            scale_down_serve_ms=5.0,  # ...below: drain one replica
            scale_cooldown_s=30.0,    # min seconds between decisions
            respawn_backoff_s=0.5,
            respawn_backoff_cap_s=30.0,
            # bounded {version -> act closure} history kept for the
            # gateway's version-pinned serves (oldest evicted; an
            # evicted pin surfaces as a counted gateway catch_up)
            act_history=8,
        ),
        # production session gateway (surreal_tpu/gateway/): the
        # tenant-facing session tier in front of the inference fleet —
        # external sessions attach (id + lease), act over the gateway
        # wire protocol (tcp struct frames; pickle as the negotiated
        # per-session fallback), and detach. The gateway OWNS the
        # session->replica mapping (rendezvous-hashed like workers), so
        # routing survives client churn and replica death (sessions
        # rebind to survivors from the session table — counted
        # migrations, invisible to tenants). Admission is per-tenant:
        # token-bucket act rates, max-session quotas, bounded
        # backpressure queues (oldest evicted WITH an error reply), and
        # lease expiry reaping idle sessions. Version pinning serves a
        # tenant from a held param version while others ride the fanout
        # head; the act cache short-circuits duplicate observations at
        # the same version (hit/miss counted).
        gateway=Config(
            enabled=False,
            bind=None,            # fixed service address (None = allocate
                                  # a loopback port at start)
            max_sessions=256,     # global cap (0 = unbounded)
            lease_s=30.0,         # idle lease; any session frame renews
            act_cache=256,        # LRU act-result entries (0 = off)
            pin_versions=True,    # honor per-session version pins
            # per-tenant quotas; the 'default' entry covers tenants not
            # named here. rate=0 disables the token bucket.
            tenant_quotas=Config(
                default=Config(
                    max_sessions=64,   # sessions per tenant (0 = unbounded)
                    rate=200.0,        # acts/s refill
                    burst=400.0,       # bucket depth
                    queue_depth=64,    # backpressure queue bound
                ),
            ),
            # gateway serve-thread supervision (the shared respawn
            # schedule — utils/respawn.py)
            respawn_backoff_s=0.5,
            respawn_backoff_cap_s=30.0,
        ),
        # host-env (gym/dm_control) loops: collect iteration k+1 on a
        # worker thread while the device learns on k (the reference's
        # learner never waited on actors — its prefetch thread kept
        # batches queued, SURVEY.md §3.4). Costs one update of policy
        # staleness, which PPO ratios / V-trace absorb; false restores
        # strict rollout->learn alternation.
        overlap_rollouts=True,
        multihost=Config(          # multi-controller scaling (parallel/multihost.py)
            coordinator=None,      # "host:port" of process 0 ($JAX_COORDINATOR_ADDRESS)
            num_processes=None,    # total hosts/processes ($JAX_NUM_PROCESSES); None/1 = single
            process_id=None,       # this process's rank ($JAX_PROCESS_ID)
        ),
    ),
    total_env_steps=1_000_000,
    # persistent XLA compile cache (utils/compat.py::enable_compile_cache,
    # wired by SessionHooks so every driver — single- and multi-host —
    # shares it): a directory for jax_compilation_cache_dir. Relative
    # paths resolve under the session folder; None disables. Relaunching
    # a session (or any session pointed at the same absolute dir) reuses
    # the compiled executables instead of re-paying XLA compile time —
    # WALLCLOCK_r05 measured compile, not train time, as the dominant
    # spread on the pong workload. Hit/miss counts flow as
    # 'compile_cache' telemetry events (surfaced by `surreal_tpu diag`).
    compile_cache_dir=None,
    # persistent JSON tuning cache (surreal_tpu/tune/cache.py), the
    # compile cache's sibling: one entry per workload fingerprint holding
    # the measured winner + its full trial record. Relative paths resolve
    # under the session folder; None defaults to '<folder>/tuning_cache';
    # an absolute path shares one cache across sessions (the pattern for
    # `surreal_tpu tune` once + `algo.autotune='cache'` everywhere).
    tuning_cache_dir=None,
    checkpoint=Config(
        every_n_iters=500,
        keep_last=3,
        keep_best=True,
        restore_from=None,   # foreign session folder to warm-start from
        auto_resume=True,    # resume from own folder's latest checkpoint
        # off-policy only: also checkpoint the replay buffer so a resume
        # skips the warmup refill (the reference did NOT checkpoint replay,
        # SURVEY.md §5.4 — this is a beyond-parity opt-in; storage cost is
        # the buffer itself)
        include_replay=False,
    ),
    # fault-tolerant training (session/interrupt.py, launch/recovery.py):
    recovery=Config(
        # SIGTERM/SIGINT sentinel: latch the signal, stop at the next
        # iteration boundary, write an emergency checkpoint — a TPU
        # preemption costs at most one iteration instead of one
        # checkpoint interval. Polled, never raced against orbax saves.
        interrupt=True,
        # divergence guard on the in-graph health/* signals, checked at
        # the metrics cadence: 'rollback' restores the newest FINITE
        # checkpoint (+ replay extra/ when snapshotted), re-seeds the
        # offending batch, and applies bounded LR backoff; 'warn' only
        # logs/emits (and still refuses to checkpoint poisoned state);
        # 'off' disables detection. Multi-host drivers force 'warn'
        # (rollback is a collective restore — relaunch with auto_resume
        # instead).
        on_divergence="rollback",
        max_rollbacks=3,          # then TrainingDiverged — bounded, loud
        lr_backoff=0.5,           # lr scale = lr_backoff ** rollback_count
        min_lr_scale=0.05,        # ...floored here (bounded backoff)
        grad_norm_limit=None,     # optional extra trip wire (None = NaN only)
        # this many consecutive HEALTHY metrics windows clear the rollback
        # streak: the budget targets a state that RE-diverges, not isolated
        # transients spread over a production-length run (same reset rule
        # as the SEED respawn backoff)
        heal_after_windows=20,
    ),
    # deterministic chaos harness (utils/faults.py): a list of fault specs
    # ({"site": ..., "kind": ..., "at": K, "times": N, ...}) injected at
    # fixed call counts of named data-plane/trainer sites — worker kills,
    # dropped/delayed frames, slab corruption, forced NaN state, SIGTERM
    # mid-iteration. None = chaos off (and the registry is reset at every
    # run start, so it can never leak between runs). CLI: --set
    # 'session_config.faults.plan=[{"site":"trainer.iteration",...}]'.
    faults=Config(plan=None),
    metrics=Config(
        every_n_iters=10,
        tensorboard=True,
        console=True,
    ),
    telemetry=Config(
        # telemetry spine (session/telemetry.py): span tracing into an
        # append-only JSONL event log under <folder>/telemetry/, mirrored
        # as time/* scalars through the MetricsWriter. Spans accumulate
        # in-memory and are written as ONE 'phases' event per metrics
        # cadence, so log volume scales with metrics.every_n_iters, not
        # iteration rate; the in-graph health/* diagnostics
        # (learners/base.py::training_health) ride the metrics dict and
        # sync at the same cadence — the hot loop gains zero extra
        # device->host syncs (tests/test_telemetry.py proves it).
        # Read a session offline with `python -m surreal_tpu diag <folder>`.
        enabled=True,
        # multi-host runs: each rank appends liveness events to its own
        # telemetry/heartbeat_rank<k>.jsonl at this cadence (seconds);
        # ranks whose host cannot write the folder disable silently
        heartbeat_every_s=10.0,
        # size-based rotation for events.jsonl: past this size the log is
        # renamed to events.jsonl.1 (one rotated segment kept; an older
        # .1 is overwritten) and a fresh file starts — diag and the
        # _iter_jsonl readers stitch .1 + current in order. None = never
        # rotate (the pre-PR-13 behavior).
        max_log_mb=256,
    ),
    # live ops plane (ISSUE 13, session/opsplane.py): every tier pushes
    # its gauge/hop row to a run-scoped aggregator; at the metrics cadence
    # the learner merges them into telemetry/ops_snapshot.json (the file
    # `surreal_tpu top <folder>` renders) and feeds the flight recorder —
    # a bounded ring of the last `ring` snapshots + fault/recovery events,
    # dumped to telemetry/flightrec/<trigger>/ when the recovery guard
    # trips, a chaos fault fires, or an SLO error budget exhausts (at most
    # one dump per trigger per min_dump_interval_s).
    ops=Config(
        enabled=True,
        ring=64,
        min_dump_interval_s=5.0,
    ),
    # per-tenant SLOs (session/slo.py), evaluated per metrics window
    # against the gateway's per-tenant stats + the merged hop percentiles.
    # Objectives default to None = not declared (no noise in normal runs);
    # set a target to arm one. `budget` is the tolerated breach fraction
    # over a rolling `budget_windows` evaluation windows — exhausting it
    # emits a counted slo_breach with exhausted=True and freezes a flight
    # recorder dump under flightrec/slo/.
    slo=Config(
        enabled=True,
        budget_windows=20,
        budget=0.2,
        act_rtt_p99_ms=None,      # gateway act round-trip p99 (ms)
        attach_p99_ms=None,       # session attach/hello latency p99 (ms)
        throttle_rate=None,       # throttled / (throttled + served) per window
        staleness_updates=None,   # published version - oldest replica version
    ),
    # watchdog & incident engine (ISSUE 15, session/watchdog.py +
    # session/incidents.py): detector sweeps over the merged ops snapshot
    # at the metrics cadence — EWMA/MAD breakouts on the headline
    # latencies/throughputs, queue/backpressure saturation, monotonic
    # growth of every dropped/bad_frames counter, tier liveness from the
    # ops plane's DEAD rendering, and online regression vs a committed
    # BENCH baseline. Firings open root-caused incidents (one open at a
    # time) persisted under telemetry/incidents/ and rendered by
    # `surreal_tpu why <folder>`. Pure host arithmetic over the snapshot
    # dict — no device->host syncs (transfer-guard tested), overhead
    # committed <=1% of iteration time (perf_gate.gate_watchdog).
    watchdog=Config(
        enabled=True,
        warmup=8,            # sweeps before breakout detectors arm
        window=32,           # rolling median/MAD window (sweeps)
        mad_k=6.0,           # breakout: |x - median| > mad_k * MAD floor
        min_rel=0.25,        # ... AND relative deviation above this
        sustain=2,           # consecutive outlier sweeps before firing
        queue_depth_max=512.0,   # saturation threshold for queue gauges
        respawn_burst=2,     # respawn deltas per window that count as a burst
        growth_windows=2,    # consecutive growing windows for drop counters
        staleness_growth_windows=4,  # ... for lineage/staleness_p99
        staleness_floor=64.0,  # versions; the startup ramp toward
        # steady-state pipeline depth stays below this and never fires
        regression_frac=0.5,     # fire when live throughput/MFU < frac*bench
        regression_sustain=3,
        baseline_dir=None,   # dir of BENCH_r*.json rows (None -> repo root)
        # incident engine knobs (session/incidents.py)
        close_windows=5,         # clean sweeps before incident_close
        evidence_window_s=120.0,  # fault/recovery correlation horizon
        update_every=5,          # firing windows between incident_update
        max_captures=4,          # auto profile+flightrec captures per run
        capture_cooldown_s=60.0,
    ),
    # closed-loop remediation (ISSUE 16, session/remediate.py): once per
    # metrics cadence — after the watchdog sweep and the incident
    # observe — the engine maps the open incident's top-ranked cause
    # tier to ONE bounded action on an existing actuator (fleet
    # scale_up, per-tenant throttle via AdmissionController.set_quota,
    # RespawnSchedule-backed targeted restart, learner batch/precision
    # downshift via the config overrides path). Every action is a
    # counted `remediation` event + an atomic
    # telemetry/actions/action-<n>.json record + evidence on its
    # incident; a counter-detector watches the triggering objective for
    # verify_windows post-action sweeps and reverts what regressed
    # further. Suppressions (budget/cooldown) are loud, never silent.
    remediate=Config(
        enabled=True,
        max_actions=8,        # global per-run action budget
        cooldown_s=30.0,      # per-action-kind cooldown
        verify_windows=4,     # post-action sweeps before a verdict
        regress_margin=0.1,   # "regressed further" relative margin
        throttle_factor=0.5,  # tenant quota multiplier per throttle
        min_rate=1.0,         # throttled tenants never drop below this
        shed_rate=50.0,       # rate applied when the old quota was
                              # unlimited (rate=0 has nothing to scale)
    ),
    eval=Config(
        every_n_iters=100,
        episodes=5,
        mode="deterministic",  # 'deterministic' | 'stochastic'
        max_steps=None,        # per-episode step cap (None -> env time limit
                               # on device, 10k on host)
    ),
    # cost/MFU accounting (session/costs.py): per-program FLOPs / bytes
    # from XLA's cost model, recorded once per hot program at driver
    # startup, plus live perf/mfu + perf/membw_util gauges at the metrics
    # cadence (pure host arithmetic over already-recorded phase times —
    # zero extra device->host syncs, transfer-guard tested).
    perf=Config(
        enabled=True,
        # peak-spec override: peak FLOP/s and memory bytes/s used as the
        # MFU / bandwidth-utilization denominators. None resolves from
        # the device-kind table in session/costs.py (TPU generations +
        # a nominal CPU figure); set both for unlisted hardware.
        peak_flops=None,
        peak_membw=None,
        # memory_analysis needs a real XLA compile (not shared with the
        # jit call cache on this pin): 'auto' runs it only when cheap
        # (single-process with the persistent compile cache active —
        # the AOT compile then warms the same cache the first jit call
        # reads); True/False force it
        memory_analysis="auto",
    ),
    # on-demand profiling (session/profile.py): jax.profiler windows
    # captured at iteration boundaries into <folder>/telemetry/profiles/,
    # each logged as a 'profile' telemetry event (rendered by diag).
    profile=Config(
        # watch <folder>/profile.trigger (written by `surreal_tpu
        # profile <folder>`, checked at most once per second): when it
        # appears, capture a num_iters window starting at the next
        # iteration boundary, then remove the file
        trigger_file=True,
        num_iters=5,
        # auto-trigger: an iteration slower than slow_iter_factor x the
        # iteration-time EWMA starts a capture (None = off). Detection is
        # host wall-clock between iteration boundaries — no device syncs.
        slow_iter_factor=None,
        max_auto_captures=2,  # bound auto captures per run
    ),
    profiler=Config(
        enabled=False,     # legacy fixed trace window (SURVEY.md §5.1);
        start_iter=20,     # still honored — captures now land under
        num_iters=5,       # telemetry/profiles/ with the on-demand ones
    ),
    publish=Config(
        # live parameter publishing (reference: the learner published every
        # publish_interval and agents/evals attached to the running session,
        # SURVEY.md §3.4/§2.1 PS row). When enabled the session starts a
        # ParameterPublisher + ParameterServer and publishes the agent's
        # acting view every N iterations; the server address lands in
        # <folder>/param_server.json so `surreal_tpu actor` / `eval
        # --follow` processes can discover it.
        enabled=False,
        every_n_iters=1,
        bind="tcp://127.0.0.1:*",  # REP endpoint(s) served to actor/eval
                                   # clients; set a real interface for
                                   # cross-machine actors
        # parameter FANOUT (distributed/param_fanout.py): versioned
        # weight frames over pub/sub — publish bytes scale with one
        # encode + N subscribes instead of N full-pytree fetch pickles.
        # wire='bf16' casts floating leaves to bfloat16 on the wire (f32
        # reconstruct, ops/precision.py's bf16 dtype); delta=true encodes
        # zlib'd deltas against the subscribers' acked version (a stale
        # ack re-keys with a full frame; a subscriber that missed a frame
        # falls back to ParameterClient.fetch — counted, never silent).
        fanout=Config(
            enabled=False,
            wire="f32",      # 'f32' | 'bf16'
            delta=True,
            ack_ttl_s=60.0,  # acks older than this don't pin full frames
        ),
    ),
    seed=0,
)


def base_config() -> Config:
    """The three-tree default bundle.

    ``learner_config`` is deliberately EMPTY here: the learner tree layers
    as user-overrides -> per-algorithm defaults -> BASE_LEARNER_CONFIG
    inside ``learners.build_learner``. Materializing BASE defaults into the
    user tree at bundle time would turn them into explicit "user" values
    that silently stomp per-algorithm defaults (e.g. IMPALA's lr)."""
    return Config(
        learner_config=Config(),
        env_config=BASE_ENV_CONFIG,
        session_config=BASE_SESSION_CONFIG,
    )
