"""Incident engine: detector firings -> root-caused incident reports
(ISSUE 15).

The :class:`~surreal_tpu.session.watchdog.Watchdog` says *something is
anomalous*; this module says *what probably caused it*. Once per ops
snapshot the engine consumes the sweep's firings:

- **lifecycle** — firings with no open incident OPEN one; further
  firings extend it; ``close_windows`` consecutive clean sweeps CLOSE it
  (sustained-healthy, not first-quiet-window). Each transition is a
  counted telemetry event (``incident_open`` / ``incident_update`` /
  ``incident_close``) and the full record is (re)written atomically to
  ``<folder>/telemetry/incidents/incident-<n>.json``.
- **correlation** — evidence inside a bounded time window around the
  incident: chaos fault injections, recovery-guard trips, per-tenant SLO
  breaches from the snapshot's table, DEAD tiers, and the slowest recent
  exemplar span trees (trace ids included, so ``surreal_tpu trace``
  picks up where ``why`` leaves off).
- **causality** — a static dataflow graph of the tiers
  (workers->fleet->gateway for the act path; sender->shard->sampler->
  learner->fanout->fleet for the experience/param loop) ranks cause
  hypotheses upstream-first: a tier with hard evidence (injected fault,
  DEAD) that sits upstream of the symptomatic tiers outranks the tier
  that merely *shows* the symptom.
- **auto-capture** — one ProfileManager capture + one flight-recorder
  dump per incident, cooldown- and count-bounded, linked from the
  incident record.

``incidents_report`` / ``incidents_brief`` at the bottom are the
``surreal_tpu why`` renderers — pure file reading (no jax, no zmq),
same discipline as ``top``/``trace``, reused by ``diag``/``top``'s
"Incidents" section.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque

from surreal_tpu.session.costs import GAUGE_REGISTRY

INCIDENTS_DIR = "incidents"  # <folder>/telemetry/incidents/

# static dataflow causality graph: tier -> the tiers immediately UPSTREAM
# of it (the ones whose failure would surface as this tier's symptom).
# Act path: workers -> fleet -> gateway. Experience/param loop: workers
# (senders) -> experience (shards/sampler) -> learner -> param_fanout ->
# fleet (replicas apply the published weights).
UPSTREAM = {
    "gateway": ("fleet",),
    "fleet": ("workers", "param_fanout"),
    "learner": ("experience",),
    "experience": ("workers",),
    "param_fanout": ("learner",),
    "workers": (),
}

# chaos site -> the dataflow tier it injects into (utils/faults.py SITES)
SITE_TIER = {
    "trainer.iteration": "learner",
    "env_worker.step": "workers",
    "transport.send": "workers",
    "server.serve": "fleet",
    "param_service.reply": "param_fanout",
    "experience.shard": "experience",
    "experience.sample": "experience",
    "experience.send": "experience",
    "fleet.replica": "fleet",
    "param.publish": "param_fanout",
    "gateway.session": "gateway",
    "ops.push": "learner",
    "trace.emit": "learner",
    "watchdog.eval": "learner",
}

# SLO objective -> the tier that owns the contract
OBJECTIVE_TIER = {
    "act_rtt_p99_ms": "gateway",
    "attach_p99_ms": "gateway",
    "throttle_rate": "gateway",
    "staleness_updates": "param_fanout",
}


def upstream_closure(tier: str) -> set[str]:
    """Every tier transitively upstream of ``tier`` in the static graph."""
    seen: set[str] = set()
    stack = list(UPSTREAM.get(tier, ()))
    while stack:
        u = stack.pop()
        if u in seen:
            continue
        seen.add(u)
        stack.extend(UPSTREAM.get(u, ()))
    return seen


def unit_for(signal: str) -> str | None:
    """The display/threshold unit of a detector signal: the registered
    gauge unit when the signal IS a gauge, a suffix convention for the
    derived signals (``*_ms``, ``*_per_s``)."""
    rec = GAUGE_REGISTRY.get(signal)
    if isinstance(rec, dict):
        return rec.get("unit")
    if signal.endswith("_ms"):
        return "ms"
    if signal.endswith("_per_s") or signal == "throughput":
        return "steps/s"
    if signal == "mfu":
        return "ratio"
    return None


def rank_causes(detector_counts: dict, evidence: dict) -> list[dict]:
    """Upstream-first cause hypotheses from the accumulated detector
    firings and correlated evidence. Returns ``[{tier, score, reasons}]``
    best-first. Pure dict arithmetic (shared by the live engine and any
    offline re-ranking)."""
    scores: dict[str, float] = {}
    reasons: dict[str, list[str]] = {}

    def add(tier, pts, why):
        if not tier:
            return
        scores[tier] = scores.get(tier, 0.0) + pts
        r = reasons.setdefault(tier, [])
        if why not in r:
            r.append(why)

    fault_tiers: dict[str, int] = {}
    for ev in evidence.get("faults", ()):
        tier = SITE_TIER.get(str(ev.get("site", "")))
        if tier is None:
            continue
        n = fault_tiers.get(tier, 0)
        fault_tiers[tier] = n + 1
        add(
            tier, 3.0 if n == 0 else 0.5,
            f"injected fault {ev.get('kind', '?')} @ {ev.get('site')}",
        )
    dead_seen: set[str] = set()
    for name in evidence.get("dead_tiers", ()):
        tier = str(name).split(".", 1)[0]
        if tier in dead_seen:
            add(tier, 0.5, f"tier {name} DEAD")
        else:
            dead_seen.add(tier)
            add(tier, 2.5, f"tier {name} DEAD (3x cadence silent)")
    for key, n in (detector_counts or {}).items():
        det, _, rest = str(key).partition(":")
        if det == "liveness":
            continue  # dead tiers already scored above
        tier, _, signal = rest.partition(":")
        add(tier, 1.0, f"{det} firing on {signal} (x{n})")
    slo_objs: set[tuple] = set()
    for ev in evidence.get("slo_breaches", ()):
        key = (ev.get("tenant"), ev.get("objective"))
        if key in slo_objs:
            continue
        slo_objs.add(key)
        add(
            OBJECTIVE_TIER.get(str(ev.get("objective"))), 0.75,
            f"SLO breach {ev.get('objective')} (tenant {ev.get('tenant')})",
        )
    if evidence.get("recoveries"):
        add("learner", 1.5, "recovery guard tripped")

    # upstream-first: hard evidence upstream of a symptomatic tier
    # explains it — boost the upstream hypothesis per downstream symptom
    implicated = set(scores)
    for tier in list(implicated):
        ups = upstream_closure(tier)
        for upstream in ups & implicated:
            add(
                upstream, 0.5,
                f"upstream of symptomatic tier {tier}",
            )
    out = [
        {"tier": t, "score": round(s, 2), "reasons": reasons.get(t, [])}
        for t, s in scores.items()
    ]
    out.sort(key=lambda h: (-h["score"], h["tier"]))
    return out


class IncidentEngine:
    """Owns the incident lifecycle for one run (constructed by
    SessionHooks next to the Watchdog)."""

    def __init__(self, folder=None, cfg=None, on_event=None, profile=None,
                 flightrec=None, exemplar_source=None, trace_id=None):
        cfg = cfg or {}
        get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: d
        self.folder = folder
        self.trace_id = trace_id
        self._on_event = on_event
        self._profile = profile
        self._flightrec = flightrec
        self._exemplar_source = exemplar_source
        self.close_windows = max(1, int(get("close_windows", 5)))
        self.evidence_window_s = float(get("evidence_window_s", 120.0))
        self.update_every = max(1, int(get("update_every", 5)))
        self.max_captures = int(get("max_captures", 4))
        self.capture_cooldown_s = float(get("capture_cooldown_s", 60.0))
        self.max_detectors = int(get("max_detectors", 64))
        self._faults: deque = deque(maxlen=256)
        self._recoveries: deque = deque(maxlen=64)
        self._next_id = 1
        self._open: dict | None = None
        self._captures = 0
        self._last_capture = -1e18
        self.opened = 0
        self.closed = 0
        self._write_ok = folder is not None

    @property
    def open_incident(self) -> dict | None:
        """The currently open incident record (None between incidents) —
        the remediation engine's read surface."""
        return self._open

    def attach_action(self, summary: dict) -> None:
        """First-class action evidence (ISSUE 16): fold a remediation
        action summary into the open incident and persist immediately —
        an action must be visible in the record it answered, not only in
        the action journal. Verdict updates for an action id replace the
        earlier summary in place (one line per action in ``why``)."""
        inc = self._open
        if inc is None:
            return
        actions = inc["evidence"].setdefault("actions", [])
        summary = dict(summary)
        for i, a in enumerate(actions):
            if a.get("action") == summary.get("action"):
                actions[i] = summary
                break
        else:
            actions.append(summary)
        del actions[32:]
        self._write(inc)

    # -- evidence feeds (called by SessionHooks next to the ops feeds) -------
    def record_fault(self, ev: dict) -> None:
        rec = dict(ev)
        rec.setdefault("t", time.time())
        self._faults.append(rec)

    def record_recovery(self, ev: dict) -> None:
        rec = dict(ev)
        rec.setdefault("t", time.time())
        self._recoveries.append(rec)

    def _recent(self, dq, now: float) -> list[dict]:
        lo = now - self.evidence_window_s
        return [dict(ev) for ev in dq if float(ev.get("t", now)) >= lo]

    def _slowest_exemplars(self, limit: int = 4) -> list[dict]:
        if self._exemplar_source is None:
            return []
        try:
            spans = list(self._exemplar_source() or ())
        except Exception:
            return []
        timed = [s for s in spans if s.get("dur_ms") is not None]
        timed.sort(key=lambda s: -float(s["dur_ms"]))
        return [
            {
                "exemplar": s.get("exemplar"),
                "name": s.get("name"),
                "span": s.get("span"),
                "tier": s.get("tier"),
                "dur_ms": round(float(s["dur_ms"]), 3),
            }
            for s in timed[:limit]
        ]

    # -- lifecycle -----------------------------------------------------------
    def observe(self, firings: list[dict], snap: dict | None = None) -> None:
        """One post-sweep step: open/extend/close the incident and keep
        its persisted record current. Pure host work."""
        now = time.time()
        snap = snap or {}
        if self._open is None:
            if not firings:
                return
            self._open_incident(firings, snap, now)
            return
        inc = self._open
        if firings:
            inc["healthy_windows"] = 0
            inc["last_firing_t"] = now
            self._absorb(inc, firings, snap, now)
            inc["updates"] += 1
            if inc["updates"] % self.update_every == 0:
                top = inc["causes"][0] if inc["causes"] else {}
                if self._on_event is not None:
                    self._on_event("incident_update", id=inc["id"],
                                   detectors=len(inc["detector_counts"]),
                                   top_cause=top.get("tier"),
                                   updates=inc["updates"])
                self._write(inc)
        else:
            inc["healthy_windows"] += 1
            if inc["healthy_windows"] >= self.close_windows:
                self._close_incident(inc, now)
                return
        # backfill the auto-capture link once the profiler window lands
        prof = self._profile
        if (prof is not None
                and inc["artifacts"].get("profile") == "pending"
                and getattr(prof, "last_capture_dir", None)
                and os.path.basename(
                    str(prof.last_capture_dir)
                ) not in str(inc["artifacts"])):
            inc["artifacts"]["profile"] = prof.last_capture_dir
            self._write(inc)

    def _absorb(self, inc: dict, firings: list[dict], snap: dict,
                now: float) -> None:
        """Fold a sweep's firings + the snapshot's correlatable state
        into the open incident, re-ranking causes."""
        for f in firings:
            key = (
                f"{f.get('detector')}:{f.get('tier')}:{f.get('signal')}"
            )
            inc["detector_counts"][key] = (
                inc["detector_counts"].get(key, 0) + 1
            )
            f = dict(f)
            f.setdefault("unit", unit_for(str(f.get("signal"))))
            inc["detectors"].append(f)
            if f.get("detector") == "liveness":
                name = str(f.get("signal"))
                if name not in inc["evidence"]["dead_tiers"]:
                    inc["evidence"]["dead_tiers"].append(name)
        del inc["detectors"][:-self.max_detectors]
        inc["evidence"]["faults"] = self._recent(self._faults, now)
        inc["evidence"]["recoveries"] = self._recent(self._recoveries, now)
        breaches = inc["evidence"]["slo_breaches"]
        for tenant, row in (snap.get("slo") or {}).items():
            for objective, o in (row or {}).items():
                if not (isinstance(o, dict) and o.get("breached")):
                    continue
                rec = {
                    "tenant": tenant, "objective": objective,
                    "measured": o.get("measured"), "target": o.get("target"),
                    "t": now,
                }
                if not any(
                    b["tenant"] == tenant and b["objective"] == objective
                    for b in breaches
                ):
                    breaches.append(rec)
        del breaches[32:]
        inc["causes"] = rank_causes(inc["detector_counts"], inc["evidence"])

    def _open_incident(self, firings: list[dict], snap: dict,
                       now: float) -> None:
        n = self._next_id
        self._next_id += 1
        self.opened += 1
        inc = {
            "id": n, "status": "open", "trace": self.trace_id,
            "opened_t": now, "last_firing_t": now, "closed_t": None,
            "opened_iteration": snap.get("iteration"),
            "opened_seq": snap.get("seq"),
            "detectors": [], "detector_counts": {},
            "evidence": {
                "faults": [], "recoveries": [], "slo_breaches": [],
                "exemplars": self._slowest_exemplars(),
                "dead_tiers": [],
            },
            "causes": [], "artifacts": {"profile": None, "flightrec": None},
            "updates": 0, "healthy_windows": 0,
        }
        self._absorb(inc, firings, snap, now)
        # one profile capture + one flightrec dump per incident, bounded
        # by a run-wide count and a cooldown across incidents
        if (self._captures < self.max_captures
                and now - self._last_capture >= self.capture_cooldown_s):
            self._captures += 1
            self._last_capture = now
            if self._profile is not None and self._profile.request(
                f"incident{n}"
            ):
                inc["artifacts"]["profile"] = "pending"
            if self._flightrec is not None:
                inc["artifacts"]["flightrec"] = self._flightrec.dump(
                    "incident"
                )
        self._open = inc
        top = inc["causes"][0] if inc["causes"] else {}
        if self._on_event is not None:
            self._on_event(
                "incident_open", id=n,
                detectors=sorted(inc["detector_counts"]),
                top_cause=top.get("tier"), score=top.get("score"),
                iteration=snap.get("iteration"),
            )
        self._write(inc)

    def _close_incident(self, inc: dict, now: float) -> None:
        inc["status"] = "closed"
        inc["closed_t"] = now
        inc["causes"] = rank_causes(inc["detector_counts"], inc["evidence"])
        self.closed += 1
        self._open = None
        top = inc["causes"][0] if inc["causes"] else {}
        if self._on_event is not None:
            self._on_event(
                "incident_close", id=inc["id"],
                duration_s=round(now - inc["opened_t"], 3),
                top_cause=top.get("tier"),
                healthy_windows=inc["healthy_windows"],
            )
        self._write(inc)

    def close(self) -> None:
        """Session teardown: flush the open incident as-is (still
        ``open`` — a run ending mid-incident is itself evidence)."""
        if self._open is not None:
            self._write(self._open)

    # -- persistence ---------------------------------------------------------
    def _write(self, inc: dict) -> None:
        if not self._write_ok:
            return
        from surreal_tpu.session.telemetry import TELEMETRY_DIR

        folder = os.path.join(self.folder, TELEMETRY_DIR, INCIDENTS_DIR)
        path = os.path.join(folder, f"incident-{inc['id']}.json")
        try:
            os.makedirs(folder, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(inc, f, default=float)
            os.replace(tmp, path)  # readers never see a torn record
        except OSError:
            self._write_ok = False  # diagnosis must never kill training

    def gauges(self) -> dict[str, float]:
        """The engine's ``ops/*`` counters (GAUGE_REGISTRY documents
        each); merged into the learner's metrics row."""
        return {
            "ops/incidents_open": 1.0 if self._open is not None else 0.0,
            "ops/incidents_total": float(self.opened),
        }


# -- why (pure file reading, like top/trace) ----------------------------------


def load_incidents(folder: str) -> list[dict]:
    """Every persisted incident record under
    ``<folder>/telemetry/incidents/``, id order. Hostile-tolerant: a
    torn/foreign file is skipped, never a crash."""
    from surreal_tpu.session.telemetry import TELEMETRY_DIR

    inc_dir = os.path.join(folder, TELEMETRY_DIR, INCIDENTS_DIR)
    out = []
    try:
        names = os.listdir(inc_dir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("incident-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(inc_dir, name)) as f:
                rec = json.load(f)
            if isinstance(rec, dict) and rec.get("id") is not None:
                out.append(rec)
        except (OSError, json.JSONDecodeError):
            continue
    out.sort(key=lambda r: int(r["id"]))
    return out


def _fmt_value(v, unit) -> str:
    if v is None:
        return "?"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    s = f"{f:g}" if abs(f) < 1e6 else f"{f:,.0f}"
    return f"{s} {unit}" if unit else s


def _incident_lines(inc: dict, verbose: bool = True) -> list[str]:
    """One incident rendered for ``why`` (verbose) or the diag/top
    "Incidents" section (brief). The same renderer serves both so the
    views cannot drift."""
    opened = inc.get("opened_t")
    opened_s = (
        time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(float(opened)))
        if opened else "?"
    )
    status = str(inc.get("status", "open")).upper()
    dur = None
    if inc.get("closed_t") and opened:
        dur = float(inc["closed_t"]) - float(opened)
    head = (
        f"incident #{inc.get('id')} — {status}, opened {opened_s}"
        + (
            f" (iteration {inc['opened_iteration']})"
            if inc.get("opened_iteration") is not None else ""
        )
        + (f", closed after {dur:.1f} s" if dur is not None else "")
    )
    lines = [head]
    causes = inc.get("causes") or []
    ev = inc.get("evidence") or {}
    counts = inc.get("detector_counts") or {}
    if not verbose:
        top = causes[0] if causes else None
        lines.append(
            "  top cause: "
            + (
                f"{top['tier']} (score {top['score']:g})" if top
                else "(unranked)"
            )
            + " — evidence: "
            + ", ".join(
                f"{len(ev.get(k) or [])} {k}"
                for k in ("faults", "slo_breaches", "exemplars",
                          "dead_tiers", "recoveries", "actions")
                if ev.get(k)
            )
            + (f"; {len(counts)} detector(s)" if counts else "")
        )
        return lines
    if counts:
        lines.append("  detectors fired:")
        for key in sorted(counts):
            det, _, rest = key.partition(":")
            tier, _, signal = rest.partition(":")
            unit = unit_for(signal)
            last = next(
                (
                    d for d in reversed(inc.get("detectors") or [])
                    if d.get("signal") == signal
                    and d.get("detector") == det
                ),
                None,
            )
            detail = ""
            if last is not None:
                detail = (
                    f" — last {_fmt_value(last.get('value'), unit)}"
                    f" vs baseline "
                    f"{_fmt_value(last.get('baseline'), unit)}"
                )
            lines.append(
                f"    {det:<10} {signal:<28} tier {tier:<12} "
                f"x{counts[key]}{detail}"
            )
    if causes:
        lines.append("  ranked causes (upstream-first):")
        for i, c in enumerate(causes[:5], 1):
            lines.append(
                f"    {i}. {c.get('tier'):<12} score {c.get('score'):g}"
            )
            for r in (c.get("reasons") or [])[:4]:
                lines.append(f"       - {r}")
    kinds = []
    for kind, rows in (
        ("fault", ev.get("faults")),
        ("recovery", ev.get("recoveries")),
        ("slo_breach", ev.get("slo_breaches")),
        ("exemplar", ev.get("exemplars")),
    ):
        for row in rows or []:
            kinds.append((kind, row))
    if kinds or ev.get("dead_tiers"):
        lines.append("  correlated evidence:")
        for name in ev.get("dead_tiers") or []:
            lines.append(f"    dead_tier   {name}")
        for kind, row in kinds[:16]:
            if kind == "fault":
                lines.append(
                    f"    fault       {row.get('kind', '?')} @ "
                    f"{row.get('site', '?')}"
                )
            elif kind == "recovery":
                lines.append(
                    f"    recovery    {row.get('reason', '?')}"
                    + (
                        f" (iteration {row.get('iteration')})"
                        if row.get("iteration") is not None else ""
                    )
                )
            elif kind == "slo_breach":
                lines.append(
                    f"    slo_breach  {row.get('objective')} tenant "
                    f"{row.get('tenant')}: measured "
                    f"{_fmt_value(row.get('measured'), unit_for(str(row.get('objective'))))}"
                    f" > target "
                    f"{_fmt_value(row.get('target'), unit_for(str(row.get('objective'))))}"
                )
            else:
                lines.append(
                    f"    exemplar    {row.get('name', '?')} span "
                    f"{row.get('span')} ({row.get('exemplar')}) — "
                    f"{_fmt_value(row.get('dur_ms'), 'ms')}, tier "
                    f"{row.get('tier', '?')}"
                )
    actions = ev.get("actions") or []
    if actions:
        lines.append("  actions taken (cause -> action -> verdict):")
        for a in actions[:8]:
            lines.append(
                f"    #{a.get('action')} {a.get('cause_tier', '?'):<12}"
                f" -> {a.get('kind', '?'):<18}"
                f" {a.get('detail', '')}"
                f" -> {a.get('verdict') or 'verifying'}"
                + (" (reverted)" if a.get("reverted") else "")
            )
    arts = inc.get("artifacts") or {}
    art_bits = [
        f"{k} {v}" for k, v in sorted(arts.items())
        if v and v != "pending"
    ]
    if art_bits:
        lines.append("  captured artifacts: " + "; ".join(art_bits))
    elif arts.get("profile") == "pending":
        lines.append("  captured artifacts: profile capture pending")
    return lines


def incidents_report(folder: str, incident: int | None = None) -> str | None:
    """The ``surreal_tpu why`` view: every incident's timeline —
    detector firings, ranked causes, correlated evidence with trace ids,
    artifact links. ``incident`` narrows to one id. None when the folder
    has no telemetry at all (mirrors ``trace``); a telemetry folder with
    zero incidents renders an explicit all-clear."""
    from surreal_tpu.session.telemetry import TELEMETRY_DIR

    if not os.path.isdir(os.path.join(folder, TELEMETRY_DIR)):
        return None
    incidents = load_incidents(folder)
    header = f"surreal_tpu why — {folder}"
    trace = next((i.get("trace") for i in incidents if i.get("trace")), None)
    if trace:
        header += f" (trace {trace})"
    lines = [header]
    if incident is not None:
        incidents = [i for i in incidents if int(i["id"]) == int(incident)]
        if not incidents:
            lines.append(f"  no incident #{incident} recorded")
            return "\n".join(lines)
    if not incidents:
        lines.append(
            "  no incidents recorded — every watchdog sweep came back "
            "healthy (or session_config.watchdog.enabled=false)"
        )
        return "\n".join(lines)
    n_open = sum(1 for i in incidents if i.get("status") == "open")
    lines.append(
        f"{len(incidents)} incident(s), {n_open} open"
    )
    for inc in incidents:
        lines.append("")
        lines += _incident_lines(inc, verbose=True)
    # the run-level Actions section (ISSUE 16): the remediation journal
    # rendered cause -> action -> verdict, incident-filtered when one id
    # was requested (pure file reading, same discipline as the rest)
    from surreal_tpu.session.remediate import actions_report_lines

    act_lines = actions_report_lines(folder, incident=incident)
    if act_lines:
        lines.append("")
        lines += act_lines
    return "\n".join(lines)


def incidents_brief(folder: str, limit: int = 4) -> list[str]:
    """The diag/top "Incidents" section: newest ``limit`` incidents, one
    brief block each (same renderer as ``why``). Empty list when none
    were recorded — the section simply doesn't appear."""
    incidents = load_incidents(folder)
    if not incidents:
        return []
    n_open = sum(1 for i in incidents if i.get("status") == "open")
    lines = [
        f"  {len(incidents)} incident(s) recorded, {n_open} open "
        "(full report: `surreal_tpu why <folder>`)"
    ]
    for inc in incidents[-limit:]:
        for ln in _incident_lines(inc, verbose=False):
            lines.append("  " + ln)
    return lines
