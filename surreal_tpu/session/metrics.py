"""Metrics writer (parity: the reference's tensorplex + loggerplex +
tensorboard trio, SURVEY.md §5.5 and §2.2).

The reference ran three separate observability *processes*: tensorplex
(scalar aggregation across workers), loggerplex (remote text logs) and a
tensorboard server, wired over ZMQ. The rebuild is one SPMD program, so the
whole trio collapses into one in-process writer:

- cross-worker averaging  -> :class:`~surreal_tpu.session.tracker.MetricAggregator`
  (tensorplex's averaging groups, already local)
- scalar event stream     -> tensorboard event files written directly
  (``<folder>/tb/``), readable by any stock tensorboard
- remote text logging     -> :func:`get_logger` writing console +
  ``<folder>/logs/<name>.log``

Honors ``session_config.metrics.tensorboard`` / ``.console``. The
tensorboard backend degrades to a no-op (with one warning) if the
``tensorboard`` package is unavailable, so headless images still train.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Mapping

_TB_IMPORT_ERROR = None
try:  # tensorboard is present in this image; guard anyway (graceful headless)
    from tensorboard.compat.proto.event_pb2 import Event
    from tensorboard.compat.proto.summary_pb2 import Summary
    from tensorboard.summary.writer.event_file_writer import EventFileWriter
except Exception as e:  # pragma: no cover - exercised only without tensorboard
    _TB_IMPORT_ERROR = e


class MetricsWriter:
    """Scalar metrics sink for one experiment session.

    ``write(step, metrics)`` fans each float out to the enabled backends;
    tags keep their namespaced form (``loss/total``, ``episode/return``,
    ``eval/return`` — the role the reference's tensorplex groups played).
    """

    def __init__(
        self,
        folder: str,
        tensorboard: bool = True,
        console: bool = True,
        name: str = "train",
    ):
        self.folder = folder
        self.console = console
        self._tb = None
        if tensorboard:
            if _TB_IMPORT_ERROR is not None:
                logging.getLogger("surreal_tpu").warning(
                    "metrics.tensorboard=True but tensorboard is not "
                    "importable (%s); scalar events disabled",
                    _TB_IMPORT_ERROR,
                )
            else:
                tb_dir = os.path.join(folder, "tb", name)
                os.makedirs(tb_dir, exist_ok=True)
                self._tb = EventFileWriter(tb_dir)

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        clean = {
            k: float(v)
            for k, v in metrics.items()
            if float(v) == float(v)  # drop NaN (windows with no episodes)
        }
        if self._tb is not None:
            event = Event(
                step=int(step),
                summary=Summary(
                    value=[
                        Summary.Value(tag=k, simple_value=v)
                        for k, v in clean.items()
                    ]
                ),
            )
            event.wall_time = time.time()
            self._tb.add_event(event)
        if self.console:
            parts = " ".join(f"{k}={v:.4g}" for k, v in sorted(clean.items()))
            print(f"[{step}] {parts}", flush=True)

    def flush(self) -> None:
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        if self._tb is not None:
            self._tb.flush()
            self._tb.close()
            self._tb = None

    def __enter__(self) -> "MetricsWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def make_metrics_writer(session_config, name: str = "train") -> MetricsWriter:
    """Build a writer from a ``session_config`` tree (the one call sites use)."""
    m = session_config.metrics
    return MetricsWriter(
        session_config.folder,
        tensorboard=m.tensorboard,
        console=m.console,
        name=name,
    )


def get_logger(name: str, folder: str | None = None) -> logging.Logger:
    """Structured text logging (loggerplex role): console + per-session file
    ``<folder>/logs/<name>.log``. Idempotent per (name, folder); a call with
    a *different* folder retargets the file handler (closing the old one)
    so sequential sessions in one process never cross-write logs."""
    logger = logging.getLogger(f"surreal_tpu.{name}")
    logger.setLevel(logging.INFO)
    logger.propagate = False
    fmt = logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s %(message)s", "%H:%M:%S"
    )
    have = {getattr(h, "_surreal_id", None) for h in logger.handlers}
    if "console" not in have:
        h = logging.StreamHandler()
        h.setFormatter(fmt)
        h._surreal_id = "console"
        logger.addHandler(h)
    if folder is not None:
        log_dir = os.path.join(folder, "logs")
        file_id = f"file:{log_dir}"
        if file_id not in have:
            for stale in [
                h
                for h in logger.handlers
                if str(getattr(h, "_surreal_id", "")).startswith("file:")
            ]:
                logger.removeHandler(stale)
                stale.close()
            os.makedirs(log_dir, exist_ok=True)
            h = logging.FileHandler(os.path.join(log_dir, f"{name}.log"))
            h.setFormatter(fmt)
            h._surreal_id = file_id
            logger.addHandler(h)
    return logger
