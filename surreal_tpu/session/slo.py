"""Per-tenant SLO objectives with rolling error budgets (ISSUE 13).

The gateway made the run multi-tenant (PR 12); this module makes the
tenants' experience *contractual*: declared ``session_config.slo.*``
objectives are evaluated once per ops-plane snapshot window against the
gateway's live per-tenant stats and hop percentiles, each (tenant,
objective) pair carries a rolling error budget over the last
``budget_windows`` evaluations, and every breach is a counted, never-
silent ``slo_breach`` telemetry event. Budget *exhaustion* is
edge-triggered back to the caller (the OpsAggregator) so it can dump the
flight recorder exactly once per incident, not once per window.

Objectives (a ``None`` target disables that objective — the default, so
an unconfigured run evaluates nothing and emits nothing):

    act_rtt_p99_ms     gateway act serve p99 (``gateway_act_ms`` hop)
    attach_p99_ms      session attach/hello p99 (``gateway_attach_ms``)
    throttle_rate      per-tenant fraction of acts throttled this window
                       (counter deltas: throttled / (throttled + acts))
    staleness_updates  published-vs-pinned parameter-version lag
                       (run-wide, derived by the aggregator)

Latency objectives are gateway-wide measurements applied to every tenant
attached in the window (the gateway serves all tenants from one loop, so
per-tenant latency IS the loop's latency); ``throttle_rate`` is truly
per-tenant. Pure host python — no jax, no device syncs (the transfer
guard covers the whole snapshot path).
"""

from __future__ import annotations

from collections import deque

# objective name -> config key (identical today; the indirection keeps
# config spelling stable if objective internals are renamed)
OBJECTIVES = (
    "act_rtt_p99_ms",
    "attach_p99_ms",
    "throttle_rate",
    "staleness_updates",
)


class SLOTracker:
    """Rolling per-(tenant, objective) breach windows + error budgets.

    ``evaluate`` is called once per snapshot window by the OpsAggregator;
    everything else is bookkeeping readable by ``gauges``/``table``.
    """

    def __init__(self, cfg=None, on_event=None):
        cfg = cfg or {}
        get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: d
        self.enabled = bool(get("enabled", True))
        # budget: the fraction of the last ``budget_windows`` evaluation
        # windows allowed to breach before the budget is exhausted
        self.budget_windows = max(1, int(get("budget_windows", 20)))
        self.budget = float(get("budget", 0.2))
        self.targets = {
            name: get(name, None)
            for name in OBJECTIVES
            if get(name, None) is not None
        }
        self._on_event = on_event
        # (tenant, objective) -> deque[bool] of per-window breach verdicts
        self._verdicts: dict[tuple[str, str], deque] = {}
        # (tenant, objective) pairs whose budget is currently exhausted —
        # membership edge-triggers the flight-recorder dump
        self._exhausted: set[tuple[str, str]] = set()
        # per-tenant previous counter values for window deltas
        self._prev: dict[str, dict[str, float]] = {}
        self.breaches = 0
        self.exhaustions = 0

    @property
    def active(self) -> bool:
        return self.enabled and bool(self.targets)

    # -- measurement ---------------------------------------------------------
    def _measured(self, name: str, tenant: str, stats: dict,
                  hops: dict, derived: dict):
        """The window's measured value for one objective, or None when the
        inputs carry no data (no data is NOT a breach)."""
        if name == "act_rtt_p99_ms":
            st = hops.get("gateway_act_ms")
            return float(st["p99"]) if isinstance(st, dict) else None
        if name == "attach_p99_ms":
            st = hops.get("gateway_attach_ms")
            return float(st["p99"]) if isinstance(st, dict) else None
        if name == "throttle_rate":
            prev = self._prev.setdefault(tenant, {})
            d_thr = float(stats.get("throttled", 0)) - prev.get("throttled", 0.0)
            d_act = float(stats.get("acts", 0)) - prev.get("acts", 0.0)
            if d_thr <= 0 and d_act <= 0:
                return None  # idle tenant this window
            return d_thr / max(1.0, d_thr + d_act)
        if name == "staleness_updates":
            v = derived.get("staleness_updates")
            return float(v) if v is not None else None
        return None

    # -- evaluation ----------------------------------------------------------
    def evaluate(self, tenants: dict, hops: dict | None = None,
                 derived: dict | None = None) -> tuple[dict, list]:
        """One evaluation window. Returns ``(table, newly_exhausted)``:
        the snapshot's per-tenant SLO table and the (tenant, objective)
        pairs whose budget exhausted THIS window (edge-triggered)."""
        hops = hops or {}
        derived = derived or {}
        table: dict[str, dict] = {}
        newly_exhausted: list[tuple[str, str]] = []
        if not self.active:
            return table, newly_exhausted
        allowed = max(1.0, self.budget * self.budget_windows)
        for tenant in sorted(tenants or {}):
            stats = tenants[tenant] or {}
            row: dict[str, dict] = {}
            for name, target in self.targets.items():
                measured = self._measured(name, tenant, stats, hops, derived)
                if measured is None:
                    continue
                breached = measured > float(target)
                window = self._verdicts.setdefault(
                    (tenant, name), deque(maxlen=self.budget_windows)
                )
                window.append(breached)
                used = sum(window) / allowed
                exhausted = used >= 1.0
                key = (tenant, name)
                if exhausted and key not in self._exhausted:
                    self._exhausted.add(key)
                    self.exhaustions += 1
                    newly_exhausted.append(key)
                elif not exhausted:
                    self._exhausted.discard(key)
                if breached:
                    self.breaches += 1
                    if self._on_event is not None:
                        # counted, never silent: every breached window is
                        # one slo_breach event in the telemetry spine
                        self._on_event(
                            "slo_breach", tenant=tenant, objective=name,
                            measured=round(float(measured), 4),
                            target=float(target),
                            budget_used=round(used, 3),
                            exhausted=exhausted,
                        )
                row[name] = {
                    "measured": round(float(measured), 4),
                    "target": float(target),
                    "breached": breached,
                    "budget_used": round(used, 3),
                    "exhausted": exhausted,
                }
            if row:
                table[tenant] = row
            # window counter baselines advance regardless of verdicts
            self._prev[tenant] = {
                "throttled": float(stats.get("throttled", 0)),
                "acts": float(stats.get("acts", 0)),
            }
        return table, newly_exhausted

    def gauges(self) -> dict[str, float]:
        return {
            "slo/breaches": float(self.breaches),
            "slo/exhaustions": float(self.exhaustions),
            "slo/objectives": float(len(self.targets)),
        }
