"""Telemetry spine: span tracing, a JSONL event log, per-host heartbeats,
and the ``diag`` report (parity-plus: the reference ran a whole
observability *process trio* — tensorplex/loggerplex/tensorboard,
SURVEY.md §5.5 — whose scalars flow through ``session/metrics.py``; this
module adds the structural signals that trio never had: phase-level wall
time, training-health summaries, and multi-host liveness, all readable
offline from ``<folder>/telemetry/``).

Fence discipline (the round-5 landmines this design encodes):

- host clocks NEVER enter jitted-step modules — a ``time.time()`` traced
  inside jit runs once at compile and lies forever, and
  ``jax.block_until_ready`` both serializes the async pipeline and does
  not actually wait on this image's tunneled backend (the ~1000x
  pre-round-3 inflation). ``tests/test_import_hygiene.py`` lints for both.
- hot-loop spans are UNFENCED: a span around an async-dispatched jit call
  measures dispatch time for that call, but jax's bounded in-flight queue
  applies backpressure, so per-window TOTALS converge to real wall time;
  the one true fence per window stays the metrics-cadence sync that
  already existed (``SessionHooks.end_iteration``'s ``float()``
  conversion). ``span(..., block_on=pytree)`` is available for callers
  that ARE at a fence boundary (``utils/timer.py``'s rule).
- JSONL volume is bounded by cadence, not by iteration rate: spans
  accumulate in-memory per phase and are written as ONE ``phases`` event
  per ``flush_phases`` call (the metrics cadence); only low-frequency
  side-band spans (eval, checkpoint, publish) emit individual ``span``
  events via ``emit=True``.

Event schema (``<folder>/telemetry/events.jsonl``, one JSON object per
line, ``t`` = unix seconds):

    {"type": "session",   "t": ..., "name": "train", "pid": ...}
    {"type": "phases",    "t": ..., "step": ..., "phases":
        {"<phase>": {"count": N, "total_s": S, "max_ms": M}}}
    {"type": "span",      "t": ..., "name": "...", "dur_s": ...}
    {"type": "metrics",   "t": ..., "step": ..., "values": {...}}
    {"type": "compile_cache", "t": ..., "dir": "...", "hits": H,
     "misses": M}   (cumulative; written by SessionHooks when
                     session.compile_cache_dir is active)
    {"type": "data_plane", "t": ..., "transport": "...", "pipeline": ...,
     "shm_workers": N, "pickle_workers": M, "wire_bytes_per_step": B,
     ...}           (SEED drivers via SessionHooks.data_plane_event; the
                     last event reflects the settled negotiation)
    {"type": "tune", "t": ..., "mode": "cache|search", "hit": ...,
     "source": "...", "config": {...}, ["trials": [...], ...]}
                    (autotuner decisions: trainers via
                     SessionHooks.tune_event at build, the `surreal_tpu
                     tune` CLI with full candidate timings; diag reports
                     the last one plus hit/miss counts)
    {"type": "recovery", "t": ..., "kind": "interrupt|tripped|rollback|
     checkpoint_fallback|skipped_nonfinite_checkpoint|giveup", ...}
                    (the fault-tolerance layer: preemption sentinel stops,
                     divergence-guard trips/rollbacks with lr_scale and
                     the restored step, damaged-checkpoint fallbacks —
                     session/interrupt.py, launch/recovery.py,
                     session/checkpoint.py)
    {"type": "fault", "t": ..., "site": "...", "kind": "...", "call": N}
                    (chaos-harness injections that actually fired,
                     utils/faults.py — drained into the spine by
                     SessionHooks so a chaos run documents what it
                     survived)

Heartbeats live per rank in ``telemetry/heartbeat_rank<k>.jsonl``:

    {"type": "heartbeat", "t": ..., "rank": R, "iteration": I,
     "env_steps": E}

``python -m surreal_tpu diag <folder>`` (``main/launch.py``) renders
:func:`diag_report` over these files: phase-time breakdown, health-signal
summary (the in-graph ``health/*`` diagnostics from
``learners/base.py::training_health``), and a last-heartbeat table.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from contextlib import contextmanager

TELEMETRY_DIR = "telemetry"
EVENTS_FILE = "events.jsonl"


class Tracer:
    """Span tracing + JSONL event log for one session (rank 0 owns it,
    exactly like the MetricsWriter; disabled tracers are free no-ops so
    driver loops on ranks > 0 share the same code path).

    Thread-safe: the host-overlap collector thread and the SEED server
    side-bands record spans concurrently with the main loop.
    """

    def __init__(self, folder: str | None, enabled: bool = True,
                 name: str = "train"):
        self.enabled = bool(enabled) and folder is not None
        self._lock = threading.Lock()
        self._phases: dict[str, list] = {}  # name -> [count, total_s, max_s]
        self._f = None
        self.path = None
        if self.enabled:
            try:
                tel_dir = os.path.join(folder, TELEMETRY_DIR)
                os.makedirs(tel_dir, exist_ok=True)
                self.path = os.path.join(tel_dir, EVENTS_FILE)
                self._f = open(self.path, "a", buffering=1)  # line-buffered
            except OSError:
                # telemetry must never kill training (e.g. read-only FS)
                self.enabled = False
                self._f = None
        if self.enabled:
            self.event("session", name=name, pid=os.getpid())

    # -- raw events ----------------------------------------------------------
    def event(self, type_: str, **fields) -> None:
        """Append one event line. Fields must be JSON-serializable."""
        if not self.enabled:
            return
        line = json.dumps({"type": type_, "t": time.time(), **fields},
                          default=float)
        with self._lock:
            if self._f is None:
                return
            try:
                self._f.write(line + "\n")
            except OSError:
                # telemetry must never kill training: a mid-run disk-full/
                # mount hiccup disables the log instead of propagating
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
                self.enabled = False

    # -- spans ---------------------------------------------------------------
    @contextmanager
    def span(self, name: str, block_on=None, emit: bool = False):
        """Time a region into the ``name`` phase accumulator.

        ``block_on``: pytree of device arrays to ``jax.block_until_ready``
        before stopping the clock (ONLY for fence-boundary callers — see
        the module doc). ``emit=True`` additionally writes an individual
        ``span`` event (low-frequency side-bands only).
        """
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if block_on is not None:
                import jax

                jax.block_until_ready(block_on)
            dur = time.perf_counter() - t0
            with self._lock:
                st = self._phases.setdefault(name, [0, 0.0, 0.0])
                st[0] += 1
                st[1] += dur
                st[2] = max(st[2], dur)
            if emit:
                self.event("span", name=name, dur_s=dur)

    def flush_phases(self, step) -> dict[str, float]:
        """Write one ``phases`` event for the window since the last flush
        and return ``time/<phase>_ms`` mean-per-call scalars — the mirror
        the caller merges into the MetricsWriter stream. Resets the
        window. Called at the metrics cadence by SessionHooks."""
        with self._lock:
            phases = {
                k: {"count": c, "total_s": t, "max_ms": mx * 1e3}
                for k, (c, t, mx) in self._phases.items()
            }
            self._phases.clear()
        if not phases:
            return {}
        self.event("phases", step=int(step), phases=phases)
        return {
            f"time/{k}_ms": v["total_s"] / max(v["count"], 1) * 1e3
            for k, v in phases.items()
        }

    def log_metrics(self, step, metrics) -> None:
        """Mirror one synced metrics row into the event log (what ``diag``
        reads for the health summary)."""
        if not self.enabled or not metrics:
            return
        self.event(
            "metrics", step=int(step),
            values={k: float(v) for k, v in metrics.items()},
        )

    def close(self) -> None:
        # flush the tail window first: a run shorter than one metrics
        # cadence (or one that crashed into its finally-close) must still
        # record the spans it accumulated. step=-1 marks an at-close
        # flush; diag ignores it for last-step reporting.
        self.flush_phases(step=-1)
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
        self.enabled = False


class HeartbeatWriter:
    """Per-host liveness events for multi-host runs: each rank appends to
    its OWN ``telemetry/heartbeat_rank<k>.jsonl`` (no cross-rank
    coordination — a wedged rank is visible precisely because it stops
    writing). Ranks whose host cannot write the session folder disable
    themselves silently: ranks > 0 are not required to mount it
    (launch/multihost_trainer.py's session discipline)."""

    def __init__(self, folder: str | None, rank: int, every_s: float = 10.0,
                 enabled: bool = True):
        self.rank = int(rank)
        self.every_s = float(every_s)
        self._last: float | None = None
        self._path = None
        if enabled and folder:
            try:
                tel_dir = os.path.join(folder, TELEMETRY_DIR)
                os.makedirs(tel_dir, exist_ok=True)
                self._path = os.path.join(
                    tel_dir, f"heartbeat_rank{self.rank}.jsonl"
                )
                with open(self._path, "a"):
                    pass  # probe writability up front
            except OSError:
                self._path = None

    def beat(self, iteration: int, env_steps: int, force: bool = False) -> None:
        """Append a heartbeat, time-throttled to ``every_s`` (call it every
        iteration; it is a no-op between beats)."""
        if self._path is None:
            return
        now = time.monotonic()
        if not force and self._last is not None and now - self._last < self.every_s:
            return
        self._last = now
        rec = {
            "type": "heartbeat", "t": time.time(), "rank": self.rank,
            "iteration": int(iteration), "env_steps": int(env_steps),
        }
        try:
            with open(self._path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            self._path = None  # host lost the folder; stop trying


# -- diag --------------------------------------------------------------------

_HEALTH_PREFIXES = ("health/", "loss/", "policy/kl", "episode/return")


def _iter_jsonl(path):
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line from a live/killed session
    except OSError:
        return


def diag_summary(folder: str) -> dict | None:
    """Aggregate the session's telemetry files into one dict, or None when
    no event log exists. Pure file reading — no jax, safe off-chip."""
    events_path = os.path.join(folder, TELEMETRY_DIR, EVENTS_FILE)
    events = list(_iter_jsonl(events_path))
    hb_paths = sorted(
        glob.glob(os.path.join(folder, TELEMETRY_DIR, "heartbeat_rank*.jsonl"))
    )
    if not events and not hb_paths:
        return None

    phases: dict[str, dict] = {}
    health: dict[str, dict] = {}
    compile_cache = None
    data_plane = None
    tune = None
    tune_hits = tune_misses = 0
    recovery_counts: dict[str, int] = {}
    recovery_last = None
    fault_count = 0
    fault_sites: dict[str, int] = {}
    fault_last = None
    nonfinite_windows = 0
    t_first = t_last = None
    last_step = None
    for ev in events:
        t = ev.get("t")
        if isinstance(t, (int, float)):
            t_first = t if t_first is None else min(t_first, t)
            t_last = t if t_last is None else max(t_last, t)
        if ev.get("type") == "phases":
            step = ev.get("step")
            if isinstance(step, int) and step >= 0:  # -1 = at-close flush
                last_step = step
            for name, st in (ev.get("phases") or {}).items():
                agg = phases.setdefault(
                    name, {"count": 0, "total_s": 0.0, "max_ms": 0.0}
                )
                agg["count"] += int(st.get("count", 0))
                agg["total_s"] += float(st.get("total_s", 0.0))
                agg["max_ms"] = max(agg["max_ms"], float(st.get("max_ms", 0.0)))
        elif ev.get("type") == "compile_cache":
            # counters are cumulative; the last event is the session total
            compile_cache = {
                "dir": ev.get("dir"),
                "hits": int(ev.get("hits", 0)),
                "misses": int(ev.get("misses", 0)),
            }
        elif ev.get("type") == "data_plane":
            # the last event is the settled negotiation (SEED drivers emit
            # one after the first learn and one at run end)
            data_plane = {
                k: v for k, v in ev.items() if k not in ("type", "t")
            }
        elif ev.get("type") == "tune":
            # the last event is the active decision; hit/miss counts
            # accumulate over the session (trainer builds + CLI runs)
            tune = {k: v for k, v in ev.items() if k not in ("type", "t")}
            if ev.get("hit"):
                tune_hits += 1
            else:
                tune_misses += 1
        elif ev.get("type") == "recovery":
            kind = str(ev.get("kind", "?"))
            recovery_counts[kind] = recovery_counts.get(kind, 0) + 1
            recovery_last = {
                k: v for k, v in ev.items() if k not in ("type", "t")
            }
        elif ev.get("type") == "fault":
            fault_count += 1
            site = str(ev.get("site", "?"))
            fault_sites[site] = fault_sites.get(site, 0) + 1
            fault_last = {
                k: v for k, v in ev.items() if k not in ("type", "t")
            }
        elif ev.get("type") == "metrics":
            last_step = ev.get("step", last_step)
            vals = ev.get("values") or {}
            if vals.get("health/nonfinite", 0):
                nonfinite_windows += 1
            for k, v in vals.items():
                if not isinstance(v, (int, float)):
                    continue
                if not any(k.startswith(p) or k == p for p in _HEALTH_PREFIXES):
                    continue
                if v != v:  # NaN rows carry no summary information
                    continue
                h = health.setdefault(
                    k, {"last": v, "min": v, "max": v, "n": 0}
                )
                h["last"] = v
                h["min"] = min(h["min"], v)
                h["max"] = max(h["max"], v)
                h["n"] += 1

    heartbeats = {}
    for path in hb_paths:
        last = None
        for rec in _iter_jsonl(path):
            if rec.get("type") == "heartbeat":
                last = rec
        if last is not None:
            heartbeats[int(last.get("rank", -1))] = last

    return {
        "folder": folder,
        "events": len(events),
        "wall_s": (t_last - t_first) if (t_first is not None and t_last is not None) else 0.0,
        "last_step": last_step,
        "phases": phases,
        "health": health,
        "compile_cache": compile_cache,
        "data_plane": data_plane,
        "tune": tune,
        "tune_hits": tune_hits,
        "tune_misses": tune_misses,
        "recovery": (
            {"counts": recovery_counts, "last": recovery_last}
            if recovery_counts else None
        ),
        "faults": (
            {"count": fault_count, "by_site": fault_sites, "last": fault_last}
            if fault_count else None
        ),
        "nonfinite_windows": nonfinite_windows,
        "heartbeats": heartbeats,
    }


def diag_report(folder: str) -> str | None:
    """Human-readable diag: phase-time breakdown, health summary,
    last-heartbeat table. None when the folder has no telemetry."""
    s = diag_summary(folder)
    if s is None:
        return None
    wall = s["wall_s"]
    lines = [
        f"Telemetry diag — {s['folder']}",
        f"{s['events']} events over {wall:.1f} s"
        + (f", last step {s['last_step']}" if s["last_step"] is not None else ""),
        "",
        "Phase-time breakdown",
    ]
    if s["phases"]:
        lines.append(
            f"  {'phase':<20} {'calls':>8} {'total s':>10} {'mean ms':>10} "
            f"{'max ms':>10} {'% wall':>7}"
        )
        for name, st in sorted(
            s["phases"].items(), key=lambda kv: -kv[1]["total_s"]
        ):
            mean_ms = st["total_s"] / max(st["count"], 1) * 1e3
            pct = 100.0 * st["total_s"] / wall if wall > 0 else 0.0
            lines.append(
                f"  {name:<20} {st['count']:>8} {st['total_s']:>10.2f} "
                f"{mean_ms:>10.2f} {st['max_ms']:>10.2f} {pct:>6.1f}%"
            )
        lines.append(
            "  (device-loop phases measure async dispatch; window totals "
            "are honest under backpressure — see session/telemetry.py)"
        )
    else:
        lines.append("  (no phase windows recorded)")
    cc = s.get("compile_cache")
    if cc is not None:
        total = cc["hits"] + cc["misses"]
        lines += [
            "",
            f"Compile cache — {cc.get('dir')}",
            f"  {cc['hits']} hits / {cc['misses']} misses"
            + (
                f" ({100.0 * cc['hits'] / total:.0f}% warm)"
                if total else ""
            ),
        ]
    dpl = s.get("data_plane")
    if dpl is not None:
        lines += [
            "",
            "Data plane — "
            + ", ".join(f"{k}={dpl[k]}" for k in sorted(dpl)),
        ]
    tn = s.get("tune")
    if tn is not None:
        cfg = tn.get("config") or {}
        lines += [
            "",
            f"Autotuner — mode={tn.get('mode')} "
            f"source={tn.get('source')} "
            f"{'cache hit' if tn.get('hit') else 'cache miss'} "
            f"({s.get('tune_hits', 0)} hits / {s.get('tune_misses', 0)} "
            "misses this session)",
            "  config: "
            + (
                ", ".join(f"{k}={cfg[k]}" for k in sorted(cfg))
                if cfg else "(static defaults)"
            ),
        ]
        trials = tn.get("trials")
        if trials:
            lines.append(
                f"  {len(trials)} candidates measured "
                f"(default {tn.get('default_ms', 0):.2f} ms -> chosen "
                f"{tn.get('chosen_ms', 0):.2f} ms/iter):"
            )
            for t in trials[:16]:
                lines.append(
                    f"    {t.get('iter_ms', 0):>9.2f} ms  {t.get('config')}"
                )
            if len(trials) > 16:
                lines.append(f"    ... {len(trials) - 16} more")
    rec = s.get("recovery")
    if rec is not None:
        counts = ", ".join(
            f"{k}={rec['counts'][k]}" for k in sorted(rec["counts"])
        )
        lines += ["", f"Recovery — {counts}"]
        last = rec.get("last") or {}
        if last:
            lines.append(
                "  last: "
                + ", ".join(f"{k}={last[k]}" for k in sorted(last))
            )
    flt = s.get("faults")
    if flt is not None:
        sites = ", ".join(
            f"{k}: {flt['by_site'][k]}" for k in sorted(flt["by_site"])
        )
        lines += [
            "",
            f"Faults injected (chaos harness) — {flt['count']} fired "
            f"({sites})",
        ]
    lines += ["", "Training health"]
    if s["health"]:
        lines.append(
            f"  {'signal':<26} {'last':>12} {'min':>12} {'max':>12} {'rows':>6}"
        )
        for k in sorted(s["health"]):
            h = s["health"][k]
            lines.append(
                f"  {k:<26} {h['last']:>12.4g} {h['min']:>12.4g} "
                f"{h['max']:>12.4g} {h['n']:>6}"
            )
        if s["nonfinite_windows"]:
            lines.append(
                f"  !! {s['nonfinite_windows']} metrics window(s) flagged "
                "health/nonfinite > 0 — NaN/inf hit the grads or params"
            )
        else:
            lines.append("  nonfinite guard: clean (no window flagged)")
    else:
        lines.append("  (no metrics rows recorded)")
    lines += ["", "Heartbeats"]
    if s["heartbeats"]:
        now = time.time()
        lines.append(
            f"  {'rank':>4} {'age s':>8} {'iteration':>10} {'env_steps':>12}"
        )
        for rank in sorted(s["heartbeats"]):
            hb = s["heartbeats"][rank]
            age = now - float(hb.get("t", now))
            lines.append(
                f"  {rank:>4} {age:>8.1f} {hb.get('iteration', 0):>10} "
                f"{hb.get('env_steps', 0):>12}"
            )
    else:
        lines.append("  (none recorded — single-host session)")
    return "\n".join(lines)
