"""Telemetry spine: span tracing, a JSONL event log, per-host heartbeats,
and the ``diag`` report (parity-plus: the reference ran a whole
observability *process trio* — tensorplex/loggerplex/tensorboard,
SURVEY.md §5.5 — whose scalars flow through ``session/metrics.py``; this
module adds the structural signals that trio never had: phase-level wall
time, training-health summaries, and multi-host liveness, all readable
offline from ``<folder>/telemetry/``).

Fence discipline (the round-5 landmines this design encodes):

- host clocks NEVER enter jitted-step modules — a ``time.time()`` traced
  inside jit runs once at compile and lies forever, and
  ``jax.block_until_ready`` both serializes the async pipeline and does
  not actually wait on this image's tunneled backend (the ~1000x
  pre-round-3 inflation). ``tests/test_import_hygiene.py`` lints for both.
- hot-loop spans are UNFENCED: a span around an async-dispatched jit call
  measures dispatch time for that call, but jax's bounded in-flight queue
  applies backpressure, so per-window TOTALS converge to real wall time;
  the one true fence per window stays the metrics-cadence sync that
  already existed (``SessionHooks.end_iteration``'s ``float()``
  conversion). ``span(..., block_on=pytree)`` is available for callers
  that ARE at a fence boundary (``utils/timer.py``'s rule).
- JSONL volume is bounded by cadence, not by iteration rate: spans
  accumulate in-memory per phase and are written as ONE ``phases`` event
  per ``flush_phases`` call (the metrics cadence); only low-frequency
  side-band spans (eval, checkpoint, publish) emit individual ``span``
  events via ``emit=True``.

Event schema (``<folder>/telemetry/events.jsonl``, one JSON object per
line, ``t`` = unix seconds):

    {"type": "session",   "t": ..., "name": "train", "pid": ...}
    {"type": "phases",    "t": ..., "step": ..., "phases":
        {"<phase>": {"count": N, "total_s": S, "max_ms": M}}}
    {"type": "span",      "t": ..., "name": "...", "dur_s": ...}
                    (low-frequency side-band spans via span(emit=True);
                     ISSUE 14 adds CAUSAL spans from Tracer.emit_span —
                     the same type with {"exemplar": ..., "span": S,
                     "parent": P, "tier": "...", "dur_ms": ...} — one
                     head-sampled request's hop across tiers; the
                     `surreal_tpu trace` CLI assembles them into
                     per-exemplar span trees)
    {"type": "metrics",   "t": ..., "step": ..., "values": {...}}
    {"type": "compile_cache", "t": ..., "dir": "...", "hits": H,
     "misses": M}   (cumulative; written by SessionHooks when
                     session.compile_cache_dir is active)
    {"type": "data_plane", "t": ..., "transport": "...", "pipeline": ...,
     "shm_workers": N, "pickle_workers": M, "wire_bytes_per_step": B,
     ...}           (SEED drivers via SessionHooks.data_plane_event; the
                     last event reflects the settled negotiation)
    {"type": "tune", "t": ..., "mode": "cache|search", "hit": ...,
     "source": "...", "config": {...}, ["trials": [...], ...]}
                    (autotuner decisions: trainers via
                     SessionHooks.tune_event at build, the `surreal_tpu
                     tune` CLI with full candidate timings; diag reports
                     the last one plus hit/miss counts)
    {"type": "recovery", "t": ..., "kind": "interrupt|tripped|rollback|
     checkpoint_fallback|skipped_nonfinite_checkpoint|giveup", ...}
                    (the fault-tolerance layer: preemption sentinel stops,
                     divergence-guard trips/rollbacks with lr_scale and
                     the restored step, damaged-checkpoint fallbacks —
                     session/interrupt.py, launch/recovery.py,
                     session/checkpoint.py)
    {"type": "fault", "t": ..., "site": "...", "kind": "...", "call": N}
                    (chaos-harness injections that actually fired,
                     utils/faults.py — drained into the spine by
                     SessionHooks so a chaos run documents what it
                     survived)
    {"type": "program_cost", "t": ..., "name": "...", "flops": F,
     "bytes_accessed": B, "arithmetic_intensity": AI, "phase": "...",
     "peak_flops": ..., "peak_membw": ..., ...}
                    (cost/MFU accounting, session/costs.py: one per
                     registered hot program, recorded at driver startup)
    {"type": "precision", "t": ..., "policy": "f32|mixed|bf16|bf16_fp8",
     "compute_dtype": "...", "data_dtype": "...", "loss_scaling": ...,
     "fp8": ...}
                    (the active precision policy, ops/precision.py —
                     emitted once per run by SessionHooks.begin_run;
                     diag's Performance section leads with it)
    {"type": "hops", "t": ..., "<hop>_ms": {"p50": ..., "p90": ...,
     "p99": ..., "n": N}, ...}
                    (per-hop latency percentiles of the SEED
                     cross-process timeline: worker_to_server,
                     serve_batch, chunk_queue_dwell, learn_dispatch —
                     emitted at the metrics cadence)
    {"type": "profile", "t": ..., "dir": "...", "reason":
     "trigger_file|slow_iter(...)|profiler_knob", "start_iter": ...,
     "end_iter": ...}
                    (on-demand profiler captures, session/profile.py —
                     the trace artifact lives under dir)
    {"type": "param_fetch", "t": ..., "span": S, "version": V,
     "unchanged": ..., "bytes": B}
                    (parameter-service hop: span-tagged client fetches
                     mirrored by ParameterServer when SessionHooks owns
                     it)
    {"type": "serving_tier", "t": ..., "replicas": {"0": {state,
     address, min_batch, serve_ms, workers, queue_depth, ...}, ...},
     "autoscale": ..., "num_workers": N, "fleet/...": ...}
                    (the act-serving tier's per-replica snapshot —
                     distributed/fleet.py, one per metrics row while an
                     InferenceFleet is active; rendered by diag's
                     "Serving tier" section)
    {"type": "experience_plane", "t": ..., "kind": "...",
     "num_shards": N, "shard_mode": "...", "transports": [...],
     "shards": {"0": {fill, ingested_rows, samples_served,
     ingest_transit_ms: {p50,...}, ...}, ...}, "sender": {...},
     "sampler": {...}, ...}
                    (the sharded experience plane's settled shape —
                     per-shard replay gauges + sender->shard->learner
                     hops; one per metrics row, the last one wins.
                     surreal_tpu/experience/, rendered by diag's
                     "Experience plane" section)
    {"type": "gateway", "t": ..., "address": "...", "tenants": {"name":
     {sessions, max_sessions, rate, acts, queued, throttled, evicted,
     rejected}, ...}, "pinned_versions": {...}, "cache_hit_rate": ...,
     "gateway/...": ...}
                    (the session gateway's tenant-facing snapshot —
                     surreal_tpu/gateway/, one per metrics row while the
                     gateway is live; rendered by diag's "Gateway"
                     section)
    {"type": "ops_snapshot", "t": ..., "seq": N, "tiers": T, "dead": D,
     "breaches": B, "bad_frames": ...}
                    (one per metrics cadence while the ops plane is
                     live — a summary POINTER; the full merged snapshot
                     lives in telemetry/ops_snapshot.json, which
                     `surreal_tpu top` renders. session/opsplane.py)
    {"type": "slo_breach", "t": ..., "tenant": "...", "objective": "...",
     "measured": ..., "target": ..., "budget_used": ..., "exhausted": ...}
                    (one per breached evaluation window per (tenant,
                     objective) — counted, never silent.
                     session/slo.py via the OpsAggregator)
    {"type": "ops_flightrec", "t": ..., "trigger":
     "recovery|fault|slo|...", "dir": "...", "snapshots": K, "events": M}
                    (a flight-recorder dump landed on disk under
                     telemetry/flightrec/<trigger>/ — the pre-incident
                     snapshot ring + fault/recovery events, trace-
                     correlated. session/opsplane.py)

Every event additionally carries ``trace`` (the run-scoped trace id
SessionHooks mints and spawned components inherit) and ``seq`` (a
per-process span-sequence counter) — the correlation keys diag uses to
stitch one cross-process timeline.

Heartbeats live per rank in ``telemetry/heartbeat_rank<k>.jsonl``:

    {"type": "heartbeat", "t": ..., "rank": R, "iteration": I,
     "env_steps": E}

``python -m surreal_tpu diag <folder>`` (``main/launch.py``) renders
:func:`diag_report` over these files: phase-time breakdown, health-signal
summary (the in-graph ``health/*`` diagnostics from
``learners/base.py::training_health``), and a last-heartbeat table.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager

TELEMETRY_DIR = "telemetry"
EVENTS_FILE = "events.jsonl"
PROFILES_DIR = "profiles"  # <folder>/telemetry/profiles/<tag>/ captures

# every event ``type`` any module may emit, name -> emitting layer. The
# GAUGE_REGISTRY discipline (session/costs.py) extended to events: an
# emit site using a type not documented here fails
# tests/test_import_hygiene.py's registry lint, so the schema docstring
# above and diag can never silently drift from what the code writes.
EVENT_REGISTRY = {
    "session": "Tracer.__init__ (session/telemetry.py)",
    "phases": "Tracer.flush_phases (session/telemetry.py)",
    "span": "Tracer.span(emit=True) side-bands + Tracer.emit_span causal "
            "trace exemplars (session/telemetry.py)",
    "metrics": "Tracer.log_metrics (session/telemetry.py)",
    "heartbeat": "HeartbeatWriter (session/telemetry.py, own file)",
    "compile_cache": "SessionHooks compile-cache counters (launch/hooks.py)",
    "data_plane": "SEED drivers via SessionHooks.data_plane_event",
    "tune": "autotuner decisions (tune/, launch/ via tune_event)",
    "recovery": "fault-tolerance layer (session/interrupt.py, "
                "launch/recovery.py, session/checkpoint.py)",
    "fault": "chaos firings drained by SessionHooks (utils/faults.py)",
    "program_cost": "cost/MFU accounting (session/costs.py)",
    "precision": "active precision policy (launch/hooks.py begin_run)",
    "hops": "cross-process hop percentiles (launch/seed_trainer.py)",
    "profile": "on-demand profiler captures (session/profile.py)",
    "param_fetch": "parameter-service fetches (distributed/param_service.py)",
    "serving_tier": "inference-fleet snapshot (distributed/fleet.py)",
    "experience_plane": "sharded experience plane (experience/plane.py)",
    "experience_close": "final exactly-once row accounting at plane "
                        "teardown (experience/plane.py::accounting via "
                        "the drivers' close paths) — the chaos "
                        "conservation oracle's input",
    "chaos_campaign": "chaos campaign run summary: seed, profile, plan, "
                      "oracle verdicts (chaos/campaign.py)",
    "chaos_violation": "one invariant-oracle violation found by a chaos "
                       "campaign run, with its (shrunk) schedule "
                       "(chaos/campaign.py)",
    "gateway": "session gateway tenant snapshot (gateway/server.py)",
    "ops_snapshot": "ops-plane merged-snapshot pointer (session/opsplane.py)",
    "slo_breach": "per-tenant SLO window breach (session/slo.py)",
    "ops_flightrec": "flight-recorder dump record (session/opsplane.py)",
    "incident_open": "watchdog firings opened an incident "
                     "(session/incidents.py)",
    "incident_update": "open incident absorbed further firings "
                       "(session/incidents.py, rate-bounded)",
    "incident_close": "incident closed on sustained-healthy windows "
                      "(session/incidents.py)",
    "remediation": "remediation engine action executed/suppressed/errored "
                   "(session/remediate.py)",
    "remediation_verdict": "counter-detector verdict on a completed "
                           "verification window (session/remediate.py)",
    "loadgen": "tenant load generator stop summary (gateway/loadgen.py)",
    "learner_group": "elastic learner-group membership transitions "
                     "(parallel/learner_group.py via "
                     "SessionHooks.learner_group_event)",
    "engine": "loop-engine stage snapshot: declared stages, boundary/step "
              "latency percentiles, staging occupancy, deferred/skipped/"
              "killed boundary counters (engine/core.py, metrics cadence)",
}


def latency_percentiles(samples) -> dict[str, float] | None:
    """{p50, p90, p99, n} of a latency sample window (pure python — used
    by the inference server's hop stats and the SEED data plane; no numpy
    so the server thread never allocates for bookkeeping)."""
    xs = sorted(float(x) for x in samples)
    if not xs:
        return None
    n = len(xs)

    def pct(p: float) -> float:
        return xs[min(n - 1, int(p * (n - 1) + 0.5))]

    return {"p50": pct(0.50), "p90": pct(0.90), "p99": pct(0.99), "n": n}


class TraceContext:
    """One head-sampled request's position in its causal span tree
    (ISSUE 14): the exemplar id names the tree, ``span_id`` this hop,
    ``parent_id`` the hop that caused it. Pure data — emitters pass it
    across tier boundaries (gateway -> fleet replica -> learner chunk)
    and call :meth:`Tracer.emit_span` at each hop."""

    __slots__ = ("exemplar", "span_id", "parent_id")

    def __init__(self, exemplar: str, span_id: int,
                 parent_id: int | None = None):
        self.exemplar = str(exemplar)
        self.span_id = int(span_id)
        self.parent_id = None if parent_id is None else int(parent_id)

    def child(self, span_id: int) -> "TraceContext":
        return TraceContext(self.exemplar, span_id, self.span_id)


def head_sampled(counter: int, sample_n: int) -> bool:
    """The 1-in-N head-sampling rule shared by every trace emitter: the
    FIRST request of a stream (counter 1) is always an exemplar, then
    every ``sample_n``-th after it. ``sample_n <= 0`` disables."""
    if sample_n <= 0:
        return False
    return (int(counter) - 1) % int(sample_n) == 0


class LineageReducer:
    """Exact per-update staleness from per-transition lineage stamps
    (ISSUE 14 tentpole, piece 2): every transition carries the param
    version that ACTED it; the reducer turns one update's version column
    into the exact staleness distribution the SLO plane previously only
    approximated from fanout-vs-fleet version gaps.

    Transfer-guard discipline: the version column is already host memory
    (the trainer pops it before ``device_put``) and the reduction is
    ``np.unique`` + integer arithmetic — no device values are ever
    touched, so the exact path adds zero device->host syncs.

    Percentiles use the same exact-index formula as
    :func:`latency_percentiles` (``xs[min(n-1, int(p*(n-1)+0.5))]``) over
    the sorted staleness multiset, walked via version counts instead of
    materializing 32k-element sorted lists — bit-matchable by hand."""

    def __init__(self):
        self.updates = 0
        self.last: dict[str, float] = {}

    def reduce(self, current_version: int, versions) -> dict[str, float]:
        """One update's ``lineage/*`` gauges from its acting-version
        column (any-shape host int array). Empty dict when the column is
        empty (nothing consumed, nothing to claim)."""
        import numpy as np

        arr = np.asarray(versions).reshape(-1)
        if arr.size == 0:
            return {}
        vals, counts = np.unique(arr.astype(np.int64), return_counts=True)
        cur = int(current_version)
        # staleness sorted ascending = current - version, versions walked
        # DESCENDING; cumulative counts give the element at any exact index
        stal = [int(cur - v) for v in vals[::-1]]
        cnts = [int(c) for c in counts[::-1]]
        n = int(arr.size)

        def pct(p: float) -> int:
            k = min(n - 1, int(p * (n - 1) + 0.5))
            seen = 0
            for s, c in zip(stal, cnts):
                seen += c
                if k < seen:
                    return s
            return stal[-1]

        self.updates += 1
        self.last = {
            "lineage/staleness_p50": float(pct(0.50)),
            "lineage/staleness_p99": float(pct(0.99)),
            "lineage/staleness_max": float(stal[-1]),
            "lineage/versions_per_batch": float(len(stal)),
        }
        return dict(self.last)


class Tracer:
    """Span tracing + JSONL event log for one session (rank 0 owns it,
    exactly like the MetricsWriter; disabled tracers are free no-ops so
    driver loops on ranks > 0 share the same code path).

    Thread-safe: the host-overlap collector thread and the SEED server
    side-bands record spans concurrently with the main loop.
    """

    def __init__(self, folder: str | None, enabled: bool = True,
                 name: str = "train", trace_id: str | None = None,
                 max_log_mb: float | None = None,
                 trace_sample_n: int = 64, trace_keep: int = 8):
        self.enabled = bool(enabled) and folder is not None
        self._lock = threading.Lock()
        self._phases: dict[str, list] = {}  # name -> [count, total_s, max_s]
        self._f = None
        self.path = None
        # size-based rotation (ISSUE 13): a production-length run must not
        # grow events.jsonl without bound. When the log passes max_log_mb
        # it rotates to <path>.1 (one generation — the previous .1 is
        # dropped) and _iter_jsonl/diag read the segments in order.
        self._max_bytes = (
            int(float(max_log_mb) * 1e6) if max_log_mb else None
        )
        self._bytes = 0
        self.rotations = 0
        # cross-process trace correlation (ISSUE 6): a run-scoped trace id
        # stamped (with a per-process span-sequence counter) into every
        # event; spawned env workers / the inference server / the param
        # service inherit it so diag can stitch one cross-process
        # timeline. Minted even when disabled — ranks > 0 still forward
        # it to the components they spawn.
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self._seq = 0
        # causal span trees (ISSUE 14): head-sample cadence every emitter
        # shares, a run-unique span-id counter (all emitters are threads
        # of the session process, handed THIS tracer as their span sink),
        # the chaos-counted drop tally, and the last-K exemplar ring the
        # flight recorder snapshots into its dumps
        self.trace_sample_n = int(trace_sample_n)
        self.dropped_spans = 0
        self.spans_emitted = 0
        self._span_ids = 0
        self._recent_exemplars: "deque[dict]" = deque(
            maxlen=max(1, int(trace_keep))
        )
        # last flushed phase window ({name: {count, total_s, max_ms}}) —
        # the cost accountant (session/costs.py) derives the perf/* gauges
        # from it without re-reading the event log
        self.last_window: dict[str, dict] = {}
        if self.enabled:
            try:
                tel_dir = os.path.join(folder, TELEMETRY_DIR)
                os.makedirs(tel_dir, exist_ok=True)
                self.path = os.path.join(tel_dir, EVENTS_FILE)
                self._f = open(self.path, "a", buffering=1)  # line-buffered
                self._bytes = os.path.getsize(self.path)  # resumed session
            except OSError:
                # telemetry must never kill training (e.g. read-only FS)
                self.enabled = False
                self._f = None
        if self.enabled:
            self.event("session", name=name, pid=os.getpid())

    # -- raw events ----------------------------------------------------------
    def event(self, type_: str, **fields) -> None:
        """Append one event line. Fields must be JSON-serializable."""
        if not self.enabled:
            return
        with self._lock:
            if self._f is None:
                return
            self._seq += 1
            line = json.dumps(
                {
                    "type": type_, "t": time.time(),
                    "trace": self.trace_id, "seq": self._seq,
                    **fields,
                },
                default=float,
            )
            try:
                self._f.write(line + "\n")
                self._bytes += len(line) + 1
                if self._max_bytes and self._bytes > self._max_bytes:
                    # rotate under the same lock the write holds: close,
                    # shift to .1 (dropping the previous .1 — two
                    # generations bound the disk at ~2x max_log_mb),
                    # reopen fresh
                    self._f.close()
                    os.replace(self.path, self.path + ".1")
                    self._f = open(self.path, "a", buffering=1)
                    self._bytes = 0
                    self.rotations += 1
            except OSError:
                # telemetry must never kill training: a mid-run disk-full/
                # mount hiccup disables the log instead of propagating
                try:
                    if self._f is not None:
                        self._f.close()
                except OSError:
                    pass
                self._f = None
                self.enabled = False

    # -- spans ---------------------------------------------------------------
    @contextmanager
    def span(self, name: str, block_on=None, emit: bool = False):
        """Time a region into the ``name`` phase accumulator.

        ``block_on``: pytree of device arrays to ``jax.block_until_ready``
        before stopping the clock (ONLY for fence-boundary callers — see
        the module doc). ``emit=True`` additionally writes an individual
        ``span`` event (low-frequency side-bands only).
        """
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if block_on is not None:
                import jax

                jax.block_until_ready(block_on)
            dur = time.perf_counter() - t0
            with self._lock:
                st = self._phases.setdefault(name, [0, 0.0, 0.0])
                st[0] += 1
                st[1] += dur
                st[2] = max(st[2], dur)
            if emit:
                self.event("span", name=name, dur_s=dur)

    # -- causal trace exemplars (ISSUE 14) -----------------------------------
    def next_span_id(self) -> int:
        """A run-unique span id (every trace emitter is a thread of the
        session process sharing this tracer, so one locked counter is
        globally unique within a run's event log)."""
        with self._lock:
            self._span_ids += 1
            return self._span_ids

    def trace_context(self, exemplar: str) -> TraceContext:
        """Mint a ROOT context for a newly head-sampled request."""
        return TraceContext(exemplar, self.next_span_id(), None)

    def emit_span(self, name: str, ctx: TraceContext, *,
                  tier: str | None = None, dur_ms: float | None = None,
                  **fields) -> None:
        """Emit one causal ``span`` event for hop ``ctx`` of its exemplar
        tree. The ``trace.emit`` chaos site fires here: ``drop_span``
        swallows the event but COUNTS it (``trace/dropped_spans``) and the
        span id stays allocated, so children still reference the missing
        hop and the trace CLI renders the tear instead of hiding it;
        ``delay`` stalls the emit (spans are side-band — a slow emit must
        never be mistaken for a slow hop, so callers pass dur_ms measured
        BEFORE calling)."""
        if not self.enabled:
            return
        from surreal_tpu.utils import faults

        f = faults.fire("trace.emit")
        if f is not None:
            if f["kind"] == "drop_span":
                with self._lock:
                    self.dropped_spans += 1
                return
            if f["kind"] == "delay":
                faults.sleep_ms(f)
        rec = {
            "name": name, "exemplar": ctx.exemplar, "span": ctx.span_id,
            "parent": ctx.parent_id, **fields,
        }
        if tier is not None:
            rec["tier"] = tier
        if dur_ms is not None:
            rec["dur_ms"] = float(dur_ms)
        with self._lock:
            self.spans_emitted += 1
            self._recent_exemplars.append(dict(rec, t=time.time()))
        self.event("span", **rec)

    def trace_gauges(self) -> dict[str, float]:
        """The ``trace/*`` gauge family (GAUGE_REGISTRY documents each);
        merged into the learner's metrics row each cadence."""
        return {
            "trace/spans": float(self.spans_emitted),
            "trace/dropped_spans": float(self.dropped_spans),
        }

    def recent_exemplar_spans(self) -> list[dict]:
        """The last-K exemplar span records (newest last) — the flight
        recorder writes them into every dump so a frozen incident carries
        the requests that flew through it."""
        with self._lock:
            return [dict(r) for r in self._recent_exemplars]

    def flush_phases(self, step) -> dict[str, float]:
        """Write one ``phases`` event for the window since the last flush
        and return ``time/<phase>_ms`` mean-per-call scalars — the mirror
        the caller merges into the MetricsWriter stream. Resets the
        window. Called at the metrics cadence by SessionHooks."""
        with self._lock:
            phases = {
                k: {"count": c, "total_s": t, "max_ms": mx * 1e3}
                for k, (c, t, mx) in self._phases.items()
            }
            self._phases.clear()
        self.last_window = phases
        if not phases:
            return {}
        self.event("phases", step=int(step), phases=phases)
        return {
            f"time/{k}_ms": v["total_s"] / max(v["count"], 1) * 1e3
            for k, v in phases.items()
        }

    def log_metrics(self, step, metrics) -> None:
        """Mirror one synced metrics row into the event log (what ``diag``
        reads for the health summary)."""
        if not self.enabled or not metrics:
            return
        self.event(
            "metrics", step=int(step),
            values={k: float(v) for k, v in metrics.items()},
        )

    def close(self) -> None:
        # flush the tail window first: a run shorter than one metrics
        # cadence (or one that crashed into its finally-close) must still
        # record the spans it accumulated. step=-1 marks an at-close
        # flush; diag ignores it for last-step reporting.
        self.flush_phases(step=-1)
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
        self.enabled = False


class HeartbeatWriter:
    """Per-host liveness events for multi-host runs: each rank appends to
    its OWN ``telemetry/heartbeat_rank<k>.jsonl`` (no cross-rank
    coordination — a wedged rank is visible precisely because it stops
    writing). Ranks whose host cannot write the session folder disable
    themselves silently: ranks > 0 are not required to mount it
    (launch/multihost_trainer.py's session discipline)."""

    def __init__(self, folder: str | None, rank: int, every_s: float = 10.0,
                 enabled: bool = True):
        self.rank = int(rank)
        self.every_s = float(every_s)
        self._last: float | None = None
        self._path = None
        if enabled and folder:
            try:
                tel_dir = os.path.join(folder, TELEMETRY_DIR)
                os.makedirs(tel_dir, exist_ok=True)
                self._path = os.path.join(
                    tel_dir, f"heartbeat_rank{self.rank}.jsonl"
                )
                with open(self._path, "a"):
                    pass  # probe writability up front
            except OSError:
                self._path = None

    def beat(self, iteration: int, env_steps: int, force: bool = False) -> None:
        """Append a heartbeat, time-throttled to ``every_s`` (call it every
        iteration; it is a no-op between beats)."""
        if self._path is None:
            return
        now = time.monotonic()
        if not force and self._last is not None and now - self._last < self.every_s:
            return
        self._last = now
        rec = {
            "type": "heartbeat", "t": time.time(), "rank": self.rank,
            "iteration": int(iteration), "env_steps": int(env_steps),
            # cadence rides in the record so diag can flag a rank whose
            # newest beat is older than 3x its own cadence as DEAD
            "every_s": self.every_s,
        }
        try:
            with open(self._path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            self._path = None  # host lost the folder; stop trying


# -- diag --------------------------------------------------------------------

_HEALTH_PREFIXES = ("health/", "loss/", "policy/kl", "episode/return")


def _iter_jsonl(path, rotated: bool = True):
    """Yield one JSON object per parseable line, tolerating a
    partially-written trailing line. Two torn-tail shapes exist after a
    chaos-harness kill (PR 5) mid-``write``: an incomplete JSON text
    (JSONDecodeError — skipped per line) and a line truncated INSIDE a
    multi-byte UTF-8 sequence, which raises UnicodeDecodeError from the
    file iterator itself unless decoding is lossy — ``errors='replace'``
    turns it into a replacement char the per-line parse then skips.

    ``rotated``: the Tracer's size-based rotation (ISSUE 13) shifts a
    full log to ``<path>.1``; the rotated segment is older, so it is
    read FIRST and the live file second — one chronological stream. A
    rotation racing this read at worst repeats or drops lines across
    the segment boundary; every line still parses (diag's mid-rotation
    test pins this down)."""
    paths = [path]
    if rotated and os.path.exists(path + ".1"):
        paths.insert(0, path + ".1")
    for p in paths:
        try:
            with open(p, errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail line from a live/killed session
        except OSError:
            continue


def diag_summary(folder: str) -> dict | None:
    """Aggregate the session's telemetry files into one dict, or None when
    no event log exists. Pure file reading — no jax, safe off-chip."""
    events_path = os.path.join(folder, TELEMETRY_DIR, EVENTS_FILE)
    events = list(_iter_jsonl(events_path))
    hb_paths = sorted(
        glob.glob(os.path.join(folder, TELEMETRY_DIR, "heartbeat_rank*.jsonl"))
    )
    if not events and not hb_paths:
        return None

    phases: dict[str, dict] = {}
    health: dict[str, dict] = {}
    compile_cache = None
    data_plane = None
    experience = None
    serving = None
    gateway = None
    engine = None
    trace_id = None
    programs: dict[str, dict] = {}   # program_cost events (last per name)
    precision = None                 # last 'precision' event (active policy)
    perf_last: dict[str, float] = {}  # perf/* gauges from the last row
    hops = None                      # last 'hops' event's percentiles
    profiles: list[dict] = []        # 'profile' capture events
    tune = None
    tune_hits = tune_misses = 0
    recovery_counts: dict[str, int] = {}
    recovery_last = None
    fault_count = 0
    fault_sites: dict[str, int] = {}
    fault_last = None
    nonfinite_windows = 0
    t_first = t_last = None
    last_step = None
    for ev in events:
        t = ev.get("t")
        if isinstance(t, (int, float)):
            t_first = t if t_first is None else min(t_first, t)
            t_last = t if t_last is None else max(t_last, t)
        if trace_id is None and ev.get("trace"):
            trace_id = ev["trace"]
        if ev.get("type") == "phases":
            step = ev.get("step")
            if isinstance(step, int) and step >= 0:  # -1 = at-close flush
                last_step = step
            for name, st in (ev.get("phases") or {}).items():
                agg = phases.setdefault(
                    name, {"count": 0, "total_s": 0.0, "max_ms": 0.0}
                )
                agg["count"] += int(st.get("count", 0))
                agg["total_s"] += float(st.get("total_s", 0.0))
                agg["max_ms"] = max(agg["max_ms"], float(st.get("max_ms", 0.0)))
        elif ev.get("type") == "compile_cache":
            # counters are cumulative; the last event is the session total
            compile_cache = {
                "dir": ev.get("dir"),
                "hits": int(ev.get("hits", 0)),
                "misses": int(ev.get("misses", 0)),
            }
        elif ev.get("type") == "data_plane":
            # the last event is the settled negotiation (SEED drivers emit
            # one after the first learn and one at run end)
            data_plane = {
                k: v for k, v in ev.items() if k not in ("type", "t", "trace", "seq")
            }
        elif ev.get("type") == "serving_tier":
            # the last event is the settled tier shape (one per metrics
            # row while an InferenceFleet is active)
            serving = {
                k: v for k, v in ev.items() if k not in ("type", "t", "trace", "seq")
            }
        elif ev.get("type") == "experience_plane":
            # the last event is the settled plane shape (one per metrics
            # row while a sharded experience plane is active)
            experience = {
                k: v for k, v in ev.items() if k not in ("type", "t", "trace", "seq")
            }
        elif ev.get("type") == "gateway":
            # the last event is the settled tenant picture (one per
            # metrics row while the session gateway is live)
            gateway = {
                k: v for k, v in ev.items() if k not in ("type", "t", "trace", "seq")
            }
        elif ev.get("type") == "engine":
            # the last event is the settled loop-engine picture (one per
            # metrics row; counters are cumulative)
            engine = {
                k: v for k, v in ev.items() if k not in ("type", "t", "trace", "seq")
            }
        elif ev.get("type") == "tune":
            # the last event is the active decision; hit/miss counts
            # accumulate over the session (trainer builds + CLI runs)
            tune = {k: v for k, v in ev.items() if k not in ("type", "t", "trace", "seq")}
            if ev.get("hit"):
                tune_hits += 1
            else:
                tune_misses += 1
        elif ev.get("type") == "recovery":
            kind = str(ev.get("kind", "?"))
            recovery_counts[kind] = recovery_counts.get(kind, 0) + 1
            recovery_last = {
                k: v for k, v in ev.items() if k not in ("type", "t", "trace", "seq")
            }
        elif ev.get("type") == "fault":
            fault_count += 1
            site = str(ev.get("site", "?"))
            fault_sites[site] = fault_sites.get(site, 0) + 1
            fault_last = {
                k: v for k, v in ev.items() if k not in ("type", "t", "trace", "seq")
            }
        elif ev.get("type") == "program_cost":
            name = str(ev.get("name", "?"))
            programs[name] = {
                k: v for k, v in ev.items()
                if k not in ("type", "t", "trace", "seq")
            }
        elif ev.get("type") == "precision":
            # last event wins (one per run; a resumed session re-emits)
            precision = {
                k: v for k, v in ev.items()
                if k not in ("type", "t", "trace", "seq")
            }
        elif ev.get("type") == "hops":
            # last event wins: the window's rolling-deque percentiles
            hops = {
                k: v for k, v in ev.items()
                if k not in ("type", "t", "trace", "seq")
            }
        elif ev.get("type") == "profile":
            profiles.append({
                k: v for k, v in ev.items()
                if k not in ("type", "t", "trace", "seq")
            })
        elif ev.get("type") == "metrics":
            last_step = ev.get("step", last_step)
            vals = ev.get("values") or {}
            for k, v in vals.items():
                if (
                    k.startswith(("perf/", "lineage/", "trace/"))
                    and isinstance(v, (int, float))
                ):
                    perf_last[k] = v
            if vals.get("health/nonfinite", 0):
                nonfinite_windows += 1
            for k, v in vals.items():
                if not isinstance(v, (int, float)):
                    continue
                if not any(k.startswith(p) or k == p for p in _HEALTH_PREFIXES):
                    continue
                if v != v:  # NaN rows carry no summary information
                    continue
                h = health.setdefault(
                    k, {"last": v, "min": v, "max": v, "n": 0}
                )
                h["last"] = v
                h["min"] = min(h["min"], v)
                h["max"] = max(h["max"], v)
                h["n"] += 1

    heartbeats = {}
    now = time.time()
    for path in hb_paths:
        last = None
        prev_t = None
        deltas: list[float] = []
        for rec in _iter_jsonl(path):
            if rec.get("type") == "heartbeat":
                t = rec.get("t")
                if isinstance(t, (int, float)) and prev_t is not None:
                    deltas.append(t - prev_t)
                prev_t = t if isinstance(t, (int, float)) else prev_t
                last = rec
        if last is not None:
            # staleness: a rank whose newest beat is older than 3x its
            # cadence is flagged DEAD instead of silently looking fine.
            # Cadence comes from the record (new runs), else is inferred
            # from the observed beat deltas (old logs), else defaults.
            cadence = last.get("every_s")
            if not isinstance(cadence, (int, float)) or cadence <= 0:
                cadence = (
                    sorted(deltas)[len(deltas) // 2] if deltas else 10.0
                )
            age = now - float(last.get("t", now))
            heartbeats[int(last.get("rank", -1))] = {
                **last,
                "age_s": age,
                "cadence_s": float(cadence),
                "dead": age > 3.0 * float(cadence),
            }

    return {
        "folder": folder,
        "trace_id": trace_id,
        "events": len(events),
        "wall_s": (t_last - t_first) if (t_first is not None and t_last is not None) else 0.0,
        "last_step": last_step,
        "phases": phases,
        "health": health,
        "compile_cache": compile_cache,
        "data_plane": data_plane,
        "experience": experience,
        "serving": serving,
        "gateway": gateway,
        "engine": engine,
        "tune": tune,
        "tune_hits": tune_hits,
        "tune_misses": tune_misses,
        "recovery": (
            {"counts": recovery_counts, "last": recovery_last}
            if recovery_counts else None
        ),
        "faults": (
            {"count": fault_count, "by_site": fault_sites, "last": fault_last}
            if fault_count else None
        ),
        "nonfinite_windows": nonfinite_windows,
        "heartbeats": heartbeats,
        "programs": programs,
        "precision": precision,
        "perf": perf_last,
        "hops": hops,
        "profiles": profiles,
    }


def diag_report(folder: str) -> str | None:
    """Human-readable diag: phase-time breakdown, health summary,
    last-heartbeat table. None when the folder has no telemetry."""
    s = diag_summary(folder)
    if s is None:
        return None
    wall = s["wall_s"]
    lines = [
        f"Telemetry diag — {s['folder']}",
        f"{s['events']} events over {wall:.1f} s"
        + (f", last step {s['last_step']}" if s["last_step"] is not None else "")
        + (f", trace {s['trace_id']}" if s.get("trace_id") else ""),
        "",
        "Phase-time breakdown",
    ]
    if s["phases"]:
        lines.append(
            f"  {'phase':<20} {'calls':>8} {'total s':>10} {'mean ms':>10} "
            f"{'max ms':>10} {'% wall':>7}"
        )
        for name, st in sorted(
            s["phases"].items(), key=lambda kv: -kv[1]["total_s"]
        ):
            mean_ms = st["total_s"] / max(st["count"], 1) * 1e3
            pct = 100.0 * st["total_s"] / wall if wall > 0 else 0.0
            lines.append(
                f"  {name:<20} {st['count']:>8} {st['total_s']:>10.2f} "
                f"{mean_ms:>10.2f} {st['max_ms']:>10.2f} {pct:>6.1f}%"
            )
        lines.append(
            "  (device-loop phases measure async dispatch; window totals "
            "are honest under backpressure — see session/telemetry.py)"
        )
    else:
        lines.append("  (no phase windows recorded)")
    cc = s.get("compile_cache")
    if cc is not None:
        total = cc["hits"] + cc["misses"]
        lines += [
            "",
            f"Compile cache — {cc.get('dir')}",
            f"  {cc['hits']} hits / {cc['misses']} misses"
            + (
                f" ({100.0 * cc['hits'] / total:.0f}% warm)"
                if total else ""
            ),
        ]
    dpl = s.get("data_plane")
    if dpl is not None:
        lines += [
            "",
            "Data plane — "
            + ", ".join(f"{k}={dpl[k]}" for k in sorted(dpl)),
        ]
    eng_lines = _engine_lines(s)
    if eng_lines:
        lines += ["", "Loop engine"] + eng_lines
    tier_lines = _serving_tier_lines(s)
    if tier_lines:
        lines += ["", "Serving tier"] + tier_lines
    xp_lines = _experience_plane_lines(s)
    if xp_lines:
        lines += ["", "Experience plane"] + xp_lines
    gw_lines = _gateway_lines(s)
    if gw_lines:
        lines += ["", "Gateway"] + gw_lines
    tn = s.get("tune")
    if tn is not None:
        cfg = tn.get("config") or {}
        lines += [
            "",
            f"Autotuner — mode={tn.get('mode')} "
            f"source={tn.get('source')} "
            f"{'cache hit' if tn.get('hit') else 'cache miss'} "
            f"({s.get('tune_hits', 0)} hits / {s.get('tune_misses', 0)} "
            "misses this session)",
            "  config: "
            + (
                ", ".join(f"{k}={cfg[k]}" for k in sorted(cfg))
                if cfg else "(static defaults)"
            ),
        ]
        trials = tn.get("trials")
        if trials:
            lines.append(
                f"  {len(trials)} candidates measured "
                f"(default {tn.get('default_ms', 0):.2f} ms -> chosen "
                f"{tn.get('chosen_ms', 0):.2f} ms/iter):"
            )
            for t in trials[:16]:
                lines.append(
                    f"    {t.get('iter_ms', 0):>9.2f} ms  {t.get('config')}"
                )
            if len(trials) > 16:
                lines.append(f"    ... {len(trials) - 16} more")
    perf_lines = _performance_lines(s)
    if perf_lines:
        lines += ["", "Performance"] + perf_lines
    rec = s.get("recovery")
    if rec is not None:
        counts = ", ".join(
            f"{k}={rec['counts'][k]}" for k in sorted(rec["counts"])
        )
        lines += ["", f"Recovery — {counts}"]
        last = rec.get("last") or {}
        if last:
            lines.append(
                "  last: "
                + ", ".join(f"{k}={last[k]}" for k in sorted(last))
            )
    flt = s.get("faults")
    if flt is not None:
        sites = ", ".join(
            f"{k}: {flt['by_site'][k]}" for k in sorted(flt["by_site"])
        )
        lines += [
            "",
            f"Faults injected (chaos harness) — {flt['count']} fired "
            f"({sites})",
        ]
    lines += ["", "Training health"]
    if s["health"]:
        lines.append(
            f"  {'signal':<26} {'last':>12} {'min':>12} {'max':>12} {'rows':>6}"
        )
        for k in sorted(s["health"]):
            h = s["health"][k]
            lines.append(
                f"  {k:<26} {h['last']:>12.4g} {h['min']:>12.4g} "
                f"{h['max']:>12.4g} {h['n']:>6}"
            )
        if s["nonfinite_windows"]:
            lines.append(
                f"  !! {s['nonfinite_windows']} metrics window(s) flagged "
                "health/nonfinite > 0 — NaN/inf hit the grads or params"
            )
        else:
            lines.append("  nonfinite guard: clean (no window flagged)")
    else:
        lines.append("  (no metrics rows recorded)")
    lines += ["", "Heartbeats"]
    if s["heartbeats"]:
        lines.append(
            f"  {'rank':>4} {'age s':>8} {'iteration':>10} {'env_steps':>12}"
            f"  status"
        )
        dead_ranks = []
        for rank in sorted(s["heartbeats"]):
            hb = s["heartbeats"][rank]
            age = float(hb.get("age_s", 0.0))
            dead = bool(hb.get("dead"))
            if dead:
                dead_ranks.append(rank)
            lines.append(
                f"  {rank:>4} {age:>8.1f} {hb.get('iteration', 0):>10} "
                f"{hb.get('env_steps', 0):>12}  "
                + (
                    f"DEAD (> 3x {hb.get('cadence_s', 0.0):.0f}s cadence)"
                    if dead else "alive"
                )
            )
        if dead_ranks:
            lines.append(
                f"  !! rank(s) {', '.join(str(r) for r in dead_ranks)} "
                "stopped heartbeating — wedged, killed, or the run ended"
            )
    else:
        lines.append("  (none recorded — single-host session)")
    # watchdog incidents (ISSUE 15): the `surreal_tpu why` brief, one
    # line per incident — the full root-cause report is `why`'s job.
    # Local import: incidents.py pulls in costs.py, and diag must stay a
    # pure-file-reading path that works even if that import breaks.
    try:
        from surreal_tpu.session.incidents import incidents_brief

        inc_lines = incidents_brief(s["folder"])
    except Exception:
        inc_lines = []
    if inc_lines:
        lines += ["", "Incidents (surreal_tpu why for the full report)"]
        lines += inc_lines
    return "\n".join(lines)


def _engine_lines(s: dict) -> list[str]:
    """The diag 'Loop engine' section: declared stage table (donate /
    deferrable / overlap bits), boundary + step latency percentiles,
    staging occupancy, and the deferred/skipped/killed boundary counters
    from the last ``engine`` event. Empty list when the session predates
    the engine (no event recorded)."""
    eng = s.get("engine")
    if not eng:
        return []
    lines = [
        "  pipelined={p} — {d} boundaries deferred, {sk} skipped "
        "(wedged past the stage bound), {k} stage kills".format(
            p=bool(eng.get("pipelined")),
            d=int(eng.get("deferred", 0)),
            sk=int(eng.get("skipped", 0)),
            k=int(eng.get("kills", 0)),
        ),
    ]
    st = eng.get("stage_ms") or {}
    sp = eng.get("step_ms") or {}
    if st or sp:
        lines.append(
            "  boundary p50/p99 {a:.2f}/{b:.2f} ms, step p50/p99 "
            "{c:.2f}/{d:.2f} ms, staging occupancy {o:.1%}".format(
                a=float(st.get("p50", 0.0)), b=float(st.get("p99", 0.0)),
                c=float(sp.get("p50", 0.0)), d=float(sp.get("p99", 0.0)),
                o=float(eng.get("occupancy", 0.0)),
            )
        )
    stages = eng.get("stages") or []
    if stages:
        lines.append(
            f"  {'stage':<12} {'donate':>7} {'deferrable':>11} {'overlap':>8}"
        )
        for spec in stages:
            lines.append(
                f"  {str(spec.get('name', '?')):<12} "
                f"{str(bool(spec.get('donate'))):>7} "
                f"{str(bool(spec.get('deferrable'))):>11} "
                f"{str(bool(spec.get('overlap'))):>8}"
            )
    return lines


def _serving_tier_lines(s: dict) -> list[str]:
    """The diag 'Serving tier' section: replica liveness/budget table,
    fleet-mean serve latency, scale/respawn counters from the last
    ``serving_tier`` event. Empty list when the session ran no fleet."""
    tier = s.get("serving")
    if not tier:
        return []
    lines = [
        "  {n} replica(s) alive over {w} workers — respawns {r:g}, "
        "scale ups {u:g} / downs {d:g}, autoscale {a}".format(
            n=int(tier.get("fleet/replicas_live", 0)),
            w=tier.get("num_workers", "?"),
            r=float(tier.get("fleet/respawns", 0)),
            u=float(tier.get("fleet/scale_ups", 0)),
            d=float(tier.get("fleet/scale_downs", 0)),
            a="on" if tier.get("autoscale") else "off",
        ),
    ]
    if tier.get("fleet/serve_ms") is not None:
        lines.append(
            f"  fleet serve EWMA {float(tier['fleet/serve_ms']):.2f} ms, "
            f"queue depth {float(tier.get('fleet/queue_depth', 0)):g}"
        )
    replicas = tier.get("replicas") or {}
    if replicas:
        lines.append(
            f"  {'replica':>8} {'state':<8} {'workers':>8} "
            f"{'min_batch':>10} {'serve ms':>9} {'evicted':>8}"
        )
        for rid in sorted(replicas, key=lambda x: int(x)):
            r = replicas[rid]
            serve = r.get("serve_ms")
            lines.append(
                f"  {rid:>8} {r.get('state', '?'):<8} "
                f"{r.get('workers', 0):>8} {r.get('min_batch', 0):>10} "
                + (f"{float(serve):>9.2f}" if serve is not None else f"{'n/a':>9}")
                + f" {r.get('evicted_chunks', 0):>8}"
            )
    return lines


def _experience_plane_lines(s: dict) -> list[str]:
    """The diag 'Experience plane' section: shard geometry/transport mix,
    per-shard replay gauges (fill, ingested rows, samples served, sample
    queue depth), the learner's sample-wait, and per-hop
    sender->shard->learner percentiles from the last ``experience_plane``
    event. Empty list when the session ran no plane."""
    xp = s.get("experience")
    if not xp:
        return []
    lines = [
        f"  {xp.get('kind', '?')} x {xp.get('num_shards', '?')} shards "
        f"({xp.get('shard_mode', '?')} mode), transports "
        f"{xp.get('transports', [])}",
        f"  wire {xp.get('wire_bytes_per_step', 0):.1f} B/step, learner "
        f"sample-wait {xp.get('sample_wait_ms', 0):.2f} ms (EWMA)",
    ]
    shards = xp.get("shards") or {}
    if shards:
        lines.append(
            f"  {'shard':>6} {'fill':>7} {'rows':>10} {'samples':>9} "
            f"{'queue':>6} {'ingest p50/p90/p99 ms':>24}"
        )
        for sid in sorted(shards, key=lambda x: int(x)):
            sh = shards[sid]
            tr = sh.get("ingest_transit_ms") or {}
            hop = (
                f"{tr.get('p50', 0):.2f}/{tr.get('p90', 0):.2f}/"
                f"{tr.get('p99', 0):.2f}" if tr else "n/a"
            )
            lines.append(
                f"  {sid:>6} {float(sh.get('fill', 0)):>7.2f} "
                f"{int(sh.get('ingested_rows', 0)):>10} "
                f"{int(sh.get('samples_served', 0)):>9} "
                f"{int(sh.get('sample_queue_depth', 0)):>6} {hop:>24}"
            )
    snd = xp.get("sender") or {}
    smp = xp.get("sampler") or {}
    if snd or smp:
        lines.append(
            "  sender: "
            + ", ".join(f"{k}={snd[k]:g}" for k in sorted(snd))
            + " | sampler: "
            + ", ".join(f"{k}={smp[k]:g}" for k in sorted(smp))
        )
    return lines


def _gateway_lines(s: dict) -> list[str]:
    """The diag 'Gateway' section: session/act totals, act-cache hit
    rate, migration/catch-up counters, pinned-version census, and the
    per-tenant admission table from the last ``gateway`` event. Empty
    list when the session ran no gateway."""
    gw = s.get("gateway")
    if not gw:
        return []
    acts = float(gw.get("gateway/acts", 0))
    lines = [
        "  {n:g} session(s) live at {a} — attaches {at:g} "
        "(+{re:g} re-attach), detaches {d:g}, expired {ex:g}".format(
            n=float(gw.get("gateway/sessions", 0)),
            a=gw.get("address", "?"),
            at=float(gw.get("gateway/attaches", 0)),
            re=float(gw.get("gateway/reattaches", 0)),
            d=float(gw.get("gateway/detaches", 0)),
            ex=float(gw.get("gateway/expired_leases", 0)),
        ),
        "  {ac:g} acts, cache hit-rate {hr:.0%} ({h:g} hits / {m:g} "
        "misses), migrations {mi:g}, catch-ups {cu:g}".format(
            ac=acts,
            hr=float(gw.get("cache_hit_rate", 0.0)),
            h=float(gw.get("gateway/cache_hits", 0)),
            m=float(gw.get("gateway/cache_misses", 0)),
            mi=float(gw.get("gateway/migrations", 0)),
            cu=float(gw.get("gateway/catch_ups", 0)),
        ),
    ]
    pins = gw.get("pinned_versions") or {}
    if pins:
        lines.append(
            "  pinned versions: "
            + ", ".join(
                f"v{v}×{pins[v]}" for v in sorted(pins, key=lambda x: int(x))
            )
        )
    tenants = gw.get("tenants") or {}
    if tenants:
        lines.append(
            f"  {'tenant':<12} {'sessions':>9} {'quota':>6} {'queued':>7} "
            f"{'throttled':>10} {'evicted':>8} {'rejected':>9}"
        )
        for name in sorted(tenants):
            t = tenants[name]
            quota = int(t.get("max_sessions", 0))
            lines.append(
                f"  {name:<12} {int(t.get('sessions', 0)):>9} "
                + (f"{quota:>6}" if quota else f"{'inf':>6}")
                + f" {int(t.get('queued', 0)):>7} "
                f"{int(t.get('throttled', 0)):>10} "
                f"{int(t.get('evicted', 0)):>8} "
                f"{int(t.get('rejected', 0)):>9}"
            )
    return lines


def _performance_lines(s: dict) -> list[str]:
    """The diag 'Performance' section: per-program roofline numbers
    (FLOPs / bytes / arithmetic intensity from program_cost events), the
    live perf/* gauges from the last metrics row, per-hop latency
    percentiles (the stitched cross-process timeline), and captured
    profiler traces. Empty list when the session recorded none of them."""
    progs = s.get("programs") or {}
    prec = s.get("precision") or {}
    perf = s.get("perf") or {}
    hops = s.get("hops") or {}
    profiles = s.get("profiles") or []
    lines: list[str] = []
    if prec:
        # the active precision policy leads: every roofline number below
        # was produced under it (ops/precision.py)
        lines.append(
            f"  precision policy: {prec.get('policy', '?')} "
            f"(compute {prec.get('compute_dtype', '?')}, "
            f"staging {prec.get('data_dtype', '?')}, params "
            f"{prec.get('param_dtype', 'float32')}, loss scaling "
            + ("on" if prec.get("loss_scaling") else "off")
            + (", fp8 matmuls" if prec.get("fp8") else "")
            + ")"
        )
    if progs:
        any_rec = next(iter(progs.values()))
        kind = any_rec.get("device_kind", "?")
        pk_f, pk_b = any_rec.get("peak_flops"), any_rec.get("peak_membw")
        src = any_rec.get("peak_source", "?")
        lines.append(
            f"  device {kind} — peak "
            + (f"{pk_f / 1e12:.1f} TFLOP/s" if pk_f else "? FLOP/s")
            + ", "
            + (f"{pk_b / 1e9:.0f} GB/s" if pk_b else "? B/s")
            + f" ({src})"
        )
        lines.append(
            f"  {'program':<16} {'GFLOPs/call':>12} {'MB/call':>10} "
            f"{'arith int':>10} {'phase':<12}"
        )
        for name in sorted(progs):
            p = progs[name]
            ai = p.get("arithmetic_intensity")
            lines.append(
                f"  {name:<16} {p.get('flops', 0) / 1e9:>12.3f} "
                f"{p.get('bytes_accessed', 0) / 1e6:>10.2f} "
                + (f"{ai:>10.2f} " if ai else f"{'n/a':>10} ")
                + f"{p.get('phase') or '(unphased)':<12}"
            )
    if perf:
        bits = []
        if "perf/mfu" in perf:
            bits.append(f"mfu {perf['perf/mfu'] * 100:.3f}%")
        if "perf/membw_util" in perf:
            bits.append(f"membw_util {perf['perf/membw_util'] * 100:.2f}%")
        if "perf/flops_per_s" in perf:
            bits.append(
                f"flops/s {perf['perf/flops_per_s'] / 1e9:.2f} G"
            )
        lines.append("  gauges (last metrics row): " + ", ".join(bits))
    lin_p50 = perf.get("lineage/staleness_p50")
    if lin_p50 is not None:
        lines.append(
            "  lineage (exact per-update staleness, in updates): "
            f"p50 {lin_p50:g}, p99 {perf.get('lineage/staleness_p99', 0):g}, "
            f"max {perf.get('lineage/staleness_max', 0):g}, "
            f"{perf.get('lineage/versions_per_batch', 0):g} version(s)/batch"
        )
    if perf.get("trace/spans"):
        lines.append(
            f"  trace exemplars: {perf['trace/spans']:g} span(s) emitted, "
            f"{perf.get('trace/dropped_spans', 0):g} dropped (chaos)"
        )
    if hops:
        lines.append("  per-hop latency (cross-process timeline):")
        for hop in sorted(hops):
            st = hops[hop]
            if not isinstance(st, dict):
                continue
            lines.append(
                f"    {hop:<24} p50 {st.get('p50', 0):>8.2f} ms  "
                f"p90 {st.get('p90', 0):>8.2f}  p99 {st.get('p99', 0):>8.2f}"
                f"  (n={st.get('n', 0)})"
            )
    if profiles:
        lines.append(f"  profiler captures ({len(profiles)}):")
        for p in profiles[-8:]:
            lines.append(
                f"    {p.get('dir', '?')} — reason={p.get('reason', '?')}"
                + (
                    f", iters {p.get('start_iter')}-{p.get('end_iter')}"
                    if p.get("start_iter") is not None else ""
                )
            )
    return lines


# -- trace (causal span trees, ISSUE 14) --------------------------------------


def trace_summary(folder: str) -> dict | None:
    """Collect every causal span event (the ones ``Tracer.emit_span``
    stamps with an ``exemplar`` id) from the session's event log into
    per-exemplar groups. Pure file reading — no jax, safe off-chip. None
    when no event log exists."""
    events_path = os.path.join(folder, TELEMETRY_DIR, EVENTS_FILE)
    if not (os.path.exists(events_path)
            or os.path.exists(events_path + ".1")):
        return None
    exemplars: dict[str, list[dict]] = {}
    trace_id = None
    dropped = spans = None
    for ev in _iter_jsonl(events_path):
        if trace_id is None and ev.get("trace"):
            trace_id = ev["trace"]
        if ev.get("type") == "span" and ev.get("exemplar"):
            exemplars.setdefault(str(ev["exemplar"]), []).append(ev)
        elif ev.get("type") == "metrics":
            vals = ev.get("values") or {}
            if "trace/dropped_spans" in vals:
                dropped = vals["trace/dropped_spans"]
            if "trace/spans" in vals:
                spans = vals["trace/spans"]
    return {
        "folder": folder,
        "trace_id": trace_id,
        "exemplars": exemplars,
        "spans": spans,
        "dropped_spans": dropped,
    }


def _render_exemplar(spans: list[dict]) -> list[str]:
    """One exemplar's span tree, children indented under parents, ordered
    by wall time within a level. A span whose parent id was never emitted
    (chaos ``drop_span``, a crashed tier) is NOT hidden: it renders as a
    root with the missing hop marked — a torn tree is evidence."""
    by_id = {int(s["span"]): s for s in spans if s.get("span") is not None}
    kids: dict[int | None, list[dict]] = {}
    for s in sorted(spans, key=lambda x: (x.get("t", 0), x.get("seq", 0))):
        parent = s.get("parent")
        if parent is not None and int(parent) not in by_id:
            parent = ("missing", int(parent))  # torn: render as a root
        elif parent is not None:
            parent = int(parent)
        kids.setdefault(parent, []).append(s)
    t0 = min((s.get("t", 0) for s in spans), default=0)
    lines: list[str] = []

    def emit(s: dict, depth: int, missing_parent: int | None) -> None:
        dur = s.get("dur_ms")
        rel = (s.get("t", t0) - t0) * 1e3
        lines.append(
            f"  {'  ' * depth}[+{rel:8.2f} ms] {s.get('name', '?'):<22} "
            f"span {s.get('span')}  tier {s.get('tier', '?')}"
            + (f"  {float(dur):.3f} ms" if dur is not None else "")
            + (
                f"  !! parent span {missing_parent} MISSING "
                "(dropped/torn hop)" if missing_parent is not None else ""
            )
        )
        for child in kids.get(int(s["span"]), []) if s.get("span") is not None else []:
            emit(child, depth + 1, None)

    for root in kids.get(None, []):
        emit(root, 0, None)
    for parent_key in sorted(
        (k for k in kids if isinstance(k, tuple)),
        key=lambda k: k[1],
    ):
        for orphan in kids[parent_key]:
            emit(orphan, 0, parent_key[1])
    return lines


def trace_report(folder: str, limit: int = 16) -> str | None:
    """Human-readable causal trace timelines for ``surreal_tpu trace``:
    one span tree per head-sampled exemplar, newest last, torn hops
    marked. None when the folder has no telemetry."""
    s = trace_summary(folder)
    if s is None:
        return None
    exemplars = s["exemplars"]
    header = f"Causal trace exemplars — {s['folder']}"
    if s.get("trace_id"):
        header += f" (trace {s['trace_id']})"
    lines = [header]
    total = sum(len(v) for v in exemplars.values())
    summary = f"{len(exemplars)} exemplar(s), {total} span event(s)"
    if s.get("dropped_spans"):
        summary += (
            f"; {s['dropped_spans']:g} span(s) DROPPED by chaos — "
            "trees below may be torn"
        )
    lines.append(summary)
    if not exemplars:
        lines.append("  (no causal spans recorded — telemetry.trace "
                     "disabled or nothing sampled yet)")
        return "\n".join(lines)
    ordered = sorted(
        exemplars.items(),
        key=lambda kv: min(s.get("t", 0) for s in kv[1]),
    )
    if len(ordered) > limit:
        lines.append(f"  (showing oldest {limit} of {len(ordered)})")
        ordered = ordered[:limit]
    for name, spans in ordered:
        tiers = []
        for sp in sorted(spans, key=lambda x: (x.get("t", 0),
                                               x.get("seq", 0))):
            tier = sp.get("tier", "?")
            if tier not in tiers:
                tiers.append(tier)
        lines.append("")
        lines.append(
            f"exemplar {name} — {len(spans)} span(s), tiers: "
            + " -> ".join(tiers)
        )
        lines += _render_exemplar(spans)
    return "\n".join(lines)
