"""On-demand profiling (ISSUE 6 tentpole, piece 3): programmatic
``jax.profiler`` windows started/stopped at iteration boundaries.

Three triggers, one manager (owned by SessionHooks, ticked once per
``end_iteration``):

- **legacy window** — the pre-existing ``session.profiler`` knob
  (enabled/start_iter/num_iters) still works; its capture now lands under
  ``telemetry/profiles/`` with the on-demand ones.
- **trigger file** — ``surreal_tpu profile <folder>`` writes
  ``<folder>/profile.trigger``; the running session polls for it (stat
  throttled to once per second — the hot loop pays nothing) and captures
  a ``session.profile.num_iters`` window starting at the next iteration
  boundary, then removes the file. The file's JSON body may override
  ``num_iters``.
- **slow-iteration auto-trigger** — when ``session.profile.
  slow_iter_factor`` is set, an iteration whose host wall time exceeds
  factor x the iteration-time EWMA starts a capture automatically (at
  most ``max_auto_captures`` per run). Detection is pure host clock
  deltas between boundary ticks: no device syncs, transfer-guard safe.

Every capture directory is ``<folder>/telemetry/profiles/<tag>/`` and is
announced as a ``profile`` telemetry event (``diag`` renders them), so a
session folder answers "was this run ever profiled, and where is the
trace?" offline.
"""

from __future__ import annotations

import json
import os
import time

from surreal_tpu.session.telemetry import PROFILES_DIR, TELEMETRY_DIR

TRIGGER_FILE = "profile.trigger"

# EWMA shape for the slow-iteration detector: first _WARM_TICKS ticks only
# seed the average (compiles + cache warmup dominate there), later ticks
# blend at _ALPHA. A capture in progress suspends detection.
_WARM_TICKS = 10
_ALPHA = 0.1


def write_trigger(folder: str, num_iters: int | None = None) -> str:
    """Drop the trigger file a live session polls for (the CLI side of
    ``surreal_tpu profile <folder>``). Atomic tmp+rename: the session
    may race the write."""
    path = os.path.join(folder, TRIGGER_FILE)
    body = {} if num_iters is None else {"num_iters": int(num_iters)}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(body, f)
    os.replace(tmp, path)
    return path


class ProfileManager:
    """Iteration-boundary profiler control. ``tick(iteration)`` is cheap
    in the steady state: one monotonic read, one EWMA update, and (at
    most once per second) one ``os.path.exists``."""

    def __init__(self, session_cfg, folder: str, tracer, log):
        self._folder = folder
        self._tracer = tracer
        self._log = log
        prof = session_cfg.get("profile", None)
        self._trigger_enabled = (
            bool(prof.get("trigger_file", True)) if prof is not None else True
        )
        self._num_iters = int(prof.get("num_iters", 5)) if prof is not None else 5
        factor = prof.get("slow_iter_factor", None) if prof is not None else None
        self._slow_factor = float(factor) if factor else None
        self._max_auto = (
            int(prof.get("max_auto_captures", 2)) if prof is not None else 2
        )
        self._auto_fired = 0
        # legacy fixed window (session.profiler): folded into the same
        # capture machinery so both paths share start/stop + telemetry
        legacy = session_cfg.get("profiler", None)
        self._legacy_start = None
        self._legacy_iters = 5
        if legacy is not None and legacy.get("enabled", False):
            self._legacy_start = int(legacy.get("start_iter", 20))
            self._legacy_iters = int(legacy.get("num_iters", 5))
        self._trigger_path = os.path.join(folder, TRIGGER_FILE)
        self._last_stat = 0.0
        self._pending: tuple[str, int] | None = None  # (reason, num_iters)
        self._active: dict | None = None
        # newest completed capture directory — the incident engine links
        # the capture it auto-requested into the incident record from here
        self.last_capture_dir: str | None = None
        self._last_tick: float | None = None
        self._last_iter = 0  # newest iteration ticked (close() reports it)
        self._ewma_s: float | None = None
        self._ticks = 0

    # -- capture lifecycle ---------------------------------------------------
    def _start(self, iteration: int, reason: str, num_iters: int) -> None:
        tag = f"iter{iteration:08d}"
        trace_dir = os.path.join(
            self._folder, TELEMETRY_DIR, PROFILES_DIR, tag
        )
        try:
            os.makedirs(trace_dir, exist_ok=True)
            import jax

            jax.profiler.start_trace(trace_dir)
        except Exception as e:
            # profiling must never kill training (missing profiler deps,
            # unwritable folder); record the failure instead
            self._log.warning("profiler start failed (%s): %s", reason, e)
            self._tracer.event(
                "profile", dir=trace_dir, reason=reason, error=str(e)
            )
            return
        self._active = {
            "dir": trace_dir,
            "reason": reason,
            "start_iter": int(iteration),
            "stop_at": int(iteration) + max(1, num_iters),
        }
        self._log.info(
            "profiler capture started (%s) -> %s", reason, trace_dir
        )

    def _stop(self, iteration: int) -> None:
        act = self._active
        self._active = None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            self._log.warning("profiler stop failed: %s", e)
        if act is not None:
            self.last_capture_dir = act["dir"]
            self._tracer.event(
                "profile", dir=act["dir"], reason=act["reason"],
                start_iter=act["start_iter"], end_iter=int(iteration),
            )
            self._log.info("profiler capture saved -> %s", act["dir"])

    def request(self, reason: str, num_iters: int | None = None) -> bool:
        """Queue a capture window starting at the next boundary tick —
        the incident engine's auto-capture path (programmatic spelling of
        the trigger file). Refused (False) while a capture is active or
        already queued, so one incident cannot stack windows."""
        if self._active is not None or self._pending is not None:
            return False
        self._pending = (str(reason), max(1, int(num_iters or self._num_iters)))
        return True

    # -- per-iteration tick --------------------------------------------------
    def tick(self, iteration: int) -> None:
        now = time.monotonic()
        self._last_iter = int(iteration)
        # slow-iteration detector: host wall time between boundary ticks
        if self._last_tick is not None:
            dt = now - self._last_tick
            self._ticks += 1
            if self._ewma_s is None:
                self._ewma_s = dt
            elif self._ticks <= _WARM_TICKS:
                self._ewma_s += (dt - self._ewma_s) / self._ticks
            else:
                if (
                    self._slow_factor is not None
                    and self._active is None
                    and self._pending is None
                    and self._auto_fired < self._max_auto
                    and dt > self._slow_factor * self._ewma_s
                ):
                    self._auto_fired += 1
                    self._log.warning(
                        "slow iteration %d: %.3fs vs %.3fs EWMA (>%.1fx) — "
                        "auto-capturing a profile window",
                        iteration, dt, self._ewma_s, self._slow_factor,
                    )
                    self._pending = (
                        f"slow_iter({dt:.3f}s/{self._ewma_s:.3f}s)",
                        self._num_iters,
                    )
                self._ewma_s += _ALPHA * (dt - self._ewma_s)
        self._last_tick = now

        if self._active is not None:
            if iteration >= self._active["stop_at"]:
                self._stop(iteration)
            return

        # legacy fixed window
        if self._legacy_start is not None and iteration >= self._legacy_start:
            self._legacy_start = None  # one window per run
            self._start(iteration, "profiler_knob", self._legacy_iters)
            return

        if self._pending is not None:
            reason, n = self._pending
            self._pending = None
            self._start(iteration, reason, n)
            return

        # trigger file, stat-throttled to once per second
        if self._trigger_enabled and now - self._last_stat >= 1.0:
            self._last_stat = now
            if os.path.exists(self._trigger_path):
                n = self._num_iters
                try:
                    with open(self._trigger_path) as f:
                        body = json.load(f)
                    n = int(body.get("num_iters", n))
                except (OSError, json.JSONDecodeError, ValueError, TypeError):
                    pass
                try:
                    os.unlink(self._trigger_path)
                except OSError:
                    pass
                self._start(iteration, "trigger_file", n)

    def close(self) -> None:
        # a capture cut short by run end must report the iteration it
        # actually reached, not the stop_at it never got to
        if self._active is not None:
            self._stop(self._last_iter)
