"""In-graph cost / MFU accounting (ISSUE 6 tentpole, piece 1).

The telemetry spine (PR 1) records wall-clock *phases* per process; this
module adds the compute-cost axis HEPPO-GAE (arXiv:2501.12703) used to
justify hardware-pipelined GAE: per-program FLOPs, bytes accessed, and
arithmetic intensity pulled from XLA's own cost model, resolved against a
per-backend peak-FLOPs/bandwidth table so the metrics stream carries live
``perf/mfu`` and ``perf/membw_util`` gauges — the instrument panel the
">=10x MFU" roadmap item is measured on.

Design constraints (the transfer-guard tests enforce the first):

- ZERO extra device->host syncs. Program costs come from
  ``jitted.lower(*args).cost_analysis()`` — tracing plus an HLO cost pass,
  both host-side — recorded ONCE per program at driver startup; the live
  gauges are pure host float arithmetic over the tracer's already-recorded
  phase windows (``Tracer.last_window``). Nothing here ever touches a
  device value.
- ``memory_analysis()`` needs a real backend compile, which is minutes of
  XLA on a chip and (on jax 0.4.x) is NOT shared with the jit call cache —
  so it runs only when it is known-cheap: ``session.perf.memory_analysis
  = 'auto'`` compiles only when the persistent compile cache is active
  (either order, one of the two compiles is then a disk deserialize);
  ``True``/``False`` force it.
- Honesty over coverage: a program whose tracer phase measures MORE than
  the program itself (the host ``rollout`` phase contains env stepping)
  yields a LOWER-bound MFU contribution; programs with no phase at all
  (the SEED act closure serves on its own thread) are recorded for
  ``diag`` but excluded from the live gauges rather than guessed at.

Gauge registry: every ``perf/*`` scalar the codebase emits MUST be listed
in :data:`GAUGE_REGISTRY` — ``tests/test_import_hygiene.py`` lints source
literals against it, so a new gauge cannot ship undocumented.
"""

from __future__ import annotations

from typing import Any

# the units a registered gauge may declare. The watchdog's threshold
# arithmetic keys off these (counters vs latencies vs ratios), and
# `surreal_tpu why` renders firing values with them — so the unit lint
# in tests/test_import_hygiene.py rejects anything outside this set.
GAUGE_UNITS = frozenset(
    {"ms", "bytes", "count", "ratio", "steps/s", "flops/s", "scalar"}
)


def _g(unit: str, desc: str) -> dict[str, str]:
    """One GAUGE_REGISTRY record: a documented description plus the
    machine-readable unit (ISSUE 15 — units used to live only in
    prose)."""
    return {"unit": unit, "desc": desc}


def gauge_unit(name: str) -> str | None:
    """The declared unit of a registered gauge, None for unregistered
    names (per-instance body keys the tiers invent)."""
    rec = GAUGE_REGISTRY.get(name)
    return rec.get("unit") if isinstance(rec, dict) else None


# Documented registry of every perf/*, replay/*, experience/*, fleet/*,
# param/*, and gateway/* gauge the codebase may emit.
# tests/test_import_hygiene.py::test_perf_gauges_appear_in_registry scans
# the package source for whole "<prefix>/<name>" literals and fails on
# any not listed here; every record carries a {unit, desc} dict (the unit
# lint rejects a bare string). Keep descriptions current — diag and
# README point here. Per-shard detail for the experience plane rides the
# 'experience_plane' telemetry EVENT (diag's "Experience plane" section);
# the metrics-row gauges below are the fleet aggregates.
GAUGE_REGISTRY = {
    "perf/mfu": _g("ratio",
        'model FLOP utilization over the metrics window: sum over '
        'registered programs of (flops/call x calls) / (phase seconds x '
        'peak FLOP/s). Lower bound when a phase contains non-program work.'),
    "perf/membw_util": _g("ratio",
        'memory-bandwidth utilization over the metrics window: bytes '
        'accessed (XLA cost model) per second / peak bytes/s.'),
    "perf/flops_per_s": _g("flops/s",
        'achieved model FLOP/s over the metrics window (the MFU numerator; '
        'emitted even when no peak spec is known for the device).'),
    # -- replay occupancy (replay/base.py ring gauges; device scalars) ------
    "replay/size": _g("count",
        'absolute ring fill (transitions currently held).'),
    "replay/fill": _g("ratio", 'ring fill as a fraction of capacity.'),
    "replay/max_priority": _g("scalar",
        "prioritized replay's fresh-insert priority scale (pmax-synced "
        'across dp shards).'),
    "replay/sample_age_frac": _g("ratio",
        'mean staleness of a sampled index batch as a fraction of the '
        'current fill (0 = just written).'),
    # -- experience plane (surreal_tpu/experience/; fleet aggregates) -------
    "experience/shards_live": _g("count",
        'replay shard servers currently alive.'),
    "experience/respawns": _g("count",
        'shard respawns performed by the plane supervisor this run.'),
    "experience/rows": _g("count",
        'total transitions ingested across all shards.'),
    "experience/fill": _g("ratio", 'mean shard ring fill fraction.'),
    "experience/ingest_rows_per_s": _g("steps/s",
        'summed shard ingestion rate (the actor-fleet throughput the plane '
        'absorbs).'),
    "experience/wire_bytes_per_step": _g("bytes",
        'shard-side wire bytes (in+out) per ingested transition — the '
        'zero-copy success metric (control frames vs shipped arrays).'),
    "experience/sample_queue_depth": _g("count",
        'sample requests deferred at shards (watermark not yet ingested).'),
    "experience/sample_wait_ms": _g("ms",
        "EWMA of the learner's wait for a prefetched iteration of batches — "
        '~0 means the learner never waits on experience ingest.'),
    "experience/dropped_rows": _g("count",
        "transitions dropped after the sender's bounded retry budget "
        'exhausted against a dead shard.'),
    "experience/sent_rows": _g("count",
        "sender-side transitions handed to the wire (watermark units — "
        're-based to the shard ledger on a re-hello); with ingested + '
        'dropped + inflight it closes the exactly-once conservation law '
        'the chaos oracle checks.'),
    # -- serving tier (distributed/fleet.py; fleet aggregates) --------------
    "fleet/replicas_live": _g("count",
        'inference-server replicas currently alive.'),
    "fleet/respawns": _g("count",
        'replica respawns performed by the fleet supervisor this run (in '
        'place, fixed address, exponential backoff).'),
    "fleet/scale_ups": _g("count", 'autoscale replica additions this run.'),
    "fleet/scale_downs": _g("count", 'autoscale replica drains this run.'),
    "fleet/serve_ms": _g("ms",
        "fleet-mean serve-latency EWMA — the autoscaler's up/down signal."),
    "fleet/queue_depth": _g("count",
        'summed trajectory-chunk queue depth across replicas.'),
    # -- parameter fanout (distributed/param_fanout.py) ---------------------
    "param/publishes": _g("count",
        'weight frames broadcast by the fanout this run.'),
    "param/full_frames": _g("count", 'full (key) frames among them.'),
    "param/delta_frames": _g("count", 'delta frames among them.'),
    "param/rekeys": _g("count",
        'full frames FORCED by a stale/absent subscriber ack (a dropped '
        'frame or late joiner re-keys the delta stream).'),
    "param/bytes_last_publish": _g("bytes", 'wire bytes of the newest frame.'),
    "param/bytes_published": _g("bytes",
        'cumulative fanout wire bytes this run.'),
    "param/subscribers": _g("count",
        'subscribers with a fresh (ttl-bounded) ack.'),
    # subscriber-side counters (ParameterSubscriber.gauges — actor/eval
    # processes and tests; not part of the trainer's metrics rows)
    "param/applied_frames": _g("count", 'frames this subscriber applied.'),
    "param/stale_frames": _g("count",
        'inapplicable deltas this subscriber dropped (missed frame / fresh '
        'join) — each flags needs_resync toward the fetch fallback.'),
    "param/fallback_fetches": _g("count",
        'ParameterClient.fetch catch-ups this subscriber performed (the '
        'late-joiner / dropped-frame path; counted, never silent).'),
    "param/holds": _g("count",
        'param versions the fanout currently holds pinned for gateway '
        'sessions (full frames retained until every pin releases).'),
    # -- session gateway (surreal_tpu/gateway/; tenant-facing tier) ---------
    "gateway/sessions": _g("count",
        'sessions currently attached across all tenants.'),
    "gateway/attaches": _g("count",
        'sessions admitted this run (first attach only).'),
    "gateway/reattaches": _g("count",
        're-attaches onto a live session id (client reconnect; the session '
        'record and its replica binding survive).'),
    "gateway/detaches": _g("count", 'explicit tenant detaches this run.'),
    "gateway/acts": _g("count", 'act requests served (cache hits included).'),
    "gateway/cache_hits": _g("count",
        'acts answered from the bounded (version, obs-digest) act cache '
        'without touching a fleet replica.'),
    "gateway/cache_misses": _g("count",
        'acts that paid a fleet serve_act forward.'),
    "gateway/migrations": _g("count",
        'session rebinds performed after a replica death (invisible '
        'failover; counted per moved session).'),
    "gateway/catch_ups": _g("count",
        'pinned sessions force-unpinned because their param version was '
        "evicted from the fleet's act history (flagged on the reply — "
        'counted, never silent).'),
    "gateway/pinned_sessions": _g("count",
        'sessions currently pinned to a param version.'),
    "gateway/dropped_replies": _g("count",
        'act replies swallowed by fault injection (gateway.session '
        "drop_frame); the client's bounded resend redelivers."),
    "gateway/bad_frames": _g("count",
        "malformed/hostile tenant frames dropped at the serve loop's frame "
        'boundary (truncated headers, bad obs bodies, undecodable or '
        'un-negotiated pickle fallbacks) — counted, never a crash.'),
    "gateway/respawns": _g("count",
        'gateway serve-thread respawns performed by its supervisor (in '
        'place, fixed address, shared backoff schedule).'),
    # admission plane (gateway/admission.py)
    "gateway/rejected_sessions": _g("count",
        'attach attempts refused — by session quota (global or per-tenant) '
        'or by the re-attach tenant/token credential check.'),
    "gateway/throttled_acts": _g("count",
        "acts past a tenant's token-bucket rate, parked in its bounded "
        'queue instead of served immediately.'),
    "gateway/evicted_requests": _g("count",
        "oldest queued acts evicted when a tenant's backpressure queue "
        'overflowed (each gets an ACT_ERR — counted, never silent).'),
    "gateway/expired_leases": _g("count",
        'sessions reaped idle past their lease.'),
    "gateway/queued_acts": _g("count",
        'acts currently parked across tenant queues.'),
    # -- live ops plane (session/opsplane.py; ISSUE 13) ---------------------
    "ops/tiers": _g("count",
        'tiers that have pushed at least one row to the run aggregator '
        '(gateway, fleet replicas, experience shards, learner, fanout).'),
    "ops/bad_frames": _g("count",
        "undecodable/hostile rows dropped at the aggregator's PULL boundary "
        '— counted, never a crash.'),
    "ops/snapshots": _g("count",
        'merged run snapshots written to telemetry/ops_snapshot.json (one '
        'per metrics cadence; the file `surreal_tpu top` renders).'),
    "ops/flightrec_dumps": _g("count",
        'flight-recorder dumps written under telemetry/flightrec/ (recovery '
        'trip, chaos fault, SLO budget exhaustion, or an opened incident; '
        'at most one per trigger per cooldown).'),
    # watchdog & incident engine (session/watchdog.py, session/incidents.py)
    "ops/watchdog_evals": _g("count",
        'detector sweeps run over merged ops snapshots (one per metrics '
        'cadence while session_config.watchdog.enabled).'),
    "ops/watchdog_dropped_evals": _g("count",
        'detector sweeps skipped by the watchdog.eval chaos site '
        '(drop_eval) — counted, never silent.'),
    "ops/watchdog_firings": _g("count",
        'detector firings across all sweeps this run (breakout, '
        'saturation, growth, liveness, regression).'),
    "ops/incidents_open": _g("count",
        'whether an incident is currently open (0/1 — the engine holds at '
        'most one open incident, extending it while detectors keep '
        'firing).'),
    "ops/incidents_total": _g("count",
        'incidents opened this run (each persisted under '
        'telemetry/incidents/incident-<n>.json and rendered by '
        '`surreal_tpu why`).'),
    # per-tenant SLOs (session/slo.py)
    "slo/breaches": _g("count",
        'SLO evaluation windows that breached a declared objective (every '
        'one is also a counted slo_breach telemetry event).'),
    "slo/exhaustions": _g("count",
        'error budgets exhausted this run (edge-triggered: one per '
        'incident, each freezing a flightrec/slo dump).'),
    "slo/objectives": _g("count",
        'objectives armed via session_config.slo.* targets.'),
    "lineage/staleness_p50": _g("count",
        'exact per-update staleness median: p50 over (current version - '
        'acting version) of every transition in the batch that entered this '
        'gradient, from the collection-time lineage stamps. Host numpy over '
        'the already-fetched version column — no device sync.'),
    "lineage/staleness_p99": _g("count",
        "exact per-update staleness p99 over the batch's acting-policy "
        "versions (the SLO plane's staleness objective prefers this over "
        'the published-vs-held approximation when lineage is on).'),
    "lineage/staleness_max": _g("count",
        'oldest transition that entered this update, in version lags.'),
    "lineage/versions_per_batch": _g("count",
        "distinct acting-policy versions mixed into this update's batch (1 "
        '== perfectly on-policy data).'),
    "trace/spans": _g("count",
        "causal spans emitted so far by this process's tracer (head-sampled "
        'exemplars, telemetry.trace.sample_n).'),
    "trace/dropped_spans": _g("count",
        'spans dropped by the trace.emit chaos site — counted, never '
        "silent; the exemplar's tree renders with the torn hop marked."),
    # -- closed-loop remediation (session/remediate.py; ISSUE 16) -----------
    "remediation/actions": _g("count",
        'bounded actions executed by the remediation engine (each a '
        'remediation event, an atomic telemetry/actions/action-<n>.json '
        "record, and evidence on the incident that triggered it)."),
    "remediation/suppressed": _g("count",
        'would-be actions stopped by the global max_actions budget or a '
        'per-kind cooldown — loud (a counted remediation event), never a '
        'silent retry loop.'),
    "remediation/unmapped": _g("count",
        "decision sweeps where the open incident's top cause had no bound "
        'actuator or no actionable target — counted, never guessed.'),
    "remediation/reverted": _g("count",
        'actions undone by the counter-detector after their triggering '
        'objective regressed further (quota restored, replica drained, '
        'overrides rolled back).'),
    "remediation/ineffective": _g("count",
        'actions the counter-detector judged ineffective over '
        'verify_windows post-action sweeps.'),
    "remediation/effective": _g("count",
        'actions whose triggering objective did NOT regress further over '
        'the verification window.'),
    "remediation/errors": _g("count",
        'actuator calls that raised (execute or revert) — journaled and '
        'counted; actuation must never kill training.'),
    "remediation/active": _g("count",
        'actions currently inside their verification window.'),
    # -- elastic learner group (parallel/learner_group.py; ISSUE 17) --------
    "lgroup/members": _g("count",
        'alive data-parallel learner-group members draining the '
        'experience plane.'),
    "lgroup/rebalances": _g("count",
        'shard-subset repartitions (join/leave/failure/respawn each '
        'costs one rebalance, not a run).'),
    "lgroup/rekeys": _g("count",
        'fanout full-frame re-keys forced by membership changes (each '
        'also counts into param/rekeys on the one distribution tree).'),
    "lgroup/joins": _g("count", 'members that joined mid-run.'),
    "lgroup/leaves": _g("count",
        'members removed mid-run (planned scale-down).'),
    "lgroup/respawns": _g("count",
        'crashed members revived under the RespawnSchedule backoff.'),
    "lgroup/respawn_backoff_s": _g("scalar",
        'current member-respawn backoff (exponential, capped).'),
    "lgroup/sample_wait_ms": _g("ms",
        "slowest member's EWMA batch-stitch wait — the group analogue "
        'of experience/sample_wait_ms.'),
    "lgroup/allreduce_learns": _g("count",
        'SGD updates run through the shard_map gradient all-reduce '
        '(M members on >= M devices).'),
    "lgroup/fallback_learns": _g("count",
        'M>1 updates degraded to ONE full-batch learn (single device / '
        'indivisible batch) — the honesty counter: artifacts report a '
        'ratio, never a fabricated speedup.'),
    # -- tenant load generator (gateway/loadgen.py; ISSUE 16) ---------------
    "gateway/quota_changes": _g("count",
        'runtime per-tenant quota mutations via AdmissionController.'
        'set_quota (operator reconfigs and remediation throttles alike).'),
    "loadgen/tenants": _g("count",
        'tenant threads in the generator mix (steady + abusive profiles).'),
    "loadgen/attaches": _g("count",
        'sessions the generator attached across all tenants.'),
    "loadgen/detaches": _g("count",
        'sessions the generator detached (attach_storm churns these).'),
    "loadgen/acts": _g("count",
        'acts served to generator tenants end-to-end.'),
    "loadgen/act_errors": _g("count",
        'acts answered with a counted gateway rejection (throttle '
        'eviction, quota, dead session) — the expected outcome for the '
        'abusive profiles.'),
    "loadgen/rejected": _g("count",
        'attach attempts denied by admission control.'),
    "loadgen/timeouts": _g("count",
        'acts that exhausted client retries without a reply.'),
    "loadgen/hostile_frames": _g("count",
        'malformed frames the adversarial profile put on the wire (each '
        "must land in the server's gateway/bad_frames, never a crash)."),
    "loadgen/act_rtt_ms": _g("ms",
        'mean client-observed act round-trip across generator tenants.'),
    # -- replay tiers (replay/tiers.py, experience/spill.py; ISSUE 18) ------
    "tier/hot_size": _g("count",
        "transitions resident in the device hot ring."),
    "tier/hot_fill": _g("ratio",
        "hot ring occupancy (size / hot_capacity)."),
    "tier/hot_hits": _g("count",
        'updates whose batch was drawn on-device from the hot tier '
        '(no wire frame, no host->device transfer).'),
    "tier/hot_misses": _g("count",
        'updates that fell back to the warm shard fan-in (hot ring '
        'still filling) — counted, never silent.'),
    "tier/spill_segments": _g("count",
        'WAL segments appended across shards (experience/spill.py).'),
    "tier/spill_rows": _g("count",
        'transitions spilled to the WAL across shards.'),
    "tier/spill_bytes": _g("bytes",
        'total WAL bytes on disk across shards (framed, after '
        'quantization).'),
    "tier/spill_errors": _g("count",
        'WAL appends that failed (ENOSPC, IO error) — the writer '
        'degrades and the warm ring keeps serving.'),
    "tier/spill_failed": _g("count",
        'shards whose writer latched off after consecutive append '
        'failures (1 per latched shard).'),
    "tier/cold_bytes_per_row": _g("bytes",
        'encoded WAL bytes per transition (the quantization win vs the '
        'raw f32 row — BENCH_tiers.json commits the ratio).'),
    "tier/torn_segments": _g("count",
        'torn WAL segments skipped by magic-resync on read (crash '
        'mid-append; the experience.spill chaos site drives this).'),
    # ---- loop engine (engine/core.py, ISSUE 19) ----
    "engine/stage_p50_ms": _g("ms",
        'median deferred-boundary duration (publish/checkpoint/observe '
        'side-bands + metrics materialization), last 512 boundaries.'),
    "engine/stage_p99_ms": _g("ms",
        'p99 deferred-boundary duration over the same window.'),
    "engine/occupancy": _g("ratio",
        'staging-worker busy fraction of wall time while pipelining — '
        'the off-critical-path work actually reclaimed.'),
    "engine/queue_depth": _g("count",
        'deferred boundaries in flight (bounded at 1: one pending slot).'),
    "engine/deferred_boundaries": _g("count",
        'boundaries submitted to the staging executor this run.'),
    "engine/skipped_boundaries": _g("count",
        'boundaries skipped because the previous one wedged past '
        'stage_timeout_s (never silent — warned and counted).'),
    "engine/stage_kills": _g("count",
        'engine.stage kill_stage chaos firings absorbed by the boundary '
        '(the stage crashed; training continued).'),
    # ---- chaos campaigns (chaos/campaign.py, ISSUE 20) ----
    "chaos/schedules": _g("count",
        'seeded multi-site fault schedules executed by this campaign.'),
    "chaos/violations": _g("count",
        'invariant-oracle violations across the campaign (the gate '
        'requires zero in the committed artifact).'),
    "chaos/faults_injected": _g("count",
        'fault firings actually delivered across all campaign runs '
        '(plan entries whose site reached its scheduled call count).'),
    "chaos/sites_covered": _g("count",
        'distinct fault sites that FIRED at least once this campaign '
        '(the artifact gate requires >= 10).'),
    "chaos/shrink_iters": _g("count",
        're-runs spent by the greedy shrinker reducing failing '
        'schedules to minimal form (0 on a clean campaign).'),
    "chaos/run_ms": _g("ms", 'campaign wall-clock, all runs + shrinking.'),
}

# Public peak specs per accelerator generation: (peak FLOP/s bf16,
# peak HBM bytes/s). Matched by substring against the jax device_kind
# string (lowercased). Sources: public TPU spec sheets; the v5e row is
# the same 197 TFLOP/s bench.py's MFU denominator has always used.
PEAK_SPECS: tuple[tuple[str, float, float], ...] = (
    ("v5 lite", 197e12, 819e9),   # TPU v5e (jax reports 'TPU v5 lite')
    ("v5litepod", 197e12, 819e9),
    ("v5e", 197e12, 819e9),
    ("v5p", 459e12, 2765e9),
    ("v6 lite", 918e12, 1640e9),  # Trillium
    ("v6e", 918e12, 1640e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
    # host CPU: a nominal single-core order-of-magnitude figure so test
    # images still exercise the full gauge path; real CPU runs should
    # override via session.perf.peak_flops / peak_membw
    ("cpu", 1e11, 5e10),
)


class PeakSpec:
    """Resolved peak numbers for the active backend."""

    __slots__ = ("flops", "membw", "device_kind", "source")

    def __init__(self, flops, membw, device_kind: str, source: str):
        self.flops = float(flops) if flops else None
        self.membw = float(membw) if membw else None
        self.device_kind = device_kind
        self.source = source  # 'override' | 'table' | 'unknown'

    def to_dict(self) -> dict:
        return {
            "peak_flops": self.flops,
            "peak_membw": self.membw,
            "device_kind": self.device_kind,
            "peak_source": self.source,
        }


def resolve_peak_spec(session_cfg) -> PeakSpec:
    """Peak FLOP/s + bytes/s for the active backend: the
    ``session.perf.peak_flops``/``peak_membw`` overrides win; otherwise
    the :data:`PEAK_SPECS` device-kind table; otherwise an 'unknown'
    spec (costs still recorded, utilization gauges limited to
    ``perf/flops_per_s``)."""
    from surreal_tpu.utils.compat import device_kind

    kind = device_kind()
    perf = session_cfg.get("perf", None) if session_cfg is not None else None
    over_f = perf.get("peak_flops", None) if perf is not None else None
    over_b = perf.get("peak_membw", None) if perf is not None else None
    if over_f or over_b:
        # a partial override fills the other half from the table
        t_f, t_b = _table_lookup(kind)
        return PeakSpec(over_f or t_f, over_b or t_b, kind, "override")
    t_f, t_b = _table_lookup(kind)
    if t_f is not None:
        return PeakSpec(t_f, t_b, kind, "table")
    return PeakSpec(None, None, kind, "unknown")


def _table_lookup(kind: str) -> tuple[float | None, float | None]:
    lowered = (kind or "").lower()
    for needle, flops, membw in PEAK_SPECS:
        if needle in lowered:
            return flops, membw
    return None, None


def program_costs(jitted, *args, **kwargs) -> dict | None:
    """XLA cost model of one jitted program at these arg shapes:
    ``{"flops", "bytes_accessed", "arithmetic_intensity"}``, or None when
    the backend reports nothing. Host-side only — ``lower()`` traces and
    the cost pass runs on the unoptimized HLO; no compile, no device
    work, no transfers (safe before the first dispatch, and safe on
    donated-arg programs: lowering consumes no buffers)."""
    try:
        ca = jitted.lower(*args, **kwargs).cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):  # some backends wrap per-device
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or "flops" not in ca:
        return None
    flops = float(ca["flops"])
    byts = float(ca.get("bytes accessed", 0.0))
    out = {
        "flops": flops,
        "bytes_accessed": byts,
        "arithmetic_intensity": (flops / byts) if byts > 0 else None,
    }
    return out


def program_memory(jitted, *args, **kwargs) -> dict | None:
    """``memory_analysis()`` of the COMPILED program (argument/output/temp
    bytes). Pays a real XLA compile — call only when that is known-cheap
    (see the module doc); returns None on any failure."""
    try:
        ma = jitted.lower(*args, **kwargs).compile().memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for k in (
        "argument_size_in_bytes", "output_size_in_bytes",
        "temp_size_in_bytes", "generated_code_size_in_bytes",
    ):
        v = getattr(ma, k, None)
        if v is not None:
            out[k.replace("_in_bytes", "")] = int(v)
    return out or None


class CostAccountant:
    """Per-session registry of hot-program costs + the live perf gauges.

    Drivers register each jitted hot program once, before (or right after)
    its first dispatch, naming the tracer phase that measures it::

        hooks.record_program_costs(
            "train_iter", self._train_iter, state, carry, key,
            phase="train_iter",
        )

    ``gauges(window)`` then turns any flushed phase window (the
    ``{name: {count, total_s, ...}}`` dict ``Tracer.flush_phases``
    snapshots into ``Tracer.last_window``) into ``perf/*`` host floats.
    """

    def __init__(self, session_cfg, on_event=None, log=None, policy=None):
        # policy: the learner's resolved PrecisionPolicy (ops/precision.py)
        # — stamped into every program_cost record/event so committed
        # artifacts carry bytes/MFU rows PER PRECISION POLICY, never
        # silently mixed across policy arms
        self.policy = policy
        self._cfg = session_cfg
        self.enabled = True
        perf = session_cfg.get("perf", None) if session_cfg is not None else None
        if perf is not None and not perf.get("enabled", True):
            self.enabled = False
        self._mem_mode = (
            perf.get("memory_analysis", "auto") if perf is not None else "auto"
        )
        self._on_event = on_event
        self._log = log
        self._programs: dict[str, dict] = {}
        self._failed: set[str] = set()  # don't re-lower every iteration
        # when a backend reports no cost model (record sites in host/SEED
        # loops call record_program once per iteration, idempotently)
        self.peak: PeakSpec | None = None  # resolved lazily (first record
        # touches jax.devices(); constructing hooks must not)

    @property
    def programs(self) -> dict[str, dict]:
        return dict(self._programs)

    def _memory_analysis_ok(self) -> bool:
        if self._mem_mode is True:
            return True
        if not self._mem_mode:  # False/None
            return False
        # 'auto': only when the extra AOT compile is known-cheap — a
        # persistent compile cache turns it into a disk deserialize
        # (either order: AOT first warms the cache for the jit call, or
        # vice versa). Without the cache it is a real second XLA compile
        # of the largest program in the process — minutes on a chip, and
        # a measurable tax even on the CPU test image — so 'auto' stays
        # off. Multi-process compilation may coordinate: always off there.
        import jax

        if jax.process_count() > 1:
            return False
        from surreal_tpu.utils.compat import compile_cache_active

        return compile_cache_active()

    def record_program(
        self, name: str, jitted, *args,
        phase: str | None = None, calls_per_phase: int = 1, **kwargs,
    ) -> dict | None:
        """Record one program's cost analysis (idempotent per ``name``).
        Emits a ``program_cost`` telemetry event via ``on_event``. Returns
        the record, or None when disabled / the backend reports nothing."""
        if not self.enabled or name in self._failed:
            return None
        if name in self._programs:
            return self._programs[name]
        if self.peak is None:
            # resolved on first use, not at construction: this touches
            # jax.devices(), and hooks must stay constructible pre-backend
            try:
                self.peak = resolve_peak_spec(self._cfg)
            except Exception:
                self.peak = PeakSpec(None, None, "unknown", "unknown")
        costs = program_costs(jitted, *args, **kwargs)
        if costs is None:
            self._failed.add(name)
            if self._log is not None:
                self._log.info(
                    "cost accounting: backend reports no cost model for "
                    "program %r", name,
                )
            return None
        rec = {
            "name": name,
            "phase": phase,
            "calls_per_phase": int(calls_per_phase),
            **costs,
        }
        if self.policy is not None:
            rec["precision"] = getattr(self.policy, "name", str(self.policy))
        if self._memory_analysis_ok():
            mem = program_memory(jitted, *args, **kwargs)
            if mem is not None:
                rec["memory"] = mem
        self._programs[name] = rec
        if self._log is not None:
            self._log.info(
                "program cost %r: %.3g FLOPs/call, %.3g bytes/call%s",
                name, rec["flops"], rec["bytes_accessed"],
                (
                    f", AI {rec['arithmetic_intensity']:.2f}"
                    if rec.get("arithmetic_intensity") else ""
                ),
            )
        if self._on_event is not None:
            self._on_event("program_cost", **rec, **self.peak.to_dict())
        return rec

    def gauges(self, window: dict | None) -> dict[str, float]:
        """``perf/*`` scalars for one flushed phase window — pure host
        float arithmetic (the transfer-guard tests run this under
        ``disallow_device_to_host``). Programs whose phase did not fire in
        the window contribute nothing; an empty result means no registered
        program ran."""
        if not self.enabled or not window or not self._programs:
            return {}
        flops = 0.0
        byts = 0.0
        denom_s = 0.0
        seen_phases: set[str] = set()
        for rec in self._programs.values():
            ph = rec.get("phase")
            if ph is None or ph not in window:
                continue
            st = window[ph]
            count = float(st.get("count", 0))
            flops += rec["flops"] * count * rec["calls_per_phase"]
            byts += rec["bytes_accessed"] * count * rec["calls_per_phase"]
            if ph not in seen_phases:
                seen_phases.add(ph)
                denom_s += float(st.get("total_s", 0.0))
        if denom_s <= 0.0 or (flops <= 0.0 and byts <= 0.0):
            return {}
        out = {"perf/flops_per_s": flops / denom_s}
        peak = self.peak
        if peak is not None and peak.flops:
            out["perf/mfu"] = flops / denom_s / peak.flops
        if peak is not None and peak.membw and byts > 0.0:
            out["perf/membw_util"] = byts / denom_s / peak.membw
        return out
