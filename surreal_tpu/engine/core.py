"""The loop engine: one iteration skeleton, software-pipelined boundary.

``LoopEngine.run`` owns the ``while env_steps < total`` skeleton every
driver used to hand-thread: chaos firing, the driver step closure,
counter bumps, the SessionHooks boundary (publish/checkpoint/recover/
observe), rollback dispatch, and the stop decision. With
``pipeline_sidebands`` off (default) the boundary runs inline and the
engine is bit-identical to the historical loops. With it on, the
boundary is submitted to a single-worker staging executor and overlaps
iteration k+1's collect/learn:

- **Donation-safe handoff**: when any declared stage donates its inputs
  (the fused device drivers jit with ``donate_argnums=(0, 1)``), the
  param tree handed to the deferred boundary is snapshotted with
  ``jax.tree.map(jnp.copy, state)`` BEFORE the next step dispatches —
  the runtime orders the copy ahead of the donating dispatch's buffer
  reuse. Non-donating (host) drivers pass the immutable state reference:
  rebinding, never mutation, is the loop discipline, so the reference IS
  a version pin.
- **Bounded lag, never silent**: stop/recovery decisions surface with at
  most one iteration of lag (the same bounded-staleness class as
  ``overlap_rollouts``). A wedged boundary (the ``engine.stage`` chaos
  site's ``delay_stage``) gets ``stage_timeout_s`` before the NEXT
  boundary is skipped — counted in ``engine/skipped_boundaries`` and
  logged, and the wedged boundary itself is still awaited on later
  iterations and at loop exit. The interrupt latch is checked inline
  every iteration regardless of mode, so SIGTERM stops at an iteration
  boundary with the emergency checkpoint intact even under overlap.
"""

from __future__ import annotations

import concurrent.futures
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from surreal_tpu.utils import faults


@dataclass
class LoopState:
    """The loop-carried record a driver's step closure mutates."""

    state: Any
    key: Any
    iteration: int
    env_steps: int
    extras: dict = field(default_factory=dict)


@dataclass
class Outcome:
    """What one driver step hands the boundary.

    ``metrics`` may be a dict or a zero-arg callable (resolved lazily at
    the metrics cadence, on the staging thread when pipelined — that is
    where the one ``float()`` device sync moves off the critical path).
    ``state_for_hooks`` defaults to ``ls.state``; drivers whose hooks
    state differs (multihost host-local conversion) pass a value or
    zero-arg callable. ``steps`` is the env-step advance this iteration.
    ``skip_boundary`` is the SEED stale-drop contract: count the steps,
    run no boundary, do not count an iteration (the inline interrupt
    check still fires so a preemption never sits out a stale streak).
    ``post_metrics(m_row)`` runs when the metrics cadence fired —
    drivers emit their per-cadence telemetry events there, which rides
    the deferred boundary when pipelining is on.
    """

    metrics: Any
    hook_key: Any
    steps: int
    state_for_hooks: Any = None
    skip_boundary: bool = False
    post_metrics: Callable[[dict], None] | None = None


class LoopEngine:
    """Composable iteration engine over declared stages (stages.py)."""

    def __init__(
        self,
        hooks,
        total: int,
        step: Callable[[LoopState], Outcome],
        stages,
        config,
        *,
        on_metrics=None,
        apply_fault: Callable[[LoopState, dict], None] | None = None,
        on_rollback: Callable[[LoopState], None] | None = None,
        after_step: Callable[[LoopState], None] | None = None,
        agree_stop: Callable[[int, bool], bool] | None = None,
        fire_faults: bool = True,
    ):
        from surreal_tpu.engine.stages import StageSpec

        stages = tuple(stages)
        if not stages:
            raise ValueError("LoopEngine needs at least one declared stage")
        for s in stages:
            if not isinstance(s, StageSpec):
                raise TypeError(f"stage {s!r} is not a StageSpec")
        self.hooks = hooks
        self.total = int(total)
        self.step = step
        self.stages = stages
        self.config = config
        self.on_metrics = on_metrics
        self.apply_fault = apply_fault
        self.on_rollback = on_rollback
        self.after_step = after_step
        self.agree_stop = agree_stop
        self.fire_faults = bool(fire_faults)
        self.donating = any(s.donate for s in stages)
        self.pipelined = bool(config.pipeline_sidebands) and any(
            s.deferrable for s in stages
        ) and hooks is not None
        self._executor = None
        self._pending = None  # (future, iteration) of the deferred boundary
        # observability (engine/* gauges + the `engine` telemetry event);
        # bounded windows — the gauges are a live view, not a history
        from collections import deque

        self._step_ms: deque = deque(maxlen=512)
        self._boundary_ms: deque = deque(maxlen=512)
        self._busy_ms = 0.0  # staging-worker busy time while pipelined
        self._deferred = 0
        self._skipped = 0
        self._kills = 0
        self._t0 = None
        self._warned_wedged = False

    # -- observability --------------------------------------------------------
    def gauge_row(self) -> dict[str, float]:
        """The engine/* gauges merged into every metrics row (registered
        in session/costs.py's GAUGE_REGISTRY)."""
        from surreal_tpu.session.telemetry import latency_percentiles

        b = latency_percentiles(tuple(self._boundary_ms)) or {}
        wall_ms = (
            (time.perf_counter() - self._t0) * 1e3 if self._t0 else 0.0
        )
        return {
            "engine/stage_p50_ms": float(b.get("p50", 0.0)),
            "engine/stage_p99_ms": float(b.get("p99", 0.0)),
            "engine/occupancy": (
                float(self._busy_ms / wall_ms) if wall_ms > 0 else 0.0
            ),
            "engine/queue_depth": 1.0 if self._pending is not None else 0.0,
            "engine/deferred_boundaries": float(self._deferred),
            "engine/skipped_boundaries": float(self._skipped),
            "engine/stage_kills": float(self._kills),
        }

    def _event_fields(self) -> dict:
        from surreal_tpu.session.telemetry import latency_percentiles

        return {
            "pipelined": bool(self.pipelined),
            "stages": [s.describe() for s in self.stages],
            "stage_ms": latency_percentiles(tuple(self._boundary_ms)),
            "step_ms": latency_percentiles(tuple(self._step_ms)),
            "occupancy": self.gauge_row()["engine/occupancy"],
            "deferred": self._deferred,
            "skipped": self._skipped,
            "kills": self._kills,
        }

    # -- the boundary ---------------------------------------------------------
    def _wrap_metrics(self, metrics):
        def build():
            base = metrics() if callable(metrics) else metrics
            row = dict(base) if base else {}
            row.update(self.gauge_row())
            return row

        return build

    def _run_boundary(self, iteration, env_steps, state_for_hooks, out):
        """end_iteration + the driver's per-cadence emits + the engine's
        own observability row. Runs inline, or on the staging worker when
        pipelined. Returns the boundary's stop decision."""
        f = faults.fire("engine.stage")
        if f is not None:
            kind = f.get("kind")
            if kind == "delay_stage":
                faults.sleep_ms(f)
            elif kind == "kill_stage":
                self._kills += 1
                raise faults.FaultInjected(f"engine.stage kill: {f}")
        t0 = time.perf_counter()
        try:
            m_row, stop = self.hooks.end_iteration(
                iteration, env_steps, state_for_hooks, out.hook_key,
                self._wrap_metrics(out.metrics), self.on_metrics,
            )
            if m_row is not None:
                if out.post_metrics is not None:
                    out.post_metrics(m_row)
                self.hooks.tracer.event("engine", **self._event_fields())
                self.hooks.ops.push_local(
                    "engine", gauges=self.gauge_row(),
                    body=self._event_fields(),
                )
            return bool(stop)
        finally:
            dur = (time.perf_counter() - t0) * 1e3
            self._boundary_ms.append(dur)
            if self.pipelined:
                self._busy_ms += dur

    def _collect_pending(self, timeout: float):
        """Await the deferred boundary. Returns (resolved, stop):
        ``resolved=False`` means the boundary is still wedged after
        ``timeout`` — the caller skips this iteration's boundary (counted)
        and retries on the next one."""
        fut, it_prev = self._pending
        try:
            stop = fut.result(timeout=timeout)
        except concurrent.futures.TimeoutError:
            if self.hooks is not None and not self._warned_wedged:
                self._warned_wedged = True
                self.hooks.log.warning(
                    "engine: boundary of iteration %d wedged past the %.1fs "
                    "stage bound — learn continues, subsequent boundaries "
                    "are skipped and counted until it drains",
                    it_prev, timeout,
                )
            return False, False
        except faults.FaultInjected:
            # a killed side-band stage is an organic crash of that stage,
            # not of training: counted (self._kills, bumped at fire time)
            # and surfaced through drain_fired's `fault` event at the next
            # healthy boundary
            self._pending = None
            return True, False
        self._pending = None
        self._warned_wedged = False
        return True, bool(stop)

    def _pin_state(self, ls: LoopState, out: Outcome):
        """Resolve the state the boundary will read, donation-safely."""
        state = out.state_for_hooks if out.state_for_hooks is not None else ls.state
        if self.pipelined and self.donating and not callable(state):
            import jax
            import jax.numpy as jnp

            # device-side snapshot, dispatched BEFORE the next donating
            # step: the runtime orders the copy ahead of buffer reuse
            state = jax.tree.map(jnp.copy, state)
        return state

    def _recovery_pending(self) -> bool:
        return self.hooks is not None and self.hooks.recovery.pending

    def _stop_decision(self, iteration: int, stop: bool) -> bool:
        if self.agree_stop is not None:
            return bool(self.agree_stop(iteration, stop))
        return bool(stop)

    def _flush(self):
        """Drain the deferred boundary at loop exit (stop/interrupt/budget)
        so publish/checkpoint side-bands land before the run epilogue. A
        boundary wedged past the stage bound is abandoned to the daemon
        executor — counted, logged once, never blocking shutdown."""
        if self._pending is None:
            return None
        fut, it_prev = self._pending
        try:
            stop = fut.result(timeout=max(self.config.stage_timeout_s, 5.0))
        except concurrent.futures.TimeoutError:
            self._skipped += 1
            if self.hooks is not None:
                self.hooks.log.warning(
                    "engine: abandoning the wedged boundary of iteration %d "
                    "at loop exit (counted in engine/skipped_boundaries)",
                    it_prev,
                )
            return None
        except faults.FaultInjected:
            return None
        finally:
            self._pending = None
        return stop

    # -- the skeleton ---------------------------------------------------------
    def run(self, ls: LoopState) -> LoopState:
        self._t0 = time.perf_counter()
        try:
            while ls.env_steps < self.total:
                if self.fire_faults:
                    f = faults.fire("trainer.iteration")
                    if f is not None and self.apply_fault is not None:
                        self.apply_fault(ls, f)
                t_step = time.perf_counter()
                out = self.step(ls)
                self._step_ms.append((time.perf_counter() - t_step) * 1e3)
                if out.skip_boundary:
                    ls.env_steps += out.steps
                    if self.hooks is not None and self.hooks.interrupted:
                        break
                    continue
                ls.iteration += 1
                ls.env_steps += out.steps
                if self.after_step is not None:
                    self.after_step(ls)
                if not self.pipelined:
                    if self._inline_boundary(ls, out):
                        break
                else:
                    if self._pipelined_boundary(ls, out):
                        break
            return ls
        finally:
            self._flush()
            if self._executor is not None:
                self._executor.shutdown(wait=False)

    def _inline_boundary(self, ls: LoopState, out: Outcome) -> bool:
        stop = False
        if self.hooks is not None:
            try:
                stop = self._run_boundary(
                    ls.iteration, ls.env_steps, self._pin_state(ls, out), out
                )
            except faults.FaultInjected:
                stop = False  # counted at fire time; see _collect_pending
            if self._recovery_pending():
                self.on_rollback(ls)
                return False
        return self._stop_decision(ls.iteration, stop)

    def _pipelined_boundary(self, ls: LoopState, out: Outcome) -> bool:
        # consume the PREVIOUS boundary first: its stop/recovery verdicts
        # land with exactly one iteration of lag
        if self._pending is not None:
            resolved, stop_prev = self._collect_pending(
                self.config.stage_timeout_s
            )
            if not resolved:
                # wedged past the bound: skip THIS boundary, counted
                self._skipped += 1
                if self.hooks.interrupted:
                    return True
                return False
            if self._recovery_pending():
                # roll back; the current outcome is the poisoned lineage's
                # last iteration — its boundary never runs (bounded lag)
                self.on_rollback(ls)
                return False
            if self._stop_decision(ls.iteration, stop_prev):
                return True
        if self._executor is None:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="engine-stage"
            )
        state_pinned = self._pin_state(ls, out)
        self._pending = (
            self._executor.submit(
                self._run_boundary, ls.iteration, ls.env_steps,
                state_pinned, out,
            ),
            ls.iteration,
        )
        self._deferred += 1
        # the interrupt latch is inline in BOTH modes: a SIGTERM stops at
        # this iteration boundary, _flush drains the just-submitted
        # boundary, and the driver epilogue writes the emergency checkpoint
        if self.hooks.interrupted:
            return True
        return False
