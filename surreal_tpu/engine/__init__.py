"""One pipelined loop runtime (ISSUE 19).

Every driver used to hand-thread the same iteration skeleton — fire
chaos, step, bump counters, run the SessionHooks boundary, roll back or
stop — and every driver serialized the boundary's side-band stages
(publish/checkpoint/observe/ops-push) onto the learn critical path.
``LoopEngine`` owns that skeleton once: drivers declare their stage
program (`StageSpec`, donation decision mandatory) and supply a step
closure; the engine software-pipelines the side-band boundary onto a
bounded staging executor overlapped with iteration k+1's collect/learn
when ``session.engine.pipeline_sidebands`` is on, and is bit-identical
to the historical inline loops when it is off (the default).
"""

from surreal_tpu.engine.core import LoopEngine, LoopState, Outcome
from surreal_tpu.engine.stages import (
    EngineConfig,
    StageSpec,
    overlap_collect,
    sideband_stages,
)

__all__ = [
    "EngineConfig",
    "LoopEngine",
    "LoopState",
    "Outcome",
    "StageSpec",
    "overlap_collect",
    "sideband_stages",
]
