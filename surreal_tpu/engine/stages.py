"""Stage declarations + engine config for the one loop runtime.

A driver hands the engine an ordered tuple of :class:`StageSpec`s — the
declaration is load-bearing, not documentation:

- ``donate`` (REQUIRED, keyword-only; the import-hygiene lint asserts
  every construction site spells it) records whether the stage's jitted
  program donates its loop-carried inputs. Any donating stage forces the
  engine to snapshot the param tree (``jax.tree.map(jnp.copy, ...)``)
  before a DEFERRED boundary reads it — the copy is dispatched before
  iteration k+1's donating dispatch, so the runtime orders it ahead of
  buffer reuse and the staging thread never touches donated storage.
- ``deferrable`` marks side-band stages the engine may run on the
  staging executor overlapped with the next iteration's compute.
- ``overlap`` is the rollout/learn-overlap bit: what used to be the
  per-driver ``topology.overlap_rollouts`` fork is now a property of the
  collect stage (resolved by :func:`overlap_collect`).
"""

from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class StageSpec:
    """One declared stage of a driver's iteration program."""

    name: str
    donate: bool
    deferrable: bool = False
    overlap: bool = False

    def describe(self) -> dict:
        return {
            "name": self.name,
            "donate": bool(self.donate),
            "deferrable": bool(self.deferrable),
            "overlap": bool(self.overlap),
        }


def sideband_stages() -> tuple[StageSpec, ...]:
    """The SessionHooks boundary, declared as stages. Shared by every
    driver so the publish/checkpoint/recover/observe contract cannot
    drift between them: publish/checkpoint/observe are deferrable
    side-bands; recover stays on the synchronous path (the rollback
    decision re-seeds the driver's loop state, which only the main
    thread owns — the engine consumes it with at most one iteration of
    lag when pipelining is on)."""
    return (
        StageSpec("publish", donate=False, deferrable=True),
        StageSpec("checkpoint", donate=False, deferrable=True),
        StageSpec("recover", donate=False, deferrable=False),
        StageSpec("observe", donate=False, deferrable=True),
    )


@dataclass(frozen=True)
class EngineConfig:
    """Engine knobs, read from ``session_config.engine``.

    ``pipeline_sidebands=False`` (the default) keeps every boundary
    inline — bit-identical to the historical hand-threaded loops; tests
    pin the parity per driver. ``stage_timeout_s`` is the wedged-stage
    bound: a deferred boundary that has not completed by the time the
    NEXT boundary is due gets that long before the next boundary is
    skipped (counted in ``engine/skipped_boundaries`` + the `engine`
    telemetry event — never silent)."""

    pipeline_sidebands: bool = False
    stage_timeout_s: float = 30.0
    queue_depth: int = 1

    @classmethod
    def from_session(cls, session_config) -> "EngineConfig":
        eng = session_config.get("engine", None)
        if eng is None:
            return cls()
        get = eng.get if hasattr(eng, "get") else dict(eng).get
        return cls(
            pipeline_sidebands=bool(get("pipeline_sidebands", False)),
            stage_timeout_s=float(get("stage_timeout_s", 30.0)),
            queue_depth=max(1, int(get("queue_depth", 1))),
        )

    def inline(self) -> "EngineConfig":
        """Pin the boundary inline regardless of config — the multihost
        drivers use this (a deferred, rank-local stop/rollback decision
        would race the collective schedule's agreed stop), and the
        off-policy driver pins replay-inclusive checkpoints (the saved
        buffer closure must read the exact iteration's ring)."""
        if not self.pipeline_sidebands:
            return self
        return replace(self, pipeline_sidebands=False)


def overlap_collect(session_config) -> bool:
    """Resolve the collect stage's overlap bit: ``engine.overlap_collect``
    when set, else the historical ``topology.overlap_rollouts`` (default
    True) — one resolution point instead of a per-driver fork."""
    eng = session_config.get("engine", None)
    if eng is not None:
        get = eng.get if hasattr(eng, "get") else dict(eng).get
        v = get("overlap_collect", None)
        if v is not None:
            return bool(v)
    return bool(session_config.topology.get("overlap_rollouts", True))
