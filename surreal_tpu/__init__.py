"""surreal_tpu — a TPU-native distributed RL framework.

A ground-up re-design of the capability surface of ``tanwanirahul/surreal``
(a fork of Stanford's SURREAL, CoRL 2018) for TPUs: instead of a zoo of
ZMQ-connected PyTorch processes (actors -> sharded replay -> GPU learner ->
parameter server -> actors), one experiment is one JAX SPMD program —
SEED-RL-style batched inference (``jit(vmap(policy))``), an HBM-resident
trajectory FIFO / replay with on-device GAE / V-trace (``lax.scan``), and a
data-parallel learner whose gradient allreduce rides the ICI mesh via
``shard_map``.

Layer map (mirrors SURVEY.md §1, re-homed for TPU):

- ``surreal_tpu.session``    — config trees, trackers, checkpoint, metrics (ref L6)
- ``surreal_tpu.envs``       — env factory, adapters, wrappers, JAX-native envs (ref L3)
- ``surreal_tpu.ops``        — GAE / V-trace / n-step scans, distributions, ZFilter (ref: inside learners)
- ``surreal_tpu.models``     — flax policy/value networks (ref surreal/model/)
- ``surreal_tpu.replay``     — HBM trajectory FIFO, uniform + prioritized replay (ref L4)
- ``surreal_tpu.agents``     — acting: policy heads + exploration modes (ref L5 agent/)
- ``surreal_tpu.learners``   — PPO / DDPG / IMPALA update rules + train loop (ref L5 learner/)
- ``surreal_tpu.parallel``   — mesh, shardings, collective training steps (replaces ZMQ data plane)
- ``surreal_tpu.distributed``— host<->device transport: ZMQ inference server, exp senders (ref L0/L2)
- ``surreal_tpu.launch``     — experiment launcher / component dispatch (ref L7)
"""

__version__ = "0.1.0"
