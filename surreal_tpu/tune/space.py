"""Declared candidate space per algorithm — the dimensions the search
walks, in search order.

Every dimension is a learner-config ``algo.*`` key that the trainers and
learners already thread into their hot scans (the point of the autotuner
PR: geometry knobs are searchable dimensions, not hand-tuned constants):

- ``rollout_unroll`` — the device rollout ``lax.scan`` over the horizon
  (launch/rollout.py, launch/offpolicy_trainer.py). The workloads are
  latency-bound on exactly this scan; unrolling trades program size for
  fewer sequential loop iterations.
- ``gae_impl`` — PPO's advantage recurrence: 'xla' lax.scan | 'assoc'
  log-depth associative_scan | 'pallas' fused kernel (ops/pallas_gae.py).
  The pallas kernel is selected only when MEASURED faster on the live
  backend — previously a manual config knob nobody flipped.
- ``gae_unroll`` — unroll of the time recurrences themselves (PPO's xla
  GAE scan, IMPALA's V-trace scan, the ops/returns.py estimators).
- ``sgd_unroll`` — PPO's minibatch scan inside ``_sgd_epochs``.
- ``update_unroll`` — the off-policy ``updates_per_iter`` sample+learn
  scan (launch/offpolicy_trainer.py).
- ``shuffle`` — PPO minibatch layout: 'block' (contiguous-block permute,
  the measured TPU default) | 'row' (exact reference semantics).
- ``precision`` — the precision policy (ops/precision.py): 'f32' |
  'mixed' | 'bf16'. Searched FIRST: it is the biggest lever and every
  later unroll choice should be measured under the adopted policy. The
  experimental 'bf16_fp8' is deliberately NOT in the space — numerics
  experiments stay behind an explicit knob, never a timing search.
- ``vtrace_impl`` — IMPALA's V-trace recurrence: 'xla' | 'assoc' |
  'pallas' (ops/pallas_vtrace.py) — the per-op kernel twin of
  ``gae_impl``.
- ``replay_gather`` — DDPG's batched-uniform replay data movement:
  'xla' fused gather | 'pallas' scalar-prefetch row-DMA kernel
  (ops/pallas_replay.py).

New geometry knobs join the search by adding a dimension here plus the
key to fingerprint.TUNABLE_KEYS.
"""

from __future__ import annotations


def candidate_space(extended_learner_config) -> list[tuple[str, list]]:
    """[(dim_name, candidate_values)] in search order for this algo,
    statically pruned to the workload's geometry (an unroll candidate
    longer than the loop it unrolls is the same program re-measured)."""
    algo = extended_learner_config.algo
    name = algo.name
    horizon = int(algo.get("horizon", 1))
    dims: list[tuple[str, list]] = [
        # precision first: later dims re-measure under the adopted policy
        ("precision", ["f32", "mixed", "bf16"]),
        ("rollout_unroll", [u for u in (1, 2, 4, 8) if u <= horizon]),
    ]
    if name == "ppo":
        dims.append(("gae_impl", ["xla", "assoc", "pallas"]))
        dims.append(("gae_unroll", [u for u in (1, 2, 4) if u <= horizon]))
        num_mb = int(algo.get("num_minibatches", 1))
        dims.append(("sgd_unroll", [u for u in (1, 2, 4) if u <= num_mb]))
        dims.append(("shuffle", ["block", "row"]))
    elif name == "impala":
        # the per-op V-trace kernel choice, then its xla-path unroll
        dims.append(("vtrace_impl", ["xla", "assoc", "pallas"]))
        dims.append(("gae_unroll", [u for u in (1, 2, 4) if u <= horizon]))
    elif name == "ddpg":
        upd = int(algo.get("updates_per_iter", 1))
        dims.append(("update_unroll", [u for u in (1, 2, 4, 8) if u <= upd]))
        replay = extended_learner_config.get("replay", None)
        if (
            bool(algo.get("batched_uniform_sampling", True))
            and replay is not None
            and replay.get("kind") == "uniform"
        ):
            # the batched gather exists only on the uniform fast path
            dims.append(("replay_gather", ["xla", "pallas"]))
    return [(n, vals) for n, vals in dims if len(vals) > 1]


def default_point(extended_learner_config) -> dict:
    """The static-default value of every searched dimension — the
    incumbent the search must beat, and the 'untuned arm' artifacts
    record."""
    algo = extended_learner_config.algo
    return {
        name: algo.get(name)
        for name, _vals in candidate_space(extended_learner_config)
    }


def skip_dimension(name: str, incumbent: dict, extended_learner_config) -> bool:
    """Prune dimensions made moot by the incumbent: ``gae_unroll`` only
    exists inside the 'xla' lax.scan path — under 'assoc'/'pallas' every
    candidate compiles the identical program (PPO's gae_impl; IMPALA's
    vtrace_impl is the same story for its recurrence)."""
    algo_name = extended_learner_config.algo.name
    if (
        name == "gae_unroll"
        and algo_name == "ppo"
        and incumbent.get("gae_impl", "xla") != "xla"
    ):
        return True
    if (
        name == "gae_unroll"
        and algo_name == "impala"
        and incumbent.get("vtrace_impl", "xla") != "xla"
    ):
        return True
    return False
