"""Program autotuner — measured scan-unroll and program-geometry search
with a persistent per-workload tuning cache.

PERF.md's attribution says every graded workload is LATENCY-BOUND on long
``lax.scan``s of tiny elementwise ops (headline PPO: 0.64% MFU, rollout
25.1 of 30.8 ms/iter), yet the repo's scan-unroll factors and geometry
choices (``gae_impl``, minibatch shuffle layout, update-loop shape) were
hand-set defaults. Accelerated-RL systems SEARCH these knobs instead of
guessing (Stooke & Abbeel, *Accelerated Methods for Deep RL*, 1803.02811;
HEPPO-GAE's hardware-shaped GAE pipeline) — and PR 2's persistent XLA
compile cache makes the search's extra compiles a once-per-fingerprint
cost, so measuring-and-picking is now cheaper than shipping one static
guess.

Three layers, mirroring the compile cache's design:

- :mod:`fingerprint` — a workload fingerprint (algo + model + geometry +
  backend + jax version, MINUS the searched knobs themselves) keys every
  cache entry, so a tuned config can never leak onto a workload it was
  not measured on.
- :mod:`cache` — a JSON tuning cache beside the compile cache
  (``session.tuning_cache_dir``; relative paths resolve under the session
  folder, absolute paths share one cache across sessions). Atomic writes;
  corrupt/missing entries read as misses.
- :mod:`search` (+ :mod:`space`) — greedy coordinate descent over the
  declared candidate space (rollout-scan ``unroll``, SGD/update-loop
  ``unroll``, ``gae_impl`` incl. the pallas kernel, shuffle layout), each
  candidate timed with bench.py's ``device_get``-fenced chained-iteration
  discipline through the REAL fused trainer program.

Trainers consult the cache at build time via ``algo.autotune``:

- ``'off'``   (default) — hand-set knobs, no cache traffic;
- ``'cache'`` — apply a cached winner when the fingerprint hits, fall
  back to the static defaults on a miss (never pays search cost);
- ``'search'``— on a miss, run the search at build time and persist the
  winner. Device (``jax:*``) envs search the full space against the
  fused iteration; host envs (gym/dm_control/SEED) search the
  learn-phase subset against the jitted learn program alone
  (search.LEARN_PHASE_DIMS — their rollout is host python with no scan
  to unroll); workloads with nothing searchable keep defaults.

The decision lands in telemetry as a ``tune`` event (hit/miss, chosen
config, candidate timings from the search), rendered by
``surreal_tpu diag``; ``python -m surreal_tpu tune <algo> <env>`` runs
the search standalone and writes the shared artifact.
"""

from __future__ import annotations

from typing import NamedTuple

from surreal_tpu.tune.cache import TuningCache, resolve_tuning_cache_dir
from surreal_tpu.tune.fingerprint import TUNABLE_KEYS, workload_fingerprint

AUTOTUNE_MODES = ("off", "cache", "search")


class TuneDecision(NamedTuple):
    """What the autotuner decided at trainer build time."""

    mode: str             # 'off' | 'cache' | 'search'
    key: str | None       # workload fingerprint key (None when off)
    hit: bool | None      # cache hit (None when off)
    applied: dict         # tuned knobs merged into the learner config
    source: str           # 'default' | 'cache' | 'search'
    cache_dir: str | None
    note: str = ""        # e.g. search degraded to cache for a host env

    def telemetry(self) -> dict:
        """The ``tune`` event payload (hooks.tune_event / diag)."""
        out = {
            "mode": self.mode,
            "key": self.key,
            "hit": bool(self.hit),
            "source": self.source,
            "cache_dir": self.cache_dir,
            "config": dict(self.applied),
        }
        if self.note:
            out["note"] = self.note
        return out

    def artifact(self) -> dict:
        """Compact record for bench/wallclock artifacts, so a perf row can
        never silently mix tuned and untuned arms."""
        return {
            "mode": self.mode,
            "hit": self.hit,
            "source": self.source,
            "config": dict(self.applied),
            "key": self.key,
        }


_OFF = TuneDecision(
    mode="off", key=None, hit=None, applied={}, source="default",
    cache_dir=None,
)


def _apply_tuned(config, tuned: dict) -> None:
    """Merge tuned knobs into the RAW learner override tree (the one
    ``build_learner`` extends), so a rebuild picks them up. Tuned values
    deliberately override hand-set ones: ``autotune != 'off'`` hands the
    searched keys to the tuner; pin them manually with ``autotune='off'``.
    """
    from surreal_tpu.session.config import Config

    algo = config.learner_config.get("algo", None)
    if algo is None:
        config.learner_config.algo = Config()
        algo = config.learner_config.algo
    for k, v in tuned.items():
        algo[k] = v


def resolve_autotune(config, extended_learner_config) -> TuneDecision:
    """Consult (or populate) the tuning cache for this workload; called by
    every trainer constructor BEFORE its jitted programs are built.

    ``extended_learner_config`` is the fully-extended learner tree (the
    built learner's ``.config``) — the raw user tree lacks the defaults
    the fingerprint needs. On a decision with ``applied`` non-empty the
    caller rebuilds its learner from ``config.learner_config``, which this
    function has updated in place.
    """
    algo = extended_learner_config.algo
    mode = algo.get("autotune", "off") or "off"
    if mode not in AUTOTUNE_MODES:
        raise ValueError(
            f"algo.autotune {mode!r} not in {'|'.join(AUTOTUNE_MODES)}"
        )
    if mode == "off":
        return _OFF

    key, _fp = workload_fingerprint(extended_learner_config, config.env_config)
    cache_dir = resolve_tuning_cache_dir(config.session_config)
    cache = TuningCache(cache_dir)
    entry = cache.lookup(key)
    if entry is not None:
        tuned = dict(entry.get("config", {}))
        _apply_tuned(config, tuned)
        return TuneDecision(mode, key, True, tuned, "cache", cache_dir)
    if mode == "cache":
        return TuneDecision(mode, key, False, {}, "default", cache_dir)

    # mode == 'search': run the measurement at build time and persist.
    from surreal_tpu.tune.search import search_space_for

    if not search_space_for(config, extended_learner_config):
        # e.g. host-env DDPG: the update loop runs as individual jitted
        # learns from a host loop — no searchable dimension exists
        return TuneDecision(
            mode, key, False, {}, "default", cache_dir,
            note="no searchable dimensions for this workload; "
                 "static defaults kept",
        )
    import jax

    if jax.process_count() > 1:
        # ranks would each measure with independent timing noise and pick
        # DIVERGENT programs — a collective deadlock. The cache path is
        # deterministic across ranks (same shared file), so require it.
        raise ValueError(
            "algo.autotune='search' is single-process only (per-rank "
            "timing noise would pick divergent programs): run "
            "`surreal_tpu tune` once against the shared tuning cache, "
            "then train with algo.autotune='cache'"
        )
    from surreal_tpu.tune.search import tune_workload

    result = tune_workload(config)
    tuned = dict(result.get("config", {}))
    _apply_tuned(config, tuned)
    return TuneDecision(mode, key, False, tuned, "search", cache_dir)


__all__ = [
    "AUTOTUNE_MODES",
    "TUNABLE_KEYS",
    "TuneDecision",
    "TuningCache",
    "resolve_autotune",
    "resolve_tuning_cache_dir",
    "workload_fingerprint",
]
