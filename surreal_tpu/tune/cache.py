"""Persistent JSON tuning cache — the compile cache's sibling.

One file per workload fingerprint (``tune_<key>.json``), holding the
chosen config plus the full measurement record (default/chosen timings,
every trial, platform, jax version) so artifacts and `surreal_tpu diag`
can answer "why this config?" without re-measuring. Writes are atomic
(tmp + rename): trainers on other ranks/processes poll these files and
must never observe a torn entry. Corrupt or missing entries read as
misses — a damaged cache re-measures instead of crashing the trainer.
"""

from __future__ import annotations

import json
import os


def resolve_tuning_cache_dir(session_cfg) -> str:
    """Resolve ``session.tuning_cache_dir`` exactly like the compile
    cache's knob (launch/hooks.py::maybe_enable_compile_cache): relative
    paths live under the session folder (session-local cache), absolute
    paths share one cache across sessions. Unset defaults to
    ``<folder>/tuning_cache`` so ``algo.autotune`` works with zero extra
    config. ``.get`` keeps configs saved before the knob existed loadable.
    """
    cache_dir = session_cfg.get("tuning_cache_dir", None) or "tuning_cache"
    if not os.path.isabs(cache_dir):
        cache_dir = os.path.join(session_cfg.folder, cache_dir)
    return cache_dir


class TuningCache:
    def __init__(self, cache_dir: str):
        self.dir = cache_dir

    def path(self, key: str) -> str:
        return os.path.join(self.dir, f"tune_{key}.json")

    def lookup(self, key: str) -> dict | None:
        """The stored entry for ``key``, or None (missing/corrupt read as
        a miss so a damaged file re-measures rather than crashes)."""
        try:
            with open(self.path(key)) as f:
                entry = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if not isinstance(entry, dict) or "config" not in entry:
            return None
        return entry

    def store(self, key: str, entry: dict) -> str:
        """Atomically persist ``entry`` under ``key``; returns the path."""
        os.makedirs(self.dir, exist_ok=True)
        path = self.path(key)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(entry, f, indent=2, default=str)
        os.replace(tmp, path)
        return path

    def entries(self) -> list[dict]:
        """All readable entries (diag/inspection helper)."""
        out = []
        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            return out
        for name in names:
            if name.startswith("tune_") and name.endswith(".json"):
                entry = self.lookup(name[len("tune_"):-len(".json")])
                if entry is not None:
                    out.append(entry)
        return out
