"""Measurement-driven search over the declared candidate space.

Timing discipline is bench.py's, verbatim: warmup calls absorb XLA
compilation, a throwaway chained window absorbs the one-time tunnel
artifact freshly-compiled programs show on this image, and the measured
window is a CHAINED loop (each iteration consumes the previous state)
fenced by ``jax.device_get`` of a program output — ``block_until_ready``
does not wait on this backend (the ~1000x pre-round-3 inflation; bench.py
module doc has the forensics). Candidates are timed through the REAL
fused trainer programs (``Trainer._train_iter`` /
``OffPolicyTrainer._device_train_iter``), not proxies, so the winner is
the winner of the program that will actually run.

Search strategy: greedy coordinate descent in the space's declared order
— measure the static default as the incumbent, then walk one dimension at
a time, adopting a candidate only when it beats the incumbent by
``min_gain`` (2% default; below that is window-to-window noise and the
default keeps the compile-cache-warm program). A full cartesian sweep of
the PPO space would be ~72 compiles; the greedy walk is ~12 and each
adopted knob compounds into the later dimensions' baseline.
"""

from __future__ import annotations

import copy
import sys
import time

from surreal_tpu.tune.cache import TuningCache, resolve_tuning_cache_dir
from surreal_tpu.tune.fingerprint import workload_fingerprint
from surreal_tpu.tune.space import candidate_space, skip_dimension

WARMUP = 2       # compile + first-dispatch absorption (unmeasured)
THROWAWAY = 2    # chained-window tunnel-artifact absorption (unmeasured)
ITERS = 8        # measured chained iterations per candidate
MIN_GAIN = 0.02  # adoption threshold vs the incumbent (noise floor)

# The dims that live inside the jitted LEARN program alone — the search
# surface for HOST-env workloads (gym/dm_control/SEED), whose rollout is
# host python with no device scan to unroll. The learn program is a
# device computation regardless of where the envs live, so these knobs
# are measurable (and cacheable) for host fingerprints too. precision
# and vtrace_impl qualify: the policy's dtypes and the V-trace kernel
# both live inside the jitted learn.
LEARN_PHASE_DIMS = (
    "gae_impl", "gae_unroll", "sgd_unroll", "shuffle",
    "precision", "vtrace_impl",
)


def search_space_for(config, extended_learner_config) -> list[tuple[str, list]]:
    """The dims :func:`tune_workload` will search for this workload: the
    full declared space for device (``jax:*``) envs, the learn-phase
    subset for host envs. Empty means the workload has nothing searchable
    (e.g. host-env DDPG: its update loop runs as individual jitted learns
    from a host loop) — callers treat that as 'stay on defaults'."""
    space = candidate_space(extended_learner_config)
    if not str(config.env_config.name).startswith("jax:"):
        if extended_learner_config.algo.name == "ddpg":
            # host-env DDPG stays unsearchable even though 'precision'
            # is a learn-phase dim: its update loop runs as individual
            # jitted learns over n-step REPLAY batches, which the
            # synthetic learn-batch harness (_synthetic_learn_batch,
            # PPO/IMPALA trajectory contract) cannot fabricate
            return []
        space = [(n, v) for n, v in space if n in LEARN_PHASE_DIMS]
    return space


def _candidate_config(config, point: dict):
    """A deep-copied config bundle with the candidate knobs pinned and the
    autotuner disabled (the measured trainer must not recurse into the
    cache it is populating)."""
    from surreal_tpu.session.config import Config

    cfg = copy.deepcopy(config)
    algo = cfg.learner_config.get("algo", None)
    if algo is None:
        cfg.learner_config.algo = Config()
        algo = cfg.learner_config.algo
    for k, v in point.items():
        algo[k] = v
    algo["autotune"] = "off"
    return cfg


def _measure_onpolicy(cfg, warmup: int, throwaway: int, iters: int) -> float:
    """ms/iter of the fused on-policy iteration (PPO / IMPALA)."""
    import jax

    from surreal_tpu.launch.trainer import Trainer

    trainer = Trainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    if trainer.mesh is not None and trainer.mesh.size > 1:
        from surreal_tpu.parallel.mesh import replicate_state

        state = replicate_state(trainer.mesh, state)
    carry = trainer.init_loop_state(env_key)
    metrics = None
    for _ in range(warmup + throwaway):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)
    t0 = time.perf_counter()
    for _ in range(iters):
        key, it_key = jax.random.split(key)
        state, carry, metrics = trainer._train_iter(state, carry, it_key)
    jax.device_get(metrics)  # the only trustworthy fence (bench.py)
    return (time.perf_counter() - t0) / iters * 1e3


def _measure_offpolicy(cfg, warmup: int, throwaway: int, iters: int) -> float:
    """ms/iter of the fused off-policy iteration (DDPG).

    The measurement copy caps ``replay.start_sample_size`` at one chunk so
    the timed window exercises the ``updates_per_iter`` loop (otherwise a
    large start gate would time rollout-only iterations and the update
    knobs would measure as no-ops); the gate is a traced ``lax.cond``
    predicate, so the compiled program is identical to production's.
    """
    import jax
    import jax.numpy as jnp

    from surreal_tpu.launch.offpolicy_trainer import OffPolicyTrainer
    from surreal_tpu.session.config import Config

    steps_per_chunk = int(cfg.env_config.num_envs) * int(
        cfg.learner_config.algo.get("horizon", 16)
    )
    cfg = Config(
        learner_config=Config(
            replay=Config(start_sample_size=min(1000, steps_per_chunk)),
        )
    ).extend(cfg)
    trainer = OffPolicyTrainer(cfg)
    key = jax.random.key(0)
    key, init_key, env_key = jax.random.split(key, 3)
    state = trainer.learner.init(init_key)
    if trainer.mesh is not None and trainer.mesh.size > 1:
        from surreal_tpu.parallel.mesh import replicate_state

        state = replicate_state(trainer.mesh, state)
    carry, replay_state = trainer.init_loop_state(env_key)
    beta = jnp.asarray(0.5, jnp.float32)
    off = jnp.asarray(False)
    metrics = None
    first = True
    for _ in range(warmup + throwaway):
        key, it_key = jax.random.split(key)
        state, replay_state, carry, metrics = trainer._train_iter(
            state, replay_state, carry, it_key, beta, off, jnp.asarray(first)
        )
        first = False
    jax.device_get(metrics)
    t0 = time.perf_counter()
    for _ in range(iters):
        key, it_key = jax.random.split(key)
        state, replay_state, carry, metrics = trainer._train_iter(
            state, replay_state, carry, it_key, beta, off, jnp.asarray(False)
        )
    jax.device_get(metrics)  # the only trustworthy fence (bench.py)
    return (time.perf_counter() - t0) / iters * 1e3


def _synthetic_learn_batch(specs, T: int, B: int, seed: int = 0) -> dict:
    """A [T, B] learner batch matching the PPO/IMPALA batch contract
    (utils/asserts.check_learn_batch), shapes/dtypes from the env specs,
    values from a fixed-seed RNG — the timed learn program is
    shape-determined, values only have to be plausible (finite logps,
    sparse episode boundaries)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    obs_shape = (T, B, *specs.obs.shape)
    if np.dtype(specs.obs.dtype) == np.uint8:
        obs = rng.integers(0, 256, obs_shape, dtype=np.uint8)
        next_obs = rng.integers(0, 256, obs_shape, dtype=np.uint8)
    else:
        obs = rng.standard_normal(obs_shape, dtype=np.float32)
        next_obs = rng.standard_normal(obs_shape, dtype=np.float32)
    done = rng.random((T, B)) < 1.0 / 50.0  # ~one boundary per 50 steps
    batch = {
        "obs": obs,
        "next_obs": next_obs,
        "reward": rng.standard_normal((T, B), dtype=np.float32),
        "done": done,
        "terminated": done & (rng.random((T, B)) < 0.5),
        "behavior_logp": rng.normal(-1.0, 0.1, (T, B)).astype(np.float32),
    }
    if specs.discrete:
        n = int(specs.action.n)
        batch["action"] = rng.integers(0, n, (T, B), dtype=np.int32)
        batch["behavior"] = {
            "logits": rng.normal(0.0, 0.1, (T, B, n)).astype(np.float32)
        }
    else:
        a = int(specs.action.shape[0])
        batch["action"] = rng.uniform(-1.0, 1.0, (T, B, a)).astype(np.float32)
        batch["behavior"] = {
            "mean": rng.normal(0.0, 0.1, (T, B, a)).astype(np.float32),
            "log_std": np.full((T, B, a), -0.5, np.float32),
        }
    return batch


def _measure_learn(cfg, warmup: int, throwaway: int, iters: int) -> float:
    """ms/iter of the jitted LEARN program alone, on a synthetic batch —
    the host-env measurement surface (there is no fused device iteration
    to time when envs step on the host).

    Geometry note: the batch is [algo.horizon, env_config.num_envs] — the
    trainer-facing chunk of the host loops and the non-pipelined SEED
    plane. SEED's pipelined sub-slices halve the chunk width; for
    exact-geometry winners there, tune with num_envs set to the chunk
    width you train (or pipeline_workers=false).
    """
    import jax

    from surreal_tpu.envs import make_env
    from surreal_tpu.launch.hooks import training_env_config
    from surreal_tpu.learners import build_learner

    probe = make_env(training_env_config(cfg.env_config))
    specs = probe.specs
    if hasattr(probe, "close"):
        probe.close()
    learner = build_learner(cfg.learner_config, specs)
    T = int(learner.config.algo.horizon)
    B = int(cfg.env_config.num_envs)
    batch = jax.device_put(_synthetic_learn_batch(specs, T, B))
    # state is chained (each call consumes the previous output), so the
    # loop-carried state donates exactly like the production learn paths
    learn = jax.jit(learner.learn, donate_argnums=(0,))
    key = jax.random.key(0)
    key, ik = jax.random.split(key)
    state = learner.init(ik)
    metrics = None
    for _ in range(warmup + throwaway):
        key, lk = jax.random.split(key)
        state, metrics = learn(state, batch, lk)
    jax.device_get(metrics)
    t0 = time.perf_counter()
    for _ in range(iters):
        key, lk = jax.random.split(key)
        state, metrics = learn(state, batch, lk)
    jax.device_get(metrics)  # the only trustworthy fence (bench.py)
    return (time.perf_counter() - t0) / iters * 1e3


def measure_point(
    config,
    point: dict,
    warmup: int = WARMUP,
    throwaway: int = THROWAWAY,
    iters: int = ITERS,
    surface: str = "fused",
) -> float:
    """ms/iter of the workload's measured program with ``point`` pinned:
    the fused device iteration (``surface='fused'``), or the learn-only
    program (``surface='learn'`` — the host-env surface)."""
    cfg = _candidate_config(config, point)
    if surface == "learn":
        return _measure_learn(cfg, warmup, throwaway, iters)
    if cfg.learner_config.algo.name == "ddpg":
        return _measure_offpolicy(cfg, warmup, throwaway, iters)
    return _measure_onpolicy(cfg, warmup, throwaway, iters)


def tune_workload(
    config,
    *,
    dims: list[tuple[str, list]] | None = None,
    warmup: int = WARMUP,
    throwaway: int = THROWAWAY,
    iters: int = ITERS,
    min_gain: float = MIN_GAIN,
    force: bool = False,
    verbose: bool = False,
) -> dict:
    """Search this workload's candidate space and persist the winner.

    Returns the cache entry plus ``cache_hit`` (True means a stored entry
    was returned with ZERO measurements — the pure-hit contract the second
    ``surreal_tpu tune`` run relies on) and ``measured`` (trial count).
    ``dims`` overrides the declared space (tests / bounded CLI runs).
    """
    import jax

    env_name = str(config.env_config.name)
    # host envs (gym/dm_control/SEED) have no fused device iteration to
    # time — their search surface is the jitted learn program alone, over
    # the learn-phase subset of the space (_measure_learn)
    surface = "fused" if env_name.startswith("jax:") else "learn"
    from surreal_tpu.envs import make_env
    from surreal_tpu.launch.hooks import training_env_config
    from surreal_tpu.learners import build_learner

    probe = make_env(training_env_config(config.env_config))
    learner = build_learner(config.learner_config, probe.specs)
    if hasattr(probe, "close"):
        probe.close()
    extended = learner.config
    key, fp = workload_fingerprint(extended, config.env_config)
    cache_dir = resolve_tuning_cache_dir(config.session_config)
    cache = TuningCache(cache_dir)
    if not force:
        entry = cache.lookup(key)
        if entry is not None:
            return dict(entry, cache_hit=True, measured=0)

    space = dims if dims is not None else search_space_for(config, extended)
    if not space:
        raise ValueError(
            f"no searchable dimensions for algo "
            f"{extended.algo.name!r} on {env_name!r} (host-env workloads "
            "search the learn-phase subset only — "
            f"{', '.join(LEARN_PHASE_DIMS)}); nothing to tune"
        )
    point = {name: extended.algo.get(name) for name, _ in space}

    def note(msg):
        if verbose:
            print(f"tune: {msg}", file=sys.stderr, flush=True)

    note(f"fingerprint {key} ({env_name}, algo={extended.algo.name}, "
         f"surface={surface}); searching {[n for n, _ in space]}")
    trials = []

    def run_trial(p):
        ms = measure_point(config, p, warmup, throwaway, iters,
                           surface=surface)
        trials.append({"config": dict(p), "iter_ms": ms})
        note(f"{p} -> {ms:.2f} ms/iter")
        return ms

    default_snapshot = dict(point)
    default_ms = run_trial(point)
    incumbent_ms = default_ms
    for name, values in space:
        if skip_dimension(name, point, extended):
            note(f"skip {name} (moot under {point})")
            continue
        best_val, best_ms = None, None
        for val in values:
            if val == point.get(name):
                continue  # the incumbent's value is already measured
            ms = run_trial({**point, name: val})
            if best_ms is None or ms < best_ms:
                best_val, best_ms = val, ms
        if best_ms is not None and best_ms < incumbent_ms * (1.0 - min_gain):
            note(f"adopt {name}={best_val} "
                 f"({incumbent_ms:.2f} -> {best_ms:.2f} ms)")
            point[name] = best_val
            incumbent_ms = best_ms

    entry = {
        "key": key,
        "fingerprint": fp,
        "config": dict(point),        # the full chosen point (pins every
                                      # searched dim, defaults included)
        "default": default_snapshot,
        "default_ms": default_ms,
        "chosen_ms": incumbent_ms,
        "speedup": default_ms / max(incumbent_ms, 1e-9),
        "trials": trials,
        "platform": str(jax.default_backend()),
        "device_kind": str(jax.devices()[0].device_kind),
        "jax": jax.__version__,
        "measure": {
            "surface": surface,  # 'fused' device iteration | 'learn'
                                 # (host-env learn-only program)
            "warmup": warmup,
            "throwaway": throwaway,
            "iters": iters,
            "min_gain": min_gain,
            "timing": "device_get-fenced chained window (bench.py discipline)",
        },
        "created_t": time.time(),
    }
    cache.store(key, entry)
    return dict(entry, cache_hit=False, measured=len(trials))
