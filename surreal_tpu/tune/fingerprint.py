"""Workload fingerprint: the cache key of a tuning decision.

A tuned program is only valid for the program it was measured on, so the
fingerprint covers everything that changes the compiled iteration —
algorithm + hyperparameters, model architecture, batch geometry (env name,
num_envs, horizon live in the algo/env trees), replay shape, backend
platform and device kind, and the jax version (XLA's scheduling changes
across pins) — while EXCLUDING the searched knobs themselves: applying a
cached winner must not change the key it was stored under, or a second
lookup would miss its own result.
"""

from __future__ import annotations

import hashlib
import json

# the searched dimensions (surreal_tpu/tune/space.py declares their
# candidate values); excluded from the fingerprint along with the
# autotune mode knob itself
TUNABLE_KEYS = (
    "rollout_unroll",
    "sgd_unroll",
    "update_unroll",
    "gae_unroll",
    "gae_impl",
    "shuffle",
    "precision",      # the precision policy (ops/precision.py)
    "vtrace_impl",    # IMPALA's per-op V-trace kernel choice
    "replay_gather",  # DDPG's batched replay gather/scatter impl
)
_EXCLUDED = TUNABLE_KEYS + ("autotune",)


def fingerprint_dict(
    extended_learner_config,
    env_config,
    platform: str | None = None,
    device_kind: str | None = None,
    jax_version: str | None = None,
) -> dict:
    """The human-readable fingerprint components (stored in each cache
    entry so `cat <entry>.json` answers "tuned for WHAT?")."""
    if platform is None or device_kind is None or jax_version is None:
        import jax

        platform = platform or jax.default_backend()
        jax_version = jax_version or jax.__version__
        if device_kind is None:
            device_kind = str(jax.devices()[0].device_kind)
    algo = {
        k: v
        for k, v in extended_learner_config.algo.to_dict().items()
        if k not in _EXCLUDED
    }
    model = (
        extended_learner_config.model.to_dict()
        if "model" in extended_learner_config
        else {}
    )
    # 'auto' dtypes resolve FROM the searched precision knob
    # (ops/precision.py) — hashing them as the policy's concrete values
    # would leak the excluded knob back into the key, and hashing the
    # literal 'auto' would invalidate every pre-PR-7 cache entry. Both
    # canonicalize to the pre-policy defaults; an EXPLICIT dtype string
    # changes the program independently of the search and hashes as
    # itself.
    if model.get("dtype") in (None, "auto"):
        model["dtype"] = "float32"
    if model.get("compute_dtype") in (None, "auto"):
        model["compute_dtype"] = "bfloat16"
    optimizer = (
        extended_learner_config.optimizer.to_dict()
        if "optimizer" in extended_learner_config
        else {}
    )
    # the loss_scaling subtree is part of the precision-policy axis the
    # fingerprint deliberately excludes (its effect follows algo.precision,
    # and healthy-step numerics are exact either way — power-of-two scales)
    optimizer.pop("loss_scaling", None)
    fp = {
        "algo": algo,
        "model": model,
        "replay": extended_learner_config.replay.to_dict()
        if "replay" in extended_learner_config
        else {},
        "optimizer": optimizer,
        "env": {
            "name": env_config.name,
            "num_envs": int(env_config.get("num_envs", 1)),
            "action_repeat": env_config.get("action_repeat", 1),
            "frame_stack": env_config.get("frame_stack", 1),
            "image_size": env_config.get("image_size", None),
        },
        "backend": platform,
        "device_kind": device_kind,
        "jax": jax_version,
    }
    return fp


def fingerprint_key(fp: dict) -> str:
    """Stable 16-hex key of a fingerprint dict (sorted-key JSON; tuples
    serialize as lists, so config-tree tuple/list spelling cannot fork
    the key)."""
    blob = json.dumps(fp, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def workload_fingerprint(
    extended_learner_config, env_config, **kw
) -> tuple[str, dict]:
    """-> (key, fingerprint-dict). The one entry point callers use."""
    fp = fingerprint_dict(extended_learner_config, env_config, **kw)
    return fingerprint_key(fp), fp
