"""Divergence guard + rollback policy (the robustness layer's second leg;
ISSUE 5 tentpole).

PR 1 gave every learner in-graph ``health/*`` diagnostics — grad/param
norms and a NaN/inf guard — that ride the metrics dict and sync to host
at the existing ``metrics.every_n_iters`` cadence (zero extra
device->host syncs). This module is the POLICY on those signals: when a
synced window shows ``health/nonfinite > 0`` (or an optional grad-norm
limit exceeded), the run does not die and does not keep training on
poisoned state; it

1. **skips the poisoned save** — ``SessionHooks`` consults the guard
   before its checkpoint cadence fires, so a NaN state can never become
   the "last good" checkpoint;
2. **rolls back** — the driver restores the newest checkpoint whose
   state is actually finite (older steps are tried if the newest restored
   one is itself poisoned — possible when the checkpoint cadence outpaces
   the metrics cadence), plus the replay ``extra/`` tree on the
   off-policy path when it was snapshotted;
3. **re-seeds the offending batch** — drivers fold the rollback count
   into their PRNG chain and env carries, so a deterministic workload
   cannot replay the exact trajectory into the same divergence;
4. **applies bounded LR backoff** — writes
   ``max(min_lr_scale, lr_backoff ** nonce)`` into the restored state's
   :class:`~surreal_tpu.learners.base.RecoveryScaleState` leaves (a
   traced input of the jitted learn, so no rebuild/recompile).

After ``recovery.max_rollbacks`` failed recoveries the run raises
:class:`TrainingDiverged` — a bounded, loud end beats an unbounded
restore loop. Detection latency is the metrics cadence (the health
scalars only reach the host there); bound the damage by keeping
``metrics.every_n_iters <= checkpoint.every_n_iters``, which the
defaults satisfy.

Multi-host note: rollback is deliberately single-host. A collective
restore would need every rank to agree on the rollback inside the
collective schedule (the same deadlock shape as per-rank staleness
drops, see MultiHostSEEDTrainer); multi-host runs set the guard to
``warn`` — the trip is logged/emitted, the poisoned checkpoint is still
skipped on rank 0, and the recovery story is kill-and-relaunch with
``auto_resume`` (which now lands on the last FINITE checkpoint).

Config (``session_config.recovery``): ``interrupt`` (the
session/interrupt.py sentinel), ``on_divergence`` ('rollback' | 'warn' |
'off'), ``max_rollbacks``, ``lr_backoff``, ``min_lr_scale``,
``grad_norm_limit``. Telemetry: every trip/rollback/giveup lands as a
``recovery`` event rendered by ``surreal_tpu diag``.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from surreal_tpu.learners.base import set_recovery_lr_scale


class TrainingDiverged(RuntimeError):
    """Raised when the divergence guard exhausted its rollback budget (or
    had no checkpoint and no fresh-init fallback to roll back to)."""


class RollbackResult(NamedTuple):
    state: Any
    iteration: int
    env_steps: int
    extra: Any | None     # restored auxiliary tree (replay), when asked for
    nonce: int            # rollback count — drivers fold this into PRNG chains
    lr_scale: float


def _state_is_finite(state: Any) -> bool:
    """One host sync, rollback-path only: NaN/inf anywhere in the inexact
    leaves? (isfinite-of-sum — inf/nan propagate through the reduction, so
    one scalar check covers each leaf.)"""
    checks = [
        jnp.isfinite(jnp.sum(x))
        for x in jax.tree.leaves(state)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact)
    ]
    if not checks:
        return True
    return bool(jnp.all(jnp.stack(checks)))


class RecoveryManager:
    """One per :class:`~surreal_tpu.launch.hooks.SessionHooks`. The hooks
    call :meth:`check` on every synced metrics window (setting
    ``pending``); the DRIVER — which owns the state/carry/replay — calls
    :meth:`rollback` when it observes ``pending`` and splices the result
    back into its loop."""

    def __init__(self, config, ckpt, tracer, log):
        rc = config.session_config.get("recovery", None)
        get = rc.get if rc is not None else (lambda k, d=None: d)
        self.mode = get("on_divergence", "rollback")
        if self.mode not in ("rollback", "warn", "off"):
            raise ValueError(
                f"recovery.on_divergence {self.mode!r} not in rollback|warn|off"
            )
        self.max_rollbacks = int(get("max_rollbacks", 3))
        self.lr_backoff = float(get("lr_backoff", 0.5))
        self.min_lr_scale = float(get("min_lr_scale", 0.05))
        self.heal_after_windows = int(get("heal_after_windows", 20))
        limit = get("grad_norm_limit", None)
        self.grad_norm_limit = None if limit is None else float(limit)
        self.rollbacks = 0
        self.pending: str | None = None   # trip reason awaiting the driver
        # what the MOST RECENT synced window showed (None = healthy):
        # final_checkpoint consults this in warn mode, where pending is
        # never set but a poisoned run-end save must still be refused
        self.last_window_tripped: str | None = None
        self._healthy_streak = 0
        self._trip_iteration: int | None = None
        self._ckpt = ckpt
        self._tracer = tracer
        self._log = log

    def disable_rollback(self, reason: str) -> None:
        """Downgrade to 'warn' (multi-host drivers: rollback is a
        collective restore these loops cannot run — see module doc)."""
        if self.mode == "rollback":
            self.mode = "warn"
            self._log.info("divergence rollback disabled: %s", reason)

    # -- detection (called by SessionHooks at the metrics cadence) -----------
    def check(self, metrics, iteration: int, env_steps: int) -> str | None:
        """Inspect one synced metrics window; returns the trip reason (and
        sets ``pending`` in rollback mode) or None."""
        if self.mode == "off" or not metrics:
            return None
        reason = None
        if metrics.get("health/nonfinite", 0.0) > 0.0:
            reason = "nonfinite"
        elif (
            self.grad_norm_limit is not None
            and metrics.get("health/grad_norm", 0.0) > self.grad_norm_limit
        ):
            reason = "grad_norm"
        self.last_window_tripped = reason
        if reason is None:
            # healing: the rollback budget targets a state that RE-diverges,
            # not isolated transients spread over a production-length run —
            # sustained healthy windows clear the streak (the same reset
            # rule the SEED respawn backoff applies to worker crash loops).
            # The backed-off lr_scale persists until the NEXT rollback
            # recomputes it from the reset nonce: raising it mid-run would
            # mean mutating the driver's live state from a policy object.
            self._healthy_streak += 1
            if self.rollbacks and self._healthy_streak >= self.heal_after_windows:
                self._log.info(
                    "divergence guard healed: %d healthy windows since the "
                    "last rollback — clearing the rollback streak (%d)",
                    self._healthy_streak, self.rollbacks,
                )
                self._tracer.event(
                    "recovery", kind="healed", rollbacks_cleared=self.rollbacks,
                    healthy_windows=self._healthy_streak,
                )
                self.rollbacks = 0
            return None
        self._healthy_streak = 0
        self._trip_iteration = iteration
        self._log.warning(
            "divergence guard tripped at iteration %d (%s: nonfinite=%s "
            "grad_norm=%s) — mode=%s",
            iteration, reason, metrics.get("health/nonfinite"),
            metrics.get("health/grad_norm"), self.mode,
        )
        self._tracer.event(
            "recovery", kind="tripped", reason=reason, mode=self.mode,
            iteration=int(iteration), env_steps=int(env_steps),
            grad_norm=metrics.get("health/grad_norm"),
        )
        if self.mode == "rollback":
            self.pending = reason
        return reason

    # -- rollback (called by the driver that owns the loop state) ------------
    def rollback(
        self, template_state: Any, *, fresh=None, extra_template: Any | None = None
    ) -> RollbackResult:
        """Restore the newest FINITE checkpoint and clear ``pending``.

        ``template_state`` supplies the restore pytree structure (the
        driver's current — poisoned — state is fine). ``fresh(nonce)``
        builds a from-scratch state when no usable checkpoint exists (the
        guard tripped before the first save): the run restarts at
        iteration 0 rather than dying. ``extra_template`` asks for the
        step-aligned auxiliary tree (the off-policy replay snapshot) from
        the same step. Raises :class:`TrainingDiverged` when the bounded
        budget is exhausted or no recovery source exists.
        """
        reason, self.pending = self.pending or "manual", None
        # the poisoned state is being replaced with a finite one: the
        # last-window flag no longer describes the live state (a run that
        # ends right after a rollback may still final-checkpoint)
        self.last_window_tripped = None
        self.rollbacks += 1
        nonce = self.rollbacks
        if self.rollbacks > self.max_rollbacks:
            self._tracer.event(
                "recovery", kind="giveup", reason=reason, rollbacks=self.rollbacks,
            )
            raise TrainingDiverged(
                f"divergence guard tripped {self.rollbacks} times "
                f"(recovery.max_rollbacks={self.max_rollbacks}); the last-"
                "good checkpoint re-diverges even with LR backoff — "
                "inspect `surreal_tpu diag` health signals"
            )
        restored = self.restore_newest_finite(template_state)
        extra = None
        if restored is not None:
            state, meta, step = restored
            iteration, env_steps = int(meta["iteration"]), int(meta["env_steps"])
            source = f"checkpoint step {step}"
            if extra_template is not None and self._ckpt is not None:
                extra = self._ckpt.restore_extra(extra_template, step=step)
        elif fresh is not None:
            state, iteration, env_steps = fresh(nonce), 0, 0
            source = "fresh init (no finite checkpoint existed)"
        else:
            self._tracer.event("recovery", kind="giveup", reason=reason)
            raise TrainingDiverged(
                "divergence guard tripped with no finite checkpoint to "
                "roll back to and no fresh-init fallback"
            )
        lr_scale = max(self.min_lr_scale, self.lr_backoff ** nonce)
        state = set_recovery_lr_scale(state, lr_scale)
        self._log.warning(
            "rollback #%d (%s): resumed from %s at iteration %d "
            "(%d env steps), lr scale %.3g — offending batch re-seeded",
            nonce, reason, source, iteration, env_steps, lr_scale,
        )
        self._tracer.event(
            "recovery", kind="rollback", reason=reason, nonce=nonce,
            from_iteration=self._trip_iteration, to_iteration=iteration,
            env_steps=env_steps, lr_scale=lr_scale, source=source,
            extra_restored=extra is not None,
        )
        return RollbackResult(state, iteration, env_steps, extra, nonce, lr_scale)

    def restore_newest_finite(self, template_state):
        """Newest checkpoint whose state is actually FINITE — one walk for
        the rollback path AND auto-resume (SessionHooks.restore): a
        relaunch after a kill must land on the last finite checkpoint, not
        merely the last readable one. Delegates to the CheckpointManager's
        own damage-fallback walk with a finiteness ``validate`` hook (one
        source of truth for skip/raise semantics: damaged steps fall back
        with telemetry, an every-step restore failure raises the newest
        error loudly, poison-everywhere returns None). Returns
        (state, meta, step) or None; the saved step IS ``meta['iteration']``
        (CheckpointManager.save's contract)."""
        if self._ckpt is None:
            return None
        restored = self._ckpt.restore(template_state, validate=_state_is_finite)
        if restored is None:
            return None
        state, meta = restored
        return state, meta, int(meta["iteration"])
