"""Multi-host (multi-controller) training driver — the loop that composes
the multi-host primitives in ``parallel/multihost.py`` into a runnable
trainer (parity: the reference scaled training across machines with
symphony-launched process groups — learner on one box, agent pools on
others, ZMQ between them, SURVEY.md §3.1/§5.8; the rebuild scales the JAX
way: every host runs THIS SAME program over ONE global device mesh and XLA
emits ICI collectives within a slice, DCN collectives across hosts).

# precision: dtype-transparent like parallel/dp.py — the precision
# policy (ops/precision.py) rides inside the learners every rank builds
# identically from the same config, so replicas stay bitwise-identical
# under any policy; rank 0's hooks record/validate it.

Per-process discipline (the multi-controller contract):

- **Same program, same seeds.** Every rank derives the identical PRNG key
  chain, so replicated jit inputs (learn keys, init keys) agree everywhere
  by construction. Per-host divergence (env seeding, exploration noise) is
  always an explicit ``fold_in`` of the rank or of the global env index.
- **Rank 0 owns the session.** Metrics, logs, checkpoints, and eval run on
  process 0 only, against a HOST-LOCAL numpy copy of the (replicated)
  state — so the session services stay single-controller and orbax never
  needs multi-process coordination. Ranks > 0 run no session services and
  do not even need the session folder mounted.
- **Restore-and-broadcast.** On startup rank 0 restores (auto-resume /
  warm-start, same rules as single-host), then broadcasts state + counters
  to all ranks via a device collective — kill ALL processes, relaunch with
  the same config, and the curve continues.
- **Per-host env feed.** For the fused/off-policy drivers
  ``env_config.num_envs`` is the GLOBAL batch width; each process
  contributes ``num_envs / process_count`` (the SEED driver keeps SEED's
  own per-worker convention — see ``MultiHostSEEDTrainer``):

  * device envs (``jax:*``): the env carry is created directly as a
    global array sharded over ``dp`` (a jitted SPMD init — each process
    materializes only its addressable shards), and the fused
    rollout+learn ``dp_train_iter`` runs on the global mesh unchanged;
  * host envs (gym/dm_control/robosuite-class): each process steps its
    OWN local env batch (the reference's per-machine agent pool), then
    ``local_batch_to_global`` assembles the global learn batch, every
    host's slice riding its own devices.

Stop discipline: a reward-target stop decided by rank 0's ``on_metrics``
is broadcast on metrics-cadence iterations (the only iterations a stop
can originate, and a schedule every rank computes locally) so all ranks
leave the collective schedule together — a rank stopping alone would
deadlock the others' next psum, and agreeing every iteration would
de-pipeline the async hot loop.
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import numpy as np

from surreal_tpu.engine import (
    EngineConfig,
    LoopEngine,
    LoopState,
    Outcome,
    StageSpec,
    sideband_stages,
)
from surreal_tpu.launch.hooks import SessionHooks, host_metrics
from surreal_tpu.launch.offpolicy_trainer import OffPolicyTrainer
from surreal_tpu.launch.rollout import host_rollout, init_device_carry
from surreal_tpu.launch.seed_trainer import SEEDTrainer
from surreal_tpu.launch.trainer import Trainer
from surreal_tpu.parallel.mesh import check_dp_divisible, replicate_state
from surreal_tpu.parallel.multihost import local_batch_to_global
from surreal_tpu.session.config import Config
from surreal_tpu.session.telemetry import HeartbeatWriter, Tracer

_COUNTER_SPLIT = 2**31  # int64 counters ride int32 collectives as (hi, lo)


def _to_host_local(tree):
    """Replicated global arrays -> host-local numpy (every process holds a
    full copy of a fully-replicated array, so this is a local read)."""
    return jax.tree.map(np.asarray, tree)


def _acting_refresh(act_base, state):
    """Host-local acting snapshot: read ONLY params + obs_stats from the
    replicated global ``state`` (a local read) and graft them onto the
    device-resident ``act_base`` built at run start — optimizer moments
    never cross the host boundary again (they'd triple the per-iteration
    refresh bytes for leaves acting never reads)."""
    params = jax.device_put(jax.tree.map(np.asarray, state.params))
    stats = jax.device_put(jax.tree.map(np.asarray, state.obs_stats))
    return act_base._replace(params=params, obs_stats=stats)


class _MultiHostSession:
    """The multi-controller session discipline shared by every multi-host
    driver: rank bookkeeping, restore-and-broadcast, and the once-compiled
    cross-rank stop agreement. Mixed into a Trainer-family class that sets
    ``self.mesh`` before the mixin methods run."""

    def _init_multihost(self, kind: str) -> None:
        self.rank = jax.process_index()
        self.nprocs = jax.process_count()
        self._agree_fn = None
        self._agree_sharding = None
        if self.nprocs < 2:
            raise ValueError(
                f"{kind} needs an initialized multi-process runtime "
                "(jax.process_count() >= 2); use the single-host driver"
            )

    # -- rank-0 session services + cross-rank agreement ---------------------
    def _broadcast_from_rank0(self, state, iteration: int, env_steps: int):
        """Ship rank 0's (restored) state + counters to every rank, so
        ranks > 0 need neither the session folder nor a shared FS."""
        from jax.experimental import multihost_utils

        counters = np.array(
            [
                iteration // _COUNTER_SPLIT, iteration % _COUNTER_SPLIT,
                env_steps // _COUNTER_SPLIT, env_steps % _COUNTER_SPLIT,
            ],
            np.int32,
        )
        state, counters = multihost_utils.broadcast_one_to_all(
            (_to_host_local(state), counters)
        )
        c = [int(x) for x in np.asarray(counters)]
        return state, c[0] * _COUNTER_SPLIT + c[1], c[2] * _COUNTER_SPLIT + c[3]

    def _agree_stop(self, stop: bool) -> bool:
        """All ranks adopt rank 0's stop decision (a lone stopper would
        deadlock everyone else's next collective).

        Hand-rolled rather than ``multihost_utils.broadcast_one_to_all``:
        that helper constructs a fresh jit per call, which would recompile
        (and open a new gloo/ICI context) EVERY iteration; this one jits
        once per run. Each process contributes its flag at its own mesh
        positions; the replicated sum broadcasts rank 0's decision (ranks
        > 0 contribute zeros)."""
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._agree_fn is None:
            # one flag element per device (1-D dim mapped to ALL mesh axes),
            # so the local slice is exactly this process's device count
            self._agree_sharding = NamedSharding(
                self.mesh, P(tuple(self.mesh.axis_names))
            )
            self._agree_fn = jax.jit(
                lambda x: jnp.minimum(jnp.sum(x), 1),
                out_shardings=NamedSharding(self.mesh, P()),
                donate_argnums=(0,),  # flags are rebuilt fresh every call
            )
        n_local = len([d for d in self.mesh.devices.flat if d.process_index == self.rank])
        local = np.full(
            (n_local,), np.int32(1 if (stop and self.rank == 0) else 0)
        )
        flags = jax.make_array_from_process_local_data(self._agree_sharding, local)
        return bool(self._agree_fn(flags))

    def _maybe_agree_stop(self, iteration: int, stop: bool, metrics_every: int) -> bool:
        """A stop can only originate on metrics-cadence iterations (rank
        0's hooks gate ``on_metrics`` behind the metrics fire), and every
        rank computes that cadence locally — so the cross-host agreement
        runs only there and the hot loop stays async otherwise. Mirrors
        PeriodicTracker: fires when iteration % period == 0."""
        if iteration % metrics_every != 0:
            return False
        return self._agree_stop(stop)

    def _telemetry(self, hooks):
        """Per-rank telemetry handles: rank 0 spans through hooks' tracer
        (ranks > 0 get a disabled no-op tracer — same code path, zero
        cost), and EVERY rank gets a HeartbeatWriter appending liveness
        events to its own ``telemetry/heartbeat_rank<k>.jsonl``. Ranks
        whose host cannot write the session folder disable themselves
        silently (the folder need not be mounted off rank 0)."""
        cfg = self.config.session_config
        tel = cfg.get("telemetry", None)
        tracer = hooks.tracer if hooks is not None else Tracer(None, enabled=False)
        hb = HeartbeatWriter(
            cfg.folder,
            self.rank,
            every_s=float(tel.heartbeat_every_s) if tel is not None else 10.0,
            enabled=bool(tel.enabled) if tel is not None else True,
        )
        return tracer, hb

    def _begin_session(self, state):
        """Rank-0 session prologue shared by every multi-host run():
        restore on rank 0 -> broadcast to all ranks -> replicate over the
        mesh -> start counters. Returns (hooks, state, iteration,
        env_steps); hooks is None on ranks > 0.

        Preemption discipline: a preempting scheduler SIGTERMs the whole
        group. Rank 0's hooks own an interrupt sentinel and turn the latch
        into a stop that ``_maybe_agree_stop`` broadcasts at the next
        metrics-cadence iteration (interrupt latency is bounded by
        ``metrics.every_n_iters``); ranks > 0 install a latch-only
        sentinel here so the default SIGTERM handler cannot kill them
        mid-collective while rank 0 still needs their participation for
        that agreement (a second signal escalates, session/interrupt.py).
        Divergence ROLLBACK is downgraded to 'warn' on rank 0: restoring
        is a collective operation these loops cannot run per-rank — the
        multi-host recovery story is kill-and-relaunch with auto_resume,
        which now lands on the last FINITE checkpoint (the poisoned-save
        skip still applies)."""
        hooks = SessionHooks(self.config, self.learner) if self.rank == 0 else None
        self._rank_interrupt = None
        if hooks is None:
            # ranks > 0 never construct hooks, but every process compiles
            # the same programs — enable the persistent compile cache here
            # (ranks without the folder mounted degrade to cold compiles)
            from surreal_tpu.launch.hooks import maybe_enable_compile_cache

            maybe_enable_compile_cache(self.config.session_config)
            from surreal_tpu.session.interrupt import InterruptSentinel

            rec = self.config.session_config.get("recovery", None)
            self._rank_interrupt = InterruptSentinel(
                enabled=bool(rec.get("interrupt", True)) if rec is not None else True
            )
        else:
            hooks.recovery.disable_rollback(
                "multi-host run: per-rank restore would desynchronize the "
                "collective schedule; relaunch with auto_resume instead"
            )
        try:
            iteration, env_steps = 0, 0
            if hooks is not None:
                state, iteration, env_steps = hooks.restore(state)
            state, iteration, env_steps = self._broadcast_from_rank0(
                state, iteration, env_steps
            )
            state = replicate_state(self.mesh, state)
            if hooks is not None:
                hooks.begin_run(iteration, env_steps)
        except BaseException:
            # the caller only closes hooks it received; a prologue failure
            # must not leak the writer/checkpoint manager
            if hooks is not None:
                hooks.close()
            raise
        return hooks, state, iteration, env_steps

    def _end_session(self, hooks, iteration: int, env_steps: int, lazy_host_state):
        """Run-end epilogue: rank 0 writes the final checkpoint (the
        emergency checkpoint, on the interrupt path), then ALL ranks leave
        the collective schedule together (rank 0 may still be writing
        while others would otherwise tear down the runtime)."""
        if hooks is not None:
            hooks.final_checkpoint(iteration, env_steps, lazy_host_state)
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("surreal_tpu:run_end")
        if self._rank_interrupt is not None:
            self._rank_interrupt.close()
        return hooks.last_metrics if hooks is not None else {}


class MultiHostTrainer(_MultiHostSession, Trainer):
    """On-policy multi-controller trainer (PPO / IMPALA families).

    Requires ``jax.distributed`` to be initialized first
    (``parallel.multihost.initialize_from_topology``) so ``jax.devices()``
    spans all hosts; ``Trainer.__init__`` then builds the GLOBAL mesh and
    the dp train step with no multi-host-specific code.
    """

    def __init__(self, config):
        self._init_multihost("MultiHostTrainer")
        global_envs = config.env_config.num_envs
        check_dp_divisible(
            global_envs, self.nprocs, "num_envs", "the process count"
        )
        self.global_num_envs = global_envs
        self.local_num_envs = global_envs // self.nprocs
        if config.env_config.name.startswith("jax:"):
            # device envs are global: the carry is one dp-sharded array, so
            # Trainer.__init__ sees the GLOBAL batch width (its dp check
            # must hold globally); carry creation is overridden in run()
            super().__init__(config)
        else:
            # host-env adapters size their worker batch from num_envs:
            # each process builds only ITS slice of the global env batch
            local_cfg = Config(
                env_config=Config(num_envs=self.local_num_envs)
            ).extend(config)
            super().__init__(local_cfg)
            # ...but step accounting stays global
            self.num_envs = self.global_num_envs
            self.config = config
        if self.device_mode:
            if self.mesh.size == 1:
                raise ValueError("multi-host run resolved a size-1 mesh")
        else:
            from surreal_tpu.parallel.dp import dp_learn
            from surreal_tpu.parallel.mesh import make_mesh

            self.mesh = make_mesh(config.session_config.topology)
            check_dp_divisible(global_envs, self.mesh.shape["dp"])
            self._learn = dp_learn(self.learner, self.mesh)

    # -- main loop -----------------------------------------------------------
    def run(
        self,
        max_env_steps: int | None = None,
        on_metrics: Callable[[int, dict], None] | None = None,
    ):
        """Multi-controller variant of ``Trainer.run``: same cadences and
        hook behavior, but session services fire on rank 0 only and all
        ranks stay on one collective schedule. ``on_metrics`` fires on
        rank 0; its stop decision is broadcast."""
        cfg = self.config.session_config
        total = max_env_steps or cfg.total_env_steps
        steps_per_iter = self.horizon * self.global_num_envs
        metrics_every = max(1, cfg.metrics.every_n_iters)

        def maybe_agree_stop(iteration: int, stop: bool) -> bool:
            return self._maybe_agree_stop(iteration, stop, metrics_every)

        key = jax.random.key(self.seed)  # identical chain on every rank
        key, init_key, env_key = jax.random.split(key, 3)
        state = self.learner.init(init_key)
        hooks = None
        try:
            hooks, state, iteration, env_steps = self._begin_session(state)
            tracer, heartbeat = self._telemetry(hooks)
            ls = LoopState(
                state=state, key=key, iteration=iteration,
                env_steps=env_steps,
            )

            def lazy_host_state():
                return _to_host_local(ls.state)

            # the boundary stays inline on every rank (EngineConfig.inline):
            # a deferred, rank-local stop decision would race the agreed
            # collective stop schedule
            engine_cfg = EngineConfig.from_session(cfg).inline()

            def after_step(ls):
                heartbeat.beat(ls.iteration, ls.env_steps)

            if self.device_mode:
                from jax.sharding import NamedSharding, PartitionSpec as P

                # SPMD carry init: one jitted program over the global mesh;
                # every leaf is [B_global, ...] sharded over dp, and each
                # process computes only its addressable shards. Per-env
                # seeding comes from the global env index (the split inside
                # init_device_carry), so no rank folding is needed.
                ls.extras["carry"] = jax.jit(
                    lambda k: init_device_carry(
                        self.env, k, self.global_num_envs
                    ),
                    out_shardings=NamedSharding(self.mesh, P("dp")),
                    donate_argnums=(),  # one-shot init; nothing loop-carried
                )(env_key)
                if hooks is not None:
                    # cost/MFU accounting (rank 0): lower + HLO cost pass
                    # are rank-local — no collective, no compile
                    hooks.record_program_costs(
                        "train_iter", self._train_iter, state,
                        ls.extras["carry"], jax.random.fold_in(key, 0),
                        phase="train_iter",
                    )
                stages = (
                    StageSpec("collect", donate=True),
                    StageSpec("learn", donate=True),
                ) + sideband_stages()

                def step(ls):
                    ls.key, it_key, hk_key = jax.random.split(ls.key, 3)
                    # unfenced dispatch span (see launch/trainer.py's note)
                    with tracer.span("train_iter"):
                        ls.state, ls.extras["carry"], metrics = (
                            self._train_iter(
                                ls.state, ls.extras["carry"], it_key
                            )
                        )
                    return Outcome(
                        metrics=metrics, hook_key=hk_key,
                        steps=steps_per_iter,
                        state_for_hooks=lazy_host_state,
                    )
            else:
                obs_holder = [
                    self.env.reset(
                        seed=self.config.env_config.seed + self.rank
                    )
                ]
                from collections import deque

                from surreal_tpu.launch.hooks import HOST_METRICS_WINDOW

                recent_returns: deque = deque(maxlen=HOST_METRICS_WINDOW)
                # full local copy ONCE (moments land on device and stay);
                # per-iteration refreshes graft params + obs_stats only
                act_holder = [jax.device_put(lazy_host_state())]
                stages = (
                    StageSpec("collect", donate=False),
                    StageSpec("learn", donate=False),
                ) + sideband_stages()

                def step(ls):
                    ls.key, r_key, l_key, hk_key = jax.random.split(ls.key, 4)
                    # act against a host-local param copy (the SEED host
                    # loop is per-process; only learn is global), with
                    # per-rank exploration streams. One params+stats
                    # upload per ITERATION: shipping the numpy pytree
                    # straight into the per-step jitted act would re-pay
                    # it every env step of the rollout
                    act_holder[0] = _acting_refresh(act_holder[0], ls.state)
                    with tracer.span("rollout"):
                        obs_holder[0], batch, ep_stats = host_rollout(
                            self.env, self._act, act_holder[0],
                            obs_holder[0],
                            jax.random.fold_in(r_key, self.rank),
                            self.horizon,
                        )
                    gbatch = local_batch_to_global(
                        self.mesh, batch, batch_dim=1
                    )
                    with tracer.span("learn"):
                        ls.state, metrics = self._learn(
                            ls.state, gbatch, l_key
                        )
                    if hooks is not None:
                        # first iteration only (idempotent): the learn
                        # program needs a representative global batch
                        hooks.record_program_costs(
                            "learn", self._learn, ls.state, gbatch, l_key,
                            phase="learn",
                        )
                    recent_returns.extend(ep_stats["returns"])
                    # episode stats are rank-0-local (each host sees
                    # only its own episodes); learner metrics are
                    # global — the psum already crossed hosts
                    return Outcome(
                        metrics=host_metrics(metrics, recent_returns),
                        hook_key=hk_key, steps=steps_per_iter,
                        state_for_hooks=lazy_host_state,
                    )

            engine = LoopEngine(
                hooks, total, step, stages, engine_cfg,
                on_metrics=on_metrics, after_step=after_step,
                agree_stop=maybe_agree_stop, fire_faults=False,
            )
            ls = engine.run(ls)
            state, iteration, env_steps = ls.state, ls.iteration, ls.env_steps
            return state, self._end_session(
                hooks, iteration, env_steps, lazy_host_state
            )
        finally:
            if hooks is not None:
                hooks.close()


class MultiHostOffPolicyTrainer(_MultiHostSession, OffPolicyTrainer):
    """Off-policy (DDPG-family) multi-controller trainer: the same global
    mesh discipline as :class:`MultiHostTrainer`, with the replay data
    plane sharded across EVERY device of EVERY host (replay/sharded.py —
    the reference's ShardedReplay scaled past one machine; each host's
    devices hold their own buffer shards and sample locally, the gradient
    psum fans in across hosts).

    Device (``jax:*``) envs only: the fused rollout+replay+update program
    is one SPMD computation over the global mesh. Host-env off-policy
    stays single-controller (its replay lives on one host's devices) —
    the launcher routes that combination to OffPolicyTrainer.
    """

    def __init__(self, config):
        self._init_multihost("MultiHostOffPolicyTrainer")
        if not config.env_config.name.startswith("jax:"):
            raise ValueError(
                "multi-host off-policy training needs a device env "
                f"(jax:*); got {config.env_config.name!r} — host-env "
                "off-policy runs single-host (replay on one host)"
            )
        check_dp_divisible(
            config.env_config.num_envs, self.nprocs,
            "num_envs", "the process count",
        )
        if config.session_config.checkpoint.get("include_replay", False):
            raise ValueError(
                "checkpoint.include_replay is single-host only: the "
                "multi-host replay is sharded across every host's devices "
                "and rank-0 orbax cannot address the other hosts' shards "
                "— resume refills the buffer instead (the reference's own "
                "semantics, SURVEY.md §5.4)"
            )
        # OffPolicyTrainer.__init__ builds the GLOBAL mesh (jax.devices()
        # spans hosts once jax.distributed is up), the per-device-scaled
        # replay, and the dp_offpolicy_iter shard_map — unchanged.
        super().__init__(config)
        if self.mesh is None or self.mesh.size == 1:
            raise ValueError("multi-host run resolved a size-1 mesh")

    def run(
        self,
        max_env_steps: int | None = None,
        on_metrics: Callable[[int, dict], None] | None = None,
    ):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from surreal_tpu.parallel.dp import offpolicy_carry_specs
        from surreal_tpu.replay.sharded import sharded_replay_init

        cfg = self.config.session_config
        total = max_env_steps or cfg.total_env_steps
        steps_per_iter = self.horizon * self.num_envs
        metrics_every = max(1, cfg.metrics.every_n_iters)

        key = jax.random.key(self.seed)  # identical chain on every rank
        key, init_key, env_key = jax.random.split(key, 3)
        state = self.learner.init(init_key)
        hooks = None
        try:
            hooks, state, iteration, env_steps = self._begin_session(state)
            tracer, heartbeat = self._telemetry(hooks)

            # SPMD carry init: one jitted program over the global mesh;
            # each process materializes only its addressable env shards.
            carry_shapes = jax.eval_shape(self._init_carry, env_key)
            carry_sh = jax.tree.map(
                lambda spec: NamedSharding(self.mesh, spec),
                offpolicy_carry_specs(carry_shapes, "dp"),
                is_leaf=lambda x: isinstance(x, P),
            )
            carry = jax.jit(
                self._init_carry, out_shardings=carry_sh,
                donate_argnums=(),  # one-shot init; nothing loop-carried
            )(env_key)
            # replay shards allocate per-device via shard_map (SPMD too)
            replay_state = sharded_replay_init(
                self.replay, self._replay_example(), self.mesh
            )

            import jax.numpy as jnp

            if hooks is not None:
                # cost/MFU accounting (rank 0; lower is rank-local)
                hooks.record_program_costs(
                    "train_iter", self._train_iter, state, replay_state,
                    carry, jax.random.fold_in(key, 0), jnp.float32(0),
                    jnp.asarray(False), jnp.asarray(True),
                    phase="train_iter",
                )

            ls = LoopState(
                state=state, key=key, iteration=iteration,
                env_steps=env_steps,
                extras={
                    "replay": replay_state, "carry": carry,
                    "first_call": True,
                },
            )

            def lazy_host_state():
                return _to_host_local(ls.state)

            def after_step(ls):
                heartbeat.beat(ls.iteration, ls.env_steps)

            stages = (
                StageSpec("collect", donate=True),
                StageSpec("stage", donate=True),
                StageSpec("learn", donate=True),
            ) + sideband_stages()

            def step(ls):
                ls.key, it_key, hk_key = jax.random.split(ls.key, 3)
                # beta/warmup derive from env_steps, identical on every
                # rank (same counter chain) -> consistent replicated inputs
                beta = jnp.asarray(
                    self._beta(ls.env_steps, total), jnp.float32
                )
                warmup = jnp.asarray(
                    ls.env_steps < self.algo.exploration.warmup_steps
                )
                # unfenced dispatch span (see launch/trainer.py's note)
                with tracer.span("train_iter"):
                    (
                        ls.state, ls.extras["replay"], ls.extras["carry"],
                        metrics,
                    ) = self._train_iter(
                        ls.state, ls.extras["replay"], ls.extras["carry"],
                        it_key, beta, warmup,
                        jnp.asarray(ls.extras["first_call"]),
                    )
                ls.extras["first_call"] = False
                return Outcome(
                    metrics=metrics, hook_key=hk_key, steps=steps_per_iter,
                    state_for_hooks=lazy_host_state,
                )

            # inline boundary on every rank — see MultiHostTrainer.run
            engine = LoopEngine(
                hooks, total, step, stages,
                EngineConfig.from_session(cfg).inline(),
                on_metrics=on_metrics, after_step=after_step,
                agree_stop=lambda it, stop: self._maybe_agree_stop(
                    it, stop, metrics_every
                ),
                fire_faults=False,
            )
            ls = engine.run(ls)
            state, iteration, env_steps = ls.state, ls.iteration, ls.env_steps
            return state, self._end_session(
                hooks, iteration, env_steps, lazy_host_state
            )
        finally:
            if hooks is not None:
                hooks.close()


class MultiHostSEEDTrainer(_MultiHostSession, SEEDTrainer):
    """SEED topology across machines — the reference's truest scaling
    shape mapped to TPU: EVERY host runs its own inference server + env
    worker fleet (the per-machine agent pools), and each iteration every
    rank contributes its local trajectory chunk to ONE global dp learn
    (gradient psum across hosts over ICI/DCN).

    Collective-schedule discipline: staleness DROPS are disallowed
    (``max_staleness`` must stay None) — dropping is a per-rank decision,
    and a rank skipping a learn while others enter the psum would
    deadlock the mesh. IMPALA/V-trace absorbs the bounded staleness this
    topology produces by construction; the staleness METRIC still flows.
    Acting is strictly host-local: the server's policy closure runs on a
    host-local copy of ONLY the acting leaves (params + obs normalizer,
    refreshed after each global learn), never on the globally-sharded
    state — a per-request collective would stall every other rank, and
    shipping optimizer moments host-side every iteration would triple the
    refresh bytes for nothing.

    Batch-width semantics: ``env_config.num_envs`` keeps the SEED
    convention (PER-WORKER batch width, exactly as single-host SEED —
    NOT the global width the module docstring describes for the fused
    drivers). Each rank's chunk is [horizon, num_envs]; the global learn
    batch is num_envs x process_count (one chunk per rank), which must
    divide the dp axis.
    """

    def __init__(self, config):
        self._init_multihost("MultiHostSEEDTrainer")
        explicit_dp = int(config.session_config.topology.mesh.dp)
        if explicit_dp > 1:
            raise ValueError(
                "multi-host SEED uses the full global mesh (topology."
                f"mesh.dp=-1); explicit dp={explicit_dp} subset meshes are "
                "a single-host SEED feature"
            )
        SEEDTrainer.__init__(self, config)
        # pipelined sub-slices would halve the per-rank chunk width, and
        # the collective learn schedule is built on [horizon, num_envs]
        # chunks (one per rank, global width num_envs * nprocs checked
        # against dp below) — keep the documented width; round-trip
        # hiding matters least here since every rank acts host-locally
        self.pipeline_workers = False
        if self.max_staleness is not None:
            raise ValueError(
                "max_staleness is single-host SEED only: dropping a chunk "
                "is a per-rank decision that would desynchronize the "
                "collective learn schedule — rely on V-trace (IMPALA) to "
                "absorb bounded staleness in the multi-host topology"
            )
        from surreal_tpu.parallel.dp import dp_learn
        from surreal_tpu.parallel.mesh import check_dp_divisible, make_mesh

        self.mesh = make_mesh(config.session_config.topology)
        check_dp_divisible(
            config.env_config.num_envs * self.nprocs,
            self.mesh.shape["dp"],
            what="num_envs * process_count",
        )
        # donation is SAFE here, unlike single-host SEED: every rank's
        # inference server acts from its own host-local ``_act_base``
        # copy (params+obs_stats grafts), never from the globally-sharded
        # train state this learn donates
        self._learn = dp_learn(self.learner, self.mesh)

    def _worker_env_config(self, env_cfg):
        """Per-rank seed decorrelation: worker i exists on EVERY rank, so
        without an offset each rank's fleet would produce byte-identical
        env streams and the global learn batch would carry duplicated
        trajectories."""
        return Config(
            seed=env_cfg.seed + self.rank * max(1, self.num_workers)
        ).extend(env_cfg)

    def _refresh_act_state(self, state):
        """Params+obs_stats-only acting refresh (see ``_acting_refresh``)."""
        self._act_base = _acting_refresh(self._act_base, state)
        return self._act_base

    def run(
        self,
        max_env_steps: int | None = None,
        on_metrics: Callable[[int, dict], None] | None = None,
    ):
        import threading

        cfg = self.config.session_config
        total = max_env_steps or cfg.total_env_steps
        metrics_every = max(1, cfg.metrics.every_n_iters)
        steps_per_iter = (
            self.algo.horizon * self.config.env_config.num_envs * self.nprocs
        )

        key = jax.random.key(cfg.seed)  # identical chain on every rank
        key, init_key, act_key = jax.random.split(key, 3)
        state = self.learner.init(init_key)
        hooks = None
        plane = None
        stop = threading.Event()
        try:
            hooks, state, iteration, env_steps = self._begin_session(state)
            tracer, heartbeat = self._telemetry(hooks)

            def lazy_host_state():
                return _to_host_local(state)

            # per-rank exploration streams; acting base lives on the LOCAL
            # default device (full initial copy once, then params-only
            # refreshes via _refresh_act_state)
            key_holder = [jax.random.fold_in(act_key, self.rank)]
            self._act_base = jax.device_put(lazy_host_state())
            # every rank's worker fleet inherits ITS tracer's trace id
            # (ranks > 0 mint one even with telemetry disabled)
            self._trace_id = tracer.trace_id
            plane = self._start_data_plane(
                self._make_act_fn(self._act_base, key_holder), stop,
                # first chunk waits out EVERY rank's compiles
                first_chunk_timeout=900.0,
            )
            # steady-state: the learn is COLLECTIVE, so this rank's next
            # chunk can wait on the slowest rank's fleet
            plane.steady_timeout = 120.0
            server = plane.server
            self._workers = plane.workers  # exposed for tests/fault injection

            from collections import deque

            from surreal_tpu.launch.seed_trainer import hop_event

            learn_ms: deque = deque(maxlen=256)
            ls = LoopState(
                state=state, key=key, iteration=iteration,
                env_steps=env_steps,
            )

            def lazy_ls_state():
                return _to_host_local(ls.state)

            lazy_host_state = lazy_ls_state

            def after_step(ls):
                heartbeat.beat(ls.iteration, ls.env_steps)
                plane.supervise()

            stages = (
                StageSpec("collect", donate=False, overlap=True),
                StageSpec("learn", donate=True),
            ) + sideband_stages()

            def step(ls):
                with tracer.span("chunk-wait"):
                    chunk = plane.next_chunk()
                versions = chunk.pop("param_version")
                # lineage stamps / exemplar metadata are host-side only
                # (ISSUE 14) — they must not enter the collective batch
                chunk.pop("lineage", None)
                chunk.pop("_exemplar", None)
                staleness = server.version - int(versions.min())
                gbatch = local_batch_to_global(self.mesh, chunk, batch_dim=1)
                ls.key, lkey, hk_key = jax.random.split(ls.key, 3)
                t_learn0 = time.perf_counter()
                with tracer.span("learn"):
                    ls.state, metrics = self._learn(ls.state, gbatch, lkey)
                learn_ms.append((time.perf_counter() - t_learn0) * 1e3)
                if hooks is not None:
                    # first iteration only (idempotent)
                    hooks.record_program_costs(
                        "learn", self._learn, ls.state, gbatch, lkey,
                        phase="learn",
                    )
                with tracer.span("param-publish"):
                    server.set_act_fn(
                        self._make_act_fn(
                            self._refresh_act_state(ls.state), key_holder
                        )
                    )
                if hooks is not None:
                    # learner metrics are global (psum crossed hosts);
                    # server/episode stats are rank-0-local by design
                    metrics = dict(
                        metrics,
                        **{
                            "staleness/updates_behind": float(staleness),
                            "workers/respawns": float(plane.respawns),
                            "workers/respawn_backoff_s": float(
                                plane.respawn_backoff_s
                            ),
                            "server/chunk_age_s": float(plane.last_chunk_age_s),
                        },
                        **server.queue_stats(),
                        **(server.episode_stats() or {}),
                    )

                def post_metrics(m_row):
                    # per-hop latency percentiles (host deques only)
                    hooks.tracer.event(
                        "hops", **hop_event(server, plane, learn_ms)
                    )

                return Outcome(
                    metrics=metrics, hook_key=hk_key, steps=steps_per_iter,
                    state_for_hooks=lazy_ls_state,
                    post_metrics=post_metrics if hooks is not None else None,
                )

            # inline boundary on every rank — see MultiHostTrainer.run
            engine = LoopEngine(
                hooks, total, step, stages,
                EngineConfig.from_session(cfg).inline(),
                on_metrics=on_metrics, after_step=after_step,
                agree_stop=lambda it, stop: self._maybe_agree_stop(
                    it, stop, metrics_every
                ),
                fire_faults=False,
            )
            ls = engine.run(ls)
            state, iteration, env_steps = ls.state, ls.iteration, ls.env_steps
            return state, self._end_session(
                hooks, iteration, env_steps, lazy_host_state
            )
        finally:
            stop.set()
            if plane is not None:
                plane.close()
            if hooks is not None:
                hooks.close()
