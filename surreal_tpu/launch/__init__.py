"""Entry points / training drivers (parity: reference ``surreal/main/`` +
``surreal/launch/``, SURVEY.md §2.1 main-dispatch row)."""

from surreal_tpu.launch.trainer import Trainer

__all__ = ["Trainer"]
