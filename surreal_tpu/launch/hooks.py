"""Session-services hooks shared by every training driver: metrics writing,
periodic checkpoint with keep-best, periodic eval, restore/auto-resume, and
an optional profiler trace window.

Parity map (SURVEY.md §3.4 learner loop + §2.1): the reference's learner
main loop interleaved ``tensorplex scalars``, ``PeriodicCheckpoint.save()``
and parameter publishing, while separate eval processes scored checkpoints
(§3.5) — here those side-bands are one :class:`SessionHooks` object called
once per iteration from Trainer / OffPolicyTrainer / SEEDTrainer, so the
three drivers cannot drift in their observability behavior.

Restore semantics (§5.3/§5.4): ``checkpoint.restore_from`` names another
session folder to warm-start from (the reference's ``restore_folder``);
``checkpoint.auto_resume`` (default on) resumes from this session's own
latest checkpoint when present — which is the whole failure-recovery
story: a killed job relaunched with the same config continues its curve.
"""

from __future__ import annotations

import os
import time

import jax

from surreal_tpu.launch.recovery import RecoveryManager
from surreal_tpu.session.checkpoint import CheckpointManager, make_checkpoint_manager
from surreal_tpu.session.config import Config
from surreal_tpu.session.costs import CostAccountant
from surreal_tpu.session.interrupt import InterruptSentinel
from surreal_tpu.session.metrics import get_logger, make_metrics_writer
from surreal_tpu.session.opsplane import OpsAggregator
from surreal_tpu.session.profile import ProfileManager
from surreal_tpu.session.telemetry import Tracer
from surreal_tpu.session.tracker import PeriodicTracker
from surreal_tpu.utils import faults


def maybe_enable_compile_cache(session_cfg) -> str | None:
    """Resolve + enable ``session.compile_cache_dir`` (the persistent XLA
    compile cache); returns the active absolute dir, or None when the knob
    is unset or enabling failed. Relative paths resolve under the session
    folder, so the default spelling ``compile_cache_dir=xla_cache`` keeps
    the cache session-local while an absolute path shares one cache across
    sessions (the warm-relaunch win). One function for every caller:
    SessionHooks (all single-host drivers + multi-host rank 0) and the
    multi-host prologue for ranks > 0, which never construct hooks.
    ``.get`` keeps configs saved before the knob existed loadable."""
    cache_dir = session_cfg.get("compile_cache_dir", None)
    if not cache_dir:
        return None
    if not os.path.isabs(cache_dir):
        cache_dir = os.path.join(session_cfg.folder, cache_dir)
    from surreal_tpu.utils.compat import enable_compile_cache

    return cache_dir if enable_compile_cache(cache_dir) else None


class SessionHooks:
    """One per training run. Driver contract:

        hooks = SessionHooks(config, learner)
        try:
            state, it, steps = hooks.restore(state)    # once, before the loop
            hooks.begin_run(it, steps)
            while ...:
                ...train...
                m, stop = hooks.end_iteration(
                    it, steps, state, key, metrics, on_metrics
                )
                if stop: break
            hooks.final_checkpoint(it, steps, state)
        finally:
            hooks.close()

    ``end_iteration`` owns the metrics cadence: it syncs device scalars to
    host floats only when ``metrics.every_n_iters`` fires (keeping the hot
    loop async), fires eval/checkpoint/profiler on their own cadences, and
    forwards fired metrics to the caller's ``on_metrics``.
    """

    def __init__(self, config, learner, name: str = "train"):
        self.config = config
        cfg = config.session_config
        os.makedirs(cfg.folder, exist_ok=True)
        self.log = get_logger(name, cfg.folder)
        self.writer = make_metrics_writer(cfg, name=name)
        # telemetry spine: span tracing + JSONL event log under
        # <folder>/telemetry/ (session/telemetry.py). Drivers record their
        # phase spans through hooks.tracer so Trainer / OffPolicyTrainer /
        # SEEDTrainer / the multi-host drivers cannot drift; hooks itself
        # spans its own side-bands (metrics-sync, publish, eval,
        # checkpoint) below. `.get` keeps configs saved before the knob
        # existed loadable.
        tel = cfg.get("telemetry", None)
        # causal tracing + lineage knobs (ISSUE 14): telemetry.trace.*
        # sets the exemplar head-sampling rate (1-in-N per stream; 0
        # disables span emission) and how many recent exemplars ride a
        # flight-recorder dump; telemetry.lineage toggles the
        # per-transition provenance stamps (on by default — the exact
        # staleness distribution depends on them)
        trace_cfg = tel.get("trace", None) if tel is not None else None
        self.trace_sample_n = int(
            trace_cfg.get("sample_n", 64) if trace_cfg is not None else 64
        )
        trace_keep = int(
            trace_cfg.get("keep", 8) if trace_cfg is not None else 8
        )
        self.lineage_enabled = bool(
            tel.get("lineage", True) if tel is not None else True
        )
        self.tracer = Tracer(
            cfg.folder,
            enabled=bool(tel.enabled) if tel is not None else True,
            name=name,
            # size-based JSONL rotation (ISSUE 13 satellite): a week-long
            # run must not grow events.jsonl without bound
            max_log_mb=tel.get("max_log_mb", None) if tel is not None else None,
            trace_sample_n=self.trace_sample_n,
            trace_keep=trace_keep,
        )
        # cross-process trace correlation: the run-scoped trace id every
        # telemetry event carries; spawned env workers / the inference
        # server / param clients inherit it (session/telemetry.py)
        self.trace_id = self.tracer.trace_id
        # cost/MFU accounting (session/costs.py): drivers register their
        # jitted hot programs via record_program_costs; the perf/* gauges
        # ride the metrics cadence in end_iteration below. The learner's
        # precision policy stamps every program_cost record so artifacts
        # carry per-policy rows (ops/precision.py).
        self.costs = CostAccountant(
            cfg, on_event=self.tracer.event, log=self.log,
            policy=getattr(learner, "policy", None),
        )
        # persistent XLA compile cache: enabled before the driver's first
        # jitted call compiles (drivers construct hooks inside run(), and
        # tracing/compilation is lazy until the first dispatch)
        self.compile_cache_dir = maybe_enable_compile_cache(cfg)
        if self.compile_cache_dir is not None:
            self.log.info(
                "persistent compile cache at %s", self.compile_cache_dir
            )
        self.ckpt: CheckpointManager | None = make_checkpoint_manager(
            cfg, on_event=self.tracer.event
        )
        # precision: the learner's resolved policy (ops/precision.py) —
        # recorded into checkpoint run metadata (restore fails loudly on
        # a policy mismatch), emitted as a 'precision' telemetry event in
        # begin_run, and rendered by `surreal_tpu diag`'s Performance
        # section
        pol = getattr(learner, "policy", None)
        self.precision = pol
        self._precision_meta = pol.meta() if pol is not None else None
        self._ckpt_every = PeriodicTracker(max(1, cfg.checkpoint.every_n_iters))
        # robustness layer (ISSUE 5): the preemption sentinel latches
        # SIGTERM/SIGINT and end_iteration turns it into a stop at the
        # next boundary — the driver's normal final checkpoint then IS the
        # emergency checkpoint, at most one iteration behind the signal.
        # The recovery manager is the divergence-guard policy on PR 1's
        # in-graph health/* signals (launch/recovery.py). `.get` keeps
        # configs saved before the knobs existed loadable.
        rec = cfg.get("recovery", None)
        self.interrupt = InterruptSentinel(
            enabled=bool(rec.get("interrupt", True)) if rec is not None else True
        )
        self.recovery = RecoveryManager(config, self.ckpt, self.tracer, self.log)
        # live ops plane (ISSUE 13): the run-scoped cross-tier aggregator.
        # Wire tiers (gateway, fleet replicas, experience shards) push
        # into ``ops.address`` — process tiers inherit it through spawn
        # kwargs like the trace id; learner-thread tiers land through
        # push_local below. ``snapshot()`` rides the metrics cadence.
        self.ops = OpsAggregator(
            cfg.folder, trace_id=self.trace_id,
            cfg=cfg.get("ops", None), slo_cfg=cfg.get("slo", None),
            on_event=self.tracer.event,
        )
        # the last-K causal exemplar span trees ride every flightrec
        # dump (ISSUE 14): a post-mortem sees individual request paths
        # from the minutes before the incident, not just gauges
        self.ops.flightrec.exemplar_source = self.tracer.recent_exemplar_spans
        self._interrupt_logged = False
        # optional step-aligned auxiliary state (the off-policy trainer
        # sets this to snapshot its replay buffer when
        # checkpoint.include_replay is on); zero-arg callable -> pytree
        self.extra_state_fn = None

        self.evaluator = None
        ev = cfg.eval
        if ev.every_n_iters and ev.every_n_iters > 0 and ev.episodes > 0:
            from surreal_tpu.launch.evaluator import Evaluator

            self.evaluator = Evaluator(config.env_config, ev, learner)
            self._eval_every = PeriodicTracker(ev.every_n_iters)
        if self.ckpt is not None:
            self.ckpt.best_key = (
                "eval/return" if self.evaluator else "episode/return"
            )

        # live parameter publishing (reference §3.4: the learner published
        # every publish_interval; external actors/evals attach to the run).
        # Multi-host drivers construct hooks on rank 0 only, so publishing
        # is single-controller for free.
        self._publisher = None
        self._param_server = None
        self._fanout = None
        pub = cfg.get("publish", None)
        if pub is not None and pub.enabled:
            from surreal_tpu.agents import make_agent
            from surreal_tpu.distributed.param_service import (
                ParameterPublisher,
                ParameterServer,
            )

            self._pub_agent = make_agent(learner)
            self._publisher = ParameterPublisher()
            # on_event: fetch requests carry a client span id; the server
            # mirrors each serve into the telemetry spine so diag's
            # cross-process timeline covers the param-service hop too
            self._param_server = ParameterServer(
                self._publisher.address, bind=pub.bind,
                on_event=self.tracer.event,
            )
            # parameter fanout (ISSUE 10, distributed/param_fanout.py):
            # versioned weight FRAMES over pub/sub — one encode + N
            # subscribes instead of N full-pytree fetch pickles, with
            # delta/bf16 wire arms. The publisher/server pair above STAYS
            # as the fallback/late-joiner fetch path. `.get` keeps old
            # configs loadable.
            fan = pub.get("fanout", None)
            if fan is not None and fan.get("enabled", False):
                from surreal_tpu.distributed.param_fanout import ParameterFanout

                self._fanout = ParameterFanout(
                    wire=str(fan.get("wire", "f32")),
                    delta=bool(fan.get("delta", True)),
                    ack_ttl_s=float(fan.get("ack_ttl_s", 60.0)),
                )
            self._pub_every = PeriodicTracker(max(1, pub.every_n_iters))
            # discovery file: how `surreal_tpu actor` / `eval --follow`
            # find a live session without the operator copying ports
            # around. Written atomically (tmp + rename): pollers race this
            # write, and a half-written json would crash them mid-read.
            import json

            self._discovery_path = os.path.join(cfg.folder, "param_server.json")
            tmp_path = self._discovery_path + ".tmp"
            discovery = {
                "addresses": self._param_server.addresses,
                "publisher": self._publisher.address,
            }
            if self._fanout is not None:
                discovery["fanout"] = self._fanout.address
                discovery["fanout_ack"] = self._fanout.ack_address
            with open(tmp_path, "w") as f:
                json.dump(discovery, f)
            os.replace(tmp_path, self._discovery_path)
            self.log.info(
                "parameter server live at %s (publish every %d iters)",
                self._param_server.addresses, self._pub_every.period,
            )

        # on-demand profiling (session/profile.py): legacy profiler knob,
        # trigger-file captures, and the slow-iteration auto-trigger all
        # live behind one boundary tick
        self.profile = ProfileManager(cfg, cfg.folder, self.tracer, self.log)
        # watchdog & incident engine (ISSUE 15): detector sweeps over each
        # merged ops snapshot, firings correlated into root-caused
        # incident records under telemetry/incidents/ (`surreal_tpu why`).
        # Both are pure host arithmetic at the metrics cadence.
        wd_cfg = cfg.get("watchdog", None)
        self.watchdog = None
        self.incidents = None
        if wd_cfg is None or wd_cfg.get("enabled", True):
            import jax

            from surreal_tpu.session.incidents import IncidentEngine
            from surreal_tpu.session.watchdog import Watchdog

            base_dir = (
                wd_cfg.get("baseline_dir", None) if wd_cfg is not None else None
            )
            self.watchdog = Watchdog(
                cfg=wd_cfg,
                baseline_rows=Watchdog.load_baseline(base_dir)
                if base_dir
                else None,
                platform=jax.default_backend(),
                geometry=f"{jax.device_count()}x{type(jax.devices()[0]).__name__}",
            )
            self.incidents = IncidentEngine(
                folder=cfg.folder,
                cfg=wd_cfg,
                on_event=self.tracer.event,
                profile=self.profile,
                flightrec=self.ops.flightrec,
                exemplar_source=self.tracer.recent_exemplar_spans,
                trace_id=self.trace_id,
            )
        # closed-loop remediation (ISSUE 16): the incident stream's top
        # cause mapped to ONE bounded, journaled, counter-detected action
        # per sweep. Rides the incident engine (no incidents, nothing to
        # remediate); actuators are bound later by the driver
        # (bind_remediation_actuators) once the fleet/gateway exist.
        self.remediate = None
        rem_cfg = cfg.get("remediate", None)
        if self.incidents is not None and (
            rem_cfg is None or rem_cfg.get("enabled", True)
        ):
            from surreal_tpu.session.remediate import RemediationEngine

            self.remediate = RemediationEngine(
                folder=cfg.folder,
                cfg=rem_cfg,
                incidents=self.incidents,
                on_event=self.tracer.event,
                trace_id=self.trace_id,
            )
        self._last_eval: dict[str, float] = {}
        self._last_train: dict[str, float] = {}
        self._metrics_every = PeriodicTracker(max(1, cfg.metrics.every_n_iters))
        self._t0 = None
        self._steps0 = 0

    @property
    def fanout(self):
        """The live :class:`ParameterFanout` (None unless
        ``publish.fanout.enabled``) — the gateway's publisher-side
        pinned-version holds need it."""
        return self._fanout

    @property
    def last_metrics(self) -> dict[str, float]:
        """Latest synced train metrics merged with latest eval metrics."""
        return {**self._last_train, **self._last_eval}

    def bind_remediation_actuators(self, **surfaces) -> None:
        """Hand the remediation engine its actuator surfaces (fleet,
        admission, restart map, learner downshift/restore) once the
        driver has built them — no-op when remediation is off. See
        :meth:`RemediationEngine.bind_actuators`."""
        if self.remediate is not None:
            self.remediate.bind_actuators(**surfaces)

    def data_plane_event(self, **info) -> None:
        """Record the SEED data plane's negotiated shape (transport mix,
        pipeline occupancy, wire bytes/step) as one log line + one
        telemetry ``data_plane`` event — `surreal_tpu diag` surfaces the
        last one, so a session folder answers "did shm actually engage?"
        without grepping metrics rows."""
        self.log.info(
            "data plane: %s",
            " ".join(f"{k}={v}" for k, v in sorted(info.items())),
        )
        self.tracer.event("data_plane", **info)

    def serving_event(self, **info) -> None:
        """Record the serving tier's per-replica snapshot (replica
        liveness/budgets/serve latency, scale decisions) as one telemetry
        ``serving_tier`` event per metrics row — ``surreal_tpu diag``'s
        "Serving tier" section renders the last one."""
        self.tracer.event("serving_tier", **info)
        # the merged fleet view is a learner-thread tier: no wire hop.
        # (per-replica liveness rides each replica's OWN wire row.)
        self.ops.push_local("fleet", body=info)

    def gateway_event(self, **info) -> None:
        """Record the session gateway's tenant-facing snapshot (sessions,
        admission counters, cache hit-rate, pinned versions) as one
        telemetry ``gateway`` event per metrics row — ``surreal_tpu
        diag``'s "Gateway" section renders the last one."""
        self.tracer.event("gateway", **info)

    def experience_event(self, **info) -> None:
        """Record the experience plane's settled shape (shard transports,
        per-shard fill/ingest, wire bytes/step, sample-wait) as one
        telemetry ``experience_plane`` event per metrics row —
        ``surreal_tpu diag``'s "Experience plane" section renders the
        last one plus the per-hop sender->shard->learner percentiles."""
        self.tracer.event("experience_plane", **info)
        self.ops.push_local("experience", body=info)

    def learner_group_event(self, **info) -> None:
        """Journal one learner-group membership transition (join/leave/
        member_failed/respawn/handoff with the shard assignment) as a
        telemetry ``learner_group`` event — the elastic-membership audit
        trail the chaos tests and post-mortems read."""
        self.log.info(
            "learner group: %s",
            " ".join(f"{k}={v}" for k, v in sorted(info.items())),
        )
        self.tracer.event("learner_group", **info)

    def record_program_costs(
        self, name: str, jitted, *args,
        phase: str | None = None, calls_per_phase: int = 1, **kwargs,
    ) -> None:
        """Register one jitted hot program with the cost accountant
        (idempotent per name — host-loop drivers call it after their
        first learn, when a representative batch exists). ``phase`` names
        the tracer phase whose window times this program; programs with
        no dedicated phase (the SEED act closure) pass None and are
        recorded for diag without contributing to the live gauges.
        Host-side work only (lower + HLO cost pass): safe before the
        first dispatch and on donated-arg programs."""
        self.costs.record_program(
            name, jitted, *args,
            phase=phase, calls_per_phase=calls_per_phase, **kwargs,
        )

    def tune_event(self, **info) -> None:
        """Record the autotuner's build-time decision (mode, cache
        hit/miss, chosen config — and candidate timings when the search
        ran) as one log line + one telemetry ``tune`` event, surfaced by
        ``surreal_tpu diag`` so a session folder answers "which program
        geometry actually trained?" without grepping configs."""
        self.log.info(
            "autotune: %s",
            " ".join(f"{k}={v}" for k, v in sorted(info.items())),
        )
        self.tracer.event("tune", **info)

    def final_metrics(self, env_steps: int, extras=None) -> None:
        """Refresh the trailing metrics snapshot at run end. Drivers whose
        loop can consume env-step budget WITHOUT a metrics-cadence fire
        (the SEED drop path discards stale chunks but counts their steps)
        call this so ``last_metrics``/the writer reflect where the run
        actually ended, not the last learn."""
        m = dict(self._last_train)
        m.update({k: float(v) for k, v in (extras or {}).items()})
        m["time/env_steps"] = env_steps
        m["time/env_steps_per_s"] = (env_steps - self._steps0) / max(
            time.time() - (self._t0 or time.time()), 1e-9
        )
        self._last_train = m
        self.writer.write(env_steps, m)
        self.tracer.log_metrics(env_steps, m)

    # -- restore -------------------------------------------------------------
    def restore(self, init_state):
        """-> (state, start_iteration, start_env_steps).

        Own-folder auto-resume takes precedence over ``restore_from``: a
        warm-started job that crashes and relaunches with the same config
        must continue its OWN curve, not re-warm-start from the foreign
        folder; restore_from only seeds the very first run."""
        cfg = self.config.session_config.checkpoint
        if cfg.auto_resume and self.ckpt is not None:
            # precision guard FIRST: a policy mismatch must surface as
            # the named error, not as orbax's structure traceback from
            # the restore walk below (session/checkpoint.py). Inside the
            # auto_resume branch deliberately: a launch that will never
            # restore (auto_resume=False, fresh training into the same
            # folder) must not be blocked by the old run's policy —
            # begin_run then overwrites the sidecar with the new one.
            self.ckpt.check_precision(self._precision_meta)
            # newest FINITE checkpoint, not merely the newest readable one:
            # in warn mode (multi-host) a poisoned run-end save can exist,
            # and resuming into it would re-trip forever — the walk skips
            # damaged AND nonfinite steps (launch/recovery.py), emitting
            # recovery telemetry for each skip
            restored = self.recovery.restore_newest_finite(init_state)
            if restored is not None:
                state, meta, _step = restored
                self.log.info(
                    "auto-resumed at iteration %d (%d env steps)",
                    meta["iteration"], meta["env_steps"],
                )
                self._reseed_cadences(int(meta["iteration"]))
                return state, int(meta["iteration"]), int(meta["env_steps"])
        if cfg.restore_from:
            mgr = CheckpointManager(cfg.restore_from, on_event=self.tracer.event)
            # same precision guard for foreign warm-starts
            mgr.check_precision(self._precision_meta)
            restored = mgr.restore(init_state)
            mgr.close()
            if restored is None:
                raise FileNotFoundError(
                    f"checkpoint.restore_from={cfg.restore_from!r} has no checkpoint"
                )
            state, meta = restored
            self.log.info(
                "restored from %s at iteration %d (%d env steps)",
                cfg.restore_from, meta["iteration"], meta["env_steps"],
            )
            # warm-start from foreign folder: keep its counters so schedules
            # (lr anneal, beta anneal) continue rather than restart
            self._reseed_cadences(int(meta["iteration"]))
            return state, int(meta["iteration"]), int(meta["env_steps"])
        return init_state, 0, 0

    def _reseed_cadences(self, iteration: int) -> None:
        self._ckpt_every = PeriodicTracker(
            self._ckpt_every.period, init_count=iteration
        )
        if self.evaluator is not None:
            self._eval_every = PeriodicTracker(
                self._eval_every.period, init_count=iteration
            )
        if self._publisher is not None:
            self._pub_every = PeriodicTracker(
                self._pub_every.period, init_count=iteration
            )

    # -- per-iteration -------------------------------------------------------
    def begin_run(self, iteration: int, env_steps: int) -> None:
        """Start the wall-clock + cadence counters from the (possibly
        resumed) position."""
        self._metrics_every = PeriodicTracker(
            self._metrics_every.period, init_count=iteration
        )
        self._t0 = time.time()
        self._steps0 = env_steps
        if self._precision_meta is not None:
            # the active precision policy: one telemetry event per run
            # (diag renders it in Performance) + the checkpoint sidecar
            # restore validates against (written here, BEFORE the first
            # save, so even a run killed mid-first-interval leaves the
            # guard in place)
            self.tracer.event("precision", **self.precision.telemetry())
            self.log.info(
                "precision policy: %s",
                " ".join(
                    f"{k}={v}"
                    for k, v in sorted(self.precision.telemetry().items())
                ),
            )
            if self.ckpt is not None:
                self.ckpt.save_run_metadata(self._precision_meta)

    def end_iteration(
        self,
        iteration: int,
        env_steps: int,
        state,
        key: jax.Array,
        metrics=None,
        on_metrics=None,
    ):
        """Per-iteration side-bands, shared verbatim by every driver.

        ``metrics`` is the iteration's metric scalars — a dict of device
        scalars, or a zero-arg callable returning one (to defer assembling
        host-side extras) — synced to host floats only when the metrics
        cadence fires. ``state`` may likewise be a zero-arg callable
        resolved only when a state-consuming hook (eval, checkpoint)
        actually fires — multi-host drivers pass a lambda that pulls the
        replicated global state to host-local numpy, a transfer too costly
        to do every iteration. Returns (synced_metrics_or_None, stop)
        where stop echoes a truthy ``on_metrics(iteration, m)``.
        """
        state_box = [state]

        def resolve_state():
            if callable(state_box[0]):
                state_box[0] = state_box[0]()
            return state_box[0]

        m = None
        trip_reason = None
        if self._metrics_every.track_increment():
            # the ONE device->host sync of the cadence window: float() on
            # the device scalars blocks until the dispatched iterations
            # land, so this span is the fenced wall-time of the window tail
            with self.tracer.span("metrics-sync"):
                raw = metrics() if callable(metrics) else (metrics or {})
                m = {k: float(v) for k, v in raw.items()}
            m["time/env_steps"] = env_steps
            m["time/env_steps_per_s"] = (env_steps - self._steps0) / max(
                time.time() - (self._t0 or time.time()), 1e-9
            )
            self._last_train = m
            self._emit_cache_event()
            # divergence guard: the health/* scalars just synced are the
            # detection signal (launch/recovery.py); in rollback mode a
            # trip sets recovery.pending, which the DRIVER resolves via
            # rollback()
            trip_reason = self.recovery.check(m, iteration, env_steps)
            if trip_reason is not None:
                # incident: freeze the minutes BEFORE the trip (the
                # flight recorder's ring) next to the trip itself
                self.ops.record_recovery({
                    "reason": str(trip_reason),
                    "iteration": int(iteration), "env_steps": int(env_steps),
                })
                self.ops.dump("recovery")
                if self.incidents is not None:
                    self.incidents.record_recovery({
                        "reason": str(trip_reason),
                        "iteration": int(iteration),
                        "env_steps": int(env_steps),
                    })
        # skip the state-consuming side-bands while the guard is tripped in
        # BOTH rollback and warn modes (warn is the multi-host setting — a
        # poisoned save would make auto_resume restore the poison).
        # last_window_tripped PERSISTS between cadence windows, so publish/
        # eval/checkpoint cadences firing on off-metrics iterations are
        # covered too; it clears on the next healthy window or rollback.
        tripped = (
            trip_reason is not None
            or self.recovery.pending is not None
            or self.recovery.last_window_tripped is not None
        )
        if (
            self._publisher is not None
            and self._pub_every.track_increment()
            and not tripped  # never publish poisoned params to live actors
        ):
            with self.tracer.span("param-publish", emit=True):
                view = self._pub_agent.acting_view(resolve_state())
                version = self._publisher.publish(view)
                if self._fanout is not None:
                    # broadcast the same view as a versioned frame
                    # (full/delta/bf16 per the fanout knobs); the
                    # publisher/server blob above stays the fetch
                    # fallback for late joiners
                    self._fanout.publish(view)
            # ops plane: the fanout tier's row — its published version vs
            # the fleet replicas' held versions is the staleness derivation
            self.ops.push_local(
                "param_fanout",
                gauges={
                    "version": float(version),
                    **(
                        self._fanout.gauges()
                        if self._fanout is not None else {}
                    ),
                },
            )
            if m is not None:
                m["publish/version"] = float(version)
                if self._fanout is not None:
                    m.update(self._fanout.gauges())
                self._last_train = m
        evaled: dict[str, float] = {}
        if (
            self.evaluator is not None
            and self._eval_every.track_increment()
            and not tripped  # a poisoned state's eval is wasted episodes
        ):
            with self.tracer.span("eval", emit=True):
                evaled = self.evaluator.evaluate(resolve_state(), key)
            self._last_eval = evaled
        if m is not None:
            # mirror the window's span accumulators as time/* scalars —
            # AFTER the publish/eval blocks so this window's side-band
            # spans land in this row, not the next (checkpoint fires after
            # the write by design and stays in the next window)
            m.update(self.tracer.flush_phases(env_steps))
            # perf/mfu + perf/membw_util over the same window: pure host
            # float arithmetic from the flushed phase times and the
            # startup-recorded program costs — zero device->host syncs
            # beyond the metrics already synced above (transfer-guard
            # tested in tests/test_telemetry.py)
            m.update(self.costs.gauges(self.tracer.last_window))
            # ops plane: the learner's own row, then the merged run
            # snapshot — pure host float/dict work on rows the tiers
            # already pushed, zero device->host syncs beyond the metrics
            # synced above (the same transfer-guard covers it)
            self.ops.push_local(
                "learner",
                gauges={
                    k: v for k, v in m.items()
                    if isinstance(v, (int, float))
                },
            )
            snap = self.ops.snapshot(int(iteration), int(env_steps))
            m.update(self.ops.gauges())
            # watchdog sweep over the snapshot just merged + incident
            # lifecycle — both pure host arithmetic on the snapshot dict
            # (no device state in reach), so the same transfer-guard test
            # covers them
            if self.watchdog is not None and snap is not None:
                firings = self.watchdog.evaluate(snap)
                self.incidents.observe(firings, snap)
                m.update(self.watchdog.gauges())
                m.update(self.incidents.gauges())
                # remediation decision sweep: the incident just observed
                # -> at most one bounded action + verification ticks for
                # the actions already in flight. Same pure-host-dict
                # discipline, same transfer-guard.
                if self.remediate is not None:
                    self.remediate.step(firings, snap)
                    m.update(self.remediate.gauges())
            self._last_train = m
        if m or evaled:
            self.writer.write(env_steps, {**(m or {}), **evaled})
            self.tracer.log_metrics(env_steps, {**(m or {}), **evaled})
        if self.ckpt is not None and self._ckpt_every.track_increment():
            if tripped:
                # a tripped window's state must never become "last good" —
                # the rollback about to happen would restore the poison
                self.log.warning(
                    "skipping checkpoint at iteration %d: divergence guard "
                    "tripped this window", iteration,
                )
            else:
                with self.tracer.span("checkpoint", emit=True):
                    self.ckpt.save(
                        iteration,
                        resolve_state(),
                        env_steps=env_steps,
                        metrics=self.last_metrics,
                    )
                    if self.extra_state_fn is not None:
                        self.ckpt.save_extra(iteration, self.extra_state_fn())
        self.profile.tick(iteration)
        # chaos-harness visibility: mirror any faults fired since the last
        # boundary into the telemetry spine (empty list in normal runs) —
        # and into the flight recorder, whose dump freezes the snapshots
        # leading up to the incident
        fired = faults.drain_fired()
        for ev in fired:
            self.tracer.event("fault", **ev)
            self.ops.record_fault(ev)
            if self.incidents is not None:
                self.incidents.record_fault(ev)
        if fired:
            self.ops.dump("fault")
        stop = m is not None and on_metrics is not None and bool(
            on_metrics(iteration, m)
        )
        if self.interrupt.fired:
            # preemption-safe shutdown: stop at THIS boundary; the driver's
            # final_checkpoint is the emergency save (no handler ever
            # touches orbax — session/interrupt.py)
            if not self._interrupt_logged:
                self._interrupt_logged = True
                self.log.warning(
                    "interrupt (signal %s) latched: stopping after iteration "
                    "%d, emergency checkpoint follows",
                    self.interrupt.signum, iteration,
                )
                self.tracer.event(
                    "recovery", kind="interrupt",
                    signum=self.interrupt.signum,
                    iteration=int(iteration), env_steps=int(env_steps),
                )
            stop = True
        return m, stop

    @property
    def interrupted(self) -> bool:
        """True once the preemption sentinel latched a signal — loops with
        iteration paths that bypass ``end_iteration`` (the SEED stale-drop
        path) poll this so an interrupt cannot get stuck behind a streak."""
        return self.interrupt.fired

    def final_checkpoint(self, iteration: int, env_steps: int, state) -> None:
        """Always leave a resumable checkpoint at run end — including the
        interrupt path, where this IS the emergency checkpoint. ``state``
        may be a zero-arg callable (see ``end_iteration``). Skipped when
        the divergence guard is pending OR the last synced window tripped
        (the warn-mode spelling, where pending is never set — multi-host):
        persisting poison would make the relaunch resume into the same
        NaNs the guard just caught."""
        if self.recovery.pending is not None or self.recovery.last_window_tripped:
            self.log.warning(
                "skipping final checkpoint: divergence guard %s "
                "(relaunch will resume from the last finite checkpoint)",
                "pending" if self.recovery.pending else "tripped on the "
                "last synced window",
            )
            return
        if self.ckpt is not None and self.ckpt.latest_step() != iteration:
            self.ckpt.save(
                iteration,
                state() if callable(state) else state,
                env_steps=env_steps,
                metrics={**self._last_train, **self._last_eval},
            )
            if self.extra_state_fn is not None:
                self.ckpt.save_extra(iteration, self.extra_state_fn())

    def _emit_cache_event(self) -> None:
        """Mirror the compile-cache hit/miss counters into the telemetry
        log (one 'compile_cache' event per metrics cadence + one at close;
        `surreal_tpu diag` reports the last one). Host-side ints only —
        no device sync rides on this."""
        if self.compile_cache_dir is None:
            return
        from surreal_tpu.utils.compat import compile_cache_counts

        self.tracer.event(
            "compile_cache", dir=self.compile_cache_dir,
            **compile_cache_counts(),
        )

    def close(self) -> None:
        self.interrupt.close()  # restore the process's previous handlers
        for ev in faults.drain_fired():  # tail faults since the last boundary
            self.tracer.event("fault", **ev)
            self.ops.record_fault(ev)
            if self.incidents is not None:
                self.incidents.record_fault(ev)
        # flush still-verifying actions (a run ending mid-verification is
        # itself evidence), then a still-open incident (closed_t stays
        # None — the record shows the run ended mid-incident), before the
        # planes they read from come down
        if self.remediate is not None:
            self.remediate.close()
        if self.incidents is not None:
            self.incidents.close()
        # stop the ops receiver BEFORE the tiers that push into it come
        # down (a pushed row into a closed PULL is just dropped, but the
        # join here keeps thread teardown deterministic)
        self.ops.close()
        self.profile.close()  # stop + record a capture cut short by exit
        if self._param_server is not None:
            self._param_server.close()
            self._param_server = None
            # a dead session must not advertise its ports: a relaunched
            # actor would otherwise latch onto the stale address and spend
            # its whole wait budget timing out against it
            try:
                os.unlink(self._discovery_path)
            except OSError:
                pass
        if self._fanout is not None:
            self._fanout.close()
            self._fanout = None
        if self._publisher is not None:
            self._publisher.close()
            self._publisher = None
        if self.evaluator is not None:
            self.evaluator.close()
        if self.ckpt is not None:
            self.ckpt.close()
        self.writer.close()
        self._emit_cache_event()  # final counts for runs shorter than a cadence
        self.tracer.close()
        # detach + close this session's file log handler: without this the
        # fd into <folder>/logs/ outlives the session for the rest of the
        # process (get_logger only retargets when a DIFFERENT folder
        # arrives) — the chaos residue oracle counts that as a leak
        for h in list(self.log.handlers):
            if str(getattr(h, "_surreal_id", "")).startswith("file:"):
                self.log.removeHandler(h)
                h.close()


HOST_METRICS_WINDOW = 20  # rolling episode-return window; host loops size
                          # their deque(maxlen=...) with this


def host_metrics(metrics, recent_returns, window: int = HOST_METRICS_WINDOW):
    """Deferred host-metrics assembly for host-env loops: the learner's
    metric scalars plus a rolling-mean ``episode/return`` from the env
    wrappers' completed-episode stats. Returns a zero-arg callable for
    ``SessionHooks.end_iteration`` (synced only when the cadence fires)."""
    import numpy as np

    def build():
        m = dict(metrics)
        if recent_returns:
            # list(...) first: callers pass a deque(maxlen=window), which
            # doesn't support slice indexing
            m["episode/return"] = float(np.mean(list(recent_returns)[-window:]))
        return m

    return build


def training_env_config(env_config) -> Config:
    """The training env never records video — that is eval's job (the
    reference wired VideoWrapper only into ``run_eval``, SURVEY.md §3.5)."""
    return Config(video=Config(enabled=False)).extend(env_config)
