"""Off-policy training driver (DDPG-family): collect -> replay -> K SGD
updates, the reference's actor/replay/learner triangle (SURVEY.md §3.2-3.4)
as one program.

Device mode fuses the whole iteration — H env steps (with Gaussian or
carried-OU exploration noise), n-step folding, replay insert, and
``updates_per_iter`` sample+learn steps (plus prioritized-priority refresh)
— into ONE jitted function: the off-policy analogue of Trainer's fused
on-policy iteration. Replay warmup is a ``lax.cond`` (skip updates until
``start_sample_size``), so the compiled program is identical across the
warmup boundary. The fused program donates its loop-carried pytrees
(state, replay shards, env carry) so XLA updates their HBM in place.

Host mode double-buffers: the exploration rollout + its host->device
staging run on a prefetch thread while the device drains the SGD updates
(see ``_run_host``).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from surreal_tpu.engine import (
    EngineConfig,
    LoopEngine,
    LoopState,
    Outcome,
    StageSpec,
    overlap_collect,
    sideband_stages,
)
from surreal_tpu.envs import is_jax_env, make_env
from surreal_tpu.envs.jax.base import batch_step
from surreal_tpu.launch.hooks import SessionHooks, host_metrics, training_env_config
from surreal_tpu.launch.rollout import successor_and_termination
from surreal_tpu.learners import build_learner
from surreal_tpu.learners.aggregator import nstep_transitions
from surreal_tpu.learners.ddpg import ou_noise_step
from surreal_tpu.replay import build_replay
from surreal_tpu.session.config import Config
from surreal_tpu.utils import faults


class OffPolicyCarry(NamedTuple):
    env_state: Any
    obs: jax.Array
    noise: jax.Array      # [B, act_dim] OU state (zeros when gaussian)
    ep_return: jax.Array  # [B]
    ep_length: jax.Array  # [B]
    tail: Any             # last n_step-1 steps of the previous chunk (None if n=1)


TRANS_KEYS = ("obs", "next_obs", "action", "reward", "done", "terminated")


def scrub_fake_prefix_windows(trans, n: int, B: int):
    """Overwrite the n-1 fictitious leading windows of the run's FIRST
    folded chunk with its first real window.

    ``nstep_transitions`` flattens [S, B] windows row-major, so window s of
    env b is flat row ``s*B+b``: the fabricated rows (windows starting in
    the all-zero tail that seeds the cross-chunk carry) occupy
    ``[0, (n-1)*B)`` and the first real window block is ``[(n-1)*B, n*B)``.
    Tiling that block over the fake rows keeps per-env alignment and static
    shapes under jit; duplicating B real transitions n-1 times, once per
    run, is harmless — replay never holds made-up transitions.
    """
    nb = (n - 1) * B
    return jax.tree.map(
        lambda x: x.at[:nb].set(
            jnp.tile(x[nb : nb + B], (n - 1, *([1] * (x.ndim - 1))))
        ),
        trans,
    )


class OffPolicyTrainer:
    def __init__(self, config):
        self.config = config
        self.env = make_env(training_env_config(config.env_config))
        self.learner = build_learner(config.learner_config, self.env.specs)
        # program autotuner: same build-time cache consult as Trainer's
        # (launch/trainer.py) — applied knobs rewrite the learner overrides
        from surreal_tpu.tune import resolve_autotune

        self.tune_decision = resolve_autotune(config, self.learner.config)
        if self.tune_decision.applied:
            self.learner = build_learner(config.learner_config, self.env.specs)
        algo = self.learner.config.algo
        self.algo = algo
        # precision: the learner's resolved policy governs replay staging
        # (storage example dtype below) — one knob for models, learners,
        # AND replay dtypes (ops/precision.py). replay_gather routes the
        # ring gather/scatter through the pallas row-DMA kernels (a
        # searched dimension); injected into the replay build config so
        # the replay layer stays algo-agnostic.
        self._replay_build_cfg = Config(
            gather_impl=algo.get("replay_gather", "xla")
        ).extend(self.learner.config.replay)
        # searched scan unrolls (tune/space.py); `.get` keeps configs saved
        # before the knobs existed loadable
        self._rollout_unroll = int(algo.get("rollout_unroll", 1))
        self._update_unroll = max(
            1, min(int(algo.get("update_unroll", 1)),
                   int(algo.get("updates_per_iter", 1))),
        )
        self.horizon = algo.horizon
        self.num_envs = config.env_config.num_envs
        self.device_mode = is_jax_env(self.env)
        self.seed = config.session_config.seed
        # remote experience plane (surreal_tpu/experience/): replay lives
        # in shard-server processes fed by an ExperienceSender and drained
        # by a prefetched ShardedSampler — `replay.kind='remote'` with
        # `replay.remote_kind` selecting the shard discipline. Host path
        # only: the device path's replay IS device memory (replay/sharded
        # dp shards); a host-memory shard tier behind a fused device loop
        # would reintroduce the per-iteration host sync the fusion removed.
        replay_kind = self.learner.config.replay.kind
        self.remote = replay_kind == "remote"
        if self.remote and self.device_mode:
            raise ValueError(
                "replay.kind='remote' (the sharded experience plane) runs "
                "the host off-policy path; device (jax:*) envs keep "
                "in-process device-resident replay — use a host env, or "
                "replay.kind='uniform'|'prioritized'"
            )
        self.prioritized = replay_kind == "prioritized" or (
            self.remote
            and self.learner.config.replay.get("remote_kind", "uniform")
            == "prioritized"
        )
        self.mesh = None
        if self.device_mode:
            from surreal_tpu.parallel.mesh import make_mesh

            self.mesh = make_mesh(config.session_config.topology)
            if self.mesh.size > 1:
                # dp over the mesh: per-device replay shards (the
                # reference's ShardedReplay role, replay/sharded.py) +
                # gradient pmean inside learner.learn
                from surreal_tpu.parallel.dp import dp_offpolicy_iter
                from surreal_tpu.parallel.mesh import check_dp_divisible
                from surreal_tpu.replay.sharded import scale_replay_config

                dp = self.mesh.shape["dp"]
                check_dp_divisible(self.num_envs, dp)
                self.replay = build_replay(
                    scale_replay_config(self._replay_build_cfg, dp)
                )
                self._train_iter = dp_offpolicy_iter(
                    self._device_train_iter, self.mesh
                )
            else:
                self.replay = build_replay(self._replay_build_cfg)
                # donate the loop-carried state / replay shards / env
                # carry: XLA reuses their HBM (the replay storage is the
                # program's largest allocation) instead of holding two
                # copies live across the fused iteration; run() never
                # reads a pre-iteration reference again
                self._train_iter = jax.jit(
                    self._device_train_iter, donate_argnums=(0, 1, 2)
                )
        else:
            # remote plane: no in-process replay object — the buffer lives
            # in the shard servers (built inside _run_host_remote, where
            # the session's trace id exists)
            self.replay = (
                None if self.remote else build_replay(self._replay_build_cfg)
            )
            # acting reuses the same state every env step: never donate
            self._act = jax.jit(
                self.learner.act, static_argnames="mode", donate_argnums=()
            )
            # NOT donated: the overlapped host loop's staging thread acts
            # from the latest published state — the very buffers a
            # donating learn would invalidate mid-rollout
            self._learn = jax.jit(self.learner.learn, donate_argnums=())
            # NOT donated: at n_step=1 `full` IS the rollout traj, which
            # update_obs_stats still reads after the fold
            self._nstep = jax.jit(
                lambda traj: nstep_transitions(traj, algo.gamma, algo.n_step),
                donate_argnums=(),
            )
            if not self.remote:
                # replay state is loop-carried on the train thread only:
                # donate it through insert/sample/priority-refresh so the
                # host path updates the buffer in place too
                self._insert = jax.jit(self.replay.insert, donate_argnums=(0,))
                self._sample = jax.jit(self.replay.sample, donate_argnums=(0,))
                if self.prioritized:
                    self._update_prio = jax.jit(
                        self.replay.update_priorities, donate_argnums=(0,)
                    )
        # uniform-replay fast path (see run_updates in _device_train_iter):
        # one batched index draw + gather for the whole update loop.
        # hasattr gates replay kinds without a batched sampler (fifo).
        self._batched_sampling = (
            not self.prioritized
            and not self.remote
            and bool(algo.get("batched_uniform_sampling", True))
            and hasattr(self.replay, "sample_many")
        )

    # -- device (fused) path -------------------------------------------------
    def _init_carry(self, env_key: jax.Array) -> OffPolicyCarry:
        """Fresh rollout carry for ``num_envs`` envs. Pure and jittable —
        the multi-host driver runs it under jit with dp out-shardings so
        each process materializes only its addressable env shards."""
        act_dim = int(self.env.specs.action.shape[0])
        keys = jax.random.split(env_key, self.num_envs)
        env_state, obs = jax.vmap(self.env.reset)(keys)
        n = self.algo.n_step
        if n > 1:
            B = self.num_envs
            obs_shape = self.env.specs.obs.shape
            tail = {
                "obs": jnp.zeros((n - 1, B, *obs_shape), jnp.float32),
                "next_obs": jnp.zeros((n - 1, B, *obs_shape), jnp.float32),
                "action": jnp.zeros((n - 1, B, act_dim), jnp.float32),
                "reward": jnp.zeros((n - 1, B), jnp.float32),
                # done=True + terminated=True: windows starting in the
                # fake prefix die at once with reward 0 and discount 0
                "done": jnp.ones((n - 1, B), bool),
                "terminated": jnp.ones((n - 1, B), bool),
            }
        else:
            tail = None
        return OffPolicyCarry(
            env_state=env_state,
            obs=obs,
            noise=jnp.zeros((self.num_envs, act_dim), jnp.float32),
            ep_return=jnp.zeros(self.num_envs, jnp.float32),
            ep_length=jnp.zeros(self.num_envs, jnp.int32),
            tail=tail,
        )

    def committed_carry(self, env_key: jax.Array) -> OffPolicyCarry:
        """Fresh rollout carry committed to the active mesh — shared by
        init_loop_state and the divergence-rollback path (which re-seeds
        the env carry without re-allocating the replay storage)."""
        carry = self._init_carry(env_key)
        if self.mesh is not None and self.mesh.size > 1:
            # commit the carry with the shard_map's own specs at init
            # (same reason as Trainer.run: an uncommitted carry breaks
            # the first iteration's donation and pays a reshard)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from surreal_tpu.parallel.dp import offpolicy_carry_specs

            carry = jax.device_put(
                carry,
                jax.tree.map(
                    lambda spec: NamedSharding(self.mesh, spec),
                    offpolicy_carry_specs(carry),
                    is_leaf=lambda x: isinstance(x, P),
                ),
            )
        return carry

    def init_loop_state(self, env_key: jax.Array):
        """(carry, replay_state) committed to the active mesh — ONE
        constructor for run(), the autotuner's measurement harness
        (tune/search.py), and tests, so none of them can drift from the
        dp path's sharding/donation contract."""
        carry = self.committed_carry(env_key)
        example = self._replay_example()
        if self.mesh is not None and self.mesh.size > 1:
            from surreal_tpu.replay.sharded import sharded_replay_init

            replay_state = sharded_replay_init(self.replay, example, self.mesh)
        else:
            replay_state = self.replay.init(example)
        return carry, replay_state

    def _replay_example(self) -> dict:
        """Single-transition example pytree sizing the replay storage.

        # precision: obs-class leaves allocate in the policy's staging
        # dtype (bf16 halves the buffer — the program's LARGEST
        # allocation; ``ring_insert`` casts incoming f32 rollouts to the
        # storage dtype). Reward/discount stay f32: the TD target sums
        # n-step rewards and bf16 accumulation drifts.
        """
        act_dim = int(self.env.specs.action.shape[0])
        obs_dtype = jnp.dtype(self.learner.policy.data_dtype)
        return {
            "obs": jnp.zeros(self.env.specs.obs.shape, obs_dtype),
            "next_obs": jnp.zeros(self.env.specs.obs.shape, obs_dtype),
            "action": jnp.zeros((act_dim,), jnp.float32),
            "reward": jnp.zeros((), jnp.float32),
            "discount": jnp.zeros((), jnp.float32),
        }

    def _rollout(self, state, carry: OffPolicyCarry, key: jax.Array, warmup):
        explo = self.algo.exploration

        def step(c: OffPolicyCarry, step_key):
            akey, nkey, wkey = jax.random.split(step_key, 3)
            if explo.noise == "ou":
                a_det, _ = self.learner.act(state, c.obs, akey, "eval_deterministic")
                noise = ou_noise_step(
                    c.noise, nkey, explo.ou_theta, explo.sigma, explo.ou_dt
                )
                action = jnp.clip(a_det + noise, -1.0, 1.0)
            else:
                action, _ = self.learner.act(state, c.obs, akey, "training")
                noise = c.noise
            # exploration warmup: uniform-random actions until the replay
            # holds enough diverse data (classic off-policy bootstrap fix)
            random_action = jax.random.uniform(
                wkey, action.shape, action.dtype, -1.0, 1.0
            )
            action = jnp.where(warmup, random_action, action)
            env_state, obs2, reward, done, info = batch_step(self.env, c.env_state, action)
            next_obs, terminated = successor_and_termination(obs2, done, info)
            ep_return = c.ep_return + reward
            ep_length = c.ep_length + 1
            trans = {
                "obs": c.obs,
                "next_obs": next_obs,
                "action": action,
                "reward": reward,
                "done": done,
                "terminated": terminated,
                "ep_return": jnp.where(done, ep_return, 0.0),
                "ep_done": done,
            }
            new_c = c._replace(
                env_state=env_state,
                obs=obs2,
                # reset OU state at episode boundaries; mask is rank-matched
                # to the [B, act_dim] noise, independent of the obs rank
                noise=jnp.where(done[:, None], 0.0, noise),
                ep_return=jnp.where(done, 0.0, ep_return),
                ep_length=jnp.where(done, 0, ep_length),
            )
            return new_c, trans

        keys = jax.random.split(key, self.horizon)
        # searched rollout-scan unroll (algo.rollout_unroll, tune/space.py)
        return jax.lax.scan(
            step, carry, keys,
            unroll=max(1, min(self._rollout_unroll, self.horizon)),
        )

    def _device_train_iter(
        self, state, replay_state, carry, key, beta, warmup, first, axis_name=None
    ):
        rkey, ukey = jax.random.split(key)
        carry, traj = self._rollout(state, carry, rkey, warmup)
        chunk = {k: traj[k] for k in TRANS_KEYS}
        n = self.algo.n_step
        if n > 1:
            # prepend the previous chunk's tail so the n-1 steps at every
            # chunk boundary still become window STARTS (without this they
            # would silently never enter replay); carry the new tail on.
            full = jax.tree.map(
                lambda a, b: jnp.concatenate([a, b], axis=0), carry.tail, chunk
            )
            carry = carry._replace(
                tail=jax.tree.map(lambda x: x[-(n - 1):], full)
            )
        else:
            full = chunk
        trans = nstep_transitions(full, self.algo.gamma, n)
        if n > 1:
            # the very first chunk's prepended tail is fabricated (no
            # previous chunk exists), so the n-1 windows starting inside it
            # are fictitious (obs=0, action=0) — scrub them before insert.
            trans = jax.lax.cond(
                first,
                lambda t: scrub_fake_prefix_windows(t, n, chunk["reward"].shape[1]),
                lambda t: t,
                trans,
            )
        replay_state = self.replay.insert(replay_state, trans)
        # obs-normalizer: fold each fresh obs exactly once per chunk
        state = self.learner.update_obs_stats(state, chunk["obs"], axis_name)

        def run_updates(operand):
            state, replay_state = operand
            ukeys = jax.random.split(ukey, self.algo.updates_per_iter)

            if self._batched_sampling:
                # uniform-replay fast path: ALL updates_per_iter index
                # sets drawn in one batched randint + ONE ring gather,
                # instead of a full-buffer gather inside every scan step
                # (64 sequential draws at the DDPG default). Record-
                # equivalent by construction: sample_many derives set k
                # from ukeys[k] exactly as sample() would, and learn
                # consumes the same ukeys[k] — tests/test_replay.py pins
                # bit-equal indices/batches, tests/test_tune.py pins the
                # fused iteration against the sequential path. Prioritized
                # replay keeps the sequential path: priorities change
                # between updates, so later draws depend on earlier TDs.
                replay_state, batches, idx = self.replay.sample_many(
                    replay_state, ukeys
                )

                def one_update_batched(state, xs):
                    batch, update_key, idx_k = xs
                    state, metrics = self.learner.learn(
                        state, batch, update_key, axis_name
                    )
                    # same staleness gauge as the sequential path below
                    age = self.replay.age_frac(replay_state, idx_k)
                    if axis_name is not None:
                        age = jax.lax.pmean(age, axis_name)
                    metrics["replay/sample_age_frac"] = age
                    metrics.pop("priority/td_abs")
                    return state, metrics

                state, metrics = jax.lax.scan(
                    one_update_batched, state, (batches, ukeys, idx),
                    unroll=self._update_unroll,
                )
                return state, replay_state, jax.tree.map(jnp.mean, metrics)

            def one_update(c, update_key):
                state, replay_state = c
                if self.prioritized:
                    replay_state, batch, info = self.replay.sample(
                        replay_state, update_key, beta=beta
                    )
                    batch = dict(batch, is_weights=info["is_weights"])
                else:
                    replay_state, batch, info = self.replay.sample(
                        replay_state, update_key
                    )
                state, metrics = self.learner.learn(
                    state, batch, update_key, axis_name
                )
                # sample-staleness gauge (device scalar, telemetry spine):
                # how old the drawn transitions are relative to the fill.
                # Each dp shard draws its own indices, so pmean keeps the
                # scalar genuinely replicated for the shard_map out spec
                age = self.replay.age_frac(replay_state, info["idx"])
                if axis_name is not None:
                    age = jax.lax.pmean(age, axis_name)
                metrics["replay/sample_age_frac"] = age
                td_abs = metrics.pop("priority/td_abs")
                if self.prioritized:
                    replay_state = self.replay.update_priorities(
                        replay_state, info["idx"], td_abs
                    )
                return (state, replay_state), metrics

            # searched update-loop unroll (algo.update_unroll)
            (state, replay_state), metrics = jax.lax.scan(
                one_update,
                (state, replay_state),
                ukeys,
                unroll=self._update_unroll,
            )
            return state, replay_state, jax.tree.map(jnp.mean, metrics)

        def skip_updates(operand):
            state, replay_state = operand
            # lax.cond branches must return one pytree structure: derive
            # the zero metrics tree from run_updates' OWN output shape
            # (abstract trace only — nothing executes), so new learner /
            # health / gauge keys can never desync the two branches
            metrics_shape = jax.eval_shape(lambda: run_updates(operand)[2])
            zero_metrics = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), metrics_shape
            )
            return state, replay_state, zero_metrics

        state, replay_state, metrics = jax.lax.cond(
            self.replay.can_sample(replay_state),
            run_updates,
            skip_updates,
            (state, replay_state),
        )
        if axis_name is not None and self.prioritized:
            # max_priority diverges across shards (each sees its own TDs);
            # pmax keeps the fresh-insert priority scale global, and keeps
            # the scalar genuinely replicated for the shard_map out spec
            replay_state = replay_state._replace(
                max_priority=jax.lax.pmax(replay_state.max_priority, axis_name)
            )
        # replay occupancy gauges, after the pmax so prioritized
        # max_priority is the globally-synced value (fills/sizes are
        # lockstep-identical across shards by construction)
        metrics.update(self.replay.gauges(replay_state))
        n_done = traj["ep_done"].sum()
        ep_return_sum = traj["ep_return"].sum()
        if axis_name is not None:
            n_done = jax.lax.psum(n_done, axis_name)
            ep_return_sum = jax.lax.psum(ep_return_sum, axis_name)
        metrics["episode/return"] = jnp.where(
            n_done > 0, ep_return_sum / jnp.maximum(n_done, 1), jnp.nan
        )
        metrics["episode/count"] = n_done.astype(jnp.float32)
        return state, replay_state, carry, metrics

    # -- main loop -----------------------------------------------------------
    def run(
        self,
        max_env_steps: int | None = None,
        on_metrics: Callable[[int, dict], None] | None = None,
    ):
        cfg = self.config.session_config
        total = max_env_steps or cfg.total_env_steps
        steps_per_iter = self.horizon * self.num_envs

        key = jax.random.key(self.seed)
        key, init_key, env_key = jax.random.split(key, 3)
        state = self.learner.init(init_key)
        # chaos harness: install (or RESET) the fault registry for this run
        faults.configure_from(self.config.session_config)
        # divergence-rollback fallback when no finite checkpoint exists yet
        self._fresh_init = lambda nonce: self.learner.init(
            jax.random.fold_in(init_key, nonce)
        )
        hooks = SessionHooks(self.config, self.learner)
        try:
            state, iteration, env_steps = hooks.restore(state)
            hooks.begin_run(iteration, env_steps)
            if self.tune_decision.mode != "off":
                hooks.tune_event(**self.tune_decision.telemetry())
            if not self.device_mode:
                runner = (
                    self._run_host_remote if self.remote else self._run_host
                )
                return runner(
                    total, on_metrics, hooks, state, iteration, env_steps
                )
            if self.mesh is not None and self.mesh.size > 1:
                from surreal_tpu.parallel.mesh import replicate_state

                state = replicate_state(self.mesh, state)
            carry, replay_state = self.init_loop_state(env_key)
            if (
                cfg.checkpoint.get("include_replay", False)
                and hooks.ckpt is not None
            ):
                # snapshot the buffer at every checkpoint (closure reads
                # the loop's CURRENT replay_state) and, on resume, reload
                # the snapshot aligned to the restored step so learning
                # continues without a warmup refill
                hooks.extra_state_fn = lambda: {"replay": replay_state}
                if iteration > 0:
                    restored = hooks.ckpt.restore_extra(
                        {"replay": replay_state}, step=iteration
                    )
                    if restored is not None:
                        replay_state = restored["replay"]
            include_replay = bool(
                cfg.checkpoint.get("include_replay", False)
            ) and hooks.ckpt is not None
            # cost/MFU accounting: register the fused program once before
            # the first dispatch (host-side lower + HLO cost pass only)
            hooks.record_program_costs(
                "train_iter", self._train_iter, state, replay_state, carry,
                jax.random.fold_in(key, 0), jnp.float32(0),
                jnp.asarray(False), jnp.asarray(True),
                phase="train_iter",
            )
            # the fused iteration donates state+replay+carry: a deferred
            # boundary reads a jnp.copy snapshot of the param tree. The
            # replay-inclusive checkpoint closure must read the EXACT
            # iteration's ring, so include_replay pins the boundary
            # inline (EngineConfig.inline) — copying the buffer per
            # boundary would dwarf the win being bought.
            stages = (
                StageSpec("collect", donate=True),
                StageSpec("stage", donate=True),
                StageSpec("learn", donate=True),
            ) + sideband_stages()
            engine_cfg = EngineConfig.from_session(cfg)
            if include_replay and engine_cfg.pipeline_sidebands:
                hooks.log.warning(
                    "engine.pipeline_sidebands is pinned off: "
                    "checkpoint.include_replay snapshots the live ring"
                )
                engine_cfg = engine_cfg.inline()
            ls = LoopState(
                state=state, key=key, iteration=iteration,
                env_steps=env_steps,
                extras={"replay": replay_state, "carry": carry,
                        "first_call": True},
            )
            if include_replay:
                # re-point the checkpoint closure at the loop-carried ring
                hooks.extra_state_fn = lambda: {"replay": ls.extras["replay"]}

            def step(ls):
                ls.key, it_key, hk_key = jax.random.split(ls.key, 3)
                beta = jnp.asarray(
                    self._beta(ls.env_steps, total), jnp.float32
                )
                warmup = jnp.asarray(
                    ls.env_steps < self.algo.exploration.warmup_steps
                )
                # unfenced dispatch span (see launch/trainer.py's note)
                with hooks.tracer.span("train_iter"):
                    (ls.state, ls.extras["replay"], ls.extras["carry"],
                     metrics) = self._train_iter(
                        ls.state, ls.extras["replay"], ls.extras["carry"],
                        it_key, beta, warmup,
                        jnp.asarray(ls.extras["first_call"]),
                    )
                ls.extras["first_call"] = False
                return Outcome(
                    metrics=metrics, hook_key=hk_key, steps=steps_per_iter,
                )

            def apply_fault(ls, f):
                ls.state = faults.apply_trainer_fault(f, ls.state)

            def on_rollback(ls):
                rb = hooks.recovery.rollback(
                    ls.state, fresh=self._fresh_init,
                    # replay rides the rollback when it was snapshotted;
                    # otherwise the buffer is kept — its contents are
                    # DATA (worst case: some poisoned-policy transitions
                    # that re-trip the bounded guard), not parameters
                    extra_template=(
                        {"replay": ls.extras["replay"]}
                        if include_replay else None
                    ),
                )
                ls.state, ls.iteration, ls.env_steps = (
                    rb.state, rb.iteration, rb.env_steps
                )
                if self.mesh is not None and self.mesh.size > 1:
                    from surreal_tpu.parallel.mesh import replicate_state

                    ls.state = replicate_state(self.mesh, ls.state)
                if rb.extra is not None:
                    ls.extras["replay"] = rb.extra["replay"]
                ls.key = jax.random.fold_in(ls.key, rb.nonce)
                ls.extras["carry"] = self.committed_carry(
                    jax.random.fold_in(env_key, rb.nonce)
                )
                # the fresh carry's n-step tail is fabricated again:
                # re-scrub the first folded chunk after the rollback
                ls.extras["first_call"] = True

            engine = LoopEngine(
                hooks, total, step, stages, engine_cfg,
                on_metrics=on_metrics, apply_fault=apply_fault,
                on_rollback=on_rollback,
            )
            ls = engine.run(ls)
            state, iteration, env_steps = ls.state, ls.iteration, ls.env_steps
            hooks.final_checkpoint(iteration, env_steps, state)
            return state, hooks.last_metrics
        finally:
            hooks.close()

    def _beta(self, env_steps: int, total: int) -> float:
        """Prioritized IS beta anneal beta0 -> 1.0 over training."""
        if not self.prioritized:
            return 0.0
        frac = min(env_steps / max(total, 1), 1.0)
        b0 = self.learner.config.replay.priority_beta0
        return b0 + (1.0 - b0) * frac

    # -- host path -----------------------------------------------------------
    def _explore_rollout(self, hooks, roll, a_state, warmup, act_dim):
        """One H-step exploration rollout, shared by the host paths
        (in-process ``collect_chunk`` and the remote plane's
        ``collect_and_send``): warmup/OU/training actions, terminal-obs
        and truncation handling, episode-reset noise masking. Mutates
        ``roll`` (key/obs/noise); returns (time-major numpy trajectory
        dict, completed-episode returns) — the returns ride the staged
        item so only the MAIN thread touches the metrics deque (extending
        it from the staging thread would race host_metrics' iteration of
        the deque, the same hazard trainer.py's overlap collector routes
        through its queue)."""
        explo = self.algo.exploration
        steps: list[dict] = []
        chunk_returns: list[float] = []
        obs, noise = roll["obs"], roll["noise"]
        with hooks.tracer.span("rollout"):
            for _ in range(self.horizon):
                roll["key"], akey, nkey = jax.random.split(roll["key"], 3)
                if warmup:
                    action = np.random.default_rng(
                        int(jax.random.randint(akey, (), 0, 2**31 - 1))
                    ).uniform(
                        -1.0, 1.0, (self.num_envs, act_dim)
                    ).astype(np.float32)
                elif explo.noise == "ou":
                    a_det, _ = self._act(
                        a_state, jnp.asarray(obs), akey,
                        mode="eval_deterministic",
                    )
                    # np.array (copy), NOT np.asarray: asarray of a jax
                    # array is a read-only view, and the episode-reset
                    # masking below writes into it
                    noise = np.array(ou_noise_step(
                        jnp.asarray(noise), nkey, explo.ou_theta,
                        explo.sigma, explo.ou_dt,
                    ))
                    action = np.clip(np.asarray(a_det) + noise, -1.0, 1.0)
                else:
                    a, _ = self._act(
                        a_state, jnp.asarray(obs), akey, mode="training"
                    )
                    action = np.asarray(a)
                out = self.env.step(action)
                term_obs = out.info.get("terminal_obs", out.obs)
                done_b = out.done.reshape(
                    out.done.shape + (1,) * (out.obs.ndim - 1)
                )
                truncated = np.asarray(out.info.get(
                    "truncated", np.zeros(len(out.done), bool)
                ))
                steps.append({
                    "obs": obs,
                    "next_obs": np.where(done_b, term_obs, out.obs),
                    "action": action,
                    "reward": out.reward,
                    "done": out.done,
                    "terminated": out.done & ~truncated,
                })
                if out.done.any():
                    noise[out.done] = 0.0
                if "episode_returns" in out.info:
                    chunk_returns.extend(
                        np.asarray(out.info["episode_returns"]).tolist()
                    )
                obs = out.obs
        roll["obs"], roll["noise"] = obs, noise
        traj = {k: np.stack([s[k] for s in steps]) for k in steps[0]}
        return traj, chunk_returns

    def _run_host(self, total, on_metrics, hooks, state, iteration, env_steps):
        """Host-env loop. With ``topology.overlap_rollouts`` (default on)
        the exploration rollout + its host->device staging run on a
        prefetch thread (learners/prefetch.py): while the device drains
        chunk k's ``updates_per_iter`` SGD steps, the staging thread
        simulates chunk k+1 and ships it as ONE ``device_put`` — iteration
        wall-clock ~max(rollout, updates) instead of their sum. The
        staging thread acts from the latest PUBLISHED state — with one
        chunk queued and one mid-collection, up to TWO iterations behind
        (off-policy by construction, the same bounded staleness the
        replay already serves; the warmup flag shares the bound);
        ``overlap_rollouts=false`` restores strict collect->update
        alternation with zero policy lag."""
        steps_per_iter = self.horizon * self.num_envs
        act_dim = int(self.env.specs.action.shape[0])

        base_key = jax.random.key(self.seed + 1)
        key = jax.random.fold_in(base_key, 0)  # update/sample chain
        replay_state = self.replay.init(self._replay_example())
        ckpt_cfg = self.config.session_config.checkpoint
        if ckpt_cfg.get("include_replay", False) and hooks.ckpt is not None:
            # same replay-snapshot contract as the device path
            hooks.extra_state_fn = lambda: {"replay": replay_state}
            if iteration > 0:
                restored = hooks.ckpt.restore_extra(
                    {"replay": replay_state}, step=iteration
                )
                if restored is not None:
                    replay_state = restored["replay"]
        explo = self.algo.exploration
        n = self.algo.n_step
        if n > 1:
            B = self.num_envs
            obs_shape = self.env.specs.obs.shape
            host_tail = {
                "obs": jnp.zeros((n - 1, B, *obs_shape), jnp.float32),
                "next_obs": jnp.zeros((n - 1, B, *obs_shape), jnp.float32),
                "action": jnp.zeros((n - 1, B, act_dim), jnp.float32),
                "reward": jnp.zeros((n - 1, B), jnp.float32),
                "done": jnp.ones((n - 1, B), bool),
                "terminated": jnp.ones((n - 1, B), bool),
            }
        else:
            host_tail = None

        from collections import deque

        from surreal_tpu.launch.hooks import HOST_METRICS_WINDOW
        from surreal_tpu.learners.prefetch import Prefetcher

        recent_returns: deque = deque(maxlen=HOST_METRICS_WINDOW)

        # rollout-side mutable state, owned by whichever thread runs
        # collect_chunk (the staging thread under overlap, this one
        # otherwise — never both); the holders publish the acting state
        # and consumed-step count across the seam
        roll = {
            "key": jax.random.fold_in(base_key, 1),
            "obs": self.env.reset(seed=self.config.env_config.seed),
            "noise": np.zeros((self.num_envs, act_dim), np.float32),
        }
        act_holder = [state]
        steps_holder = [env_steps]

        def collect_chunk():
            """One H-step exploration rollout (``_explore_rollout``),
            stacked time-major and shipped to device as one transfer.
            Returns (device_traj, completed-episode returns)."""
            traj, chunk_returns = self._explore_rollout(
                hooks, roll, act_holder[0],  # one coherent policy per chunk
                steps_holder[0] < explo.warmup_steps, act_dim,
            )
            with hooks.tracer.span("h2d-transfer"):
                return jax.device_put(traj), chunk_returns

        overlap = overlap_collect(self.config.session_config)
        prefetch = (
            Prefetcher(collect_chunk, name="offpolicy-stage") if overlap else None
        )
        include_replay = bool(
            ckpt_cfg.get("include_replay", False)
        ) and hooks.ckpt is not None
        # nothing donates on the host path (the staging thread acts from
        # act_holder[0]); include_replay still pins the boundary inline —
        # the checkpoint closure reads the live ring (see the device path)
        stages = (
            StageSpec("collect", donate=False, overlap=overlap),
            StageSpec("stage", donate=False, overlap=overlap),
            StageSpec("learn", donate=False),
        ) + sideband_stages()
        engine_cfg = EngineConfig.from_session(self.config.session_config)
        if include_replay and engine_cfg.pipeline_sidebands:
            hooks.log.warning(
                "engine.pipeline_sidebands is pinned off: "
                "checkpoint.include_replay snapshots the live ring"
            )
            engine_cfg = engine_cfg.inline()
        ls = LoopState(
            state=state, key=key, iteration=iteration, env_steps=env_steps,
            extras={"replay": replay_state, "first_chunk": True},
        )
        if ckpt_cfg.get("include_replay", False) and hooks.ckpt is not None:
            # re-point the checkpoint closure at the loop-carried ring
            hooks.extra_state_fn = lambda: {"replay": ls.extras["replay"]}

        def step(ls):
            nonlocal host_tail
            if prefetch is not None:
                with hooks.tracer.span("chunk-wait"):
                    traj, ep_returns = prefetch.get()
            else:
                # no chunk-wait span: collect_chunk records its own
                # rollout/h2d phases, and wrapping it here would count
                # the same wall time twice in the diag breakdown
                traj, ep_returns = collect_chunk()
            recent_returns.extend(ep_returns)
            if host_tail is not None:
                full = jax.tree.map(
                    lambda a, b: jnp.concatenate([a, b], axis=0), host_tail, traj
                )
                host_tail = jax.tree.map(
                    lambda x: x[-(self.algo.n_step - 1):], full
                )
            else:
                full = traj
            trans = self._nstep(full)
            if host_tail is not None and ls.extras["first_chunk"]:
                # same scrub as the device path: the run's first prepended
                # tail is fabricated, so its windows must not enter replay
                trans = scrub_fake_prefix_windows(
                    trans, self.algo.n_step, self.num_envs
                )
            ls.extras["first_chunk"] = False
            with hooks.tracer.span("replay-insert"):
                ls.extras["replay"] = self._insert(ls.extras["replay"], trans)
            ls.state = self.learner.update_obs_stats(ls.state, traj["obs"])
            if bool(self.replay.can_sample(ls.extras["replay"])):
                beta = jnp.asarray(
                    self._beta(ls.env_steps, total), jnp.float32
                )
                for _ in range(self.algo.updates_per_iter):
                    ls.key, skey = jax.random.split(ls.key)
                    with hooks.tracer.span("replay-sample"):
                        if self.prioritized:
                            ls.extras["replay"], batch, info = self._sample(
                                ls.extras["replay"], skey, beta=beta
                            )
                            batch = dict(batch, is_weights=info["is_weights"])
                        else:
                            ls.extras["replay"], batch, info = self._sample(
                                ls.extras["replay"], skey
                            )
                    with hooks.tracer.span("learn"):
                        ls.state, metrics = self._learn(ls.state, batch, skey)
                    # cost accounting, first update only (idempotent;
                    # needs a representative replay batch to lower)
                    hooks.record_program_costs(
                        "learn", self._learn, ls.state, batch, skey,
                        phase="learn",
                    )
                    td_abs = metrics.pop("priority/td_abs")
                    if self.prioritized:
                        ls.extras["replay"] = self._update_prio(
                            ls.extras["replay"], info["idx"], td_abs
                        )
                metrics["replay/sample_age_frac"] = self.replay.age_frac(
                    ls.extras["replay"], info["idx"]
                )
            else:
                metrics = {}
            metrics = dict(metrics, **self.replay.gauges(ls.extras["replay"]))
            # publish the updated acting state + consumed-step count to
            # the staging thread (its next chunk explores with them)
            act_holder[0] = ls.state
            steps_holder[0] = ls.env_steps + steps_per_iter
            ls.key, hk_key = jax.random.split(ls.key)
            return Outcome(
                metrics=host_metrics(metrics, recent_returns),
                hook_key=hk_key, steps=steps_per_iter,
            )

        def apply_fault(ls, f):
            ls.state = faults.apply_trainer_fault(f, ls.state)
            act_holder[0] = ls.state

        def on_rollback(ls):
            rb = hooks.recovery.rollback(
                ls.state, fresh=self._fresh_init,
                extra_template=(
                    {"replay": ls.extras["replay"]} if include_replay else None
                ),
            )
            ls.state, ls.iteration, ls.env_steps = (
                rb.state, rb.iteration, rb.env_steps
            )
            if rb.extra is not None:
                ls.extras["replay"] = rb.extra["replay"]
            # staging thread keeps collecting: hand it the restored
            # acting state + rolled-back step count; chunks already
            # staged from the poisoned policy are data the replay
            # (and the bounded guard) absorb
            act_holder[0] = ls.state
            steps_holder[0] = ls.env_steps
            ls.key = jax.random.fold_in(ls.key, rb.nonce)

        try:
            engine = LoopEngine(
                hooks, total, step, stages, engine_cfg,
                on_metrics=on_metrics, apply_fault=apply_fault,
                on_rollback=on_rollback,
            )
            ls = engine.run(ls)
            state, iteration, env_steps = ls.state, ls.iteration, ls.env_steps
            hooks.final_checkpoint(iteration, env_steps, state)
            return state, hooks.last_metrics
        finally:
            if prefetch is not None:
                prefetch.close()

    # -- remote experience plane (host path) ---------------------------------
    def _run_host_remote(self, total, on_metrics, hooks, state, iteration,
                         env_steps):
        """Host loop over the sharded experience plane
        (``replay.kind='remote'``, surreal_tpu/experience/): the collector
        thread hash-routes every folded transition to the shard servers
        through the ExperienceSender, and the learner consumes batches the
        ShardedSampler prefetched from ALL shards during the PREVIOUS
        iteration's SGD drain — the learner never waits on experience
        ingest (the residue is the experience/sample_wait_ms gauge).

        Pipeline discipline: iteration k requests its batches (watermarked
        at chunk k's per-shard row counts) and trains on the batches
        requested at iteration k-1 — one chunk of bounded sampling
        staleness, the same bounded-lag class as ``overlap_rollouts``'s
        acting staleness. Under ``overlap_rollouts=false`` the record is
        exactly reproducible run-to-run (watermark deferral at the shard
        — tests pin it)."""
        from collections import deque

        from surreal_tpu.experience import ExperiencePlane
        from surreal_tpu.launch.hooks import HOST_METRICS_WINDOW, host_metrics
        from surreal_tpu.learners.prefetch import Prefetcher

        steps_per_iter = self.horizon * self.num_envs
        act_dim = int(self.env.specs.action.shape[0])
        replay_cfg = self.learner.config.replay
        # replay tiers (ISSUE 18): `replay.tiers.hot` fronts the plane
        # with a device-resident ring, `replay.tiers.spill` turns the
        # shards' ingest into a durable WAL. tiers absent => tiers_cfg
        # None => the plane build below is byte-identical to today.
        tiers_cfg = replay_cfg.get("tiers", None)
        if tiers_cfg is not None:
            tiers_cfg = (
                tiers_cfg.to_dict()
                if hasattr(tiers_cfg, "to_dict") else dict(tiers_cfg)
            )
            spill_cfg = dict(tiers_cfg.get("spill") or {})
            if spill_cfg.get("enabled") and not spill_cfg.get("dir"):
                import os

                # default spill dir under the session folder, next to
                # telemetry/checkpoints — `replay_from_log` finds it there
                spill_cfg["dir"] = os.path.join(
                    self.config.session_config.folder, "spill"
                )
                tiers_cfg["spill"] = spill_cfg
        ckpt_cfg = self.config.session_config.checkpoint
        if ckpt_cfg.get("include_replay", False):
            hooks.log.warning(
                "checkpoint.include_replay is not supported with "
                "replay.kind='remote' (the buffer lives in the shard "
                "servers); resumes refill through warmup"
            )
        base_key = jax.random.key(self.seed + 1)
        key = jax.random.fold_in(base_key, 0)  # update/learn key chain
        explo = self.algo.exploration
        n = self.algo.n_step
        B = self.num_envs
        obs_shape = self.env.specs.obs.shape
        if n > 1:
            host_tail = {
                "obs": np.zeros((n - 1, B, *obs_shape), np.float32),
                "next_obs": np.zeros((n - 1, B, *obs_shape), np.float32),
                "action": np.zeros((n - 1, B, act_dim), np.float32),
                "reward": np.zeros((n - 1, B), np.float32),
                "done": np.ones((n - 1, B), bool),
                "terminated": np.ones((n - 1, B), bool),
            }
        else:
            host_tail = None

        # elastic data-parallel learner group (parallel/learner_group.py):
        # topology.learner_group.members > 0 routes draining + learn
        # through the group — M members over disjoint shard subsets,
        # gradient all-reduce, one fanout version stream, join/leave
        # mid-run. Absent config keeps the plane-wide sampler path
        # untouched.
        lg_cfg = self.config.session_config.topology.get(
            "learner_group", None
        )
        plane = ExperiencePlane(
            kind="prioritized" if self.prioritized else "uniform",
            example=jax.device_get(self._replay_example()),
            capacity=int(replay_cfg.capacity),
            batch_size=int(replay_cfg.batch_size),
            start_sample_size=int(replay_cfg.start_sample_size),
            updates_per_iter=int(self.algo.updates_per_iter),
            num_slots=B,
            # worst-case rows one chunk routes to ONE shard: every folded
            # window (tail prepend keeps window count == horizon)
            max_insert_rows=self.horizon * B,
            priority_alpha=float(replay_cfg.priority_alpha),
            priority_beta0=float(replay_cfg.priority_beta0),
            priority_eps=float(replay_cfg.priority_eps),
            cfg=self.config.session_config.topology.get(
                "experience_plane", None
            ),
            base_key=jax.random.fold_in(base_key, 2),
            trace_id=hooks.trace_id,
            build_sampler=lg_cfg is None,
            tiers=tiers_cfg,
        )
        # hot tier: device-resident newest-transition ring fronting the
        # shard fan-in (replay/tiers.py). Uniform + plane-wide sampler
        # only — the learner group partitions shards across members and
        # prioritized draws need live shard priority state.
        tiered = None
        hot_cfg = dict((tiers_cfg or {}).get("hot") or {})
        if hot_cfg.get("enabled"):
            if lg_cfg is not None or self.prioritized:
                hooks.log.warning(
                    "replay.tiers.hot ignored: requires uniform replay "
                    "and no learner group"
                )
            else:
                from surreal_tpu.experience.sampler import TieredSampler
                from surreal_tpu.replay.tiers import HotTier

                hot = HotTier(
                    capacity=int(
                        hot_cfg.get("capacity", replay_cfg.capacity)
                    ),
                    batch_size=int(replay_cfg.batch_size),
                    gather_impl=hot_cfg.get("gather_impl"),
                    min_fill=hot_cfg.get("min_fill"),
                    # storage in the WARM example's staging dtypes: a hot
                    # sample is dtype-identical to a warm fan-in batch
                    example=self._replay_example(),
                )
                tiered = TieredSampler(plane.sampler, hot)
                plane.attach_tiers(tiered)
        group = None
        if lg_cfg is not None:
            from surreal_tpu.parallel.learner_group import LearnerGroup

            group = LearnerGroup(
                learner=self.learner,
                plane=plane,
                batch_size=int(replay_cfg.batch_size),
                members=int(lg_cfg.get("members", 1)),
                # the SAME key chain the plane-wide sampler would own —
                # the 1-member group's record is bit-identical to it
                base_key=jax.random.fold_in(base_key, 2),
                single_learn=self._learn,
                fanout=hooks.fanout,
                recovery=hooks.recovery,
                on_event=hooks.learner_group_event,
                handoff_template=state,
            )
            hooks.bind_remediation_actuators(learner_group=group)
        sampler = group if group is not None else plane.sampler
        recent_returns: deque = deque(maxlen=HOST_METRICS_WINDOW)
        roll = {
            "key": jax.random.fold_in(base_key, 1),
            "obs": self.env.reset(seed=self.config.env_config.seed),
            "noise": np.zeros((B, act_dim), np.float32),
            "tail": host_tail,
            "first": True,
        }
        act_holder = [state]
        steps_holder = [env_steps]
        # row s*B+b of the flattened window fold belongs to env slot b
        row_slots = np.arange(self.horizon * B, dtype=np.int64) % B

        def collect_and_send():
            """One exploration chunk: rollout (``_explore_rollout``) ->
            n-step fold -> hash-route to the shards. Runs on the staging
            thread under overlap, so ingest (including the fold's device
            round trip) never blocks the learner. Returns (per-shard
            watermarks AFTER this chunk, the chunk's obs stack,
            completed-episode returns)."""
            traj, chunk_returns = self._explore_rollout(
                hooks, roll, act_holder[0],
                steps_holder[0] < explo.warmup_steps, act_dim,
            )
            if roll["tail"] is not None:
                full = {
                    k: np.concatenate([roll["tail"][k], traj[k]], axis=0)
                    for k in traj
                }
                roll["tail"] = {k: v[-(n - 1):] for k, v in full.items()}
            else:
                full = traj
            trans = self._nstep(full)
            if roll["tail"] is not None and roll["first"]:
                # the run's first prepended tail is fabricated — same
                # scrub as the in-process host path
                trans = scrub_fake_prefix_windows(trans, n, B)
            roll["first"] = False
            with hooks.tracer.span("experience-send"):
                wm = plane.sender.send_rows(
                    jax.device_get(trans), row_slots
                )
            if tiered is not None:
                # hot tier eats the SAME flat rows the shards just got,
                # but from the fold's still-device-resident output — the
                # append is a jitted ring insert, no host round trip
                tiered.append(dict(trans))
            return wm, traj["obs"], chunk_returns

        overlap = overlap_collect(self.config.session_config)
        prefetch = (
            Prefetcher(collect_and_send, name="offpolicy-xp-stage")
            if overlap else None
        )
        pending_jobs = [0]
        stages = (
            StageSpec("collect", donate=False, overlap=overlap),
            StageSpec("stage", donate=False, overlap=overlap),
            StageSpec("learn", donate=False),
        ) + sideband_stages()
        ls = LoopState(
            state=state, key=key, iteration=iteration, env_steps=env_steps,
        )

        def step(ls):
            # consume the batches prefetched during the PREVIOUS
            # iteration's learn drain (zero-wait in the steady state —
            # the sample-wait span/gauge measures the residue). This
            # runs BEFORE the next chunk is sent in strict mode, which
            # is exactly what makes the record deterministic: the
            # shard serves every watermarked sample at the precise
            # ring state the watermark names.
            staged = None
            if pending_jobs[0]:
                with hooks.tracer.span("sample-wait"):
                    staged = sampler.get_iteration()
                pending_jobs[0] -= 1
            if prefetch is not None:
                with hooks.tracer.span("chunk-wait"):
                    wm, obs_chunk, ep_returns = prefetch.get()
            else:
                wm, obs_chunk, ep_returns = collect_and_send()
            recent_returns.extend(ep_returns)
            ls.state = self.learner.update_obs_stats(ls.state, obs_chunk)
            if sum(wm) >= int(replay_cfg.start_sample_size):
                sampler.request_iteration(
                    wm, self._beta(ls.env_steps, total)
                )
                pending_jobs[0] += 1
            metrics = {}
            if staged:
                infos, tds = [], []
                for batch, skey, info in staged:
                    with hooks.tracer.span("learn"):
                        if group is not None:
                            ls.state, metrics = group.learn(
                                ls.state, batch, skey
                            )
                        else:
                            ls.state, metrics = self._learn(
                                ls.state, batch, skey
                            )
                            hooks.record_program_costs(
                                "learn", self._learn, ls.state, batch,
                                skey, phase="learn",
                            )
                    td_abs = metrics.pop("priority/td_abs")
                    infos.append(info)
                    tds.append(np.asarray(td_abs))
                if self.prioritized:
                    # ONE batched priority frame per shard per
                    # iteration (the sample_many discipline on-wire)
                    sampler.update_priorities(infos, tds)
            plane.supervise()
            if group is not None:
                group.supervise()
            act_holder[0] = ls.state
            steps_holder[0] = ls.env_steps + steps_per_iter
            ls.key, hk_key = jax.random.split(ls.key)
            base_build = host_metrics(metrics, recent_returns)

            def build_metrics(base=base_build):
                # plane.gauges() polls shard stats over the wire —
                # deferred into the metrics callable so it runs only
                # when the cadence fires
                row = dict(base(), **plane.gauges())
                if group is not None:
                    row.update(group.gauges())
                return row

            return Outcome(
                metrics=build_metrics, hook_key=hk_key,
                steps=steps_per_iter,
                post_metrics=lambda m_row: hooks.experience_event(
                    **plane.telemetry_event()
                ),
            )

        def apply_fault(ls, f):
            ls.state = faults.apply_trainer_fault(f, ls.state)
            act_holder[0] = ls.state

        def on_rollback(ls):
            rb = hooks.recovery.rollback(ls.state, fresh=self._fresh_init)
            ls.state, ls.iteration, ls.env_steps = (
                rb.state, rb.iteration, rb.env_steps
            )
            # shard contents are DATA (same rationale as the
            # in-process rollback path); the restored state re-arms
            # acting and the key chain re-seeds
            act_holder[0] = ls.state
            steps_holder[0] = ls.env_steps
            ls.key = jax.random.fold_in(ls.key, rb.nonce)

        try:
            engine = LoopEngine(
                hooks, total, step, stages,
                EngineConfig.from_session(self.config.session_config),
                on_metrics=on_metrics, apply_fault=apply_fault,
                on_rollback=on_rollback,
            )
            ls = engine.run(ls)
            state, iteration, env_steps = ls.state, ls.iteration, ls.env_steps
            hooks.final_checkpoint(iteration, env_steps, state)
            return state, hooks.last_metrics
        finally:
            # the collect stage (the only sender) ran on this thread, so
            # the ledger is quiesced here — record the close accounting
            # for the chaos exactly-once oracle before stopping the plane
            try:
                hooks.tracer.event(
                    "experience_close", quiesced=1.0, **plane.accounting()
                )
            except Exception:
                hooks.log.warning(
                    "experience_close accounting failed", exc_info=True
                )
            # unblock any bounded sender/sampler wait running on the
            # staging thread FIRST, so the prefetch join below succeeds
            # before plane.close() closes the sockets that thread is using
            plane._stop.set()
            if prefetch is not None:
                prefetch.close()
            if group is not None:
                group.close()
            plane.close()

    # -- replay-from-log (offline; spill tier as WAL) ------------------------
    def replay_from_log(self, log_path: str,
                        max_updates: int | None = None) -> dict:
        """Offline training replay from the spill tier's write-ahead log.

        Reads every ``shard*.log`` under ``log_path`` (or one explicit
        file) in the deterministic global segment order ``(seq, shard)``,
        streams the decoded transitions into an in-process
        ``UniformReplay`` ring, and runs the off-policy update schedule
        against it: once the ring passes ``start_sample_size``, each
        ingested segment is followed by ``updates_per_iter`` sample+learn
        steps on a key chain derived only from the session seed. Two
        invocations over the same log therefore produce bit-identical
        parameters (tested in tests/test_tiers.py) — the spill tier is a
        durable replay record, not just an archive.

        Torn segments (a crash mid-append, the ``experience.spill``
        chaos site) are skipped by the reader's magic-resync and counted
        in the returned ``torn_segments`` — never a crash, never silent.

        Returns {"state", "params_digest", "updates", "rows",
        "segments", "torn_segments", "metrics"}.
        """
        import hashlib

        from surreal_tpu.experience import wire
        from surreal_tpu.experience.spill import SpillLog
        from surreal_tpu.replay.uniform import UniformReplay

        if self.device_mode:
            raise ValueError(
                "replay-from-log is a host-path mode (the WAL is written "
                "by the remote plane's shard servers)"
            )
        replay = UniformReplay(self._replay_build_cfg)
        rstate = replay.init(self._replay_example())
        # loop-carried on this thread only: donate through insert/sample
        # like the in-process host path does
        insert = jax.jit(replay.insert, donate_argnums=(0,))
        sample = jax.jit(replay.sample, donate_argnums=(0,))
        key = jax.random.key(self.seed)
        key, init_key = jax.random.split(key)
        state = self.learner.init(init_key)
        log = SpillLog(log_path)
        start = int(self._replay_build_cfg.start_sample_size)
        upi = int(self.algo.updates_per_iter)
        updates = rows = segments = size = 0
        metrics: dict = {}
        for _header, flat, n in log.segments():
            batch = wire.unflatten_fields(
                {k: jnp.asarray(v) for k, v in flat.items()}
            )
            rstate = insert(rstate, batch)
            size = min(size + n, replay.capacity)
            rows += n
            segments += 1
            if size < start:
                continue
            done = False
            for _ in range(upi):
                if max_updates is not None and updates >= max_updates:
                    done = True
                    break
                key, skey, lkey = jax.random.split(key, 3)
                rstate, b, _ = sample(rstate, skey)
                state, metrics = self._learn(state, b, lkey)
                updates += 1
            if done:
                break
        digest = hashlib.sha256()
        for leaf in jax.tree.leaves(
            jax.device_get(getattr(state, "params", state))
        ):
            digest.update(np.ascontiguousarray(leaf).tobytes())
        return {
            "state": state,
            "params_digest": digest.hexdigest(),
            "updates": updates,
            "rows": rows,
            "segments": segments,
            "torn_segments": int(log.torn_segments),
            "metrics": {
                k: float(np.asarray(jax.device_get(v)).mean())
                for k, v in metrics.items()
            },
        }
