"""SEED trainer: central inference server + host env workers + learner —
the fully-disaggregated topology for envs that cannot live on device
(BASELINE config ⑤'s "SEED-RL batched inference"; reference call stack
SURVEY.md §3.2 with the actor pool collapsed).

Data flow:
  env workers --ZMQ/DCN--> InferenceServer (one batched policy forward)
     └─ trajectory chunks --queue--> staging thread (double-buffered
        host->device transfer, learners/prefetch.py) --> learner.learn
        (V-trace corrects the one-update staleness; works for IMPALA
        and, with staleness caveats, PPO)

Workers run as threads (fine for gym classic-control) or OS processes
(``worker_mode='process'`` — MuJoCo-heavy stepping releases the GIL
poorly, so real deployments fork the reference's actor-pool way; both
modes run the same ``run_env_worker``).

Staleness: every transition carries the params version that chose its
action (InferenceServer tags them; SURVEY.md §7 hard-parts). V-trace
(IMPALA) absorbs bounded staleness by construction; for PPO-over-SEED set
``max_staleness`` to drop chunks whose oldest transition was acted more
than that many updates ago instead of silently training on them.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Callable

import jax
import numpy as np

from surreal_tpu.engine import (
    EngineConfig,
    LoopEngine,
    LoopState,
    Outcome,
    StageSpec,
    sideband_stages,
)
from surreal_tpu.distributed.env_worker import run_env_worker
from surreal_tpu.distributed.inference_server import InferenceServer
from surreal_tpu.learners import build_learner
from surreal_tpu.utils import faults


_FROM_CONFIG = object()  # sentinel: None is a meaningful max_staleness value


def hop_event(server, plane, learn_ms, gateway=None) -> dict:
    """Assemble the per-hop latency percentiles for one ``hops``
    telemetry event — the stitched cross-process timeline (worker step ->
    frame in flight -> serve batch -> queue dwell -> learn), rendered by
    ``surreal_tpu diag``. The learn hop measures DISPATCH time (the span
    discipline of session/telemetry.py), named accordingly. A live
    gateway joins with its act/transit/attach windows (ISSUE 13: GACT
    frames stamp t_send under the local-address clock guard)."""
    from surreal_tpu.session.telemetry import latency_percentiles

    hops = dict(server.hop_stats())
    p = latency_percentiles(list(plane.dwell_ms))
    if p is not None:
        hops["chunk_queue_dwell_ms"] = p
    p = latency_percentiles(list(learn_ms))
    if p is not None:
        hops["learn_dispatch_ms"] = p
    if gateway is not None:
        hops.update(gateway.hop_stats())
    return hops


class _DataPlane:
    """Running SEED data plane: server + worker fleet + supervision.

    ``next_chunk`` waits for experience while supervising workers on every
    empty poll — a dead SOLE worker must be respawned while waiting, not
    after a chunk it can no longer produce. ``respawns`` accumulates for
    the metrics stream. The chunk timeout resets to ``steady_timeout``
    after the first chunk (the first waits out XLA compiles — minutes on a
    tunneled TPU; in the multi-host loop the steady wait also covers the
    slowest rank's fleet, since the learn is collective)."""

    # a respawn that survives this long clears its worker's failure streak
    # (the exponential backoff below targets CRASH LOOPS, not one-off kills)
    _HEALTHY_S = 10.0

    def __init__(
        self, trainer, server, workers, env_cfg, stop, first_timeout,
        respawn_backoff_s: float = 0.5, respawn_backoff_cap_s: float = 30.0,
    ):
        self.trainer = trainer
        self.server = server
        self.workers = workers
        self.env_cfg = env_cfg
        self.stop = stop
        self.respawns = 0
        self._timeout = first_timeout
        # the steady starvation deadline must COVER the worker-silence
        # recovery window: a worker wedged waiting on a reply that will
        # never come (e.g. its step frame dropped on the wire) only
        # self-kills after worker_silence_s, and the respawn that refills
        # the chunk queue happens on our own supervise() pass after that —
        # a deadline shorter than the budget makes the sole-worker
        # recovery path unreachable (found by the chaos campaign:
        # transport.send drop_frame wedged seed_experience forever)
        self.steady_timeout = max(
            30.0, float(getattr(trainer, "worker_silence_s", 0.0)) * 1.5
        )
        self.last_chunk_age_s = 0.0  # queue dwell of the last chunk served
        # rolling queue-dwell samples for the per-hop latency percentiles
        # (the 'hops' telemetry event; appended by whichever thread runs
        # next_chunk — GIL-atomic, snapshot via list() on the reader)
        self.dwell_ms: deque = deque(maxlen=256)
        # exponential respawn backoff (satellite of ISSUE 5): a worker that
        # dies at startup used to respawn-loop hot — burning CPU on env
        # construction and flooding the server with hellos. The schedule
        # (immediate first respawn, base * 2^k capped, healthy-streak
        # reset) is the shared utils/respawn.py state machine — one
        # implementation for workers, experience shards, and inference
        # replicas.
        from surreal_tpu.utils.respawn import RespawnSchedule

        self._sched = RespawnSchedule(
            len(workers), respawn_backoff_s, respawn_backoff_cap_s,
            healthy_s=self._HEALTHY_S,
        )
        self.respawn_backoff_s = 0.0  # gauge: backoff set by the last respawn
        # supervision runs from the prefetch staging thread (empty-poll
        # waits) AND the trainer thread (drop path / post-learn): without
        # the lock both could respawn the same dead worker
        self._supervise_lock = threading.Lock()

    def supervise(self) -> None:
        """Workers are expendable (SURVEY.md §5.3: the reference delegated
        actor recovery to Kubernetes restart policies; here the trainer IS
        the supervisor): any dead worker is replaced in-place, under the
        backoff schedule above. Safe because workers are stateless — a
        fresh worker re-opens its DEALER socket under the same identity
        and the server's first message from it (obs-only) replaces the
        stale pending state without fabricating a transition.

        With a serving TIER (``server`` is an InferenceFleet) the same
        pass also supervises replicas, and a respawned worker routes via
        ``address_for`` — a worker whose replica died re-hellos to a
        SURVIVOR, not to the corpse's address."""
        if hasattr(self.server, "supervise"):
            self.server.supervise()
        with self._supervise_lock:
            now = time.monotonic()
            for i, w in enumerate(self.workers):
                if w.is_alive():
                    self._sched.note_alive(i, now)
                    continue
                if not self._sched.due(i, now):
                    continue  # backing off a crash-looping worker
                self.workers[i] = self.trainer._spawn_one(
                    i, self.env_cfg, self.server, self.stop
                )
                self.respawns += 1
                self.respawn_backoff_s = self._sched.respawned(i, now)

    def next_chunk(self) -> dict:
        deadline = time.monotonic() + self._timeout
        self._timeout = self.steady_timeout
        while True:
            if self.stop.is_set():
                # teardown: the staging thread must not sit out its full
                # chunk timeout against a closed server
                raise TimeoutError("data plane stopped") from None
            try:
                chunk = self.server.chunks.get(timeout=2.0)
                # queue-latency gauge: how long the chunk waited for the
                # learner (the server stamps _t_ready at assembly)
                self.last_chunk_age_s = time.monotonic() - chunk.pop(
                    "_t_ready", time.monotonic()
                )
                self.dwell_ms.append(self.last_chunk_age_s * 1e3)
                return chunk
            except queue.Empty:
                self.supervise()
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        "no experience chunks arriving from workers"
                    ) from None

    def close(self) -> None:
        self.stop.set()
        self.server.close()
        for w in self.workers:
            if hasattr(w, "terminate"):  # subprocess workers
                w.terminate()
                w.join(timeout=5)


class SEEDTrainer:
    def __init__(
        self,
        config,
        worker_mode: str | None = None,
        max_staleness: int | None | object = _FROM_CONFIG,
    ):
        # config is the user-facing path (session.topology.worker_mode,
        # learner.algo.max_staleness — both CLI-reachable via --set); the
        # constructor args override for tests/embedding
        if worker_mode is None:
            worker_mode = config.session_config.topology.get("worker_mode", "thread")
        if worker_mode not in ("thread", "process"):
            raise ValueError(f"worker_mode {worker_mode!r} not in thread|process")
        algo_name = config.learner_config.algo.name
        if algo_name == "ddpg":
            # the server stitches chunks from behavior-policy info (logp);
            # DDPG's deterministic actor has none — its disaggregated
            # topology is OffPolicyTrainer's host mode (replay-driven)
            raise ValueError(
                "SEEDTrainer supports on-policy learners (ppo, impala); "
                "for ddpg use OffPolicyTrainer (host mode)"
            )
        self.config = config
        from surreal_tpu.envs import make_env

        # build one env to read specs, then close (workers build their own)
        probe = make_env(config.env_config)
        self.specs = probe.specs
        probe.close()
        self.learner = build_learner(config.learner_config, self.specs)
        # program autotuner: same build-time cache consult as the fused
        # drivers. A host-env SEED workload has no fused device iteration,
        # so its search surface is the jitted LEARN program alone
        # (tune/search.py LEARN_PHASE_DIMS: sgd_unroll, gae_impl,
        # gae_unroll, shuffle) — `surreal_tpu tune <algo> <host-env>`
        # populates exactly this fingerprint, and 'search' mode runs the
        # learn-only search at build
        from surreal_tpu.tune import resolve_autotune

        self.tune_decision = resolve_autotune(config, self.learner.config)
        if self.tune_decision.applied:
            self.learner = build_learner(config.learner_config, self.specs)
        if getattr(self.learner, "requires_act_carry", False):
            # Design note (round-5 VERDICT item 5): trajectory policies DO
            # act over the wire now — via Agent.remote_act / eval --follow,
            # where one process owns one lockstep env batch and the K/V
            # carry lives client-side. The SEED server stays unsupported
            # deliberately: its micro-batches mix worker slices that
            # advance asynchronously, while the act carry keeps a single
            # scalar segment position for the whole batch (lockstep by
            # construction — SequenceActingMixin.act_init). Server-side
            # carry would need per-row positions, per-row wrap, and
            # gather/scatter of K/V rows per micro-batch composition —
            # a different (and recompile-heavy) design for no current user.
            raise ValueError(
                "model.encoder.kind='trajectory' is not supported by the "
                "SEED inference server (its micro-batches mix worker "
                "slices that advance asynchronously; the segment carry is "
                "lockstep). Trajectory policies act via the fused device "
                "collectors, the evaluator, `surreal_tpu actor`, and "
                "`eval --follow`."
            )
        self.algo = self.learner.config.algo
        topo = config.session_config.topology
        self.num_workers = max(1, topo.num_env_workers)
        self.worker_mode = worker_mode
        # host data plane (distributed/shm_transport.py). `.get` keeps
        # configs saved before the knobs existed loadable. 'auto' resolves
        # to pickle for thread workers (in-process tests keep the original
        # wire) and to shm negotiation for process workers, which are
        # always spawned on this host.
        self.transport = topo.get("transport", "auto")
        if self.transport not in ("auto", "shm", "pickle"):
            raise ValueError(
                f"topology.transport {self.transport!r} not in auto|shm|pickle"
            )
        self.worker_transport = (
            "pickle"
            if self.transport == "auto" and worker_mode == "thread"
            else self.transport
        )
        self.worker_silence_s = float(topo.get("worker_silence_s", 120.0))
        # sharded experience plane, FIFO chunk-relay arm (ISSUE 8,
        # surreal_tpu/experience/): trajectory chunks route inference
        # server -> ExperienceSender -> ReplayShardServer -> the staging
        # thread's ShardedSampler over the negotiated experience wire —
        # the cross-host seam that lets the learner group live on a
        # different host than the actor fleet's server. `.get` keeps old
        # configs loadable.
        xp = topo.get("experience_plane", None)
        self.experience_plane_enabled = bool(
            xp.get("enabled", False)
        ) if xp is not None else False
        # chaos harness: worker indices whose FIRST process spawn already
        # carried the fault plan (see _spawn_one's respawn note)
        self._fault_plan_sent: set[int] = set()
        # cross-process trace correlation: run() sets this from hooks
        # before the data plane spawns, so every worker (thread or
        # process) inherits the run-scoped trace id via spawn kwargs
        self._trace_id: str | None = None
        # ops plane (ISSUE 13): run() sets this from hooks before the
        # data plane spawns; every wire tier (fleet replicas, experience
        # shards, gateway) inherits the aggregator address the same way
        self._ops_address: str | None = None
        # causal tracing + lineage (ISSUE 14): run() points the span sink
        # at the hooks tracer and reads the telemetry.trace.* knobs; the
        # defaults keep embedders (the multi-host subclass sets only
        # _trace_id) span-free but lineage-stamped
        self._span_sink = None
        self._trace_sample_n = 0
        self._lineage = True
        n_envs = int(config.env_config.num_envs)
        # pipelined sub-slices halve the per-chunk batch width, so the
        # learn program compiles once per width: keep widths uniform (even
        # split only) and dp-divisible
        self.pipeline_workers = bool(topo.get("pipeline_workers", True)) and (
            n_envs >= 2 and n_envs % 2 == 0
        )
        dp_axis = int(topo.mesh.dp)
        if self.pipeline_workers and dp_axis > 1 and (n_envs // 2) % dp_axis:
            self.pipeline_workers = False
        if max_staleness is _FROM_CONFIG:
            # read the EXTENDED algo tree (build_learner layered per-algo +
            # base defaults onto it), not the raw user overrides
            max_staleness = self.algo.get("max_staleness", None)
        self.max_staleness = max_staleness

        # acting reuses the same state every serve: never donate.
        # precision: the learner's resolved policy (ops/precision.py)
        # lives inside act/learn — SEED's serve path and learn program
        # need no dtype forks; hooks records/validates the policy
        self._jit_act = jax.jit(
            self.learner.act, static_argnames="mode", donate_argnums=()
        )
        # multi-chip learner: an EXPLICIT dp axis (topology.mesh.dp > 1;
        # the -1 "use everything" default stays single-device here because
        # SEED batch width is set by num_envs, which must divide dp) runs
        # learn under shard_map with gradient psum — same dp_learn as the
        # fused trainers; acting stays one forward over replicated params.
        self.mesh = None
        dp = int(config.session_config.topology.mesh.dp)
        if dp > 1:
            from surreal_tpu.parallel.dp import dp_learn
            from surreal_tpu.parallel.mesh import check_dp_divisible, make_mesh

            check_dp_divisible(
                config.env_config.num_envs, dp, what="env_config.num_envs"
            )
            tp = max(1, int(config.session_config.topology.mesh.tp))
            if dp * tp > jax.device_count():
                raise ValueError(
                    f"topology.mesh dp={dp} tp={tp} asks for {dp * tp} "
                    f"devices but only {jax.device_count()} exist"
                )
            # an explicit dp may use a SUBSET of devices (the rest serve
            # inference/other work); make_mesh itself demands all devices
            self.mesh = make_mesh(
                config.session_config.topology,
                devices=jax.devices()[: dp * tp],
            )
            # donate=False: the inference server's act_fn closure aliases
            # the live train state and serves from it CONCURRENTLY with
            # the next learn — a donating learn would invalidate buffers
            # mid-serve (the multi-host SEED subclass acts from a separate
            # host-local copy, but shares this builder)
            self._learn = dp_learn(self.learner, self.mesh, donate=False)
        else:
            # NOT donated — same aliasing as above (see dp_learn's note)
            self._learn = jax.jit(self.learner.learn, donate_argnums=())
        # learner-group learn program (parallel/learner_group.py): SEED
        # has no sharded replay plane to partition, so elastic membership
        # does not apply here — but the group's gradient-all-reduce learn
        # is the SAME program, so topology.learner_group.members > 1
        # routes SEED's learn through it when mesh.dp did not already
        # claim the learn seam. SEED learners carry no per-row TD
        # bookkeeping; the synthetic priority/td_abs vector group_learn
        # threads for out-tree stability is popped before metrics ride
        # the stream.
        lg = config.session_config.topology.get("learner_group", None)
        lg_m = int(lg.get("members", 1)) if lg is not None else 1
        if self.mesh is None and lg_m > 1:
            from jax.sharding import Mesh

            from surreal_tpu.parallel.learner_group import group_learn
            from surreal_tpu.parallel.mesh import check_dp_divisible

            check_dp_divisible(
                config.env_config.num_envs, lg_m, what="env_config.num_envs"
            )
            if lg_m > jax.device_count():
                raise ValueError(
                    f"topology.learner_group members={lg_m} asks for "
                    f"{lg_m} devices but only {jax.device_count()} exist"
                )
            # batch_dim=1: SEED stages time-major [T, B, ...] chunks —
            # the group shards the env-batch dim, never the trajectory
            _group = group_learn(
                self.learner,
                Mesh(np.asarray(jax.devices()[:lg_m]), ("lg",)),
                batch_dim=1,
            )

            def _lg_learn(state, batch, key):
                state, metrics = _group(state, batch, key)
                metrics.pop("priority/td_abs", None)
                return state, metrics

            self._learn = _lg_learn

    def _spawn_one(self, i: int, env_cfg, route, stop):
        """Start env worker ``i`` as a thread or subprocess.

        ``route`` is the serving endpoint: a plain address string, or the
        server/fleet object — whose ``address_for(i)`` applies the
        session-affinity map (a fleet hashes workers over ALIVE replicas,
        so a respawn after a replica death lands on a survivor).

        Process mode uses the ``spawn`` start method: forking after jax/zmq
        have started threads is unsafe, and workers only need numpy + the
        host env anyway.
        """
        address = (
            route.address_for(i) if hasattr(route, "address_for") else route
        )
        kwargs = dict(
            transport=self.worker_transport,
            pipeline=self.pipeline_workers,
            server_silence_s=self.worker_silence_s,
            trace_id=self._trace_id,
        )
        if self.worker_mode == "process":
            import multiprocessing as mp

            # chaos harness: a spawned worker starts with an empty fault
            # registry — forward the plan so worker-site injections
            # (kill_worker, drop_frame, corrupt_slab) reach process mode
            # too; thread workers share this process's registry already.
            # FIRST spawn per index only: a respawned process would restart
            # its call counters at zero and re-fire one-shot faults forever
            # (a kill_worker injection must kill once, not crash-loop the
            # respawn path it exists to test)
            plan = faults.get().plan
            if plan and i not in self._fault_plan_sent:
                kwargs["fault_plan"] = plan
                self._fault_plan_sent.add(i)
            ctx = mp.get_context("spawn")
            w = ctx.Process(
                target=run_env_worker,
                args=(env_cfg.to_dict(), address, i),
                kwargs=kwargs,
                daemon=True,
            )
        else:
            w = threading.Thread(
                target=run_env_worker,
                args=(env_cfg, address, i),
                kwargs=dict(kwargs, stop_event=stop),
                daemon=True,
            )
        w.start()
        return w

    def _spawn_workers(self, env_cfg, route, stop):
        return [
            self._spawn_one(i, env_cfg, route, stop)
            for i in range(self.num_workers)
        ]

    def _start_data_plane(self, act_fn, stop, first_chunk_timeout: float):
        """Spawn the inference server + worker fleet and return a
        :class:`_DataPlane` handle — the shared lifecycle for the
        single-host and multi-host SEED loops (supervision, chunk waits,
        teardown live in ONE place)."""
        from surreal_tpu.launch.hooks import training_env_config

        topo = self.config.session_config.topology
        common = dict(
            unroll_length=self.algo.horizon,
            max_wait_ms=5.0,
            transport="pickle" if self.worker_transport == "pickle" else "auto",
            trace_id=self._trace_id,
            # robustness: nonfinite obs payloads (a corrupt slab slot, a
            # worker gone insane) are sanitized + counted rather than
            # poisoning the whole micro-batch. `.get` keeps old configs
            # loadable.
            sanitize_obs=bool(topo.get("sanitize_obs", True)),
            # ops plane: replicas push their own rows to the aggregator
            ops_address=self._ops_address,
            # causal trace exemplars + per-transition lineage stamps
            span_sink=self._span_sink,
            trace_sample_n=self._trace_sample_n,
            lineage=self._lineage,
        )
        # serving tier (ISSUE 10, distributed/fleet.py): >1 replica (or
        # autoscale on) runs the replicated fleet with session-affinity
        # routing and per-replica coalescing budgets; the single-server
        # path below stays byte-identical to the pre-tier behavior.
        fc = topo.get("inference_fleet", None)
        n_replicas = int(fc.get("replicas", 1)) if fc is not None else 1
        fleet_on = fc is not None and (
            n_replicas > 1 or bool(fc.get("autoscale", False))
        )
        if fleet_on:
            from surreal_tpu.distributed.fleet import InferenceFleet

            server = InferenceFleet(
                act_fn,
                num_workers=self.num_workers,
                replicas=n_replicas,
                min_replicas=int(fc.get("min_replicas", 1)),
                max_replicas=int(fc.get("max_replicas", 4)),
                autoscale=bool(fc.get("autoscale", False)),
                scale_up_serve_ms=float(fc.get("scale_up_serve_ms", 40.0)),
                scale_down_serve_ms=float(fc.get("scale_down_serve_ms", 5.0)),
                scale_cooldown_s=float(fc.get("scale_cooldown_s", 30.0)),
                respawn_backoff_s=float(fc.get("respawn_backoff_s", 0.5)),
                respawn_backoff_cap_s=float(
                    fc.get("respawn_backoff_cap_s", 30.0)
                ),
                **common,
            )
        else:
            server = InferenceServer(
                act_fn=act_fn,
                # coalesce all workers into one forward per lockstep
                # round: with min_batch=1 a W-worker fleet degrades to ~W
                # serves per round, and serve latency (not compute) is
                # the bound. auto_tune keeps this true as the fleet
                # shrinks/regrows (worker death, respawn) and scales the
                # coalescing wait to the serve-latency EWMA. (The fleet
                # installs per-REPLICA budgets from its affinity map.)
                min_batch=self.num_workers,
                auto_tune=True,
                **common,
            )
        try:
            env_cfg = self._worker_env_config(
                training_env_config(self.config.env_config)
            )
            workers = self._spawn_workers(env_cfg, server, stop)
        except BaseException:
            # a failed spawn must not leak the ROUTER socket + serve thread
            server.close()
            raise
        return _DataPlane(
            self, server, workers, env_cfg, stop, first_chunk_timeout,
            respawn_backoff_s=float(topo.get("respawn_backoff_s", 0.5)),
            respawn_backoff_cap_s=float(topo.get("respawn_backoff_cap_s", 30.0)),
        )

    def _worker_env_config(self, env_cfg):
        """Hook: per-rank seed decorrelation in the multi-host subclass."""
        return env_cfg

    def _make_act_fn(self, state, key_holder):
        def act_fn(obs_np):
            # pad the micro-batch to the next power of two: the server
            # coalesces a VARIABLE number of worker requests per forward,
            # and every distinct batch size is a fresh XLA compile — with
            # padding the compile count is log2-bounded and the steady
            # state reuses one cached executable
            n = obs_np.shape[0]
            padded = 1 << (n - 1).bit_length()
            if padded != n:
                obs_np = np.concatenate(
                    [obs_np, np.repeat(obs_np[-1:], padded - n, axis=0)], axis=0
                )
            key_holder[0], sub = jax.random.split(key_holder[0])
            actions, info = self._jit_act(state, obs_np, sub, mode="training")
            # one transfer for the whole result pytree: per-array np.asarray
            # would pay the host<->device round trip once per array, which
            # dominates serve latency on tunneled/remote TPUs
            actions, info = jax.device_get((actions, info))
            return actions[:n], {k: v[:n] for k, v in info.items()}

        return act_fn

    def run(
        self,
        max_env_steps: int | None = None,
        on_metrics: Callable[[int, dict], None] | None = None,
    ):
        cfg = self.config.session_config
        total = max_env_steps or cfg.total_env_steps

        key = jax.random.key(cfg.seed)
        key, init_key, act_key = jax.random.split(key, 3)
        state = self.learner.init(init_key)
        # chaos harness: install (or RESET) the fault registry for this run
        faults.configure_from(cfg)
        self._fresh_init = lambda nonce: self.learner.init(
            jax.random.fold_in(init_key, nonce)
        )
        from surreal_tpu.launch.hooks import SessionHooks

        hooks = SessionHooks(self.config, self.learner)
        plane = None
        prefetch = None
        xplane = None
        gateway = None
        stop = threading.Event()
        try:
            state, iteration, env_steps = hooks.restore(state)
            if self.mesh is not None:
                from surreal_tpu.parallel.mesh import replicate_state

                state = replicate_state(self.mesh, state)
            hooks.begin_run(iteration, env_steps)
            if self.tune_decision.mode != "off":
                hooks.tune_event(**self.tune_decision.telemetry())
            key_holder = [act_key]
            # workers inherit the run-scoped trace id via spawn kwargs
            self._trace_id = hooks.trace_id
            self._ops_address = hooks.ops.address
            # causal tracing + lineage (ISSUE 14): the hooks tracer is
            # the one span sink for every tier in this process, and the
            # telemetry.trace.* knobs set the head-sampling rate
            self._span_sink = hooks.tracer
            self._trace_sample_n = hooks.trace_sample_n
            self._lineage = hooks.lineage_enabled
            # the FIRST chunk waits out the policy's XLA compiles plus a
            # full unroll of round trips (can be minutes on a tunneled
            # TPU); workers keep their own 120s liveness budget per step,
            # reset by each served reply
            plane = self._start_data_plane(
                self._make_act_fn(state, key_holder), stop,
                first_chunk_timeout=600.0,
            )
            # cost accounting for the act closure: one policy forward at
            # the coalesced fleet width, padded to the power of two the
            # act_fn actually compiles for. No tracer phase times it (it
            # serves on the server thread), so it is recorded for diag
            # but excluded from the live MFU gauges.
            total_envs = self.num_workers * int(self.config.env_config.num_envs)
            padded = 1 << max(total_envs - 1, 0).bit_length()
            hooks.record_program_costs(
                "act", self._jit_act, state,
                jax.ShapeDtypeStruct(
                    (padded, *self.specs.obs.shape), self.specs.obs.dtype
                ),
                jax.random.fold_in(act_key, 0), mode="training",
                phase=None,
            )
            server = plane.server
            self._workers = plane.workers  # exposed for tests/fault injection

            # session gateway (ISSUE 12, gateway/): the tenant-facing
            # session tier in front of the serving fleet. Opt-in (the
            # training loop's own workers never route through it) and
            # fleet-only — it needs version-aware serve_act ingress.
            topo = self.config.session_config.topology
            gw_cfg = topo.get("gateway", None)
            if (
                gw_cfg is not None
                and bool(gw_cfg.get("enabled", False))
                and hasattr(server, "serve_act")
            ):
                from surreal_tpu.gateway import GatewayServer

                gateway = GatewayServer(
                    server,
                    bind=gw_cfg.get("bind", None),
                    max_sessions=int(gw_cfg.get("max_sessions", 256)),
                    lease_s=float(gw_cfg.get("lease_s", 30.0)),
                    tenant_quotas=gw_cfg.get("tenant_quotas", None),
                    act_cache=int(gw_cfg.get("act_cache", 256)),
                    pin_versions=bool(gw_cfg.get("pin_versions", True)),
                    # the hooks-owned ParameterFanout: session pins also
                    # hold the pinned version's full frame publisher-side
                    fanout=hooks.fanout,
                    trace_id=hooks.trace_id,
                    respawn_backoff_s=float(
                        gw_cfg.get("respawn_backoff_s", 0.5)
                    ),
                    respawn_backoff_cap_s=float(
                        gw_cfg.get("respawn_backoff_cap_s", 30.0)
                    ),
                    ops_address=hooks.ops.address,
                    # head-sampled gateway.act root spans for sessions
                    # that negotiated the "trace" cap
                    span_sink=self._span_sink,
                    trace_sample_n=self._trace_sample_n,
                )
                self._gateway = gateway  # exposed for tests
                hooks.log.info("session gateway live at %s", gateway.address)
                # discovery file: how an external tenant finds — and
                # RE-finds, after a cold restart rebinds the port — the
                # live gateway (the param_server.json idiom: atomic
                # tmp+rename, pollers race this write). Unlinked at
                # close so a stale file never points tenants at a dead
                # endpoint; surviving a SIGKILL is fine, the relaunch
                # overwrites it before tenants can re-attach.
                import json as _json
                import os as _os

                gw_discovery = _os.path.join(
                    self.config.session_config.folder, "gateway.json"
                )
                tmp = gw_discovery + ".tmp"
                with open(tmp, "w") as f:
                    _json.dump(
                        {"address": gateway.address,
                         "lease_s": float(gw_cfg.get("lease_s", 30.0))},
                        f,
                    )
                _os.replace(tmp, gw_discovery)

            # experience-plane chunk relay (FIFO arm): a relay thread
            # ships every assembled chunk through the ExperienceSender;
            # the staging thread below pops from the shard tier instead
            # of the server's in-process queue. Locally this is a
            # loop-through; across hosts it is the learner-group seam.
            if self.experience_plane_enabled:
                from surreal_tpu.experience import ExperiencePlane

                topo = self.config.session_config.topology
                xplane = ExperiencePlane(
                    kind="fifo",
                    cfg=topo.get("experience_plane", None),
                    trace_id=hooks.trace_id,
                    ops_address=hooks.ops.address,
                )

                def relay_chunks():
                    while not stop.is_set():
                        try:
                            chunk = server.chunks.get(timeout=0.5)
                        except queue.Empty:
                            continue
                        chunk = dict(chunk)
                        chunk.pop("_t_ready", None)
                        # chunk METADATA (not a wire column): an adopted
                        # exemplar ends its tree at the relay hop here —
                        # the lineage COLUMNS still cross the wire as
                        # ordinary spec fields
                        ex = chunk.pop("_exemplar", None)
                        if ex is not None and self._span_sink is not None:
                            from surreal_tpu.session.telemetry import (
                                TraceContext,
                            )

                            self._span_sink.emit_span(
                                "xplane.relay",
                                TraceContext(
                                    ex["exemplar"],
                                    self._span_sink.next_span_id(),
                                    ex["parent"],
                                ),
                                tier="experience",
                            )
                        try:
                            xplane.sender.send_chunk(chunk)
                        except Exception as e:
                            # Prefetcher's discipline: a producer error is
                            # re-raised to the consumer — a silently dead
                            # relay would present as a misleading pop
                            # timeout with the root cause lost
                            relay_error.append(e)
                            return

                relay_error: list[Exception] = []
                relay_thread = threading.Thread(
                    target=relay_chunks, daemon=True, name="xp-relay"
                )
                relay_thread.start()

            # closed-loop remediation (ISSUE 16): hand the hooks-owned
            # engine its actuator surfaces now that every tier exists.
            # The learner downshift rides the existing overrides path —
            # it mutates the live algo Config (batch halved, full->mixed
            # precision), effective at the next learner (re)build — and
            # returns the prior values so the counter-detector can
            # revert; None (nothing left to downshift) is counted
            # unmapped by the engine.
            def _learner_downshift():
                prior = {}
                b = int(self.algo.get("batch_size", 0) or 0)
                if b >= 64:
                    prior["batch_size"] = b
                    self.algo["batch_size"] = b // 2
                if self.algo.get("precision") == "full":
                    prior["precision"] = "full"
                    self.algo["precision"] = "mixed"
                return prior or None

            def _learner_restore(prior):
                for k, v in (prior or {}).items():
                    self.algo[k] = v

            hooks.bind_remediation_actuators(
                fleet=server if hasattr(server, "scale_up") else None,
                admission=getattr(gateway, "admission", None),
                restart={
                    k: v for k, v in {
                        "workers": plane.supervise,
                        "fleet": getattr(server, "supervise", None),
                        "gateway": (
                            gateway.supervise if gateway is not None
                            else None
                        ),
                        "experience": (
                            xplane.supervise if xplane is not None
                            else None
                        ),
                    }.items() if v is not None
                },
                learner_downshift=_learner_downshift,
                learner_restore=_learner_restore,
            )

            def next_chunk_from_xplane():
                """Pop one chunk from the shard tier, supervising BOTH
                planes while waiting (mirrors _DataPlane.next_chunk's
                contract: a dead sole worker or shard must be respawned
                while we wait, not after)."""
                deadline = time.monotonic() + plane._timeout
                plane._timeout = plane.steady_timeout
                while True:
                    if stop.is_set():
                        raise TimeoutError("data plane stopped") from None
                    if relay_error:
                        raise RuntimeError(
                            "experience-plane relay thread died"
                        ) from relay_error[0]
                    got = xplane.sampler.pop_chunk(timeout_s=2.0)
                    if got is not None:
                        rows, _n = got
                        return rows
                    plane.supervise()
                    xplane.supervise()
                    if time.monotonic() >= deadline:
                        raise TimeoutError(
                            "no experience chunks arriving through the "
                            "experience plane"
                        ) from None

            # double-buffered staging (learners/prefetch.py): the staging
            # thread waits on the chunk queue AND pays the host->device
            # transfer for chunk k+1 while the learner crunches chunk k —
            # with the dp-committed sharding, so the jitted learn never
            # reshards. param_version stays HOST-side (the staleness
            # decision needs it before any device work would be useful).
            from surreal_tpu.learners.prefetch import Prefetcher

            def stage_next_chunk():
                chunk = (
                    next_chunk_from_xplane() if xplane is not None
                    else plane.next_chunk()
                )
                versions = chunk.pop("param_version")
                # lineage stamps and the adopted exemplar stay HOST-side
                # (the staleness/provenance decisions need them before
                # any device work; the transfer-guard proves the lineage
                # reduction adds no device->host syncs)
                lineage = chunk.pop("lineage", None)
                exemplar = chunk.pop("_exemplar", None)
                n_steps = int(
                    chunk["reward"].shape[0] * chunk["reward"].shape[1]
                )
                with hooks.tracer.span("h2d-transfer"):
                    if self.mesh is not None:
                        # split host->devices directly along the dp-sharded
                        # batch dim; a plain device_put would commit the
                        # whole chunk to device 0 and reshard inside the jit
                        from surreal_tpu.parallel.mesh import batch_sharded

                        batch = jax.device_put(
                            chunk, batch_sharded(self.mesh, batch_dim=1)
                        )
                    else:
                        batch = jax.device_put(chunk)
                return batch, versions, n_steps, lineage, exemplar

            prefetch = Prefetcher(stage_next_chunk, name="seed-stage")

            dropped_stale = 0
            discarded_steps = 0
            dp_event_emitted = False
            learn_ms: deque = deque(maxlen=256)  # learn-hop samples
            # exact per-update staleness from the per-transition acting
            # versions (ISSUE 14): host-side numpy reduction, replacing
            # the ops plane's fanout-vs-fleet approximation
            from surreal_tpu.session.telemetry import (
                LineageReducer,
                TraceContext,
            )

            lineage_reducer = LineageReducer()

            def data_plane_extras() -> dict:
                """One source of truth for the drop/eviction/episode
                accounting, used for every in-loop metrics row AND the
                run-end reconciliation (keeping the two in lockstep)."""
                return {
                    "staleness/dropped_chunks": float(dropped_stale),
                    "staleness/steps_discarded": float(discarded_steps),
                    "workers/respawns": float(plane.respawns),
                    "workers/respawn_backoff_s": float(plane.respawn_backoff_s),
                    "server/chunk_age_s": float(plane.last_chunk_age_s),
                    **server.queue_stats(),
                    **(server.episode_stats() or {}),
                }

            # the SEED collect stage is ALWAYS overlapped: workers stream
            # chunks into the server queue regardless of the engine knob
            stages = (
                StageSpec("collect", donate=False, overlap=True),
                StageSpec("learn", donate=False),
            ) + sideband_stages()
            ls = LoopState(
                state=state, key=key, iteration=iteration,
                env_steps=env_steps,
            )

            def step(ls):
                nonlocal dropped_stale, discarded_steps, dp_event_emitted
                with hooks.tracer.span("chunk-wait"):
                    batch, versions, n_steps, lineage, exemplar = (
                        prefetch.get()
                    )
                staleness = server.version - int(versions.min())
                # Accounting contract: trainer-side stale DROPS count into
                # env_steps (deterministic, the trainer chose to discard);
                # server-side queue EVICTIONS are surfaced as
                # server/evicted_* metrics but NOT folded into the budget —
                # they spike during the learner's first compiles, and
                # folding them would make run length race against XLA
                # compile time (observed: the respawn fault-injection test's
                # budget consumed before the supervisor could act).
                if self.max_staleness is not None and staleness > self.max_staleness:
                    # acted by a too-old policy: drop, don't train. The
                    # steps DID happen — count them, and keep supervising
                    # workers (a streak of stale chunks must not pause
                    # respawn or stretch wall-clock past the step budget).
                    # The prefetcher already paid this chunk's transfer —
                    # a bounded waste (drops are the exception path). The
                    # engine's skip path counts the steps, runs no
                    # boundary, and still honors the interrupt latch (a
                    # preemption must not sit out a stale streak).
                    dropped_stale += 1
                    discarded_steps += n_steps
                    plane.supervise()
                    return Outcome(
                        metrics=None, hook_key=None, steps=n_steps,
                        skip_boundary=True,
                    )
                ls.key, lkey, hk_key = jax.random.split(ls.key, 3)
                t_learn0 = time.perf_counter()
                with hooks.tracer.span("learn"):
                    ls.state, metrics = self._learn(ls.state, batch, lkey)
                learn_ms.append((time.perf_counter() - t_learn0) * 1e3)
                if exemplar is not None:
                    # the adopted exemplar's final hop: THIS learn step
                    # consumed the chunk the replica stamped — the tree
                    # now spans gateway/worker -> replica -> learner
                    hooks.tracer.emit_span(
                        "learn.dispatch",
                        TraceContext(
                            exemplar["exemplar"],
                            hooks.tracer.next_span_id(),
                            exemplar["parent"],
                        ),
                        tier="learner",
                        dur_ms=learn_ms[-1],
                        version=int(server.version),
                    )
                # cost accounting, first learn only (idempotent; needs a
                # representative staged chunk to lower)
                hooks.record_program_costs(
                    "learn", self._learn, ls.state, batch, lkey,
                    phase="learn",
                )
                with hooks.tracer.span("param-publish"):
                    server.set_act_fn(
                        self._make_act_fn(ls.state, key_holder)
                    )
                plane.supervise()
                if gateway is not None:
                    gateway.supervise()
                if not dp_event_emitted:
                    # negotiated data-plane shape, once the fleet settled
                    # (visible in `surreal_tpu diag` without a metrics row)
                    hooks.data_plane_event(
                        transport=self.worker_transport,
                        pipeline=self.pipeline_workers,
                        workers=self.num_workers,
                        **server.transport_stats(),
                    )
                    dp_event_emitted = True
                metrics = dict(
                    metrics,
                    **{"staleness/updates_behind": float(staleness)},
                    # exact per-update staleness distribution + the span
                    # counters; the ops plane's SLO staleness objective
                    # prefers the lineage gauges over its derived
                    # fanout-vs-fleet approximation when they are present
                    **(
                        lineage_reducer.reduce(server.version, versions)
                        if self._lineage else {}
                    ),
                    **(
                        hooks.tracer.trace_gauges()
                        if self._trace_sample_n > 0 else {}
                    ),
                    **data_plane_extras(),
                    # cached (last-cadence) plane gauges: the wire poll
                    # happens at the cadence (post_metrics), not per
                    # iteration
                    **(xplane.gauges(poll=False) if xplane is not None else {}),
                    **(gateway.gauges() if gateway is not None else {}),
                )

                def post_metrics(m_row):
                    # per-hop latency percentiles ride the metrics cadence
                    # (host-side deques only — no device work)
                    hooks.tracer.event(
                        "hops", **hop_event(server, plane, learn_ms, gateway)
                    )
                    if hasattr(server, "maybe_autoscale"):
                        # serving tier: one scale decision per cadence
                        # (cooldown-bounded, driven by the serve-latency
                        # EWMA) + the per-replica telemetry snapshot
                        server.maybe_autoscale()
                        hooks.serving_event(**server.tier_event())
                    if gateway is not None:
                        hooks.gateway_event(**gateway.event())
                    if xplane is not None:
                        xplane._poll_stats()
                        hooks.experience_event(**xplane.telemetry_event())

                return Outcome(
                    metrics=metrics, hook_key=hk_key, steps=n_steps,
                    post_metrics=post_metrics,
                )

            def apply_fault(ls, f):
                ls.state = faults.apply_trainer_fault(f, ls.state)

            def on_rollback(ls):
                rb = hooks.recovery.rollback(ls.state, fresh=self._fresh_init)
                ls.state, ls.iteration, ls.env_steps = (
                    rb.state, rb.iteration, rb.env_steps
                )
                if self.mesh is not None:
                    from surreal_tpu.parallel.mesh import replicate_state

                    ls.state = replicate_state(self.mesh, ls.state)
                # the live act closure aliases the poisoned state:
                # re-arm acting from the restored one immediately (the
                # version bump also marks in-flight chunks stale)
                server.set_act_fn(self._make_act_fn(ls.state, key_holder))
                ls.key = jax.random.fold_in(ls.key, rb.nonce)

            engine = LoopEngine(
                hooks, total, step, stages,
                EngineConfig.from_session(self.config.session_config),
                on_metrics=on_metrics, apply_fault=apply_fault,
                on_rollback=on_rollback,
            )
            ls = engine.run(ls)
            state, iteration, env_steps = ls.state, ls.iteration, ls.env_steps
            # the drop path consumes budget without firing the metrics
            # cadence; reconcile the trailing snapshot with reality (only
            # when it actually trails — an unconditional flush would
            # duplicate the final writer row at every_n_iters=1)
            if hooks.last_metrics.get("time/env_steps") != env_steps:
                hooks.final_metrics(env_steps, data_plane_extras())
            if dp_event_emitted:
                # settled end-of-run gauges (bytes/step over the whole run)
                hooks.data_plane_event(
                    transport=self.worker_transport,
                    pipeline=self.pipeline_workers,
                    workers=self.num_workers,
                    **server.transport_stats(),
                )
            hooks.final_checkpoint(iteration, env_steps, state)
            return state, hooks.last_metrics
        finally:
            stop.set()
            if prefetch is not None:
                prefetch.close()
            if xplane is not None:
                # quiesce the relay first (the driver stop is already
                # set) so the close accounting reads a settled ledger; a
                # relay wedged in a bounded sender wait is unblocked by
                # the plane stop below and the accounting marked
                # unquiesced (the chaos exactly-once oracle then skips
                # strict conservation for this run)
                relay_thread.join(timeout=5)
                try:
                    hooks.tracer.event(
                        "experience_close",
                        quiesced=float(not relay_thread.is_alive()),
                        **xplane.accounting(),
                    )
                except Exception:
                    hooks.log.warning(
                        "experience_close accounting failed", exc_info=True
                    )
                # unblock any remaining bounded sender waits and JOIN
                # before close() touches the DEALER sockets the relay
                # shares (zmq sockets are not thread-safe)
                xplane._stop.set()
                relay_thread.join(timeout=5)
                xplane.close()
            if gateway is not None:
                # sessions die with the run; close BEFORE the fleet so the
                # gateway never serves into torn-down replicas
                gateway.close()
                import os as _os

                try:
                    _os.unlink(_os.path.join(
                        self.config.session_config.folder, "gateway.json"
                    ))
                except OSError:
                    pass  # best-effort: never written, or already gone
            if plane is not None:
                plane.close()
            hooks.close()
