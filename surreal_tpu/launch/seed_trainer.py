"""SEED trainer: central inference server + host env workers + learner —
the fully-disaggregated topology for envs that cannot live on device
(BASELINE config ⑤'s "SEED-RL batched inference"; reference call stack
SURVEY.md §3.2 with the actor pool collapsed).

Data flow:
  env workers --ZMQ/DCN--> InferenceServer (one batched policy forward)
     └─ trajectory chunks --queue--> learner.learn (V-trace corrects the
        one-update staleness; works for IMPALA and, with staleness caveats,
        PPO)

Workers run as threads (fine for gym classic-control) or OS processes
(``worker_mode='process'`` — MuJoCo-heavy stepping releases the GIL
poorly, so real deployments fork the reference's actor-pool way; both
modes run the same ``run_env_worker``).

Staleness: every transition carries the params version that chose its
action (InferenceServer tags them; SURVEY.md §7 hard-parts). V-trace
(IMPALA) absorbs bounded staleness by construction; for PPO-over-SEED set
``max_staleness`` to drop chunks whose oldest transition was acted more
than that many updates ago instead of silently training on them.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

import jax
import numpy as np

from surreal_tpu.distributed.env_worker import run_env_worker
from surreal_tpu.distributed.inference_server import InferenceServer
from surreal_tpu.learners import build_learner


class SEEDTrainer:
    def __init__(
        self,
        config,
        worker_mode: str = "thread",
        max_staleness: int | None = None,
    ):
        if worker_mode not in ("thread", "process"):
            raise ValueError(f"worker_mode {worker_mode!r} not in thread|process")
        self.config = config
        from surreal_tpu.envs import make_env

        # build one env to read specs, then close (workers build their own)
        probe = make_env(config.env_config)
        self.specs = probe.specs
        probe.close()
        self.learner = build_learner(config.learner_config, self.specs)
        self.algo = self.learner.config.algo
        self.num_workers = max(1, config.session_config.topology.num_env_workers)
        self.worker_mode = worker_mode
        self.max_staleness = max_staleness

        self._jit_act = jax.jit(self.learner.act, static_argnames="mode")
        self._learn = jax.jit(self.learner.learn)

    def _spawn_workers(self, env_cfg, address, stop):
        """Start env workers as threads or subprocesses; returns the list.

        Process mode uses the ``spawn`` start method: forking after jax/zmq
        have started threads is unsafe, and workers only need numpy + the
        host env anyway.
        """
        workers = []
        if self.worker_mode == "process":
            import multiprocessing as mp

            ctx = mp.get_context("spawn")
            for i in range(self.num_workers):
                p = ctx.Process(
                    target=run_env_worker,
                    args=(env_cfg.to_dict(), address, i),
                    daemon=True,
                )
                p.start()
                workers.append(p)
        else:
            for i in range(self.num_workers):
                t = threading.Thread(
                    target=run_env_worker,
                    args=(env_cfg, address, i),
                    kwargs={"stop_event": stop},
                    daemon=True,
                )
                t.start()
                workers.append(t)
        return workers

    def _make_act_fn(self, state, key_holder):
        def act_fn(obs_np):
            key_holder[0], sub = jax.random.split(key_holder[0])
            actions, info = self._jit_act(state, obs_np, sub, mode="training")
            return np.asarray(actions), {k: np.asarray(v) for k, v in info.items()}

        return act_fn

    def run(
        self,
        max_env_steps: int | None = None,
        on_metrics: Callable[[int, dict], None] | None = None,
    ):
        cfg = self.config.session_config
        total = max_env_steps or cfg.total_env_steps

        key = jax.random.key(cfg.seed)
        key, init_key, act_key = jax.random.split(key, 3)
        state = self.learner.init(init_key)
        from surreal_tpu.launch.hooks import SessionHooks, training_env_config

        hooks = SessionHooks(self.config, self.learner)
        server = None
        workers: list = []
        stop = threading.Event()
        try:
            state, iteration, env_steps = hooks.restore(state)
            hooks.begin_run(iteration, env_steps)
            key_holder = [act_key]
            server = InferenceServer(
                act_fn=self._make_act_fn(state, key_holder),
                unroll_length=self.algo.horizon,
            )
            env_cfg = training_env_config(self.config.env_config)
            workers = self._spawn_workers(env_cfg, server.address, stop)

            dropped_stale = 0
            while env_steps < total:
                try:
                    chunk = server.chunks.get(timeout=30)
                except queue.Empty:
                    raise TimeoutError("no experience chunks arriving from workers")
                versions = chunk.pop("param_version")
                staleness = server.version - int(versions.min())
                if self.max_staleness is not None and staleness > self.max_staleness:
                    dropped_stale += 1
                    continue  # acted by a too-old policy: drop, don't train
                batch = jax.device_put(chunk)
                key, lkey, hk_key = jax.random.split(key, 3)
                state, metrics = self._learn(state, batch, lkey)
                server.set_act_fn(self._make_act_fn(state, key_holder))
                iteration += 1
                env_steps += chunk["reward"].shape[0] * chunk["reward"].shape[1]
                metrics = dict(
                    metrics,
                    **{
                        "staleness/updates_behind": float(staleness),
                        "staleness/dropped_chunks": float(dropped_stale),
                    },
                )
                _, stop_flag = hooks.end_iteration(
                    iteration, env_steps, state, hk_key, metrics, on_metrics
                )
                if stop_flag:
                    break
            hooks.final_checkpoint(iteration, env_steps, state)
            return state, hooks.last_metrics
        finally:
            stop.set()
            if server is not None:
                server.close()
            for w in workers:
                if hasattr(w, "terminate"):  # subprocess workers
                    w.terminate()
                    w.join(timeout=5)
            hooks.close()
